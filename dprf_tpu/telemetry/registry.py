"""Dependency-free metrics registry: counters, gauges, histograms.

The substrate every runtime layer publishes into (coordinator,
dispatcher, worker, rpc, bench): a process-wide DEFAULT registry plus
explicit registries for tests and embedded use.  Three render targets:

  - render()    Prometheus text exposition format (served by the
                coordinator's ``/metrics`` endpoint, rpc._Handler);
  - snapshot()  JSON-serializable dict (the periodic JSONL telemetry
                snapshot written next to the session journal);
  - direct reads in tests (``Counter.value()``).

Design constraints: stdlib only (the worker path must not grow a
client-library dependency), thread-safe under the RPC server's
handler threads and the worker's async submit, and cheap enough that
per-unit increments are noise next to one device dispatch.  Timers use
the monotonic clock -- wall-clock steps must never produce negative
latencies in the journal.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Optional, Sequence, Tuple

#: default histogram buckets: spans sub-ms registry ops through
#: multi-minute compiles (the observed range of step latency and
#: compile-time observations); +Inf is implicit.
DEFAULT_BUCKETS = (0.005, 0.025, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers stay integral."""
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


def _label_str(names: Sequence[str], values: Tuple) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Shared label-child bookkeeping; subclasses define the per-child
    state and rendering."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: dict = {}

    def _key(self, labels: dict) -> Tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def _child(self, labels: dict):
        key = self._key(labels)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._new_child()
            return c

    def header(self) -> list:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.kind}"]

    def child_count(self) -> int:
        with self._lock:
            return len(self._children)

    def has_labels(self, **labels) -> bool:
        """Whether this exact label set already has a child (without
        creating one) -- lets callers bound label cardinality against
        client-controlled values."""
        with self._lock:
            return self._key(labels) in self._children


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        c = self._child(labels)
        with self._lock:
            c[0] += amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._child(labels)[0]

    def render(self) -> list:
        out = self.header()
        with self._lock:
            for key, c in sorted(self._children.items()):
                out.append(f"{self.name}"
                           f"{_label_str(self.labelnames, key)} "
                           f"{_fmt(c[0])}")
        return out

    def snapshot_values(self) -> list:
        with self._lock:
            return [{"labels": dict(zip(self.labelnames, k)),
                     "value": c[0]}
                    for k, c in sorted(self._children.items())]


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        c = self._child(labels)
        with self._lock:
            c[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        c = self._child(labels)
        with self._lock:
            c[0] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class _Timer:
    """Context manager feeding a histogram from the monotonic clock."""

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: "Histogram", labels: dict):
        self._hist = hist
        self._labels = labels

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0, **self._labels)
        return False


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(b)

    def _new_child(self):
        # [bucket counts..., +Inf count, sum]
        return [0] * (len(self.buckets) + 1) + [0.0]

    def observe(self, value: float, **labels) -> None:
        c = self._child(labels)
        with self._lock:
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    c[i] += 1
                    break
            else:
                c[len(self.buckets)] += 1
            c[-1] += value

    def time(self, **labels) -> _Timer:
        return _Timer(self, labels)

    def count(self, **labels) -> int:
        c = self._child(labels)
        with self._lock:
            return sum(c[:-1])

    def sum(self, **labels) -> float:
        c = self._child(labels)
        with self._lock:
            return c[-1]

    def render(self) -> list:
        out = self.header()
        with self._lock:
            for key, c in sorted(self._children.items()):
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum += c[i]
                    ls = _label_str(self.labelnames + ("le",),
                                    key + (_fmt(ub),))
                    out.append(f"{self.name}_bucket{ls} {cum}")
                cum += c[len(self.buckets)]
                ls = _label_str(self.labelnames + ("le",), key + ("+Inf",))
                out.append(f"{self.name}_bucket{ls} {cum}")
                base = _label_str(self.labelnames, key)
                out.append(f"{self.name}_sum{base} {_fmt(c[-1])}")
                out.append(f"{self.name}_count{base} {cum}")
        return out

    def snapshot_values(self) -> list:
        with self._lock:
            return [{"labels": dict(zip(self.labelnames, k)),
                     "buckets": dict(zip(
                         [_fmt(b) for b in self.buckets] + ["+Inf"],
                         c[:-1])),
                     "sum": c[-1], "count": sum(c[:-1])}
                    for k, c in sorted(self._children.items())]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create metric registry.  Re-declaring an existing name
    with the same kind and labelnames returns the SAME metric (every
    layer declares what it uses, none owns the registry); a conflicting
    re-declaration is a programming error and raises."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str,
             labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                want = kw.get("buckets")
                if (want is not None and
                        m.buckets != tuple(sorted(float(b)
                                                  for b in want))):
                    # silently keeping the first declaration's buckets
                    # would bin the second caller's observations into
                    # bounds it never asked for
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}")
                return m
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labelnames,
                         buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: list = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-serializable view: {name: {kind, help, values}}."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out = {}
        for m in metrics:
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "values": m.snapshot_values()}
        return out

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot(), separators=(",", ":"))
