"""Alert engine (ISSUE 10): declarative rules over the metrics
registry, evaluated in-process on the ``DPRF_ALERT_EVAL_S`` loop.

A rule is data -- metric selector + comparison + threshold + a
sustained ``for_s`` window::

    {"name": "worker_missing", "metric": "dprf_worker_health_state",
     "op": ">=", "threshold": 2, "for_s": 10, "severity": "critical",
     "summary": "worker silent past the missing threshold"}

``metric`` names a declared ``dprf_*`` metric; evaluation is PER
LABEL CHILD (so ``worker_missing`` fires once per silent worker, not
once for the fleet), optionally filtered by a ``labels`` subset.
``rate: true`` compares the per-second DELTA of a counter between
evaluation passes instead of its absolute value -- the
compile-miss-storm / reissue-storm / trace-drop detectors.  The
``DEFAULT_RULES`` pack below ships the conditions the ISSUE names;
``DPRF_ALERT_RULES`` points at a JSON file of additional rules (the
`dprf check` metrics analyzer validates every referenced metric name
against the declared registry, so a renamed metric breaks the build,
not the pager).

Lifecycle per (rule, label set): condition true -> PENDING; still
true after ``for_s`` -> FIRING; condition false for ``clear_s``
(default ``for_s`` -- the flap suppressor: a brief dip neither
resolves nor re-fires) -> RESOLVED.  A pending alert whose condition
clears before firing is dropped silently.  Every transition is an
EVENT: appended to a bounded in-memory history (served by
``op_alerts`` / ``dprf alerts``), streamed to the size-capped
``<session>.alerts.jsonl`` (``DPRF_ALERTS_MAX_BYTES``, ``.1``
rotation like every other session stream), and mirrored in the
``dprf_alerts_firing{rule}`` gauge / ``dprf_alerts_fired_total``
counter.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

#: suffix appended to a session journal path for its alert stream
ALERTS_SUFFIX = ".alerts.jsonl"

#: alert lifecycle states
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

#: events kept in memory for op_alerts (the file holds the full log)
HISTORY_MAX = 256

#: comparison operators a rule may use
OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
}

#: the default rule pack -- PURE LITERALS: the `dprf check` metrics
#: analyzer reads this assignment from the AST and verifies every
#: ``metric`` is a declared dprf_* name (a renamed metric would
#: otherwise silently disarm the rule forever)
DEFAULT_RULES = [
    {"name": "worker_missing", "metric": "dprf_worker_health_state",
     "op": ">=", "threshold": 2, "for_s": 5.0, "severity": "critical",
     "summary": "worker silent past the missing threshold (its "
                "leases will expire and reissue)"},
    {"name": "straggler", "metric": "dprf_worker_straggler",
     "op": ">=", "threshold": 1, "for_s": 15.0,
     "severity": "warning",
     "summary": "worker throughput far below the fleet's robust "
                "median (MAD z-score)"},
    {"name": "job_stalled", "metric": "dprf_job_stalled",
     "op": ">=", "threshold": 1, "for_s": 10.0,
     "severity": "critical",
     "summary": "job coverage flat across consecutive evaluation "
                "windows while running"},
    {"name": "compile_miss_storm",
     "metric": "dprf_compile_cache_misses_total", "rate": True,
     "op": ">", "threshold": 0.2, "for_s": 20.0,
     "severity": "warning",
     "summary": "sustained compile-cache misses: the fleet is "
                "recompiling instead of hashing (cold cache image? "
                "shape churn?)"},
    {"name": "reissue_storm", "metric": "dprf_units_reissued_total",
     "labels": {"reason": "lease_expired"}, "rate": True,
     "op": ">", "threshold": 0.5, "for_s": 20.0,
     "severity": "warning",
     "summary": "sustained lease expiries: workers are dying or "
                "stalling mid-unit"},
    {"name": "unit_failure_rate",
     "metric": "dprf_units_reissued_total",
     "labels": {"reason": "failed"}, "rate": True,
     "op": ">", "threshold": 0.5, "for_s": 20.0,
     "severity": "warning",
     "summary": "sustained unit failures: a poisoned range or a "
                "crashing worker build"},
    {"name": "trace_drops",
     "metric": "dprf_trace_spans_dropped_total", "rate": True,
     "op": ">", "threshold": 0.0, "for_s": 5.0,
     "severity": "warning",
     "summary": "flight-recorder spans are being dropped (ingest "
                "bound exceeded, or the trace stream stopped "
                "writing)"},
    {"name": "coverage_gap", "metric": "dprf_job_coverage_gap_total",
     "op": ">", "threshold": 0, "for_s": 5.0, "severity": "critical",
     "summary": "keyspace indices LOST from the coverage ledger "
                "(neither covered, live on a unit, nor unsplit) -- "
                "candidates are being skipped; audit the session "
                "with `dprf audit`"},
]

#: lock-discipline declaration (`dprf check` locks analyzer): the
#: engine is evaluated by the monitor thread and read by RPC handler
#: threads (op_alerts/op_trace_tail); all mutable state moves under
#: ``_lock``.  File writes happen under it too -- the TraceRecorder
#: precedent -- and never call into other locked subsystems.
GUARDED_BY = {
    "AlertEngine": {
        "_lock": ("_alerts", "_history", "_prev", "_path",
                  "_max_bytes", "eval_seconds", "evals"),
    },
}


def alerts_path(session_path: str) -> str:
    """Alert-stream location for a session journal path (idempotent,
    like trace_path)."""
    if session_path.endswith(ALERTS_SUFFIX):
        return session_path
    return session_path + ALERTS_SUFFIX


def alerts_max_bytes() -> Optional[int]:
    from dprf_tpu.telemetry.snapshot import cap_bytes
    return cap_bytes(envreg.get_int("DPRF_ALERTS_MAX_BYTES"))


def eval_interval(default: float = 5.0) -> float:
    v = envreg.get_float("DPRF_ALERT_EVAL_S", default)
    return max(0.25, float(v or default))


class AlertRule:
    """One validated rule (see the module docstring for the wire
    shape).  ``clear_s`` defaults to ``for_s``: the resolve hold that
    suppresses flapping."""

    __slots__ = ("name", "metric", "op", "threshold", "for_s",
                 "clear_s", "labels", "rate", "severity", "summary")

    def __init__(self, name: str, metric: str, op: str = ">",
                 threshold: float = 0.0, for_s: float = 0.0,
                 clear_s: Optional[float] = None, labels=None,
                 rate: bool = False, severity: str = "warning",
                 summary: str = ""):
        if not name or not metric:
            raise ValueError("alert rule needs 'name' and 'metric'")
        if op not in OPS:
            raise ValueError(
                f"alert rule {name!r}: unknown op {op!r} "
                f"(have: {sorted(OPS)})")
        self.name = str(name)
        self.metric = str(metric)
        self.op = op
        self.threshold = float(threshold)
        self.for_s = max(0.0, float(for_s))
        self.clear_s = (self.for_s if clear_s is None
                        else max(0.0, float(clear_s)))
        self.labels = dict(labels) if labels else {}
        self.rate = bool(rate)
        self.severity = str(severity)
        self.summary = str(summary)

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        if not isinstance(d, dict):
            raise ValueError("alert rule must be a JSON object")
        known = {"name", "metric", "op", "threshold", "for_s",
                 "clear_s", "labels", "rate", "severity", "summary"}
        junk = set(d) - known
        if junk:
            raise ValueError(
                f"alert rule {d.get('name')!r}: unknown keys "
                f"{sorted(junk)}")
        return cls(**{k: v for k, v in d.items()})


def load_rules(path: Optional[str] = None) -> list:
    """The default pack plus the ``DPRF_ALERT_RULES`` file (a JSON
    list of rule objects); a file rule with a default-pack name
    REPLACES that default (operator tuning beats shipped
    thresholds).  Raises ValueError on a malformed file -- a silently
    dropped rule pack is exactly the failure mode an alert engine
    must not have."""
    if path is None:
        path = envreg.get_path("DPRF_ALERT_RULES")
    rules = {r["name"]: AlertRule.from_dict(r) for r in DEFAULT_RULES}
    if path:
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(f"DPRF_ALERT_RULES file {path!r}: {e}")
        if not isinstance(doc, list):
            raise ValueError(
                f"DPRF_ALERT_RULES file {path!r}: want a JSON list "
                "of rule objects")
        for d in doc:
            r = AlertRule.from_dict(d)
            rules[r.name] = r
    return list(rules.values())


def load_alerts(path: str) -> list:
    """Read an alert-event stream back (rotated ``.1`` part first,
    torn tail lines skipped) -- the ``dprf report`` health section's
    input."""
    events = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        try:
            with open(p, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(e, dict) and e.get("rule"):
                        events.append(e)
        except OSError:
            continue
    return events


class _AlertState:
    """Lifecycle state for one (rule, label set)."""

    __slots__ = ("rule", "labels", "state", "since", "fired_at",
                 "clear_since", "value")

    def __init__(self, rule: AlertRule, labels: dict, now: float):
        self.rule = rule
        self.labels = labels
        self.state = PENDING
        self.since = now
        self.fired_at: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.value = 0.0

    def as_dict(self, now: float) -> dict:
        return {"rule": self.rule.name, "state": self.state,
                "labels": dict(self.labels),
                "severity": self.rule.severity,
                "summary": self.rule.summary,
                "value": round(self.value, 6),
                "threshold": self.rule.threshold,
                "since_s": round(max(0.0, now - self.since), 3)}


class AlertEngine:
    """Rules + lifecycle state + the event stream.  ``evaluate()`` is
    the only mutator (one caller: the health monitor loop, or a test
    driving it directly); reads come from RPC handler threads."""

    def __init__(self, rules=None, registry=None, clock=None,
                 wall=None):
        self.rules = list(rules) if rules is not None else load_rules()
        self.registry = get_registry(registry)
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self._lock = threading.Lock()
        self._alerts: dict = {}     # (rule name, label key) -> state
        self._history: deque = deque(maxlen=HISTORY_MAX)
        self._prev: dict = {}       # rate rules: key -> (value, t)
        self._path: Optional[str] = None
        self._max_bytes: Optional[int] = None
        #: cumulative evaluation cost -- the <=2% overhead assertion's
        #: measured quantity (tests/test_health.py)
        self.eval_seconds = 0.0
        self.evals = 0
        m = self.registry
        self._g_firing = m.gauge(
            "dprf_alerts_firing",
            "alerts currently in the firing state, per rule",
            labelnames=("rule",))
        self._m_fired = m.counter(
            "dprf_alerts_fired_total",
            "pending->firing transitions, per rule",
            labelnames=("rule",))

    # -- event stream ----------------------------------------------------

    def attach_file(self, path: str,
                    max_bytes: Optional[int] = None) -> "AlertEngine":
        """Stream subsequent alert events to a JSONL file (the
        session's ``.alerts.jsonl``), size-capped like the telemetry
        and trace streams."""
        with self._lock:
            self._path = path
            self._max_bytes = max_bytes
        return self

    def _emit(self, event: dict) -> None:
        """Append one event to history + the stream.  Alert
        transitions are rare (human-scale), so the stream opens per
        event -- no held handle, no release discipline to audit."""
        from dprf_tpu.telemetry.snapshot import rotate_if_over
        self._history.append(event)
        if self._path is None:
            return
        data = json.dumps(event, separators=(",", ":"),
                          default=str) + "\n"
        cap = (alerts_max_bytes() if self._max_bytes is None
               else self._max_bytes)
        try:
            rotate_if_over(self._path, len(data), cap)
            with open(self._path, "a", encoding="utf-8") as fh:
                fh.write(data)
        except OSError:
            pass   # a full disk must not kill the serve plane
    _emit._holds_lock = "_lock"

    def _event(self, st: _AlertState, state: str) -> dict:
        return {"ts": round(self._wall(), 3), "rule": st.rule.name,
                "state": state, "labels": dict(st.labels),
                "severity": st.rule.severity,
                "summary": st.rule.summary,
                "value": round(st.value, 6),
                "threshold": st.rule.threshold}
    _event._holds_lock = "_lock"

    # -- evaluation ------------------------------------------------------

    def _conditions(self, rule: AlertRule, now: float) -> dict:
        """{label key tuple: (labels dict, value, condition bool)}
        for one rule against the live registry.  Rate rules need two
        sightings of a child before they can report a condition."""
        out: dict = {}
        metric = self.registry.get(rule.metric)
        if metric is None:
            return out
        for v in metric.snapshot_values():
            labels = v.get("labels") or {}
            if any(labels.get(k) != str(val)
                   for k, val in rule.labels.items()):
                continue
            # histograms have no single value to threshold; rules
            # target counters and gauges
            if "value" not in v:
                continue
            value = float(v["value"])
            key = tuple(sorted(labels.items()))
            if rule.rate:
                prev = self._prev.get((rule.name, key))
                self._prev[(rule.name, key)] = (value, now)
                if prev is None or now <= prev[1]:
                    continue
                value = (value - prev[0]) / (now - prev[1])
            out[key] = (labels, value, OPS[rule.op](value,
                                                   rule.threshold))
        return out
    _conditions._holds_lock = "_lock"

    def evaluate(self) -> list:
        """One pass over every rule; returns the transition events it
        emitted (also appended to history / the stream)."""
        t0 = time.perf_counter()
        now = self._clock()
        events = []
        firing_count: dict = {}
        with self._lock:
            for rule in self.rules:
                for key, (labels, value, cond) in \
                        self._conditions(rule, now).items():
                    akey = (rule.name, key)
                    st = self._alerts.get(akey)
                    if cond:
                        if st is None:
                            st = self._alerts[akey] = _AlertState(
                                rule, labels, now)
                            st.value = value
                            events.append(self._event(st, PENDING))
                        st.value = value
                        st.clear_since = None
                        if (st.state == PENDING
                                and now - st.since >= rule.for_s):
                            st.state = FIRING
                            st.fired_at = now
                            st.since = now
                            self._m_fired.inc(rule=rule.name)
                            events.append(self._event(st, FIRING))
                    elif st is not None:
                        st.value = value
                        if st.state == PENDING:
                            # never fired: drop silently (no resolve
                            # event for an alert nobody was shown)
                            del self._alerts[akey]
                        else:
                            if st.clear_since is None:
                                st.clear_since = now
                            if now - st.clear_since >= rule.clear_s:
                                # the flap suppressor: the condition
                                # stayed false for the whole hold
                                events.append(self._event(st,
                                                          RESOLVED))
                                del self._alerts[akey]
            for akey, st in self._alerts.items():
                if st.state == FIRING:
                    firing_count[akey[0]] = \
                        firing_count.get(akey[0], 0) + 1
            for e in events:
                self._emit(e)
            self.eval_seconds += time.perf_counter() - t0
            self.evals += 1
        for rule in self.rules:
            self._g_firing.set(firing_count.get(rule.name, 0),
                               rule=rule.name)
        return events

    # -- reads -----------------------------------------------------------

    def active(self) -> list:
        """Every pending/firing alert, firing first."""
        now = self._clock()
        with self._lock:
            out = [st.as_dict(now) for st in self._alerts.values()]
        out.sort(key=lambda a: (a["state"] != FIRING, a["rule"]))
        return out

    def firing_names(self) -> list:
        """Compact "rule(label values)" strings for the ``dprf top``
        header line."""
        out = []
        with self._lock:
            for st in self._alerts.values():
                if st.state != FIRING:
                    continue
                lv = ",".join(str(v) for _, v in
                              sorted(st.labels.items()))
                out.append(f"{st.rule.name}({lv})" if lv
                           else st.rule.name)
        return sorted(out)

    def history(self, n: int = HISTORY_MAX) -> list:
        with self._lock:
            items = list(self._history)
        return items[-max(1, int(n)):]
