"""Telemetry subsystem: metrics registry + /metrics + JSONL snapshots.

Answers "what is this fleet doing right now" without grepping stdout
(HashKitty's central-monitoring lesson, PAPERS.md): coordinator,
dispatcher, worker, RPC, and bench all publish into a process-wide
registry; the coordinator serves it as a Prometheus ``/metrics``
endpoint on the RPC port and journals periodic JSONL snapshots next to
the session file.

Metric names (all prefixed ``dprf_``; see README "Observability"):

  dprf_candidates_hashed_total{engine,device}   keyspace swept
  dprf_units_leased_total / _completed_total / _reissued_total{reason}
  dprf_hits_total / dprf_hits_rejected_total    oracle-verified cracks
  dprf_unit_seconds                             unit latency histogram
  dprf_compile_seconds{engine,cache}            step warmup compiles
                                                (cache: hit|miss|off)
  dprf_compile_cache_hits_total{engine}         persistent-compile-
  dprf_compile_cache_misses_total{engine}         cache behavior
  dprf_keyspace_total / dprf_keyspace_covered   sweep progress gauges
  dprf_targets_total / dprf_targets_found
  dprf_workers_quarantined / dprf_worker_last_seen_timestamp{worker}
  dprf_bench_rate_hs{engine,impl,device,mode}   bench results
  dprf_tuned_batch{engine,device,attack}        tuning-subsystem batch
  dprf_unit_target_seconds / dprf_unit_size     adaptive unit sizing
  dprf_units_poisoned_total                     retry-cap parking events
  dprf_units_parked                             currently-parked gauge
                                                (0 after retry-parked)
  dprf_trace_spans_total                        flight-recorder spans
                                                (telemetry/trace.py)
  dprf_worker_pipeline_depth                    remote worker submit-
                                                ahead depth (1=serial)
  dprf_worker_idle_seconds                      seconds a worker held
                                                no submitted unit
                                                (device idle)
  dprf_phase_seconds{phase,engine,job}          sampled per-phase sweep
                                                attribution (perf.py)
  dprf_device_busy_fraction{worker}             live sliding-window
                                                sweep coverage
  dprf_roofline_frac{engine}                    EWMA throughput / the
                                                int32 roofline ceiling
  dprf_per_chip_rate_hs / dprf_scaling_efficiency{engine}
                                                multichip scaling bench
  dprf_jobs_gc_total                            age-based job reaps
  dprf_worker_health_state{worker}              health state machine
                                                (telemetry/health.py)
  dprf_worker_straggler / dprf_worker_rate_hs{worker}
                                                straggler detection
  dprf_job_eta_seconds / dprf_job_ttfh_seconds / dprf_job_stalled{job}
                                                per-job SLOs
  dprf_job_lease_wait_seconds{job}              fair-share latency
  dprf_alerts_firing{rule} / dprf_alerts_fired_total{rule}
                                                alert engine
                                                (telemetry/alerts.py)
  dprf_trace_spans_dropped_total                dropped/lost spans
  dprf_hbm_bytes_in_use/_limit/_peak{device}    device allocator
                                                memory (devstats.py)
  dprf_program_peak_bytes{engine,attack}        analyzed per-dispatch
                                                footprint (programs.py)
  dprf_roofline_model_divergence{engine}        analyzed-vs-hand op
                                                model cross-check

Alongside metrics, telemetry/trace.py records per-unit lifecycle SPANS
(the flight recorder): trace ids assigned at split time, context
propagated over the RPC messages, ``dprf top`` live view, and ``dprf
trace export`` to Perfetto -- see its module docstring.
"""

from __future__ import annotations

import socket
from typing import Optional

from dprf_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                         MetricsRegistry)
from dprf_tpu.telemetry.snapshot import (TelemetrySnapshotter,
                                         load_snapshots,
                                         snapshot_interval,
                                         telemetry_path)

# NOTE: dprf_tpu.telemetry.trace is imported lazily by its users (it
# imports get_registry from this package at recorder construction);
# `from dprf_tpu.telemetry.trace import get_tracer` is the entrypoint.

#: process-wide registry: library code with no registry threaded
#: through publishes here (the utils/logging.DEFAULT pattern); the
#: coordinator serves THIS registry unless handed another.
DEFAULT = MetricsRegistry()


def get_registry(registry: Optional[MetricsRegistry] = None
                 ) -> MetricsRegistry:
    return registry if registry is not None else DEFAULT


def declare_job_metrics(m: MetricsRegistry) -> dict:
    """The job-progress metric surface shared by the local Coordinator
    and the distributed CoordinatorState -- ONE declaration site, so
    the two runtimes' names/labels/help can never drift."""
    return {
        "hits": m.counter("dprf_hits_total", "oracle-accepted cracks"),
        "rejects": m.counter(
            "dprf_hits_rejected_total",
            "device hits the CPU oracle refused to verify"),
        "cands": m.counter(
            "dprf_candidates_hashed_total", "keyspace indices swept",
            labelnames=("engine", "device")),
        "targets": m.gauge("dprf_targets_total", "targets in the job"),
        "found": m.gauge("dprf_targets_found",
                         "targets cracked so far"),
        "unit_seconds": m.histogram(
            "dprf_unit_seconds",
            "per-unit wall cost: submit-to-resolve, or the "
            "inter-completion interval once a worker pipeline is "
            "primed (queue wait behind the stream excluded)"),
    }


def scrape_metrics(host: str, port: int, timeout: float = 10.0,
                   path: str = "/metrics") -> str:
    """Plain-socket HTTP GET of a coordinator's metrics endpoint (the
    ``dprf metrics`` subcommand; no HTTP client dependency).  Returns
    the response body; raises OSError/ValueError on failure."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(f"GET {path} HTTP/1.0\r\n"
                  f"Host: {host}\r\n\r\n".encode())
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    raw = b"".join(chunks)
    head, _, body = raw.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0].split()
    if len(status) < 2 or status[1] != b"200":
        raise ValueError(
            f"metrics endpoint answered {head.splitlines()[0]!r}")
    return body.decode("utf-8", "replace")


__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "TelemetrySnapshotter", "DEFAULT", "declare_job_metrics",
           "get_registry", "load_snapshots", "scrape_metrics",
           "snapshot_interval", "telemetry_path"]
