"""Performance attribution (ISSUE 9): per-phase sweep accounting,
roofline distance, and scaling-efficiency metrics.

The metrics layer says how fast the fleet sweeps and the trace layer
says which unit ran where -- but neither can say WHERE a sweep's time
goes.  This module splits the worker hot path into PHASES:

  generate   host-side candidate material (mixed-radix digits, word
             windows) for one dispatch
  h2d        host->device transfer of the step arguments
  device     the fused crack step itself (dispatch + device compute)
  d2h        device->host result fetch + hit decode
  verify     CPU-oracle re-hash of reported hits (coordinator side)

recorded two ways: ``phase`` child spans under the unit's ``sweep``
span (so Perfetto shows the breakdown per unit) and a
``dprf_phase_seconds{phase,engine,job}`` histogram (so ``/metrics``
and ``dprf report`` show fleet-wide p50/p95 per phase).

Honest phase timing needs ``block_until_ready`` boundaries between
the phases -- exactly the host syncs the retrace analyzer forbids on
the steady-state path, because they drain the device stream.  So
attribution is SAMPLED: ``DPRF_PERF_SAMPLE=N`` (default every 16th
unit, 0 disables) routes one unit in N through ``probe_pending`` -- a
serial, synced sweep of that one unit -- while every other unit runs
the normal pipelined submit.  ``probe_pending`` is declared in the
hot-path modules' ``PERF_PROBE`` tables, the retrace analyzer's
explicit exemption list for deliberately-syncing sampled probes (a
declaration, not a suppression comment).

The probed sweep produces exactly the hits the normal path would:
the phase loop is the per-batch step contract
(``MaskWorkerBase.submit`` without super/wide fusion), decoded
through the worker's own ``_batch_hits``/``_window_hits``.  Workers
with a custom serial ``process`` (per-salt blocks, per-target steps)
are probed coarsely: their whole ``process`` is one ``device`` phase,
because re-implementing their sweep here would risk wrong hits.

Also here, because bench and the live fleet must share one model:

  - the per-engine ROOFLINE: ops/candidate over the chip's 3-6e12
    int32 ops/s band -> ``roofline_band_hs(engine)`` and the
    ``dprf_roofline_frac{engine}`` gauge (EWMA-smoothed per-unit
    throughput / the band ceiling).  ISSUE 13: the op model is
    XLA-DERIVED (telemetry/programs.py analyzed flops per candidate,
    covering every engine that compiles a step); the hand table
    survives as a cross-check, with analyzed-vs-hand drift published
    as ``dprf_roofline_model_divergence{engine}``;
  - multichip scaling: ``dprf_scaling_efficiency{engine}`` and
    ``dprf_per_chip_rate_hs{engine}`` published by bench's scaling
    mode.
"""

from __future__ import annotations

import time
from typing import Optional

from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

#: attribution phases, in hot-path order; the ONE declaration site for
#: the ``dprf_phase_seconds`` phase label values
PHASES = ("generate", "h2d", "device", "d2h", "verify")

#: sampling cadence knob: probe every Nth unit (0 disables)
SAMPLE_ENV = "DPRF_PERF_SAMPLE"

#: EWMA smoothing for the live roofline gauge (one unit's elapsed is
#: noisy; the gauge should read like a rate, not a jitter plot)
ROOFLINE_ALPHA = 0.3

#: chip int32 issue band (ops/s) -- the bracketed VPU model in
#: BASELINE.md: 1024 lanes x ~1.5 GHz x 2-4 int32 ops/lane/cycle
CHIP_INT_OPS_BAND = (3.0e12, 6.0e12)

#: HAND roofline models (BASELINE.md tables: decode + pack + rounds +
#: compare) -- DEMOTED to a cross-check by ISSUE 13: the live model is
#: the XLA-derived one (telemetry/programs.py: optimized-HLO flops per
#: candidate, captured at every compile site), which covers EVERY
#: engine that compiles a step.  These five hand values remain only to
#: sanity-check the analyzed numbers (divergence beyond
#: MODEL_DIVERGENCE_MAX publishes dprf_roofline_model_divergence) and
#: as the fallback when analysis never ran in this process.
OPS_PER_CANDIDATE = {
    "md5": 800,        # 64 rounds ~10 ops + decode/pack/compare
    "ntlm": 600,       # MD4: 48 rounds (+ utf16 widen in pack)
    "md4": 600,
    "sha1": 1000,      # 80 rounds
    "sha256": 2000,    # 64 heavier rounds
    "sha3-256": 10200,  # 24 rounds x ~426 uint32 ops (keccak model)
}


def sample_every(default: int = 16) -> int:
    """The probe cadence: every Nth unit runs the synced phase sweep;
    0 disables sampling entirely."""
    n = envreg.get_int(SAMPLE_ENV, default)
    return max(0, int(n))


def phase_histogram(registry=None):
    """``dprf_phase_seconds`` -- the ONE declaration site (the metrics
    analyzer enforces single-site declarations)."""
    return get_registry(registry).histogram(
        "dprf_phase_seconds",
        "seconds per attribution phase of a sampled sweep "
        "(generate/h2d/device/d2h from probed units; verify from "
        "every hit verification)",
        labelnames=("phase", "engine", "job"))


def worker_engine(worker) -> str:
    return getattr(getattr(worker, "engine", None), "name", "unknown")


class PerfSampler:
    """Per-loop sampling state + the publication surface the probed
    sweep records into.  One per run loop (local Coordinator /
    remote worker_loop); ``take()`` answers "is THIS unit the sampled
    one" on the configured cadence (unit 1, N+1, 2N+1, ...)."""

    __slots__ = ("every", "hist", "tracer", "_n")

    def __init__(self, registry=None, recorder=None,
                 every: Optional[int] = None):
        from dprf_tpu.telemetry.trace import get_tracer
        self.every = sample_every() if every is None else max(0, every)
        self.hist = phase_histogram(registry)
        self.tracer = get_tracer(recorder)
        self._n = 0

    def take(self) -> bool:
        if self.every <= 0:
            return False
        self._n += 1
        return (self._n - 1) % self.every == 0

    def observe_verify(self, seconds: float, engine: str = "unknown",
                       job: str = "j0") -> None:
        """The verify phase is real work on every hit batch (no forced
        sync needed), so it is recorded unsampled."""
        self.hist.observe(seconds, phase="verify", engine=engine,
                          job=str(job))


class _ProbedUnit:
    """Resolved result of a probed sweep: quacks like PendingUnit
    (``resolve()``), carries the phase breakdown and the spans a
    remote worker ships with its complete report.  ``sweep_span`` is
    the pre-allocated span id the caller must record the unit's sweep
    span under, so the phase spans parent onto it.

    ``cands``/``batches`` (ISSUE 19 satellite): how many candidates
    the probed sweep covered, over how many dispatches.  A fused
    (loop-superstep / coarse) probe books its whole window as ONE
    ``device`` sample while the per-batch probe books one unit of many
    small dispatches -- so raw phase seconds are not comparable across
    ``--impl`` variants.  The counts ride the phase spans and let
    `dprf report` normalize to per-candidate phase cost."""

    __slots__ = ("hits", "phases", "phase_spans", "sweep_span",
                 "cands", "batches")

    def __init__(self, hits, phases, phase_spans, sweep_span,
                 cands=0, batches=0):
        self.hits = hits
        self.phases = phases
        self.phase_spans = phase_spans
        self.sweep_span = sweep_span
        self.cands = cands
        self.batches = batches

    def resolve(self):
        return self.hits


def drain_backlog(queue) -> None:
    """Block until every already-queued pipeline entry's device work
    is done (its accumulated unit flag is ready), WITHOUT resolving
    anything -- called right before a sampled probe so the probe's
    first sync boundary attributes its own unit's work, not the
    stream backlog the pipeline deliberately keeps full.  Entries
    without a flag (serial workers' already-resolved units) need no
    drain."""
    for entry in queue:
        flag = getattr(entry[1], "flag", None)
        if flag is not None:
            _block(flag)


def _block(x) -> None:
    try:
        import jax
        jax.block_until_ready(x)
    except (ImportError, AttributeError, TypeError):
        bur = getattr(x, "block_until_ready", None)
        if bur is not None:
            bur()


def _probe_strategy(worker) -> str:
    """Which instrumented sweep is SAFE for this worker.  Only the two
    standard submit loops are re-implemented here; any class with its
    own ``process`` (per-salt blocks, per-target steps, CPU oracle)
    keeps its override and is probed coarsely."""
    from dprf_tpu.parallel import worker as pw
    from dprf_tpu.runtime import worker as rw
    proc = getattr(type(worker), "process", None)
    if proc is rw.DeviceWordlistWorker.process:
        return "wordlist"
    if proc is rw.MaskWorkerBase.process:
        return "digit"
    if proc is pw.ShardedMaskWorker.process:
        # same per-batch (base_digits, n_valid) contract + _batch_hits
        # decode; probing it per stride makes the sharded path's ~zero
        # h2d visible in the phase report
        return "digit"
    return "coarse"


def _probe_digit(worker, unit) -> tuple:
    """Per-batch (base_digits, n_valid) contract with forced sync
    boundaries between phases -- MaskWorkerBase.submit minus the
    super/wide fusion, decoded through the worker's own _batch_hits
    so a probed unit yields exactly the production hits."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    t = {"generate": 0.0, "h2d": 0.0, "device": 0.0, "d2h": 0.0}
    hits: list = []
    batches = 0
    perf = time.perf_counter
    for bstart in range(unit.start, unit.end, worker.stride):
        n_valid = min(worker.stride, unit.end - bstart)
        t0 = perf()
        digits = np.asarray(worker.gen.digits(bstart), dtype=np.int32)
        t1 = perf()
        t["generate"] += t1 - t0
        base = jax.device_put(digits)
        _block(base)
        nv = jnp.int32(n_valid)
        _block(nv)
        t2 = perf()
        t["h2d"] += t2 - t1
        result = worker.step(base, nv)
        _block(result)
        t3 = perf()
        t["device"] += t3 - t2
        hits.extend(worker._batch_hits(bstart, result, unit))
        t["d2h"] += perf() - t3
        batches += 1
    return t, hits, unit.length, batches


def _probe_wordlist(worker, unit) -> tuple:
    """Word-window contract ((w0, n_valid_words) scalars): candidate
    generation happens ON DEVICE via the rule interpreter, so the
    generate phase is folded into ``device`` and h2d is the scalar
    argument transfer."""
    import jax.numpy as jnp

    from dprf_tpu.runtime.worker import word_cover_range
    t = {"generate": 0.0, "h2d": 0.0, "device": 0.0, "d2h": 0.0}
    hits: list = []
    batches = 0
    perf = time.perf_counter
    w_start, w_end = word_cover_range(unit, worker.gen.n_rules)
    w_end = min(w_end, worker.gen.n_words)
    ws = w_start
    while ws < w_end:
        nw = min(worker.word_batch, w_end - ws)
        t0 = perf()
        w0 = jnp.int32(ws)
        nv = jnp.int32(nw)
        _block((w0, nv))
        t1 = perf()
        t["h2d"] += t1 - t0
        result = worker.step(w0, nv)
        _block(result)
        t2 = perf()
        t["device"] += t2 - t1
        hits.extend(worker._window_hits(ws, nw, result, unit))
        t["d2h"] += perf() - t2
        ws += nw
        batches += 1
    # the sweep covers whole word windows; out-of-unit hits are
    # filtered, but the device DID hash the covering lanes
    return t, hits, (w_end - w_start) * worker.gen.n_rules, batches


def _probe_coarse(worker, unit) -> tuple:
    """Fallback for workers with their own serial ``process``: one
    honest total under ``device`` beats a wrong re-implementation of
    a per-salt sweep.  A fused (loop-superstep) process books the
    WHOLE unit as one device sample, so the candidate count riding
    the probe is what keeps its phase cost comparable to the
    per-batch probes (per-candidate normalization in `dprf
    report`)."""
    t0 = time.perf_counter()
    hits = worker.process(unit)
    return {"device": time.perf_counter() - t0}, hits, unit.length, 1


def probe_phases(worker, unit) -> dict:
    """Phase breakdown of one synced sweep, no publication -- the
    bench-side entry (``dprf bench`` reports it as ``phases``)."""
    strategy = _probe_strategy(worker)
    if strategy == "wordlist":
        phases, _, _, _ = _probe_wordlist(worker, unit)
    elif strategy == "digit":
        phases, _, _, _ = _probe_digit(worker, unit)
    else:
        phases, _, _, _ = _probe_coarse(worker, unit)
    return phases


def probe_pending(worker, unit, sampler: PerfSampler,
                  trace: Optional[str] = None) -> _ProbedUnit:
    """The SAMPLED unit's sweep: serial, with block_until_ready
    boundaries between phases (this is the helper the hot-path
    modules declare in ``PERF_PROBE`` -- the syncs are the point).
    Records one ``phase`` span per phase (parented on the
    pre-allocated sweep span id the caller records the sweep under)
    plus the phase histogram, and returns a resolved PendingUnit
    stand-in carrying the spans for RPC shipping."""
    from dprf_tpu.telemetry.trace import new_span_id
    strategy = _probe_strategy(worker)
    if strategy == "wordlist":
        phases, hits, cands, batches = _probe_wordlist(worker, unit)
    elif strategy == "digit":
        phases, hits, cands, batches = _probe_digit(worker, unit)
    else:
        phases, hits, cands, batches = _probe_coarse(worker, unit)
    sweep_span = new_span_id()
    engine = worker_engine(worker)
    job = getattr(unit, "job_id", "j0")
    spans = []
    ts = time.time() - sum(phases.values())
    for phase in PHASES:
        dur = phases.get(phase)
        if dur is None:
            continue
        sampler.hist.observe(dur, phase=phase, engine=engine,
                             job=str(job))
        # cands/batches ride every phase span (ISSUE 19 satellite):
        # `dprf report` divides phase seconds by candidates probed, so
        # a coarse fused probe (whole window = ONE device sample) and
        # the per-batch probes stay comparable across --impl variants
        ev = sampler.tracer.record(
            "phase", dur=dur, ts=ts, trace=trace, parent=sweep_span,
            phase=phase, unit=unit.unit_id, job=job, engine=engine,
            cands=cands, batches=batches)
        ts += dur
        if ev is not None:
            spans.append(ev)
    return _ProbedUnit(hits, phases, spans, sweep_span,
                       cands=cands, batches=batches)


# ---------------------------------------------------------------------------
# roofline model (shared by bench and the live fleet)

#: analyzed-vs-hand ratio beyond which the cross-check alarms (the
#: dprf_roofline_model_divergence gauge carries the ratio either way;
#: this is the level the README documents as "one of the models is
#: wrong")
MODEL_DIVERGENCE_MAX = 2.0


def _divergence_gauge(registry=None):
    return get_registry(registry).gauge(
        "dprf_roofline_model_divergence",
        "max(analyzed, hand) / min(analyzed, hand) ops-per-candidate "
        "ratio between the XLA-derived roofline model and the hand "
        "table (cross-check engines only; > 2 means one model is "
        "wrong)", labelnames=("engine",))


#: profiler-measured device seconds per candidate, per engine -- the
#: LAST resort of the roofline model chain.  Programs whose optimized
#: HLO reports no flop count (gather/bitwise-only pipelines like the
#: probe-table step) never produce an analyzed value, and new kernels
#: have no hand entry; a measured capture window still lets them
#: publish dprf_roofline_frac instead of dropping off the plane.
_MEASURED_SPC: dict = {}


def record_measured_cost(engine: str, seconds_per_candidate: float,
                         registry=None) -> None:
    """Record a profiler-measured device-seconds/candidate observation
    (telemetry/profiler.py's trace analysis calls this for every
    engine a capture window attributed device time to).  Published as
    a gauge so the fallback model is inspectable on /metrics."""
    if not seconds_per_candidate or seconds_per_candidate <= 0:
        return
    _MEASURED_SPC[engine] = float(seconds_per_candidate)
    get_registry(registry).gauge(
        "dprf_measured_spc",
        "profiler-measured device seconds per candidate (roofline "
        "fallback model for programs with no analyzed flop count and "
        "no hand entry)", labelnames=("engine",)).set(
            seconds_per_candidate, engine=engine)


def measured_ops_per_candidate(engine: str) -> Optional[float]:
    """Measured-cost fallback op model: device-s/candidate scaled by
    the band CEILING, i.e. "if the chip issued at peak, this is what
    the kernel's time is worth in ops".  Conservative by construction
    -- the implied roofline fraction of the measured rate itself is
    <= 1 -- and only consulted when neither an analyzed program nor a
    hand entry exists."""
    spc = _MEASURED_SPC.get(engine)
    if not spc:
        return None
    return spc * CHIP_INT_OPS_BAND[1]


def ops_per_candidate(engine: str, registry=None) -> Optional[float]:
    """The engine's roofline op model: the XLA-DERIVED value
    (telemetry/programs.py: optimized flops / candidates per dispatch)
    when a compiled program was analyzed in this process, else the
    hand table, else the profiler-measured device-s/cand fallback
    (``record_measured_cost``).  When analyzed AND hand exist the
    divergence ratio is published so a drifted hand model (or a
    mis-captured program) surfaces on /metrics instead of silently
    skewing every roofline fraction.  Returns None only when the
    engine compiled nothing here, has no hand entry, AND was never
    covered by a profiler capture window."""
    from dprf_tpu.telemetry import programs as programs_mod
    analyzed = programs_mod.analyzed_ops_per_candidate(engine)
    hand = OPS_PER_CANDIDATE.get(engine)
    if analyzed and hand:
        ratio = max(analyzed, hand) / min(analyzed, hand)
        _divergence_gauge(registry).set(ratio, engine=engine)
    return analyzed or hand or measured_ops_per_candidate(engine)


def roofline_band_hs(engine: str) -> Optional[tuple]:
    """(lo, hi) H/s ceiling band for an engine, or None when neither
    an analyzed program nor a hand model exists.  The analyzed model
    wins (see ops_per_candidate); md5's documented 4-8 GH/s
    BASELINE.md band applies only on the hand-model fallback, so the
    committed trajectory stays readable next to the derived one."""
    ops = ops_per_candidate(engine)
    if not ops:
        return None
    from dprf_tpu.telemetry import programs as programs_mod
    if engine == "md5" and not \
            programs_mod.analyzed_ops_per_candidate(engine):
        return (4.0e9, 8.0e9)
    lo, hi = CHIP_INT_OPS_BAND
    return (lo / ops, hi / ops)


def roofline_fraction(engine: str, rate_hs: float) -> Optional[float]:
    """Conservative fraction of the roofline band (vs the HI ceiling,
    like the driver bench's roofline_frac); None when the engine has
    no model or the rate is not positive."""
    band = roofline_band_hs(engine)
    if band is None or not rate_hs or rate_hs <= 0:
        return None
    return rate_hs / band[1]


def analyzed_roofline_fraction(engine: str,
                               rate_hs: float) -> Optional[float]:
    """Roofline fraction from the XLA-DERIVED model ALONE (no hand
    fallback): what bench reports as ``analyzed_roofline`` so the
    trajectory can tell a compiler-derived fraction from a hand-table
    one.  None when no program of this engine was analyzed here."""
    from dprf_tpu.telemetry import programs as programs_mod
    ops = programs_mod.analyzed_ops_per_candidate(engine)
    if not ops or not rate_hs or rate_hs <= 0:
        return None
    return rate_hs / (CHIP_INT_OPS_BAND[1] / ops)


def _roofline_gauge(registry=None):
    return get_registry(registry).gauge(
        "dprf_roofline_frac",
        "EWMA-smoothed fraction of the per-engine int32 roofline "
        "ceiling the observed throughput reaches (conservative: vs "
        "the band's upper bound)", labelnames=("engine",))


def publish_roofline(engine: str, rate_hs: float,
                     registry=None) -> Optional[float]:
    """Fold one throughput observation into the live roofline gauge
    (EWMA against the gauge's current value, so per-unit jitter reads
    as a rate).  Returns the smoothed fraction, or None when the
    engine has no published op model."""
    frac = roofline_fraction(engine, rate_hs)
    if frac is None:
        return None
    g = _roofline_gauge(registry)
    cur = g.value(engine=engine)
    smoothed = frac if cur == 0 else cur + ROOFLINE_ALPHA * (frac - cur)
    g.set(smoothed, engine=engine)
    return smoothed


def roofline_snapshot(registry=None) -> dict:
    """{engine: smoothed fraction} from the live gauge (the ``dprf
    top`` header and op_trace_tail status read this)."""
    m = get_registry(registry).get("dprf_roofline_frac")
    if m is None:
        return {}
    return {v["labels"].get("engine", "?"): v["value"]
            for v in m.snapshot_values() if v["value"] > 0}


def publish_scaling(engine: str, per_chip_hs: float, efficiency: float,
                    n_devices: int, registry=None) -> None:
    """Multichip bench publication: per-chip H/s and the 1->N scaling
    efficiency, next to the roofline gauge -- ONE declaration site for
    both gauges."""
    m = get_registry(registry)
    m.gauge("dprf_per_chip_rate_hs",
            "per-chip throughput of the last multichip scaling bench",
            labelnames=("engine",)).set(per_chip_hs, engine=engine)
    m.gauge("dprf_scaling_efficiency",
            "rate_N / (N * rate_1) of the last multichip scaling "
            "bench", labelnames=("engine",)).set(efficiency,
                                                 engine=engine)
    publish_roofline(engine, per_chip_hs, registry=registry)
