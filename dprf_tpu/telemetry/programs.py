"""Compiled-program registry (ISSUE 13): XLA-derived cost and memory
introspection for every step the fleet compiles.

The roofline story used to end at a hand-maintained
``OPS_PER_CANDIDATE`` table in telemetry/perf.py covering five fast
engines -- every other engine reported no roofline at all, and nothing
in the stack knew how much HBM a compiled step actually needs.  The
compiler knows both exactly: jax 0.4.37's AOT surface exposes
``compiled.cost_analysis()`` (optimized-HLO flops / bytes accessed)
and ``compiled.memory_analysis()`` (argument / output / temp / code
bytes).  This module captures those numbers at every compile site --
worker warmup, ``aot_compile`` (prewarm), the sharded superstep, tune
rungs, bench -- into one process-wide registry:

  - ``register_program(...)``   called from the compile sites with the
        step + its warmup args.  Registration is CHEAP (no analysis):
        the expensive part is deferred so the hot warmup path never
        pays a second compile it didn't ask for.
  - ``analyze_pending(...)``    runs the deferred analysis:
        ``step.lower(args)`` (a cached trace after warmup, ~free) ->
        ``lowered.compile()`` (served by the persistent compilation
        cache wherever the CLI enabled it) -> cost/memory analysis +
        the program FINGERPRINT (sha256 over the lowered module text,
        backend, and jax version -- the same inputs the XLA compile
        cache keys on).  Called from the overlapped-warmup background
        thread, the worker heartbeat loop, tune, prewarm, and bench --
        never from a unit's dispatch path.
  - ``analyzed_ops_per_candidate(engine)``  the derived roofline
        input: optimized flops / candidates-per-dispatch of the
        engine's per-batch program.  telemetry/perf.py consults this
        FIRST and keeps the hand table only as a cross-check.
  - ``snapshot()`` / ``ingest(...)``  the wire surface: workers ship
        their analyzed records inside heartbeats; the coordinator
        merges them (bounded, sanitized) so ``op_programs`` / ``dprf
        programs`` shows the fleet's program table, not one process's.

Degradation contract: every jax call here is best-effort.  A backend
without cost analysis, a step that cannot AOT-lower, or an old jax
loses the analyzed record -- never the job.  ``DPRF_PROGRAM_ANALYSIS=0``
is the kill switch (the hand roofline models keep working).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional

from dprf_tpu.telemetry import get_registry
from dprf_tpu.utils import env as envreg

#: kill switch for the deferred analysis (registration stays cheap
#: either way; with analysis off the registry simply never fills)
ANALYSIS_ENV = "DPRF_PROGRAM_ANALYSIS"

#: wire-record fields a coordinator accepts from a worker heartbeat
#: (client-controlled data: unknown keys drop, strings are bounded)
WIRE_KEYS = ("key", "fingerprint", "engine", "attack", "batch",
             "flops", "bytes_accessed", "flops_per_candidate",
             "peak_bytes", "argument_bytes", "output_bytes",
             "generated_code_bytes", "proc")
MAX_WIRE_STR = 128
#: records one ingest call may merge (heartbeats are unauthenticated
#: on open fleets; a junk worker must not grow coordinator memory)
MAX_INGEST = 256
#: total records a registry holds (fingerprint-keyed; a fleet compiles
#: a bounded program set, so hitting this means id churn, not scale)
MAX_RECORDS = 1024

#: lock-discipline declaration (`dprf check` locks analyzer): the
#: record/pending tables are written from warmup threads, heartbeat
#: loops, and RPC handler threads at once.
GUARDED_BY = {
    "ProgramRegistry": {"_lock": ("_records", "_pending", "_seq")},
}


def analysis_enabled() -> bool:
    return envreg.get_bool(ANALYSIS_ENV)


class ProgramRecord:
    """One analyzed executable: identity + compiler-derived costs."""

    __slots__ = ("key", "fingerprint", "engine", "attack", "batch",
                 "flops", "bytes_accessed", "peak_bytes",
                 "argument_bytes", "output_bytes",
                 "generated_code_bytes", "analyzed_at", "proc", "seq")

    def __init__(self, key, fingerprint, engine, attack, batch,
                 flops=None, bytes_accessed=None, peak_bytes=None,
                 argument_bytes=None, output_bytes=None,
                 generated_code_bytes=None, proc="local", seq=0):
        self.key = key
        self.fingerprint = fingerprint
        self.engine = engine
        self.attack = attack
        self.batch = int(batch or 0)
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.peak_bytes = peak_bytes
        self.argument_bytes = argument_bytes
        self.output_bytes = output_bytes
        self.generated_code_bytes = generated_code_bytes
        self.analyzed_at = time.time()
        self.proc = proc
        self.seq = seq

    @property
    def flops_per_candidate(self) -> Optional[float]:
        if not self.flops or self.batch <= 0:
            return None
        return self.flops / self.batch

    @property
    def bytes_per_candidate(self) -> Optional[float]:
        if not self.bytes_accessed or self.batch <= 0:
            return None
        return self.bytes_accessed / self.batch

    def total_peak_bytes(self) -> Optional[int]:
        """Peak device footprint of one dispatch: arguments + outputs
        + XLA temp allocations (the number an HBM budget reasons
        about; code size is reported separately -- it lives in HBM too
        but is shared across dispatches)."""
        parts = [self.argument_bytes, self.output_bytes,
                 self.peak_bytes]
        if all(p is None for p in parts):
            return None
        return int(sum(p or 0 for p in parts))

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "fingerprint": self.fingerprint,
            "engine": self.engine,
            "attack": self.attack,
            "batch": self.batch,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "flops_per_candidate": self.flops_per_candidate,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "total_peak_bytes": self.total_peak_bytes(),
            "proc": self.proc,
        }


def _cost_dict(compiled) -> dict:
    """Normalized compiled.cost_analysis(): jax has returned both a
    dict and a single-element list of dicts across versions."""
    try:
        ca = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 -- backend without cost analysis
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _memory_fields(compiled) -> dict:
    """compiled.memory_analysis() -> our field names; {} when the
    backend has no memory analysis (the documented None-degrade)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:   # noqa: BLE001
        return {}
    if ma is None:
        return {}
    out = {}
    for ours, theirs in (("peak_bytes", "temp_size_in_bytes"),
                         ("argument_bytes", "argument_size_in_bytes"),
                         ("output_bytes", "output_size_in_bytes"),
                         ("generated_code_bytes",
                          "generated_code_size_in_bytes")):
        v = getattr(ma, theirs, None)
        if isinstance(v, (int, float)):
            out[ours] = int(v)
    return out


def program_fingerprint(lowered) -> str:
    """sha256 over the lowered module text + backend + jax version --
    the same inputs the persistent XLA compile cache keys on, so two
    processes compiling the identical step agree on the fingerprint
    without sharing memory."""
    import jax
    h = hashlib.sha256()
    try:
        h.update(lowered.as_text().encode())
    except Exception:   # noqa: BLE001 -- a module that cannot print
        h.update(repr(lowered).encode())
    h.update(jax.default_backend().encode())
    h.update(jax.__version__.encode())
    return h.hexdigest()[:32]


class ProgramRegistry:
    """Process-wide table of compiled-program records + the pending
    (registered-but-unanalyzed) compile sites."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        #: fingerprint -> ProgramRecord
        self._records: dict = {}
        #: (engine, attack, batch) -> (step, args): deferred analysis
        self._pending: dict = {}
        self._seq = 0
        self._metrics = registry

    def _gauges(self):
        m = get_registry(self._metrics)
        return m.gauge(
            "dprf_program_peak_bytes",
            "per-dispatch device footprint (arguments + outputs + XLA "
            "temp) of the engine's analyzed per-batch program "
            "(compiled.memory_analysis; absent on backends without "
            "memory analysis)", labelnames=("engine", "attack"))

    def register(self, engine: str, attack: str, batch: int,
                 step=None, args=None, compiled=None,
                 lowered=None) -> None:
        """Record a compile site.  Cheap: analysis is deferred unless
        the caller already holds the Compiled object (aot_compile,
        prewarm), in which case reading the analysis costs ~ms --
        pass ``lowered`` alongside so the record carries the REAL
        module fingerprint (cross-process dedup depends on it)."""
        if not analysis_enabled():
            return
        key = (str(engine), str(attack), int(batch or 0))
        if compiled is not None:
            self._analyze_one(key, compiled=compiled, lowered=lowered)
            return
        if step is None or args is None:
            return
        with self._lock:
            if key in self._pending or any(
                    r.engine == key[0] and r.attack == key[1]
                    and r.batch == key[2]
                    for r in self._records.values()):
                return
            self._pending[key] = (step, args)

    def analyze_pending(self) -> int:
        """Run the deferred analysis for every pending site; returns
        how many records landed.  The compile this triggers is served
        by the persistent compilation cache wherever the CLI enabled
        it (the step was just compiled by warmup); never called from a
        dispatch path."""
        if not analysis_enabled():
            return 0
        with self._lock:
            todo = list(self._pending.items())
            self._pending.clear()
        n = 0
        for key, (step, args) in todo:
            if self._analyze_one(key, step=step, args=args):
                n += 1
        return n

    def _analyze_one(self, key, step=None, args=None,
                     compiled=None, lowered=None) -> bool:
        engine, attack, batch = key
        fingerprint = None
        try:
            if compiled is None:
                lower = getattr(step, "lower", None)
                if lower is None:
                    return False
                lowered = lower(*args)
            if lowered is not None:
                fingerprint = program_fingerprint(lowered)
                with self._lock:
                    if fingerprint in self._records:
                        return False
            if compiled is None:
                compiled = lowered.compile()
            cost = _cost_dict(compiled)
            mem = _memory_fields(compiled)
        except Exception:   # noqa: BLE001 -- analysis is best-effort:
            # a backend that cannot lower/compile/analyze loses the
            # record, never the job
            return False
        if fingerprint is None:
            # last resort (a Compiled with no Lowered in hand): the
            # shape key stands in -- same-shape programs can alias
            h = hashlib.sha256(
                f"{engine}|{attack}|{batch}".encode())
            fingerprint = "c-" + h.hexdigest()[:30]
        flops = cost.get("flops")
        rec = ProgramRecord(
            key=f"{engine}|{attack}|b{batch}",
            fingerprint=fingerprint, engine=engine, attack=attack,
            batch=batch,
            flops=float(flops) if isinstance(flops, (int, float))
            and flops > 0 else None,
            bytes_accessed=cost.get("bytes accessed"), **mem)
        self._store(rec)
        return True

    def _store(self, rec: ProgramRecord) -> None:
        with self._lock:
            if len(self._records) >= MAX_RECORDS and \
                    rec.fingerprint not in self._records:
                return
            self._seq += 1
            rec.seq = self._seq
            self._records[rec.fingerprint] = rec
        peak = rec.total_peak_bytes()
        if peak is not None:
            self._gauges().set(peak, engine=rec.engine,
                               attack=rec.attack)

    def ingest(self, records, proc: str = "?",
               limit: int = MAX_INGEST) -> int:
        """Merge wire records a worker shipped (heartbeat payload).
        Client-controlled: bounded count, known keys only, strings
        truncated, numbers coerced -- junk drops silently."""
        if not isinstance(records, (list, tuple)):
            return 0
        n = 0
        for raw in records[:max(0, int(limit))]:
            if not isinstance(raw, dict):
                continue
            clean = {}
            for k in WIRE_KEYS:
                v = raw.get(k)
                if v is None:
                    continue
                if isinstance(v, str):
                    clean[k] = v[:MAX_WIRE_STR]
                elif isinstance(v, (int, float)) and not isinstance(
                        v, bool):
                    clean[k] = v
            fp = clean.get("fingerprint")
            eng = clean.get("engine")
            if not isinstance(fp, str) or not fp or not eng:
                continue
            with self._lock:
                known = fp in self._records
            if known:
                continue
            rec = ProgramRecord(
                key=clean.get("key") or "?", fingerprint=fp,
                engine=str(eng), attack=str(clean.get("attack", "?")),
                batch=int(clean.get("batch") or 0),
                flops=clean.get("flops"),
                bytes_accessed=clean.get("bytes_accessed"),
                peak_bytes=clean.get("peak_bytes"),
                argument_bytes=clean.get("argument_bytes"),
                output_bytes=clean.get("output_bytes"),
                generated_code_bytes=clean.get("generated_code_bytes"),
                proc=str(proc))
            self._store(rec)
            n += 1
        return n

    def records_since(self, seq: int) -> tuple:
        """(wire records newer than seq, newest seq) -- the worker
        heartbeat ships only what the coordinator has not seen."""
        with self._lock:
            out = [r.as_dict() for r in self._records.values()
                   if r.seq > seq]
            return out, self._seq

    def snapshot(self) -> list:
        """Every record as a JSON-ready dict, stable order (engine,
        attack, batch) -- the op_programs / `dprf programs` payload."""
        with self._lock:
            recs = list(self._records.values())
        recs.sort(key=lambda r: (r.engine, r.attack, r.batch))
        return [r.as_dict() for r in recs]

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def analyzed_ops_per_candidate(self, engine: str) -> Optional[float]:
        """Optimized flops per candidate of the engine's analyzed
        program -- the XLA-derived roofline input.  A PEEK: never
        forces analysis (the publish path runs per completed unit).
        When several program shapes exist (per-batch, wide, superstep)
        the smallest per-candidate cost wins: fused programs amortize
        fixed work, and the roofline ceiling should reflect the best
        the chip is asked to do."""
        with self._lock:
            vals = [r.flops_per_candidate
                    for r in self._records.values()
                    if r.engine == engine
                    and r.flops_per_candidate]
        return min(vals) if vals else None

    def peak_bytes_for(self, engine: str,
                       batch: int) -> Optional[int]:
        """Per-dispatch footprint of the program(s) recorded at
        exactly this (engine, batch) -- the tune ladder's projection
        anchor: scaling THIS rung's footprint to the next rung is
        honest; scaling some other shape's (a bench program, another
        attack) is not."""
        with self._lock:
            vals = [r.total_peak_bytes()
                    for r in self._records.values()
                    if r.engine == engine and r.batch == batch]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    def peak_bytes(self, engine: Optional[str] = None) -> Optional[int]:
        """Largest analyzed per-dispatch footprint (optionally for one
        engine) -- the program-model fallback for peak_hbm_bytes on
        backends without memory_stats, and the tune ladder's
        projection anchor."""
        with self._lock:
            vals = [r.total_peak_bytes() for r in self._records.values()
                    if engine is None or r.engine == engine]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None


#: process-wide registry (the utils/logging.DEFAULT pattern): compile
#: sites with no registry threaded through publish here; the serve
#: plane merges worker records into the same one.
DEFAULT = ProgramRegistry()


def get_programs(programs: Optional[ProgramRegistry] = None
                 ) -> ProgramRegistry:
    return programs if programs is not None else DEFAULT


def register_program(engine: str, attack: str, batch: int, step=None,
                     args=None, compiled=None, lowered=None,
                     programs=None) -> None:
    get_programs(programs).register(engine, attack, batch, step=step,
                                    args=args, compiled=compiled,
                                    lowered=lowered)


def analyze_pending(programs=None) -> int:
    return get_programs(programs).analyze_pending()


def analyzed_ops_per_candidate(engine: str,
                               programs=None) -> Optional[float]:
    return get_programs(programs).analyzed_ops_per_candidate(engine)


def render_table(records: list) -> str:
    """The human half of ``dprf programs``: one row per executable."""
    rows = [("engine", "attack", "batch", "flops/cand", "bytes/cand",
             "peak", "args", "out", "fingerprint")]

    def _b(v) -> str:
        if v is None:
            return "-"
        for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                          ("KiB", 1 << 10)):
            if v >= div:
                return f"{v / div:.1f}{unit}"
        return str(int(v))

    for r in records:
        fpc = r.get("flops_per_candidate")
        batch = r.get("batch") or 0
        ba = r.get("bytes_accessed")
        bpc = (ba / batch) if ba and batch else None
        rows.append((
            str(r.get("engine")), str(r.get("attack")), str(batch),
            f"{fpc:.0f}" if fpc else "-",
            f"{bpc:.1f}" if bpc else "-",
            _b(r.get("total_peak_bytes")),
            _b(r.get("argument_bytes")), _b(r.get("output_bytes")),
            str(r.get("fingerprint"))[:12]))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(rows[0]))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(row, widths))
                     for row in rows)


__all__ = ["ANALYSIS_ENV", "DEFAULT", "ProgramRecord",
           "ProgramRegistry", "analysis_enabled", "analyze_pending",
           "analyzed_ops_per_candidate", "get_programs",
           "program_fingerprint", "register_program", "render_table"]
