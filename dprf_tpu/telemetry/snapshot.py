"""Periodic JSONL telemetry snapshots, written next to the session
journal.

Post-mortems of wedged runs (the round-5 tunnel wedge cost a full
round of measurements) need data, not guesswork: a background thread
appends one ``{"ts": ..., "elapsed_s": ..., "metrics": {...}}`` line
per interval, so the last line of the file is the fleet's state at the
moment the run died.  Append-only JSONL with the same torn-tail
tolerance as the session journal; snapshots are diagnostics, never
resume state.

The file is SIZE-CAPPED (``DPRF_TELEMETRY_MAX_BYTES``, default 16
MiB): when a write would exceed the cap the file rotates to a ``.1``
suffix (replacing any previous rotation) -- a serve session that runs
for weeks holds at most ~2x the cap on disk instead of growing without
limit.  The trace stream (telemetry/trace.py) rotates the same way.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.utils import env as envreg

#: suffix appended to a session journal path for its telemetry stream
TELEMETRY_SUFFIX = ".telemetry.jsonl"

#: default seconds between snapshot lines (override per-run with
#: DPRF_TELEMETRY_INTERVAL)
DEFAULT_INTERVAL_S = 30.0

#: size cap for the snapshot file before it rotates to `.1`
#: (DPRF_TELEMETRY_MAX_BYTES overrides; 0 disables the cap)
MAX_BYTES_ENV = "DPRF_TELEMETRY_MAX_BYTES"
DEFAULT_MAX_BYTES = 16 << 20


def cap_bytes(v: Optional[int]) -> Optional[int]:
    """Shared byte-cap semantics (telemetry snapshots AND the trace
    stream): 0 (or None) disables the cap."""
    return v if v and v > 0 else None


def snapshot_max_bytes(default: int = DEFAULT_MAX_BYTES) -> Optional[int]:
    return cap_bytes(envreg.get_int(MAX_BYTES_ENV, default))


def rotate_if_over(path: str, incoming: int,
                   max_bytes: Optional[int]) -> bool:
    """Move ``path`` aside to ``path + '.1'`` (replacing any previous
    rotation) when appending ``incoming`` bytes would push it over
    ``max_bytes``.  When the rotation target is unusable (unwritable
    dir, ``.1`` exists as a directory) the file is truncated in place
    instead -- a bounded file with lost history beats the unbounded
    growth the cap exists to prevent.  Returns True when the file was
    rotated or truncated."""
    if not max_bytes:
        return False
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size and size + incoming > max_bytes:
        try:
            os.replace(path, path + ".1")
            return True
        except OSError:
            try:
                open(path, "w").close()
                return True
            except OSError:
                return False
    return False


def telemetry_path(session_path: str) -> str:
    """Snapshot file location for a session journal path."""
    return session_path + TELEMETRY_SUFFIX


def snapshot_interval(default: float = DEFAULT_INTERVAL_S) -> float:
    return envreg.get_float("DPRF_TELEMETRY_INTERVAL", default)


class TelemetrySnapshotter:
    """Background writer: one registry snapshot line per interval plus
    a final line on stop() -- so a clean shutdown always journals the
    end-state even for runs shorter than one interval."""

    def __init__(self, path: str, registry: MetricsRegistry,
                 interval: float = DEFAULT_INTERVAL_S,
                 clock=time.time, max_bytes: Optional[int] = None):
        self.path = path
        self.registry = registry
        self.interval = max(0.25, float(interval))
        #: rotation cap; None = env default at write time
        self.max_bytes = max_bytes
        self._clock = clock
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def write_once(self) -> dict:
        line = {"ts": self._clock(),
                "elapsed_s": round(time.monotonic() - self._t0, 3),
                "metrics": self.registry.snapshot()}
        data = json.dumps(line, separators=(",", ":")) + "\n"
        with self._lock:
            cap = (snapshot_max_bytes() if self.max_bytes is None
                   else self.max_bytes)
            rotate_if_over(self.path, len(data), cap)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
        return line

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.write_once()
            except OSError:
                # a full/unwritable disk must not kill the job; the
                # next interval retries
                continue

    def start(self) -> "TelemetrySnapshotter":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.write_once()
        except OSError:
            pass


def load_snapshots(path: str) -> list:
    """Read a snapshot JSONL file back (torn tail lines skipped, like
    SessionJournal.load)."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out
