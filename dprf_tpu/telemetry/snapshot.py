"""Periodic JSONL telemetry snapshots, written next to the session
journal.

Post-mortems of wedged runs (the round-5 tunnel wedge cost a full
round of measurements) need data, not guesswork: a background thread
appends one ``{"ts": ..., "elapsed_s": ..., "metrics": {...}}`` line
per interval, so the last line of the file is the fleet's state at the
moment the run died.  Append-only JSONL with the same torn-tail
tolerance as the session journal; snapshots are diagnostics, never
resume state.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from dprf_tpu.telemetry.registry import MetricsRegistry

#: suffix appended to a session journal path for its telemetry stream
TELEMETRY_SUFFIX = ".telemetry.jsonl"

#: default seconds between snapshot lines (override per-run with
#: DPRF_TELEMETRY_INTERVAL)
DEFAULT_INTERVAL_S = 30.0


def telemetry_path(session_path: str) -> str:
    """Snapshot file location for a session journal path."""
    return session_path + TELEMETRY_SUFFIX


def snapshot_interval(default: float = DEFAULT_INTERVAL_S) -> float:
    try:
        return float(os.environ.get("DPRF_TELEMETRY_INTERVAL", default))
    except ValueError:
        return default


class TelemetrySnapshotter:
    """Background writer: one registry snapshot line per interval plus
    a final line on stop() -- so a clean shutdown always journals the
    end-state even for runs shorter than one interval."""

    def __init__(self, path: str, registry: MetricsRegistry,
                 interval: float = DEFAULT_INTERVAL_S,
                 clock=time.time):
        self.path = path
        self.registry = registry
        self.interval = max(0.25, float(interval))
        self._clock = clock
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def write_once(self) -> dict:
        line = {"ts": self._clock(),
                "elapsed_s": round(time.monotonic() - self._t0, 3),
                "metrics": self.registry.snapshot()}
        data = json.dumps(line, separators=(",", ":")) + "\n"
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
        return line

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.write_once()
            except OSError:
                # a full/unwritable disk must not kill the job; the
                # next interval retries
                continue

    def start(self) -> "TelemetrySnapshotter":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.write_once()
        except OSError:
            pass


def load_snapshots(path: str) -> list:
    """Read a snapshot JSONL file back (torn tail lines skipped, like
    SessionJournal.load)."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out
