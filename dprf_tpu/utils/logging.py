"""Structured logging for the coordinator (SURVEY.md section 5)."""

from __future__ import annotations

import sys
import time


class Log:
    def __init__(self, quiet: bool = False, stream=None):
        self.quiet = quiet
        # None = resolve sys.stderr at emit time (so redirection and
        # test capture see module-level loggers created at import)
        self._stream = stream
        self._t0 = time.monotonic()

    @property
    def stream(self):
        return self._stream or sys.stderr

    def _emit(self, level: str, msg: str, **kv) -> None:
        if self.quiet and level == "info":
            return
        extra = " ".join(f"{k}={v}" for k, v in kv.items())
        self.stream.write(
            f"[{time.monotonic() - self._t0:8.2f}s] {level:5s} {msg}"
            + (f" {extra}" if extra else "") + "\n")
        self.stream.flush()

    def info(self, msg: str, **kv) -> None:
        self._emit("info", msg, **kv)

    def warn(self, msg: str, **kv) -> None:
        self._emit("warn", msg, **kv)

    def error(self, msg: str, **kv) -> None:
        self._emit("error", msg, **kv)


#: module-level logger for library code with no Log threaded through
#: (engine factories, workers); the CLI's Log instances stay canonical.
DEFAULT = Log()
