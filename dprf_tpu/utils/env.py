"""The DPRF_* environment-knob registry: ONE declaration site.

Before this module, ~25 call sites read ``os.environ`` directly, each
re-stating the knob's name, default, and parse rule inline -- so a
renamed knob, a drifted default, or a knob documented in the README
but long deleted could not be caught anywhere.  Every ``DPRF_*`` knob
is now DECLARED here (name, default, type, docstring) and READ through
the typed getters below; ``dprf check`` (analysis/envknobs.py) forbids
raw ``os.environ``/``getenv`` reads of ``DPRF_*`` elsewhere, flags
getter calls naming undeclared knobs, asserts every declared knob has
a read site, and keeps the README knob table generated from (and in
sync with) this registry (``dprf check --write-env-docs``).

Parse rules (uniform across knobs -- the point of a registry):

  - int/float: junk values fall back to the declared default instead
    of crashing at import time;
  - bool: ``"0"`` is False; ``"1"``/``"true"``/``"yes"``/``"on"`` are
    True; anything else (including unset) is the declared default;
  - str/path: unset (or empty, for paths) means the declared default,
    which may be None ("resolve a fallback in code").

This module must stay dependency-free (stdlib only): it is imported
at module scope by the Pallas op modules and by tests/conftest.py
BEFORE jax initializes.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

#: README markers the generated knob table lives between
README_BEGIN = "<!-- dprf-env-knobs:begin (generated: dprf check --write-env-docs) -->"
README_END = "<!-- dprf-env-knobs:end -->"


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: object
    type: str            # "int" | "float" | "bool" | "str" | "path"
    doc: str
    #: secret values (tokens) are never echoed into docs or logs
    secret: bool = False


#: name -> Knob; populated by the _declare block below and NOWHERE else
KNOBS: dict = {}

_TYPES = ("int", "float", "bool", "str", "path")


def _declare(name: str, default, type: str, doc: str,
             secret: bool = False) -> None:
    if not name.startswith("DPRF_"):
        raise ValueError(f"knob {name!r} must be DPRF_-prefixed")
    if type not in _TYPES:
        raise ValueError(f"knob {name}: unknown type {type!r}")
    if name in KNOBS:
        raise ValueError(f"knob {name} declared twice")
    KNOBS[name] = Knob(name, default, type, doc, secret)


# ---------------------------------------------------------------------------
# the registry (alphabetical within each group)

# -- kernel / device tuning --------------------------------------------------
_declare("DPRF_7Z_DEVICE_DATA_CAP", 1024, "int",
         "Largest 7z payload (bytes) decrypted on-device; bigger "
         "archives fall back to the host AES tail.")
_declare("DPRF_BCRYPT_DISPATCH_S", 20.0, "float",
         "Per-dispatch wall budget (seconds) for the chunked bcrypt "
         "cost loop; keeps single dispatches inside the TPU tunnel's "
         "~60 s execution deadline.")
_declare("DPRF_BCRYPT_ROUTE", "auto", "str",
         "bcrypt routing: 'cpu' or 'device' forces a path, 'auto' "
         "measures on the TPU backend.")
_declare("DPRF_BCRYPT_SUBC", 64, "int",
         "bcrypt Pallas kernel: candidate lanes per grid cell.")
_declare("DPRF_KRB5AES_KERNEL", False, "bool",
         "Enable the krb5aes PBKDF2 device kernel on real hardware "
         "(default off until a recorded planted-crack run exists; "
         "interpret mode is always allowed).")
_declare("DPRF_KRB5_CHUNKS", 64, "int",
         "krb5 Pallas kernel: chunks per grid cell.")
_declare("DPRF_KRB5_SUBC", 32, "int",
         "krb5/pdf Pallas kernels: sublane count per chunk.")
_declare("DPRF_KRB5_UNROLL", False, "bool",
         "Unroll the krb5 kernel's inner rounds (compile-time/size "
         "trade; off by default).")
_declare("DPRF_PALLAS", "auto", "str",
         "Pallas kernel routing: '0' disables, '1' forces (interpret "
         "mode off-TPU, for tests), 'auto' uses kernels on real TPU "
         "only.")
_declare("DPRF_PALLAS_PROBE_FP", 1e-7, "float",
         "False-positive budget for the IN-KERNEL blocked probe "
         "bitmap (sharded/multi-target mask kernels).  Much tighter "
         "than DPRF_TARGETS_FP_BUDGET: kernel survivors drain through "
         "a tiny device-resident hit buffer per superstep window and "
         "cost one host oracle hash each, so false maybes must be "
         "rare per window, not merely per batch.")
_declare("DPRF_PALLAS_SUB", 128, "int",
         "Mask-attack Pallas kernels: sublanes per grid cell (tile = "
         "SUB*128 lanes).  Tuned on TPU v5 lite; tests pin 32.")
_declare("DPRF_PALLAS_SUBK", 32, "int",
         "Keccak Pallas kernel: sublanes per grid cell.")
_declare("DPRF_PDF_CHUNKS", 8, "int",
         "PDF Pallas kernel: chunks per grid cell (smaller default "
         "tile: the PDF body is ~21x heavier than krb5's).")
_declare("DPRF_PDF_K5_KERNEL", False, "bool",
         "Re-enable the 40-bit (key_len=5) PDF kernel on real "
         "hardware (gated off after a recorded Mosaic hang; "
         "interpret mode is always allowed).")
_declare("DPRF_RULES_SUBW", 8, "int",
         "Rules Pallas kernel: words per grid cell.")
_declare("DPRF_SCRYPT_MEM", 4 << 30, "int",
         "Device-memory budget (bytes) the scrypt engine sizes its "
         "V-array batches against.")
_declare("DPRF_SUPERSTEP", True, "bool",
         "Super-dispatch (multi-chunk scan loops fused into one "
         "dispatch); 0 falls back to per-batch dispatches.")
_declare("DPRF_SHARD_SUPER_CAP", 256, "int",
         "Batches fused into ONE sharded superstep dispatch "
         "(parallel/sharded.py; clamped to a power of two, and the "
         "int32 window budget still applies on top).  Each distinct "
         "power-of-two size compiles its own program, so the compile "
         "cache stays log-bounded.")

# -- runtime / distributed ---------------------------------------------------
_declare("DPRF_ASYNC_WARMUP", True, "bool",
         "Overlapped warmup: run the step compile on a background "
         "thread joined before the first dispatch; 0 restores "
         "synchronous warmup.")
_declare("DPRF_NATIVE", True, "bool",
         "Native (C) wordlist scanner; 0 forces the pure-Python "
         "fallback.")
_declare("DPRF_JOB_TTL_S", 86400.0, "float",
         "Age-based job GC: done/cancelled jobs older than this many "
         "seconds are reaped from the scheduler table (journaled as "
         "job_gc records) so long-lived fleets never wedge at the "
         "MAX_JOBS cap; 0 disables reaping.")
_declare("DPRF_ORDER_BLOCK_MIN", 1 << 16, "int",
         "Rank-ordered dispatch (--order markov): minimum suffix "
         "block size the order's prefix/suffix split preserves, so "
         "device batches and supersteps sweep contiguous index runs "
         "at least this long (bounds the steady-state H/s penalty of "
         "reordering).  An explicit per-job split pins the geometry "
         "instead; the wire job always carries the resolved split.")
_declare("DPRF_ORDER_PREFIX_MAX", 1 << 16, "int",
         "Rank-ordered dispatch: maximum number of rank-ordered "
         "prefix blocks, bounding how many index runs one rank "
         "interval can shatter into (journal snapshots, coverage "
         "digests, and resume all canonicalize over the index image "
         "of rank intervals).")
_declare("DPRF_PIPELINE_DEPTH", 2, "int",
         "Units submitted ahead of the oldest unresolved one in the "
         "local and remote worker loops (1 = serial fallback).")
_declare("DPRF_TOKEN", None, "str",
         "Shared secret for coordinator/worker mutual authentication "
         "(the --token flag wins when both are given).", secret=True)

# -- caches / tuning ---------------------------------------------------------
_declare("DPRF_COMPILE_CACHE", True, "bool",
         "Persistent XLA compile cache; 0 is the kill switch.")
_declare("DPRF_COMPILE_CACHE_DIR", None, "path",
         "Persistent XLA compile cache directory (default: "
         "~/.cache/dprf/xla, beside the tune cache).")
_declare("DPRF_COMPILE_COLD_FLOOR_S", 5.0, "float",
         "Wall-time floor (seconds) separating a served cache hit "
         "from a cold compile when the cache-entry delta is zero.")
_declare("DPRF_TUNE_DIR", None, "path",
         "Tuning-cache directory (default: the session journal's "
         "directory, else ~/.cache/dprf).")

# -- multi-target probe tables -----------------------------------------------
_declare("DPRF_TARGETS_FP_BUDGET", 1e-4, "float",
         "Bloom false-positive budget the probe-table builder sizes "
         "its blocked bitmap against (dprf_tpu/targets/probe.py); "
         "smaller budgets spend more HBM on prefilter bits in "
         "exchange for fewer exact-verify survivors.")
_declare("DPRF_TARGETS_HEADROOM_FRAC", 0.5, "float",
         "Fraction of the devstats free-HBM reading a probe table may "
         "occupy; a table over the budget degrades to the bloom-only "
         "host-verify layout instead of OOMing the device.")
_declare("DPRF_TARGETS_MAX_BYTES", 0, "int",
         "Hard byte cap for the device probe table (bloom bitmap + "
         "exact-verify digest buckets); 0 means devstats-derived "
         "headroom only.")
_declare("DPRF_TARGETS_PROBE_MIN", 4096, "int",
         "Target count at which mask workers switch from the "
         "replicated compare_multi table to the probe-table path "
         "(Bloom prefilter + bucketed exact verify).")
_declare("DPRF_TARGETS_SURVIVOR_CAP", 0, "int",
         "Fixed per-batch survivor-buffer length for prefilter "
         "survivors awaiting exact verify; 0 sizes it from the "
         "batch and the built table's false-positive estimate.")

# -- observability -----------------------------------------------------------
_declare("DPRF_COVERAGE", True, "bool",
         "Coverage audit plane (telemetry/coverage.py): per-job "
         "gap/overlap ledger, coverage gauges, and worker-side "
         "redrive/window notes; 0 is the kill switch (coverage "
         "digests still compute -- resume correctness must not "
         "depend on a telemetry knob).")
_declare("DPRF_COVERAGE_MAX_GAPS", 64, "int",
         "Cap on the gap intervals the coverage ledger, `dprf "
         "audit`, and the report's Coverage section enumerate (the "
         "totals stay exact; only the listed ranges truncate).")
_declare("DPRF_DEVSTATS_POLL_S", 15.0, "float",
         "Seconds between device-memory polls (telemetry/devstats.py: "
         "device.memory_stats() -> dprf_hbm_bytes_in_use/_limit/_peak "
         "gauges; backends without memory stats publish nothing); 0 "
         "disables the background poller.")
_declare("DPRF_PROGRAM_ANALYSIS", True, "bool",
         "XLA-derived program introspection (telemetry/programs.py): "
         "compiled steps register their cost_analysis/memory_analysis "
         "record, feeding the analyzed roofline and the program "
         "registry; 0 is the kill switch (hand roofline models only).")
_declare("DPRF_ALERT_EVAL_S", 5.0, "float",
         "Seconds between fleet-health/alert evaluation passes "
         "(worker state machine, straggler detection, per-job SLOs, "
         "alert rules -- telemetry/health.py + telemetry/alerts.py).")
_declare("DPRF_ALERT_RULES", None, "path",
         "JSON file of extra alert rules loaded next to the default "
         "pack (list of rule objects; see README 'Fleet health & "
         "alerts').  `dprf check` validates every referenced metric "
         "name against the declared dprf_* registry.")
_declare("DPRF_ALERTS_MAX_BYTES", 4 << 20, "int",
         "Size cap for the session alert-event JSONL "
         "(<session>.alerts.jsonl) before it rotates to '.1' (0 "
         "disables the cap).")
_declare("DPRF_HEARTBEAT_S", 10.0, "float",
         "Worker heartbeat cadence: a remote worker sends "
         "op_heartbeat when its main connection has been quiet this "
         "long (lease/complete traffic counts as contact); the "
         "coordinator's health state machine ages workers in "
         "multiples of this interval.  0 disables explicit "
         "heartbeats.")
_declare("DPRF_PERF_SAMPLE", 16, "int",
         "Per-phase sweep attribution cadence: every Nth unit runs a "
         "serial, synced probe recording phase spans and the "
         "dprf_phase_seconds histogram (telemetry/perf.py); 0 "
         "disables sampling.")
_declare("DPRF_JAX_PROFILE", None, "path",
         "Write a jax.profiler trace of the sweep loops to this "
         "directory (kernel-level drill-down beside the span "
         "timeline; routed through telemetry/profiler.py's "
         "single-flight capture guard).")
_declare("DPRF_AUTOPROFILE", True, "bool",
         "Alert-triggered kernel profiling: when a straggler or "
         "job_stalled alert FIRES, the coordinator's health tick "
         "requests one bounded jax.profiler capture window on the "
         "implicated worker (telemetry/profiler.py), rate-limited by "
         "DPRF_PROFILE_COOLDOWN_S; 0 disables auto-capture (manual "
         "`dprf profile --connect` still works).")
_declare("DPRF_PROFILE_COOLDOWN_S", 600.0, "float",
         "Minimum seconds between alert-triggered profile captures "
         "(global and per worker): a flapping fleet must not spend "
         "its cycles profiling itself.")
_declare("DPRF_PROFILE_SECONDS", 3.0, "float",
         "Default capture-window length for on-demand kernel "
         "profiles (`dprf profile --connect`, alert-triggered "
         "auto-capture): the worker keeps sweeping while the "
         "jax.profiler trace records, then stops and analyzes.")
_declare("DPRF_PROFILE_KEEP", 4, "int",
         "Capture dirs retained per profile root (oldest deleted "
         "first): bounded disk for repeated on-demand captures; 0 "
         "disables the reaper.")
_declare("DPRF_PROFILE_MAX_BYTES", 64 << 20, "int",
         "Per-capture raw-artifact size cap: a capture whose "
         "directory exceeds this drops its .xplane.pb bulk (the "
         "analyzed perfetto JSON is kept); 0 disables the cap.")
_declare("DPRF_PROFILE_DIR", None, "path",
         "Where a remote worker writes its on-demand capture dirs "
         "(raw traces stay on the worker host; the summary names "
         "the path).  Default: a per-process dir under the system "
         "temp root.")
_declare("DPRF_TELEMETRY_INTERVAL", 30.0, "float",
         "Seconds between telemetry snapshot lines.")
_declare("DPRF_TELEMETRY_MAX_BYTES", 16 << 20, "int",
         "Size cap for the telemetry snapshot JSONL before it "
         "rotates to '.1' (0 disables the cap).")
_declare("DPRF_TRACE", True, "bool",
         "Flight-recorder span recording; 0 is the kill switch.")
_declare("DPRF_TRACE_MAX_BYTES", 16 << 20, "int",
         "Size cap for the session trace JSONL before it rotates to "
         "'.1' (0 disables the cap).")

# -- test / bench harness ----------------------------------------------------
_declare("DPRF_BENCH_DIR", "/tmp", "path",
         "Working directory for the bench driver's session state "
         "(freshness ledger; read by the repo-root bench.py).")
_declare("DPRF_TIER_BUDGET_S", 300.0, "float",
         "Smoke-tier wall-time budget enforced by tests/conftest.py "
         "(0 disables the guard).")


# ---------------------------------------------------------------------------
# typed getters (the ONLY sanctioned DPRF_* read path)

_UNSET = object()


def knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"undeclared env knob {name!r}: declare it in "
            "dprf_tpu/utils/env.py (the registry is the single "
            "declaration site)") from None


def get_raw(name: str) -> Optional[str]:
    """The raw environment string, or None when unset.  For call
    sites that must distinguish "unset" from "set to the default"
    (e.g. an explicit env override beating a caller-passed default)."""
    knob(name)
    return os.environ.get(name)


def get_str(name: str, default=_UNSET) -> Optional[str]:
    k = knob(name)
    v = os.environ.get(name)
    if v is None or v == "":
        return k.default if default is _UNSET else default
    return v


def get_path(name: str, default=_UNSET) -> Optional[str]:
    return get_str(name, default)


def get_int(name: str, default=_UNSET) -> Optional[int]:
    k = knob(name)
    fallback = k.default if default is _UNSET else default
    v = os.environ.get(name)
    if v is None:
        return fallback
    try:
        return int(v)
    except ValueError:
        return fallback


def get_float(name: str, default=_UNSET) -> Optional[float]:
    k = knob(name)
    fallback = k.default if default is _UNSET else default
    v = os.environ.get(name)
    if v is None:
        return fallback
    try:
        return float(v)
    except ValueError:
        return fallback


def get_bool(name: str, default=_UNSET) -> bool:
    k = knob(name)
    fallback = k.default if default is _UNSET else default
    v = os.environ.get(name)
    if v is None:
        return fallback
    if v == "0":
        return False
    if v.lower() in ("1", "true", "yes", "on"):
        return True
    return fallback


# ---------------------------------------------------------------------------
# README table generation (dprf check --write-env-docs)

def _default_repr(k: Knob) -> str:
    if k.secret:
        return "(unset)"
    if k.default is None:
        return "(unset)"
    if k.type == "bool":
        return "1" if k.default else "0"
    return str(k.default)


def render_markdown_table() -> str:
    """The knob table, one row per declared knob, sorted by name --
    the exact text kept between the README markers."""
    lines = ["| Knob | Type | Default | What it does |",
             "| --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        doc = " ".join(k.doc.split())
        lines.append(f"| `{name}` | {k.type} | `{_default_repr(k)}` "
                     f"| {doc} |")
    return "\n".join(lines)


def readme_block() -> str:
    return f"{README_BEGIN}\n{render_markdown_table()}\n{README_END}"


def _split_readme(text: str):
    """(before, after) around the generated block, or None when the
    markers are missing/malformed."""
    b = text.find(README_BEGIN)
    e = text.find(README_END)
    if b < 0 or e < 0 or e < b:
        return None
    return text[:b], text[e + len(README_END):]


def readme_sync_error(readme_path: str) -> Optional[str]:
    """None when the README's generated knob table matches the
    registry; otherwise a one-line description of the drift."""
    try:
        with open(readme_path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        return f"README unreadable: {e}"
    parts = _split_readme(text)
    if parts is None:
        return ("README has no generated knob table (markers "
                f"{README_BEGIN!r}..{README_END!r}); run "
                "`dprf check --write-env-docs`")
    current = text[len(parts[0]):len(text) - len(parts[1])]
    if current != readme_block():
        return ("README knob table is out of sync with the registry; "
                "run `dprf check --write-env-docs`")
    return None


def write_readme_table(readme_path: str) -> bool:
    """Regenerate the README's knob table in place; returns True when
    the file changed.  Raises when the markers are missing -- the
    surrounding prose is hand-written and a blind append would bury
    the table somewhere arbitrary."""
    with open(readme_path, encoding="utf-8") as fh:
        text = fh.read()
    parts = _split_readme(text)
    if parts is None:
        raise ValueError(
            f"{readme_path}: knob-table markers not found; add\n"
            f"{README_BEGIN}\n{README_END}\nwhere the table belongs")
    new = parts[0] + readme_block() + parts[1]
    if new == text:
        return False
    with open(readme_path, "w", encoding="utf-8") as fh:
        fh.write(new)
    return True
