"""Host-visible device synchronization that survives broken
`block_until_ready` semantics.

Measured 2026-07-30 on the axon-tunneled TPU v5 lite backend:
`jax.block_until_ready` returns in ~0.1 ms for a dispatch whose compute
takes tens of milliseconds -- over this platform it no longer waits for
execution, only for enqueue.  Every timing loop that used it as its
sync point silently started measuring enqueue speed (a 0.5 s "timed
window" once enqueued 1,671 dispatches that then drained for 26 s),
and deadline-bounded protocols (ChunkedEks) would calibrate on enqueue
time and build oversized dispatches that trip the tunnel's ~60 s
execution deadline, faulting the backend.

`hard_sync` forces a real round trip by materializing one element of
each array leaf on the host (`jax.device_get` cannot return before the
producing computation and everything queued ahead of it on the device
stream has executed).  Cost: one tunnel RTT (~60 ms) per call (one
leaf is fetched; stream ordering covers the rest) -- always sync a
whole depth-window of dispatches, never each one.
"""

from __future__ import annotations

import numpy as np


def hard_sync(tree) -> None:
    """Block until every array in `tree` (any pytree) has actually been
    computed, by fetching one element of ONE leaf to the host.

    One fetch suffices: the device stream executes in order, so a
    gather enqueued after the producing dispatches can only yield its
    value once everything ahead of it has run -- including every other
    leaf of the same pytree.  The remaining leaves get a plain
    block_until_ready (free, and still correct on platforms where it
    does block)."""
    import jax

    fetched = False
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not fetched and leaf.size:
            if leaf.ndim == 0:
                np.asarray(jax.device_get(leaf))
            else:
                # one-element slice: the gather is a dispatch that
                # depends on `leaf`, so fetching it fences everything
                # queued before it without transferring the buffer
                np.asarray(jax.device_get(leaf.ravel()[0]))
            fetched = True
        elif isinstance(leaf, jax.Array):
            jax.block_until_ready(leaf)
        else:
            np.asarray(leaf)
