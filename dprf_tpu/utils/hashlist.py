"""Hashlist parsing: target file -> list of Target.

Lines are parsed by the selected engine (bare hex digests for fast
hashes, modular-crypt strings for bcrypt, 16800-format for PMKID).
Blank lines and '#' comments are skipped; duplicates are dropped
preserving first occurrence; malformed lines are collected, not fatal
-- a 1k-hash list with one bad line should still crack the other 999.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from dprf_tpu.engines.base import HashEngine, Target


@dataclasses.dataclass
class HashlistResult:
    targets: list
    skipped: list        # (line_number, text, error)
    duplicates: int


def parse_lines(engine: HashEngine, lines: Sequence[str]) -> HashlistResult:
    targets: list[Target] = []
    seen: set[str] = set()
    skipped, dups = [], 0
    for no, raw in enumerate(lines, 1):
        text = raw.strip()
        if not text or text.startswith("#"):
            continue
        try:
            t = engine.parse_target(text)
        except ValueError as e:
            skipped.append((no, text, str(e)))
            continue
        if t.raw in seen:
            dups += 1
            continue
        seen.add(t.raw)
        targets.append(t)
    return HashlistResult(targets=targets, skipped=skipped, duplicates=dups)


def load_hashlist(engine: HashEngine, path: str) -> HashlistResult:
    with open(path, encoding="utf-8", errors="replace") as fh:
        return parse_lines(engine, fh.readlines())
