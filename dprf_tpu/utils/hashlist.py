"""Hashlist parsing: target file -> list of Target.

Lines are parsed by the selected engine (bare hex digests for fast
hashes, modular-crypt strings for bcrypt, 16800-format for PMKID).
Blank lines and '#' comments are skipped; duplicates are dropped
preserving first occurrence; malformed lines are collected, not fatal
-- a 1k-hash list with one bad line should still crack the other 999.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from dprf_tpu.engines.base import HashEngine, Target


@dataclasses.dataclass
class HashlistResult:
    targets: list
    skipped: list        # (line_number, text, error)
    duplicates: int


def _dedup_key(t: Target):
    """Duplicates are duplicate TARGETS, not duplicate lines: the same
    digest written twice (e.g. in different hex case) is one target, or
    the engines' digest->index maps would be ambiguous and one copy
    could never be reported cracked.  Salted targets are distinct
    unless digest AND params match."""
    params = tuple(sorted((t.params or {}).items()))
    return (t.digest, params)


def parse_lines(engine: HashEngine, lines: Sequence[str]) -> HashlistResult:
    targets: list[Target] = []
    seen: set = set()
    skipped, dups = [], 0
    for no, raw in enumerate(lines, 1):
        text = raw.strip()
        if not text or text.startswith("#"):
            continue
        try:
            t = engine.parse_target(text)
        except ValueError as e:
            skipped.append((no, text, str(e)))
            continue
        key = _dedup_key(t)
        if key in seen:
            dups += 1
            continue
        seen.add(key)
        targets.append(t)
    return HashlistResult(targets=targets, skipped=skipped, duplicates=dups)


def load_hashlist(engine: HashEngine, path: str) -> HashlistResult:
    with open(path, encoding="utf-8", errors="replace") as fh:
        return parse_lines(engine, fh.readlines())
