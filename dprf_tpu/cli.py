"""The dprf command-line interface.

Flag surface pinned to BASELINE.json's north star: ``dprf crack
--engine=<algo> --device=tpu -a mask <mask> <hashfile>`` -- jobs that
ran against the reference's CPU engines select the TPU backend with
--device and otherwise run unchanged.  Subcommands: crack, bench,
engines, keyspace.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from dprf_tpu import engine_names, get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.potfile import Potfile
from dprf_tpu.runtime.session import SessionJournal, job_fingerprint
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.utils.hashlist import load_hashlist
from dprf_tpu.utils.logging import Log

_DEVICE_ALIASES = {"tpu": "jax", "jax": "jax", "cpu": "cpu"}


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dprf", description="TPU-native distributed password recovery")
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("crack", help="run a recovery job")
    c.add_argument("attack_arg", help="mask string (mask attack) or "
                   "wordlist path (wordlist attack)")
    c.add_argument("hashfile", help="file of target hashes")
    c.add_argument("--engine", "-m", required=True,
                   help="hash algorithm (see `dprf engines`)")
    c.add_argument("--device", default="tpu", choices=sorted(_DEVICE_ALIASES),
                   help="execution backend (tpu == the JAX device path)")
    c.add_argument("-a", "--attack", default="mask",
                   choices=["mask", "wordlist"])
    c.add_argument("--rules", default=None,
                   help="rule set for wordlist attacks (e.g. best64)")
    for i in range(1, 5):
        c.add_argument(f"--custom{i}", default=None,
                       help=f"custom charset ?{i}")
    c.add_argument("--session", default=None,
                   help="session journal path (enables checkpoint/resume)")
    c.add_argument("--restore", action="store_true",
                   help="resume from --session journal")
    c.add_argument("--potfile", default="dprf.potfile")
    c.add_argument("--no-potfile", action="store_true")
    c.add_argument("--unit-size", type=int, default=1 << 22)
    c.add_argument("--batch", type=int, default=1 << 18)
    c.add_argument("--hit-cap", type=int, default=64)
    c.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the run to DIR "
                   "(view with tensorboard)")
    c.add_argument("--quiet", "-q", action="store_true")

    b = sub.add_parser("bench", help="measure engine throughput")
    b.add_argument("--engine", "-m", default="md5")
    b.add_argument("--device", default="tpu", choices=sorted(_DEVICE_ALIASES))
    b.add_argument("--mask", default="?a?a?a?a?a?a?a?a")
    b.add_argument("--batch", type=int, default=1 << 20)
    b.add_argument("--seconds", type=float, default=5.0)
    b.add_argument("--impl", default="auto", choices=["auto", "xla", "pallas"],
                   help="force the generic XLA pipeline or the Pallas "
                   "kernel (md5) instead of automatic selection")
    b.add_argument("--profile", default=None, metavar="DIR")
    b.add_argument("--quiet", "-q", action="store_true")

    e = sub.add_parser("engines", help="list available engines")
    e.add_argument("--device", default=None)

    k = sub.add_parser("keyspace", help="print keyspace size of a mask")
    k.add_argument("mask")
    for i in range(1, 5):
        k.add_argument(f"--custom{i}", default=None)
    return p


def _customs(args) -> dict:
    out = {}
    for i in range(1, 5):
        v = getattr(args, f"custom{i}", None)
        if v is not None:
            out[i] = v.encode("latin-1")
    return out


def cmd_crack(args, log: Log) -> int:
    device = _DEVICE_ALIASES[args.device]
    engine = get_engine(args.engine, device="cpu")   # parser/oracle always CPU
    hl = load_hashlist(engine, args.hashfile)
    for no, text, err in hl.skipped:
        log.warn("skipping hashlist line", line=no, error=err)
    if not hl.targets:
        log.error("no valid targets in hashlist")
        return 2
    log.info("loaded targets", count=len(hl.targets),
             duplicates=hl.duplicates, engine=engine.name)

    unit_size = args.unit_size
    if args.attack == "mask":
        customs = _customs(args)
        gen = MaskGenerator(args.attack_arg, custom=customs or None)
        log.info("keyspace", mask=args.attack_arg, size=gen.keyspace)
        # Custom charsets change which candidate an index decodes to, so
        # they are part of the job identity.
        attack_desc = f"mask:{args.attack_arg}" + "".join(
            f":{i}={customs[i].hex()}" for i in sorted(customs))
    else:
        import hashlib as _hl

        from dprf_tpu.generators.wordlist import WordlistRulesGenerator
        from dprf_tpu.rules import resolve_rules_path

        # The 55-byte single-block limit only binds on the device packer;
        # a CPU-oracle job (no device wordlist worker) keeps the engine's
        # own limit (e.g. 63-byte WPA passphrases).
        dev_capable = False
        if device == "jax":
            try:
                dev_capable = hasattr(get_engine(args.engine, device="jax"),
                                      "make_wordlist_worker")
            except KeyError:
                pass
        max_len = (min(55, engine.max_candidate_len) if dev_capable
                   else engine.max_candidate_len)
        rules_id = "none"
        rules_spec = None
        if args.rules:
            rules_spec = args.rules
            with open(resolve_rules_path(args.rules), "rb") as fh:
                rules_id = _hl.sha256(fh.read()).hexdigest()[:16]
        # from_files prefers the native (C++) loader: packed tables are
        # built at memory bandwidth, never as a Python word list.
        gen = WordlistRulesGenerator.from_files(args.attack_arg, rules_spec,
                                                max_len=max_len)
        if gen.n_skipped_long:
            log.warn("skipped overlong words", count=gen.n_skipped_long,
                     max_len=max_len)
        log.info("keyspace", words=gen.n_words, rules=gen.n_rules,
                 size=gen.keyspace)
        # Wordlist contents decide what an index decodes to: fingerprint
        # the word content, not the file path.
        attack_desc = (f"wordlist:{gen.content_id()}"
                       f":rules={rules_id}")
        # Units aligned to whole words: no candidate is ever rehashed at
        # unit boundaries on the device path.
        unit_size = max(gen.n_rules,
                        (unit_size // gen.n_rules) * gen.n_rules)

    spec = JobSpec(engine=engine.name, device=device, attack=args.attack,
                   attack_arg=args.attack_arg, keyspace=gen.keyspace,
                   fingerprint=job_fingerprint(
                       engine.name, attack_desc, gen.keyspace,
                       [t.digest for t in hl.targets]))

    # Session / resume
    session = None
    completed: list = []
    restored_hits: list = []
    if args.session:
        session = SessionJournal(args.session)
        prior = SessionJournal.load(args.session)
        if args.restore:
            if prior is None:
                log.warn("no session to restore; starting fresh")
            elif prior.spec.get("fingerprint") != spec.fingerprint:
                log.error("session file belongs to a different job",
                          theirs=prior.spec.get("fingerprint"),
                          ours=spec.fingerprint)
                return 2
            else:
                completed = prior.completed
                restored_hits = prior.hits
                done = sum(e - s for s, e in completed)
                log.info("resuming session", covered=done,
                         hits=len(restored_hits))
        elif prior is not None:
            log.error("session file exists; pass --restore to resume "
                      "or remove it", path=args.session)
            return 2

    if completed:
        dispatcher = Dispatcher.from_completed(
            gen.keyspace, unit_size, completed)
    else:
        dispatcher = Dispatcher(gen.keyspace, unit_size)

    # Worker selection: each device engine builds its own fused worker
    # (make_mask_worker), so salted pipelines (PMKID, bcrypt) plug in
    # the same way the fast unsalted ones do.
    worker = None
    maker_name = ("make_mask_worker" if args.attack == "mask"
                  else "make_wordlist_worker")
    if device == "jax":
        try:
            dev_engine = get_engine(args.engine, device="jax")
        except KeyError:
            dev_engine = None
        if dev_engine is None or not hasattr(dev_engine, maker_name):
            log.warn("no jax engine for algorithm/attack; using cpu oracle",
                     engine=args.engine)
        else:
            worker = getattr(dev_engine, maker_name)(
                gen, hl.targets, batch=args.batch,
                hit_capacity=args.hit_cap, oracle=engine)
    if worker is None:
        worker = CpuWorker(engine, gen, hl.targets)

    potfile = None if args.no_potfile else Potfile(args.potfile)

    def progress(done, total, nfound, rate):
        log.info("progress", pct=f"{100.0 * done / total:.2f}%",
                 found=f"{nfound}/{len(hl.targets)}",
                 rate=f"{rate:,.0f}/s")

    coord = Coordinator(spec, hl.targets, dispatcher, worker,
                        session=session, potfile=potfile,
                        progress_cb=None if args.quiet else progress)
    coord.preload_found()
    coord.restore_hits(restored_hits)
    if coord.found:
        log.info("pre-cracked targets", count=len(coord.found))

    if args.profile:
        # jax.profiler.trace captures device + host timelines for every
        # step the coordinator drives (SURVEY.md section 5: tracing).
        import jax
        with jax.profiler.trace(args.profile):
            result = coord.run()
        log.info("profile written", dir=args.profile)
    else:
        result = coord.run()

    for ti, plain in sorted(result.found.items()):
        from dprf_tpu.runtime.potfile import encode_plain
        print(f"{hl.targets[ti].raw}:{encode_plain(plain)}")
    log.info("job finished",
             found=f"{len(result.found)}/{len(hl.targets)}",
             tested=result.tested, elapsed=f"{result.elapsed:.2f}s",
             rate=f"{result.rate:,.0f}/s",
             exhausted=result.exhausted)
    return 0 if result.found else 1


def cmd_bench(args, log: Log) -> int:
    import contextlib
    import json
    from dprf_tpu.bench import run_bench
    ctx = contextlib.nullcontext()
    if args.profile:
        import jax
        ctx = jax.profiler.trace(args.profile)
    with ctx:
        res = run_bench(engine=args.engine,
                        device=_DEVICE_ALIASES[args.device],
                        mask=args.mask, batch=args.batch,
                        seconds=args.seconds, impl=args.impl, log=log)
    print(json.dumps(res))
    return 0


def cmd_engines(args, log: Log) -> int:
    devices = [args.device] if args.device else ["cpu", "jax"]
    for dev in devices:
        try:
            names = engine_names(dev)
        except KeyError:
            names = []
        print(f"{dev}: {', '.join(names)}")
    return 0


def cmd_keyspace(args, log: Log) -> int:
    gen = MaskGenerator(args.mask, custom=_customs(args) or None)
    print(gen.keyspace)
    return 0


def main(argv: Optional[list] = None) -> int:
    args = _build_parser().parse_args(argv)
    log = Log(quiet=getattr(args, "quiet", False))
    try:
        if args.command == "crack":
            return cmd_crack(args, log)
        if args.command == "bench":
            return cmd_bench(args, log)
        if args.command == "engines":
            return cmd_engines(args, log)
        if args.command == "keyspace":
            return cmd_keyspace(args, log)
    except (ValueError, KeyError, OSError) as e:
        log.error(str(e))
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
