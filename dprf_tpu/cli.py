"""The dprf command-line interface.

Flag surface pinned to BASELINE.json's north star: ``dprf crack
--engine=<algo> --device=tpu -a mask <mask> <hashfile>`` -- jobs that
ran against the reference's CPU engines select the TPU backend with
--device and otherwise run unchanged.

Subcommands: crack (local job), serve + worker (distributed job:
coordinator RPC + remote workers, runtime/rpc.py), bench, prewarm
(ahead-of-time compile-cache population), retry-parked (admin op on a
running coordinator), top (live fleet view from the flight recorder),
health + alerts (fleet health plane: worker state machine, per-job
SLOs, alert engine -- ISSUE 10), token (mint owner-scoped tenant
tokens), trace export (session span stream -> Perfetto), engines,
keyspace.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from dprf_tpu import engine_names, get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.potfile import Potfile
from dprf_tpu.runtime.rpc import RpcError
from dprf_tpu.runtime.session import SessionJournal, job_fingerprint
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.utils import env as envreg
from dprf_tpu.utils.hashlist import load_hashlist
from dprf_tpu.utils.logging import Log

_DEVICE_ALIASES = {"tpu": "jax", "jax": "jax", "cpu": "cpu"}

#: the pre-tuning hard-coded device batch; "auto" falls back here when
#: neither the session journal nor the tune cache has an entry
DEFAULT_BATCH = 1 << 18


def _batch_size(s: str):
    """--batch value: an integer, or "auto" (resolve from the tuning
    subsystem: session journal > persistent cache > DEFAULT_BATCH)."""
    if s == "auto":
        return s
    return int(s)


def _add_job_args(c, with_hashfile: bool = True) -> None:
    """Attack/job flags shared by crack and serve."""
    c.add_argument("attack_arg", help="mask string (mask attack) or "
                   "wordlist path (wordlist attack)")
    if with_hashfile:
        c.add_argument("hashfile", nargs="?", default=None,
                       help="file of target hashes (or use "
                       "--targets-file)")
    c.add_argument("--targets-file", default=None, metavar="FILE",
                   help="bulk target list (hashcat-style hash[:salt] "
                   "lines; deduped, malformed lines reported; >= "
                   "DPRF_TARGETS_PROBE_MIN targets use the "
                   "device-resident probe table)")
    c.add_argument("--engine", "-m", required=True,
                   help="hash algorithm (see `dprf engines`)")
    c.add_argument("--device", default="tpu", choices=sorted(_DEVICE_ALIASES),
                   help="execution backend (tpu == the JAX device path)")
    c.add_argument("-a", "--attack", default="mask",
                   choices=["mask", "wordlist", "combinator",
                            "hybrid-wm", "hybrid-mw"],
                   help="mask, wordlist(+rules), combinator "
                   "('left.txt,right.txt'), or hybrid word+mask / "
                   "mask+word ('words.txt,?d?d' / '?d?d,words.txt')")
    c.add_argument("--rules", default=None,
                   help="rule set for wordlist attacks (e.g. best64)")
    c.add_argument("--markov", default=None, metavar="STATS",
                   help="mask attacks: visit each position's charset in "
                   "trained-frequency order (stats from `dprf markov`)")
    c.add_argument("--order", default="index",
                   choices=["index", "markov"],
                   help="candidate enumeration order: 'index' sweeps "
                   "the keyspace linearly; 'markov' (requires "
                   "--markov) dispatches probability-ranked units "
                   "first to minimize time-to-first-hit (DPRF_ORDER_* "
                   "knobs shape the rank blocks)")
    for i in range(1, 5):
        c.add_argument(f"--custom{i}", default=None,
                       help=f"custom charset ?{i}")
    c.add_argument("--session", default=None,
                   help="session journal path (enables checkpoint/resume)")
    c.add_argument("--restore", action="store_true",
                   help="resume from --session journal")
    c.add_argument("--potfile", default="dprf.potfile")
    c.add_argument("--no-potfile", action="store_true")
    c.add_argument("--unit-size", type=int, default=1 << 22)
    c.add_argument("--unit-seconds", type=float, default=20.0,
                   metavar="S",
                   help="adaptive unit sizing: grow/shrink each "
                   "worker's WorkUnits toward S seconds apiece from "
                   "its measured throughput (0 pins --unit-size)")
    c.add_argument("--batch", type=_batch_size, default="auto",
                   help="device batch size, or 'auto' (default): use "
                   "the tuning cache written by `dprf tune`, falling "
                   f"back to {DEFAULT_BATCH}")
    c.add_argument("--hit-cap", type=int, default=64)
    c.add_argument("--skip", type=int, default=0, metavar="N",
                   help="skip the first N keyspace indices")
    c.add_argument("--limit", type=int, default=None, metavar="N",
                   help="restrict the sweep to N indices after --skip")
    c.add_argument("--quiet", "-q", action="store_true")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dprf", description="TPU-native distributed password recovery")
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("crack", help="run a recovery job locally")
    _add_job_args(c)
    c.add_argument("--devices", type=int, default=1,
                   help="shard the job over N chips via the mesh "
                   "(any engine; with --multihost, N counts GLOBAL "
                   "devices across all hosts)")
    c.add_argument("--multihost", action="store_true",
                   help="join a cross-host device mesh via "
                   "jax.distributed (run the SAME command on every "
                   "host of the slice; TPU pods auto-detect the "
                   "coordinator)")
    c.add_argument("--coordinator-address", default=None, metavar="H:P",
                   help="multihost coordinator address (auto-detected "
                   "on TPU pods)")
    c.add_argument("--num-processes", type=int, default=None)
    c.add_argument("--process-id", type=int, default=None)
    c.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the run to DIR "
                   "(view with tensorboard)")
    c.add_argument("--increment", action="store_true",
                   help="mask attacks: sweep prefix lengths from "
                   "--increment-min to --increment-max (default: the "
                   "full mask length)")
    c.add_argument("--increment-min", type=int, default=1, metavar="N")
    c.add_argument("--increment-max", type=int, default=None, metavar="N")

    s = sub.add_parser("serve", help="run the coordinator for a "
                       "distributed job (workers connect with "
                       "`dprf worker`)")
    _add_job_args(s)
    s.add_argument("--devices", type=int, default=1,
                   help="ask each worker to shard the job's units over "
                   "N of its local chips (the wire job carries the "
                   "request; a worker's own --devices overrides, and "
                   "hosts with fewer chips degrade to what they have)")
    s.add_argument("--bind", default="127.0.0.1:41715",
                   metavar="HOST:PORT",
                   help="listen address; the protocol is unauthenticated "
                   "-- bind only to trusted networks")
    s.add_argument("--lease-timeout", type=float, default=300.0,
                   help="seconds before a silent worker's unit is "
                   "reissued")
    s.add_argument("--token", default=None,
                   help="shared secret workers must prove on connect "
                   "(default: $DPRF_TOKEN; unset = unauthenticated)")
    s.add_argument("--owner-quota", action="append", default=None,
                   metavar="OWNER=N",
                   help="per-owner AGGREGATE sweep quota (repeatable): "
                   "cap the keyspace indices all of OWNER's jobs may "
                   "sweep combined, enforced on submit and on lease")

    w = sub.add_parser("worker", help="process WorkUnits for a "
                       "`dprf serve` coordinator")
    w.add_argument("--connect", required=True, metavar="HOST:PORT")
    w.add_argument("--device", default="tpu",
                   choices=sorted(_DEVICE_ALIASES))
    w.add_argument("--devices", type=int, default=None,
                   help="shard each unit over N local chips (overrides "
                   "a job's own devices request, including an explicit "
                   "1 to pin this worker to a single chip; default: "
                   "honor the job)")
    w.add_argument("--id", default=None, help="worker id for the lease "
                   "ledger (default: host:pid)")
    w.add_argument("--batch", type=int, default=None,
                   help="override the job's device batch size")
    w.add_argument("--pipeline-depth", type=int, default=None,
                   metavar="N",
                   help="units leased ahead and submitted before the "
                   "oldest one resolves (default: $DPRF_PIPELINE_DEPTH "
                   "or 2; 1 = the serial lease->process->complete "
                   "loop)")
    w.add_argument("--token", default=None,
                   help="shared secret for an authenticated coordinator "
                   "(default: $DPRF_TOKEN)")
    w.add_argument("--quiet", "-q", action="store_true")

    b = sub.add_parser("bench", help="measure engine throughput")
    b.add_argument("--engine", "-m", default="md5")
    b.add_argument("--device", default="tpu", choices=sorted(_DEVICE_ALIASES))
    b.add_argument("--mask", default="?a?a?a?a?a?a?a?a")
    b.add_argument("--batch", type=_batch_size, default="auto",
                   help="batch size, or 'auto' (default): tuned batch "
                   "from the cache when one matches, else 1<<20")
    b.add_argument("--seconds", type=float, default=5.0)
    b.add_argument("--impl", default="auto", choices=["auto", "xla", "pallas"],
                   help="force the generic XLA pipeline or the Pallas "
                   "kernel instead of automatic selection")
    b.add_argument("--config", type=int, default=None, metavar="N",
                   help="measure acceptance workload N (1-5, see "
                   "BASELINE.md) through the real worker path instead "
                   "of the raw engine loop")
    b.add_argument("--devices", type=int, default=1, metavar="N",
                   help="scaling mode: measure the sharded step at 1 "
                   "and N chips and report per-chip rate + efficiency")
    b.add_argument("--inner", type=int, default=8, metavar="K",
                   help="scaling mode: batches fused per superstep "
                   "dispatch (1 = the per-batch compat program)")
    b.add_argument("--ablate", action="store_true",
                   help="scaling mode: also time a per-batch (inner=1) "
                   "mesh window and report superstep_speedup")
    b.add_argument("--bcrypt-cost", type=int, default=12,
                   help="cost for --config 4 (lower it off-TPU)")
    b.add_argument("--targets-sweep", action="store_true",
                   help="target-set-size sweep: measure the probe-"
                   "table step's per-candidate cost across growing "
                   "target counts (--targets-sizes) and report the "
                   "flatness ratio; --gate compares against the "
                   "TARGETS_r*.json trajectory")
    b.add_argument("--targets-sizes", default="1000,10000,100000,1000000",
                   metavar="N,N,...", help="comma-separated target "
                   "counts for --targets-sweep (10^7-ready on real "
                   "silicon; the CPU backend default caps at 10^6)")
    b.add_argument("--ttfh", action="store_true",
                   help="time-to-first-hit mode: crack planted "
                   "passwords under rank-ordered (--order markov) vs "
                   "linear dispatch and report the candidates-to-"
                   "first-hit speedup plus the steady-state H/s "
                   "penalty; --gate compares against the "
                   "TTFH_r*.json trajectory")
    b.add_argument("--plants", type=int, default=4, metavar="N",
                   help="--ttfh: planted passwords per run")
    b.add_argument("--unit-strides", type=int, default=1, metavar="K",
                   help="--config mode: device batches per WorkUnit; "
                   "real Dispatcher units span many batches, and over "
                   "a high-latency link a 1-stride unit measures the "
                   "round trip, not the chip")
    b.add_argument("--profile", default=None, metavar="DIR")
    b.add_argument("--gate", action="store_true",
                   help="regression sentinel: gate this measurement "
                   "against the committed BENCH_r*.json baseline "
                   "window (median of the last K same-device "
                   "records +/- their observed spread); the result "
                   "JSON gains a 'gate' verdict and a regression "
                   "exits non-zero")
    b.add_argument("--gate-dry", action="store_true",
                   help="no measurement: gate the NEWEST committed "
                   "BENCH record against the window before it (the "
                   "CI mode -- the trajectory audits itself)")
    b.add_argument("--baseline-dir", default=None, metavar="DIR",
                   help="directory holding BENCH_r*.json (default: "
                   "this repo's root)")
    b.add_argument("--gate-window", type=int, default=5, metavar="K",
                   help="baseline records considered by --gate")
    b.add_argument("--quiet", "-q", action="store_true")

    tn = sub.add_parser("tune", help="autotune the device batch size "
                        "for an engine and record it in the tuning "
                        "cache (consumed by `--batch auto` and bench)")
    tn.add_argument("--engine", "-m", default=None,
                    help="engine to tune (required unless --all)")
    tn.add_argument("--all", action="store_true",
                    help="sweep EVERY registered device engine (mask "
                    "attack) to pre-populate the tuning cache for a "
                    "fleet image; engines whose targets need real "
                    "salts/params are reported as skipped (tune them "
                    "individually with --hashfile).  Analyzed program "
                    "costs (telemetry/programs.py) are recorded as a "
                    "side effect of every rung")
    tn.add_argument("--device", default="tpu",
                    choices=sorted(_DEVICE_ALIASES))
    tn.add_argument("--mask", default="?a?a?a?a?a?a?a?a",
                    help="mask shaping the candidates swept during "
                    "the probe")
    tn.add_argument("--hashfile", default=None,
                    help="tune against real targets (required for "
                    "salted engines; default: one synthetic "
                    "unmatchable digest)")
    tn.add_argument("--seconds", type=float, default=2.0,
                    help="steady-state probe window per ladder rung")
    tn.add_argument("--min-batch", type=int, default=1 << 14)
    tn.add_argument("--max-batch", type=int, default=1 << 22)
    tn.add_argument("--ladder-factor", type=int, default=4,
                    help="geometric step between ladder rungs")
    tn.add_argument("--compile-budget", type=float, default=120.0,
                    metavar="S", help="skip rungs whose warmup/compile "
                    "exceeds S seconds (and stop climbing)")
    tn.add_argument("--hit-cap", type=int, default=64)
    tn.add_argument("--attack", default="mask",
                    choices=("mask", "wordlist", "combinator"),
                    help="attack shape to tune; wordlist/combinator "
                    "probe over a synthetic in-memory word source "
                    "(bench config 3's trick), so the sweep measures "
                    "the device pipeline, never file I/O")
    tn.add_argument("--rungs", default="batch",
                    choices=("batch", "inner", "sub"),
                    help="quantity to sweep: the device batch ladder "
                    "(default), the multi-batch superstep `inner` "
                    "fusion window, or the Pallas kernel tile size "
                    "(sublanes per tile)")
    tn.add_argument("--rules", default="best64",
                    help="builtin rule set shaping --attack wordlist "
                    "probes")
    tn.add_argument("--words", type=int, default=1 << 14,
                    help="synthetic word-source size for "
                    "wordlist/combinator tuning probes")
    tn.add_argument("--tune-dir", default=None,
                    help="cache directory (default: $DPRF_TUNE_DIR or "
                    "~/.cache/dprf)")
    tn.add_argument("--quiet", "-q", action="store_true")

    pw = sub.add_parser("prewarm", help="populate the persistent XLA "
                        "compile cache ahead of time (fleet images: a "
                        "worker then starts hashing in seconds, not "
                        "minutes)")
    pw.add_argument("--engines", default=None, metavar="E1,E2|all",
                    help="engines to prewarm ('all' = every registered "
                    "device engine; default: the shapes recorded in "
                    "the tuning cache)")
    pw.add_argument("--attacks", default="mask", metavar="A1,A2",
                    help="attack shapes per engine (mask, wordlist, "
                    "combinator, hybrid-wm, hybrid-mw)")
    pw.add_argument("--mask", default="?a?a?a?a?a?a?a?a",
                    help="mask shaping the prewarmed mask step (and "
                    "the mask side of hybrid shapes)")
    pw.add_argument("--rules", default=None,
                    help="rule set for wordlist-shape prewarm")
    pw.add_argument("--wordlist", default=None, metavar="FILE",
                    help="wordlist/hybrid-shape prewarm: the job's "
                    "REAL wordlist (the compiled program embeds the "
                    "packed word table; a stand-in would cache a "
                    "program no job runs)")
    pw.add_argument("--combinator", default=None, metavar="LEFT,RIGHT",
                    help="combinator-shape prewarm: the job's REAL "
                    "left,right word files (both tables are embedded)")
    pw.add_argument("--devices", type=int, default=1, metavar="N",
                    help="prewarm the SHARDED (multi-chip mesh) step "
                    "shape at N devices instead of the single-device "
                    "one; skipped gracefully on hosts with fewer")
    pw.add_argument("--batch", type=_batch_size, default="auto",
                    help="step batch, or 'auto' (default): each "
                    "engine's tuned batch from the tuning cache, "
                    f"falling back to {DEFAULT_BATCH}")
    pw.add_argument("--hit-cap", type=int, default=64)
    pw.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="compile specs in N parallel child processes")
    pw.add_argument("--cache-dir", default=None,
                    help="compile cache directory (default: "
                    "$DPRF_COMPILE_CACHE_DIR or ~/.cache/dprf/xla)")
    pw.add_argument("--spec-json", default=None, help=argparse.SUPPRESS)
    pw.add_argument("--quiet", "-q", action="store_true")

    jb = sub.add_parser("jobs", help="multi-tenant job admin against a "
                        "RUNNING coordinator: submit new jobs into the "
                        "fair-share scheduler, list/inspect/cancel/"
                        "pause them, pull per-job hits")
    jsub = jb.add_subparsers(dest="jobs_cmd", required=True)

    def _jobs_client_args(c) -> None:
        c.add_argument("--connect", required=True, metavar="HOST:PORT",
                       help="the coordinator's RPC address "
                       "(`dprf serve --bind`)")
        c.add_argument("--token", default=None,
                       help="shared secret for an authenticated "
                       "coordinator (default: $DPRF_TOKEN)")
        c.add_argument("--timeout", type=float, default=30.0)
        c.add_argument("--quiet", "-q", action="store_true")

    jsb = jsub.add_parser("submit", help="submit a new job to the "
                          "scheduler; target lines are shipped, "
                          "wordlist/rules paths must exist on the "
                          "COORDINATOR host (it rebuilds and "
                          "fingerprints the job before admitting it)")
    jsb.add_argument("attack_arg", help="mask string or wordlist path")
    jsb.add_argument("hashfile", nargs="?", default=None,
                     help="file of target hashes (or use "
                     "--targets-file)")
    jsb.add_argument("--targets-file", default=None, metavar="FILE",
                     help="bulk target list (hashcat-style hash[:salt] "
                     "lines); parsed and deduped locally, shipped with "
                     "a fingerprint the coordinator's rebuild must "
                     "match")
    jsb.add_argument("--engine", "-m", required=True)
    jsb.add_argument("-a", "--attack", default="mask",
                     choices=["mask", "wordlist", "combinator",
                              "hybrid-wm", "hybrid-mw"])
    jsb.add_argument("--rules", default=None)
    jsb.add_argument("--markov", default=None, metavar="STATS")
    jsb.add_argument("--order", default="index",
                     choices=["index", "markov"],
                     help="candidate dispatch order: 'markov' leases "
                     "probability-ranked spans first (needs --markov "
                     "stats; the coordinator resolves and pins the "
                     "bijection split on the wire job)")
    for i in range(1, 5):
        jsb.add_argument(f"--custom{i}", default=None)
    jsb.add_argument("--unit-size", type=int, default=1 << 22)
    jsb.add_argument("--unit-seconds", type=float, default=20.0)
    jsb.add_argument("--batch", type=int, default=None,
                     help="device batch size shipped to workers "
                     f"(default: {DEFAULT_BATCH})")
    jsb.add_argument("--hit-cap", type=int, default=64)
    jsb.add_argument("--devices", type=int, default=1,
                     help="ask workers to shard this job's units over "
                     "N of their local chips (unified sharded "
                     "runtime; a worker's own --devices overrides)")
    jsb.add_argument("--owner", default=None,
                     help="tenant name recorded on the job (default: "
                     "$USER)")
    jsb.add_argument("--priority", type=int, default=1,
                     help="fair-share weight: a priority-3 job "
                     "receives ~3x the leases of a priority-1 job")
    jsb.add_argument("--quota", type=int, default=None, metavar="N",
                     help="cap on keyspace indices this job may sweep")
    jsb.add_argument("--rate", type=float, default=None, metavar="U/S",
                     help="lease-rate cap in units/second (token "
                     "bucket)")
    _jobs_client_args(jsb)

    jls = jsub.add_parser("list", help="list every job with state, "
                          "coverage, and fair-share accounting")
    _jobs_client_args(jls)
    for name, helptext in (
            ("status", "one job's summary (adds its keyspace and "
             "fingerprint)"),
            ("cancel", "cancel a job: no more leases, in-flight "
             "completes dropped"),
            ("pause", "pause a job (outstanding units still land; "
             "resume with `dprf jobs resume`)"),
            ("resume", "resume a paused job")):
        c = jsub.add_parser(name, help=helptext)
        c.add_argument("job", help="job id (from submit/list)")
        _jobs_client_args(c)
    jh = jsub.add_parser("hits", help="pull a job's hits (cursor-"
                         "based): each tenant streams its OWN cracks, "
                         "not the global found set")
    jh.add_argument("job", help="job id")
    jh.add_argument("--cursor", type=int, default=0,
                    help="resume from this hit sequence number")
    jh.add_argument("--follow", action="store_true",
                    help="keep polling until the job reaches a "
                    "terminal state")
    jh.add_argument("--interval", type=float, default=2.0)
    _jobs_client_args(jh)

    rp = sub.add_parser("retry-parked", help="admin op on a RUNNING "
                        "coordinator: requeue poisoned/parked units "
                        "with a fresh retry budget, without restarting "
                        "the job")
    rp.add_argument("--connect", required=True, metavar="HOST:PORT",
                    help="the coordinator's RPC address (`dprf serve "
                    "--bind`)")
    rp.add_argument("--token", default=None,
                    help="shared secret for an authenticated "
                    "coordinator (default: $DPRF_TOKEN)")
    rp.add_argument("--timeout", type=float, default=30.0)
    rp.add_argument("--quiet", "-q", action="store_true")

    for name, helptext in (("show", "print potfile-cracked targets of a "
                            "hashlist as hash:plain"),
                           ("left", "print targets of a hashlist NOT yet "
                            "in the potfile")):
        v = sub.add_parser(name, help=helptext)
        v.add_argument("hashfile")
        v.add_argument("--engine", "-m", required=True)
        v.add_argument("--potfile", default="dprf.potfile")
        v.add_argument("--quiet", "-q", action="store_true")

    tp = sub.add_parser("top", help="live terminal view of a running "
                        "coordinator: per-worker state, current unit, "
                        "span in progress, lease countdown (reads the "
                        "flight recorder over the op_trace_tail RPC)")
    tp.add_argument("--connect", required=True, metavar="HOST:PORT",
                    help="the coordinator's RPC address (`dprf serve "
                    "--bind`)")
    tp.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="seconds between refreshes")
    tp.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="stop after N frames (0 = until the job "
                    "finishes / Ctrl-C)")
    tp.add_argument("--spans", type=int, default=400, metavar="N",
                    help="flight-recorder spans to fetch per frame")
    tp.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="only spans of this work-unit trace id (from "
                    "a lease table row or `dprf trace export`): watch "
                    "one unit's lifecycle bounce across the fleet")
    tp.add_argument("--follow", action="store_true",
                    help="incremental span streaming: each frame "
                    "fetches only spans newer than the last frame's "
                    "cursor (cuts refresh cost on big fleets)")
    tp.add_argument("--no-clear", action="store_true",
                    help="append frames instead of redrawing the "
                    "screen")
    tp.add_argument("--token", default=None,
                    help="shared secret for an authenticated "
                    "coordinator (default: $DPRF_TOKEN)")
    tp.add_argument("--timeout", type=float, default=30.0)
    tp.add_argument("--quiet", "-q", action="store_true")

    tr = sub.add_parser("trace", help="work with session trace streams "
                        "(the per-unit lifecycle spans recorded next "
                        "to the session journal)")
    trsub = tr.add_subparsers(dest="trace_cmd", required=True)
    te = trsub.add_parser("export", help="convert a session's span "
                          "stream to Chrome-trace JSON (open in "
                          "Perfetto / chrome://tracing)")
    te.add_argument("session", help="session journal path (or the "
                    ".trace.jsonl stream itself)")
    te.add_argument("-o", "--out", default=None,
                    help="output file (default: <session>"
                    ".perfetto.json)")
    te.add_argument("--quiet", "-q", action="store_true")
    tpl = trsub.add_parser("pull", help="incident response: arm a "
                           "fleet-wide flight-recorder pull (live "
                           "workers ship their LOCAL rings on their "
                           "next lease), then dump the coordinator's "
                           "merged ring to a .trace.jsonl file that "
                           "`dprf trace export` understands")
    tpl.add_argument("--connect", required=True, metavar="HOST:PORT",
                     help="the coordinator's RPC address")
    tpl.add_argument("-o", "--out", default="pulled.trace.jsonl",
                     help="output span stream (feed to `dprf trace "
                     "export`)")
    tpl.add_argument("--wait", type=float, default=2.0, metavar="S",
                     help="seconds to wait after arming so polling "
                     "workers can push their rings (0 with --no-arm)")
    tpl.add_argument("--no-arm", action="store_true",
                     help="dump only what the coordinator already "
                     "holds; do not ask workers for their rings")
    tpl.add_argument("--spans", type=int, default=1000, metavar="N",
                     help="page size per op_trace_pull request")
    tpl.add_argument("--token", default=None,
                     help="shared secret for an authenticated "
                     "coordinator (default: $DPRF_TOKEN)")
    tpl.add_argument("--timeout", type=float, default=30.0)
    tpl.add_argument("--quiet", "-q", action="store_true")

    hl = sub.add_parser("health", help="fleet health view of a "
                        "running coordinator: per-worker state "
                        "machine (healthy/degraded/missing/dead), "
                        "straggler flags, per-job SLOs (ETA, "
                        "time-to-first-hit, stall), active alerts")
    hl.add_argument("--json", action="store_true",
                    help="machine-readable snapshot on stdout (the "
                    "CI artifact format)")
    _jobs_client_args(hl)

    al = sub.add_parser("alerts", help="alert surface of a running "
                        "coordinator: active (pending/firing) alerts "
                        "and the recent transition history (the full "
                        "log is the session's .alerts.jsonl)")
    al.add_argument("--json", action="store_true",
                    help="machine-readable alerts on stdout")
    al.add_argument("--history", type=int, default=50, metavar="N",
                    help="recent transition events to fetch")
    _jobs_client_args(al)

    tok = sub.add_parser("token", help="mint an owner-scoped tenant "
                         "token from the coordinator's admin secret: "
                         "a client authenticating with it may only "
                         "cancel/pause/resume/pull its OWN jobs, and "
                         "its submissions are forced to that owner")
    tok.add_argument("--owner", required=True,
                     help="tenant name (1-64 chars of [A-Za-z0-9_-])")
    tok.add_argument("--token", default=None,
                     help="the coordinator's ADMIN secret (default: "
                     "$DPRF_TOKEN)")
    tok.add_argument("--quiet", "-q", action="store_true")

    rpt = sub.add_parser("report", help="one-shot performance report "
                         "from session artifacts alone (trace JSONL "
                         "+ telemetry snapshots + journal): "
                         "throughput, phase breakdown p50/p95, busy "
                         "fraction, compile-cache hit rate, pipeline "
                         "depth, per-job fair share -- no live "
                         "coordinator needed")
    rpt.add_argument("session", help="session journal path")
    rpt.add_argument("--json", action="store_true",
                     help="machine-readable report on stdout instead "
                     "of the text rendering")
    rpt.add_argument("--quiet", "-q", action="store_true")

    aud = sub.add_parser("audit", help="coverage audit from session "
                         "artifacts alone (perfreport/audit.py): "
                         "rebuild per-job coverage from journal "
                         "snapshots (fraction, gaps, digest "
                         "re-check), replay trace complete spans for "
                         "double-covered candidates, prove hits were "
                         "found exactly once -- exit 0 on verdict "
                         "clean, 3 otherwise")
    aud.add_argument("session", help="session journal path")
    aud.add_argument("--json", action="store_true",
                     help="machine-readable audit on stdout instead "
                     "of the text rendering")
    aud.add_argument("--quiet", "-q", action="store_true")

    pg = sub.add_parser("programs", help="compiled-program table of a "
                        "running coordinator: XLA-derived flops, "
                        "bytes accessed, and peak device memory per "
                        "executable -- the coordinator's own compile "
                        "sites plus the records workers ship in "
                        "heartbeats (op_programs RPC)")
    pg.add_argument("--json", action="store_true",
                    help="machine-readable program records on stdout "
                    "(the CI artifact format)")
    _jobs_client_args(pg)

    pf = sub.add_parser("profile", help="kernel-level profiling "
                        "(telemetry/profiler.py): analyze a "
                        "jax.profiler capture dir dependency-free "
                        "(top device ops, compute/collective/copy "
                        "fractions, generate/hash/compare phases), "
                        "or capture a bounded window on a live fleet "
                        "worker over RPC")
    pf.add_argument("target", nargs="?", default=None,
                    help="local mode: a capture dir (the --profile / "
                    "DPRF_JAX_PROFILE output) or a "
                    "perfetto_trace.json.gz file")
    pf.add_argument("--engine", "-m", default=None,
                    help="engine whose declared PROFILE_PHASES "
                    "patterns map device ops to generate/hash/"
                    "compare")
    pf.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="capture mode: request one bounded capture "
                    "window on a worker and pull back the analyzed "
                    "summary (the raw trace stays on the worker "
                    "host; its path rides the summary)")
    pf.add_argument("--worker", default=None, metavar="W",
                    help="worker id to capture on (default: the "
                    "slowest live worker)")
    pf.add_argument("--seconds", type=float, default=None,
                    help="capture window length (default: "
                    "$DPRF_PROFILE_SECONDS)")
    pf.add_argument("--wait", type=float, default=180.0, metavar="S",
                    help="seconds to wait for the worker to push its "
                    "summary before giving up (a cold worker first "
                    "warms the profiler's import stack off its sweep "
                    "path, then sweeps through the window)")
    pf.add_argument("--fetch", action="store_true",
                    help="no new capture: print the summaries the "
                    "coordinator already holds (incl. "
                    "alert-triggered auto-captures)")
    pf.add_argument("--top", type=int, default=20, metavar="N",
                    help="top-ops table length (local mode)")
    pf.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout (the "
                    "CI artifact format)")
    pf.add_argument("--token", default=None,
                    help="shared secret for an authenticated "
                    "coordinator (default: $DPRF_TOKEN)")
    pf.add_argument("--timeout", type=float, default=30.0)
    pf.add_argument("--quiet", "-q", action="store_true")

    mt = sub.add_parser("metrics", help="scrape a running coordinator's "
                        "/metrics endpoint (Prometheus text format)")
    mt.add_argument("--connect", required=True, metavar="HOST:PORT",
                    help="the coordinator's RPC address (`dprf serve "
                    "--bind`); /metrics is served on the same port")
    mt.add_argument("--json", action="store_true",
                    help="print the registry as a JSON snapshot "
                    "instead of Prometheus text (uses the RPC "
                    "protocol, so --token applies)")
    mt.add_argument("--token", default=None,
                    help="shared secret for a token-authenticated "
                    "coordinator's --json path (default: $DPRF_TOKEN; "
                    "the plain-text scrape never needs one)")
    mt.add_argument("--timeout", type=float, default=10.0)
    mt.add_argument("--quiet", "-q", action="store_true")

    ck = sub.add_parser("check", help="run the static-analysis suite "
                        "(lock discipline, RPC protocol contract, "
                        "env-knob registry, markers, metrics, worker "
                        "contract)")
    ck.add_argument("--root", default=None, metavar="DIR",
                    help="repo root to analyze (default: the tree "
                    "this package is installed in)")
    ck.add_argument("--only", action="append", default=None,
                    metavar="CHECK", help="run only these checks "
                    "(repeatable, or comma-separated)")
    ck.add_argument("--skip", action="append", default=None,
                    metavar="CHECK", help="skip these checks")
    ck.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ck.add_argument("--list", action="store_true",
                    help="list available checks and exit")
    ck.add_argument("--explain", metavar="CHECK", default=None,
                    help="print one check's rules and its declaration "
                    "tables as found in the repo, then exit")
    ck.add_argument("--show-suppressed", action="store_true",
                    help="also print findings silenced by inline "
                    "suppressions")
    ck.add_argument("--write-env-docs", action="store_true",
                    help="regenerate the README env-knob table from "
                    "the utils/env.py registry, then run the checks")
    ck.add_argument("--fix-skeletons", action="store_true",
                    help="emit GUARDED_BY / RELEASES declaration "
                    "skeletons for the lock and resource findings the "
                    "locks/threads checks raise, ready to paste next "
                    "to the offending class")
    ck.add_argument("--quiet", "-q", action="store_true")

    e = sub.add_parser("engines", help="list available engines")
    e.add_argument("--device", default=None)
    e.add_argument("--verbose", "-v", action="store_true",
                   help="one line per engine with its description")

    for name, helptext in (
            ("keyspace", "print the keyspace size of an attack (mask, "
             "wordlist+rules, combinator, hybrid)"),
            ("stdout", "print the attack's candidates, one per line, "
             "without hashing (pipe to other tools)")):
        k = sub.add_parser(name, help=helptext)
        k.add_argument("attack_arg", metavar="mask_or_files")
        k.add_argument("-a", "--attack", default="mask",
                       choices=["mask", "wordlist", "combinator",
                                "hybrid-wm", "hybrid-mw"])
        k.add_argument("--rules", default=None)
        k.add_argument("--markov", default=None, metavar="STATS")
        k.add_argument("--max-len", type=int, default=55)
        for i in range(1, 5):
            k.add_argument(f"--custom{i}", default=None)
        if name == "stdout":
            k.add_argument("--skip", type=int, default=0, metavar="N")
            k.add_argument("--limit", type=int, default=None, metavar="N")
        k.add_argument("--quiet", "-q", action="store_true")

    from dprf_tpu.generators.markov import MAX_LEN as _MARKOV_MAX_LEN
    t = sub.add_parser("markov", help="train per-position Markov stats "
                       "from a wordlist (for crack --markov)")
    t.add_argument("wordlist")
    t.add_argument("-o", "--out", required=True, metavar="STATS",
                   help="output stats file (.dprfstat)")
    t.add_argument("--max-len", type=int, default=_MARKOV_MAX_LEN)
    t.add_argument("--quiet", "-q", action="store_true")
    return p


def _customs(args) -> dict:
    out = {}
    for i in range(1, 5):
        v = getattr(args, f"custom{i}", None)
        if v is not None:
            out[i] = v.encode("latin-1")
    return out


# ---------------------------------------------------------------------------
# job construction (shared by crack / serve / worker)

def _wordlist_max_len(engine_name: str, engine, device: str) -> int:
    """The 55-byte single-block limit binds only on device engines whose
    packer lays words out as single-block uint32 messages (the
    digest_packed fast path).  bcrypt's device path packs its own uint8
    tables with no single-block constraint, so it keeps the engine's own
    72-byte limit; CPU-oracle jobs keep the engine limit too (e.g.
    63-byte WPA passphrases)."""
    if device == "jax":
        try:
            dev = get_engine(engine_name, device="jax")
        except KeyError:
            return engine.max_candidate_len
        if (hasattr(dev, "make_wordlist_worker")
                and hasattr(dev, "digest_packed")):
            # single-block limit of the DEVICE engine (55 for 64-byte
            # blocks, 111 for the SHA-512 family's 128-byte blocks)
            return min(getattr(dev, "_block_limit", 55),
                       engine.max_candidate_len)
    return engine.max_candidate_len


def _build_gen(attack: str, attack_arg: str, customs: dict,
               rules_spec, max_len: Optional[int], engine, device: str,
               log: Log, markov: Optional[str] = None):
    """Build the candidate generator + the attack identity string.

    max_len: wordlist packing width; None = derive from engine/device
    (the coordinator derives it and ships it to workers, who must use
    the identical value or their keyspace would disagree).
    Returns (gen, attack_desc, max_len).
    """
    if attack == "mask":
        counts = None
        markov_id = ""
        if markov:
            from dprf_tpu.generators.markov import load_stats, stats_digest
            counts = load_stats(markov)
            # stats permute the index->candidate map: part of the job
            # identity, so divergent stats files fail the fingerprint
            markov_id = f":markov={stats_digest(counts)}"
            log.info("markov ordering", stats=markov)
        gen = MaskGenerator(attack_arg, custom=customs or None,
                            markov_counts=counts)
        log.info("keyspace", mask=attack_arg, size=gen.keyspace)
        # Custom charsets change which candidate an index decodes to, so
        # they are part of the job identity.
        attack_desc = f"mask:{attack_arg}" + "".join(
            f":{i}={customs[i].hex()}" for i in sorted(customs)) + markov_id
        return gen, attack_desc, None
    if markov:
        raise ValueError("--markov applies to mask attacks only")

    if attack in ("combinator", "hybrid-wm", "hybrid-mw"):
        return _build_combinator_gen(attack, attack_arg, customs,
                                     max_len, engine, device, log)

    import hashlib as _hl

    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules import resolve_rules_path

    if max_len is None:
        max_len = _wordlist_max_len(engine.name, engine, device)
    rules_id = "none"
    if rules_spec:
        with open(resolve_rules_path(rules_spec), "rb") as fh:
            rules_id = _hl.sha256(fh.read()).hexdigest()[:16]
    # from_files prefers the native (C++) loader: packed tables are
    # built at memory bandwidth, never as a Python word list.
    gen = WordlistRulesGenerator.from_files(attack_arg, rules_spec,
                                            max_len=max_len)
    if gen.n_skipped_long:
        log.warn("skipped overlong words", count=gen.n_skipped_long,
                 max_len=max_len)
    log.info("keyspace", words=gen.n_words, rules=gen.n_rules,
             size=gen.keyspace)
    # Wordlist contents decide what an index decodes to: fingerprint
    # the word content, not the file path.
    attack_desc = f"wordlist:{gen.content_id()}:rules={rules_id}"
    return gen, attack_desc, max_len


#: largest mask keyspace a hybrid attack will materialize as a word
#: table (the mask side of -a 6/7 is typically a short digit/symbol
#: suffix; a full-size mask belongs in a plain mask attack instead)
_HYBRID_MASK_CAP = 1 << 20


def _build_combinator_gen(attack: str, attack_arg: str, customs: dict,
                          max_len: Optional[int], engine, device: str,
                          log: Log):
    """Combinator (-a combinator: 'left.txt,right.txt') and hybrid
    modes (-a hybrid-wm: 'words.txt,MASK'; -a hybrid-mw:
    'MASK,words.txt').  The mask side of a hybrid is materialized as a
    word table (capped -- see _HYBRID_MASK_CAP)."""
    from dprf_tpu.generators.combinator import CombinatorGenerator
    from dprf_tpu.generators.wordlist import load_words

    parts = attack_arg.split(",")
    if len(parts) != 2:
        raise ValueError(f"{attack} needs 'LEFT,RIGHT', got {attack_arg!r}")
    if max_len is None:
        max_len = _wordlist_max_len(engine.name, engine, device)

    def side(spec: str, is_mask: bool) -> list:
        if not is_mask:
            words, skipped = load_words(spec, max_len)
            if skipped:
                log.warn("skipped overlong words", file=spec,
                         count=skipped, max_len=max_len)
            return words
        mgen = MaskGenerator(spec, custom=customs or None)
        if mgen.keyspace > _HYBRID_MASK_CAP:
            raise ValueError(
                f"hybrid mask {spec!r} expands to {mgen.keyspace} words "
                f"(cap {_HYBRID_MASK_CAP}); use a shorter mask or a "
                "plain mask attack")
        return [mgen.candidate(i) for i in range(mgen.keyspace)]

    left_mask = attack == "hybrid-mw"
    right_mask = attack == "hybrid-wm"
    gen = CombinatorGenerator(side(parts[0], left_mask),
                              side(parts[1], right_mask),
                              max_len=max_len)
    log.info("keyspace", left=gen.n_left, right=gen.n_right,
             size=gen.keyspace)
    attack_desc = f"{attack}:{gen.content_id()}"
    return gen, attack_desc, max_len


def _align_unit_size(unit_size: int, attack: str, gen) -> int:
    """Units aligned to whole words: no candidate is ever rehashed at
    unit boundaries on the device path."""
    if attack != "wordlist":
        return unit_size
    return max(gen.n_rules, (unit_size // gen.n_rules) * gen.n_rules)


def _apply_tuned_inner(worker, engine_name: str, attack: str, gen,
                       hit_cap: int, log: Log):
    """Warm-start the multi-batch superstep fusion window from a
    `dprf tune --rungs inner` record.  SUPER_CAP bounds a worker's
    _super_inner window, so the instance override takes effect without
    touching the DPRF_SUPER_CAP env knob; a cache miss (or a worker
    with no superstep) leaves the default standing."""
    from dprf_tpu import tune as tune_mod
    inner = tune_mod.lookup_tuned_value(
        engine_name, "inner", attack=attack, device="jax",
        extras=_tune_extras(attack, hit_cap=hit_cap,
                            n_rules=getattr(gen, "n_rules", None)))
    if inner and hasattr(worker, "SUPER_CAP"):
        worker.SUPER_CAP = int(inner)
        log.info("tuned superstep window", inner=int(inner))
    return worker


def _select_worker(engine_name: str, device: str, attack: str, gen,
                   targets, batch: int, hit_cap: int, oracle, n_devices: int,
                   log: Log):
    """Pick the execution backend for a job's WorkUnits.

    Engine-specific device workers first (salted pipelines plug in the
    same way fast ones do); the multi-chip mesh path for fast engines
    when n_devices > 1; CPU oracle as the fallback.
    """
    _MAKERS = {"mask": "make_mask_worker",
               "wordlist": "make_wordlist_worker",
               "combinator": "make_combinator_worker",
               "hybrid-wm": "make_combinator_worker",
               "hybrid-mw": "make_combinator_worker"}
    maker_name = _MAKERS[attack]
    dev_engine = None
    if device == "jax":
        try:
            dev_engine = get_engine(engine_name, device="jax")
        except KeyError:
            pass
    if dev_engine is not None and n_devices > 1:
        import jax as _jax
        have = len(_jax.devices())
        if have < n_devices:
            # a serve-plane job may request more chips than this host
            # has: degrade to the local mesh instead of refusing the
            # job's leases (coverage is keyspace-indexed, so any
            # device count sweeps the same units)
            log.warn("host has fewer devices than requested; "
                     "clamping the mesh", requested=n_devices,
                     have=have)
            n_devices = have
    if dev_engine is not None and n_devices > 1:
        smaker = maker_name.replace("make_", "make_sharded_")
        if callable(getattr(dev_engine, smaker, None)):
            from dprf_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(n_devices)
            log.info("mesh", devices=n_devices)
            per_dev = (max(1, batch // gen.n_rules)
                       if attack == "wordlist" else batch)
            return _apply_tuned_inner(
                getattr(dev_engine, smaker)(
                    gen, targets, mesh, per_dev,
                    hit_capacity=hit_cap, oracle=oracle),
                engine_name, attack, gen, hit_cap, log)
        log.warn("engine has no multi-chip pipeline; using one chip",
                 engine=engine_name)
    if dev_engine is not None and callable(getattr(dev_engine, maker_name, None)):
        return _apply_tuned_inner(
            getattr(dev_engine, maker_name)(
                gen, targets, batch=batch, hit_capacity=hit_cap,
                oracle=oracle),
            engine_name, attack, gen, hit_cap, log)
    if device == "jax":
        log.warn("no jax engine for algorithm/attack; using cpu oracle",
                 engine=engine_name)
    return CpuWorker(oracle, gen, targets)


def _load_targets(engine, hashfile: str, log: Log):
    hl = load_hashlist(engine, hashfile)
    for no, text, err in hl.skipped:
        log.warn("skipping hashlist line", line=no, error=err)
    if not hl.targets:
        log.error("no valid targets in hashlist")
        return None
    log.info("loaded targets", count=len(hl.targets),
             duplicates=hl.duplicates, engine=engine.name)
    return hl


def _load_job_targets(args, engine, log: Log):
    """Resolve the job's target set from the hashfile positional or
    the bulk ``--targets-file`` ingest path; returns an object with a
    ``.targets`` list (HashlistResult or TargetStore) or None on a
    fatal, already-logged error."""
    tf = getattr(args, "targets_file", None)
    if tf is not None:
        if args.hashfile is not None:
            log.error("pass a hashfile positional OR --targets-file, "
                      "not both")
            return None
        from dprf_tpu.targets import TargetStore
        store = TargetStore.from_file(engine, tf, log=log)
        if not store.targets:
            log.error("no valid targets in targets file", path=tf)
            return None
        return store
    if args.hashfile is None:
        log.error("no target hashes: pass a hashfile or --targets-file")
        return None
    return _load_targets(engine, args.hashfile, log)


def _setup_session(args, spec, log: Log):
    """Returns (session, completed, restored_hits, tuning, jobs,
    digest) or None on conflict; ``jobs`` is the journal's
    scheduler-submitted job records (multi-tenant serve resume,
    jobs/build.restore_jobs) and ``digest`` is the journal's coverage
    digest for the default job's restored intervals (ISSUE 19)."""
    session = None
    completed: list = []
    restored_hits: list = []
    tuning: dict = {}
    jobs: dict = {}
    digest = None
    if args.session:
        session = SessionJournal(args.session)
        prior = SessionJournal.load(args.session)
        if args.restore:
            if prior is None:
                log.warn("no session to restore; starting fresh")
            elif prior.spec.get("fingerprint") != spec.fingerprint:
                log.error("session file belongs to a different job",
                          theirs=prior.spec.get("fingerprint"),
                          ours=spec.fingerprint)
                return None
            else:
                completed = prior.completed
                restored_hits = prior.hits
                tuning = prior.tuning
                jobs = prior.jobs
                digest = prior.coverage.get(prior.default_job)
                done = sum(e - s for s, e in completed)
                log.info("resuming session", covered=done,
                         hits=len(restored_hits), jobs=len(jobs))
        elif prior is not None:
            log.error("session file exists; pass --restore to resume "
                      "or remove it", path=args.session)
            return None
    return session, completed, restored_hits, tuning, jobs, digest


def _print_results(found: dict, targets) -> None:
    from dprf_tpu.runtime.potfile import encode_plain
    for ti, plain in sorted(found.items()):
        print(f"{targets[ti].raw}:{encode_plain(plain)}")


# ---------------------------------------------------------------------------
# crack (local)

class _JobSetup:
    """Everything the crack and serve front-ends share: targets,
    generator, spec/fingerprint, session state, dispatcher."""

    def __init__(self, engine, hl, gen, max_len, unit_size, spec,
                 session, completed, restored_hits, dispatcher,
                 tuning=None, restored_jobs=None, order=None):
        #: rank<->index bijection (generators/order.py) or None: the
        #: dispatcher leases rank spans, so the worker must be wrapped
        #: in an OrderedWorker before it sees a unit
        self.order = order
        self.engine = engine
        self.hl = hl
        self.gen = gen
        self.max_len = max_len
        self.unit_size = unit_size
        self.spec = spec
        self.session = session
        self.completed = completed
        self.restored_hits = restored_hits
        self.dispatcher = dispatcher
        #: tuning records restored from the session journal (resume)
        self.tuning = tuning or {}
        #: scheduler-submitted job records from the journal (resume)
        self.restored_jobs = restored_jobs or {}


def _setup_job(args, device: str, log: Log,
               lease_timeout: Optional[float] = None):
    """Build the full job state; None means a fatal setup error (already
    logged).  Single source of truth for the fingerprint and session
    wiring, so local and distributed jobs can never diverge."""
    engine = get_engine(args.engine, device="cpu")   # parser/oracle always CPU
    hl = _load_job_targets(args, engine, log)
    if hl is None:
        return None

    gen, attack_desc, max_len = _build_gen(args.attack, args.attack_arg,
                                           _customs(args), args.rules, None,
                                           engine, device, log,
                                           markov=getattr(args, "markov",
                                                          None))
    unit_size = _align_unit_size(args.unit_size, args.attack, gen)

    order = None
    if (getattr(args, "order", "index") or "index") != "index":
        if not getattr(args, "markov", None):
            log.error("--order markov requires --markov stats: the "
                      "rank order ranks trained-frequency levels")
            return None
        from dprf_tpu.generators.order import build_order
        try:
            order = build_order(args.order, gen)
        except ValueError as e:
            log.error("cannot build candidate order", error=str(e))
            return None
        log.info("rank-ordered dispatch", order=order.kind,
                 split=order.split, blocks=order.blocks,
                 block=order.block)

    spec = JobSpec(engine=engine.name, device=device, attack=args.attack,
                   attack_arg=args.attack_arg, keyspace=gen.keyspace,
                   fingerprint=job_fingerprint(
                       engine.name, attack_desc, gen.keyspace,
                       [t.digest for t in hl.targets]))

    sess = _setup_session(args, spec, log)
    if sess is None:
        return None
    (session, completed, restored_hits, tuning, restored_jobs,
     restored_digest) = sess

    kw = {} if lease_timeout is None else {"lease_timeout": lease_timeout}
    unit_seconds = getattr(args, "unit_seconds", 0) or 0
    if unit_seconds > 0:
        from dprf_tpu.telemetry import devstats
        from dprf_tpu.tune import AdaptiveUnitSizer
        # wordlist units stay word-aligned even when adaptively sized,
        # so no candidate is rehashed at unit boundaries
        align = gen.n_rules if args.attack == "wordlist" else 1
        kw["sizer"] = AdaptiveUnitSizer(
            unit_size, target_seconds=unit_seconds, align=align,
            # an explicit tiny --unit-size is a floor the sizer must
            # respect, not round up away from
            min_unit=max(align, min(unit_size, 1 << 10)),
            # OOM-headroom signal at the right ALTITUDE: the local
            # crack path hashes in THIS process, so local devstats is
            # the worker's own allocator; a serve coordinator's units
            # run on REMOTE workers, whose headroom arrives per-worker
            # through heartbeats (rpc.op_heartbeat) instead
            headroom_fn=(devstats.headroom_frac
                         if lease_timeout is None else None))
    # --skip/--limit restrict THIS run's sweep by pre-marking the
    # excluded ranges done (run-scoped: not part of the job identity,
    # exactly like resuming a partially-covered session)
    skip = min(getattr(args, "skip", 0) or 0, gen.keyspace)
    limit = getattr(args, "limit", None)
    restricted = list(completed)
    # under --order, skip/limit count candidates in the order they
    # are TRIED (ranks); the exclusions are mapped to their index
    # image because the journal -- and from_completed -- speak index
    if skip:
        restricted.extend(order.index_spans(0, skip) if order
                          else [(0, skip)])
        log.info("skipping keyspace prefix", skip=skip)
    if limit is not None and skip + limit < gen.keyspace:
        restricted.extend(
            order.index_spans(skip + limit, gen.keyspace) if order
            else [(skip + limit, gen.keyspace)])
        log.info("limiting sweep", limit=limit)
    if (skip or limit is not None) and session is not None:
        log.warn("--skip/--limit ranges will be journaled as covered "
                 "in this session; resume without them will NOT sweep "
                 "the excluded ranges")
    if restricted:
        # the journal's digest describes the RESTORED intervals only:
        # --skip/--limit append synthetic covered ranges, which would
        # (correctly) rebuild to a different digest -- so the check
        # only arms on a pure resume
        expect = (restored_digest
                  if not skip and limit is None else None)
        try:
            dispatcher = Dispatcher.from_completed(
                gen.keyspace, unit_size, restricted,
                expect_digest=expect, order=order, **kw)
        except ValueError as e:
            log.error("refusing to resume", error=str(e))
            return None
    else:
        dispatcher = Dispatcher(gen.keyspace, unit_size, order=order,
                                **kw)
    return _JobSetup(engine, hl, gen, max_len, unit_size, spec,
                     session, completed, restored_hits, dispatcher,
                     tuning=tuning, restored_jobs=restored_jobs,
                     order=order)


def _tune_extras(attack: str, hit_cap=None, n_rules=None) -> dict:
    """Tuning-cache key extras beyond (engine, device, attack):
    hit_capacity scales every hit buffer (moving the HBM ceiling), and
    the rules-set cardinality changes a wordlist step's word_batch for
    the same --batch -- either can fork the optimum, so they live in
    the key and can never alias a stale one."""
    extras: dict = {}
    if hit_cap is not None:
        extras["hit_cap"] = int(hit_cap)
    if attack == "wordlist" and n_rules:
        extras["rules_n"] = int(n_rules)
    return extras


def _resolve_batch(batch_arg, engine_name: str, device: str, attack: str,
                   log: Log, session=None, session_tuning=None,
                   hit_cap=None, n_rules=None):
    """--batch resolution: an explicit integer is pinned; "auto"
    consults the tuning subsystem -- the resumed session's journaled
    decision first (the resumed ledger's unit geometry was built around
    it, and the journal survives machines whose tune cache doesn't),
    then the persistent cache.  Returns (batch, tuned); a tuned choice
    is re-journaled so the NEXT resume sees it too."""
    from dprf_tpu import tune as tune_mod

    if batch_arg != "auto":
        return int(batch_arg), False
    extras = _tune_extras(attack, hit_cap=hit_cap, n_rules=n_rules)
    key = tune_mod.make_key(engine_name, attack=attack, device=device,
                            **extras)
    rec = (session_tuning or {}).get(key)
    batch = None
    if isinstance(rec, dict):
        try:
            batch = int(rec["batch"])
        except (KeyError, TypeError, ValueError):
            batch = None
        if batch:
            log.info("tuned batch restored from session", batch=batch)
            tune_mod.publish_tuned_batch(engine_name, device, attack,
                                         batch)
    if not batch:
        batch = tune_mod.lookup_tuned_batch(engine_name, attack=attack,
                                            device=device,
                                            extras=extras)
        if batch:
            log.info("tuned batch loaded from cache", batch=batch,
                     cache=tune_mod.cache_path())
    if not batch:
        log.info("no tuning entry for this job; using the default "
                 "batch (run `dprf tune` to sweep one)",
                 batch=DEFAULT_BATCH, engine=engine_name)
        return DEFAULT_BATCH, False
    if session is not None:
        session.record_tuning(key, {"batch": batch})
    return batch, True


def cmd_crack(args, log: Log) -> int:
    device = _DEVICE_ALIASES[args.device]
    if getattr(args, "multihost", False):
        # One mesh across hosts (DCN): every host runs this same
        # command; the job is deterministic (same fingerprint, same
        # Dispatcher order), so all processes drive identical step
        # sequences -- SPMD -- and the replicated hit buffers mean every
        # host observes every hit.  Only process 0 owns the potfile and
        # session journal to avoid duplicate writes.
        from dprf_tpu.parallel.mesh import init_multihost
        import jax as _jax
        init_multihost(args.coordinator_address, args.num_processes,
                       args.process_id)
        log.info("multihost mesh", process=_jax.process_index(),
                 n_processes=_jax.process_count(),
                 global_devices=len(_jax.devices()))
        if _jax.process_index() != 0:
            args.no_potfile = True
            args.session = None
    if getattr(args, "increment", False):
        return _crack_increment(args, device, log)
    rc, _, _ = _crack_single(args, device, log)
    return rc


def _mask_positions(mask: str) -> list[str]:
    """Mask string -> per-position token list ('?l', '??', literals)."""
    toks, i = [], 0
    while i < len(mask):
        if mask[i] == "?":
            if i + 1 >= len(mask):
                raise ValueError(f"dangling '?' at end of mask {mask!r}")
            toks.append(mask[i:i + 2])
            i += 2
        else:
            toks.append(mask[i])
            i += 1
    return toks


def _crack_increment(args, device: str, log: Log) -> int:
    """--increment: sweep mask prefix lengths min..max (hashcat
    semantics).  Each length is an independent job sharing the potfile,
    so already-cracked targets are skipped at later lengths and the
    sweep stops as soon as everything is found."""
    import copy

    if args.attack != "mask":
        log.error("--increment applies to mask attacks only")
        return 2
    try:
        toks = _mask_positions(args.attack_arg)
    except ValueError as e:
        log.error(str(e))
        return 2
    lo = args.increment_min
    hi = args.increment_max or len(toks)
    if not 1 <= lo <= hi <= len(toks):
        log.error(f"increment range {lo}..{hi} outside mask's "
                  f"1..{len(toks)} positions")
        return 2
    any_found = False
    for length in range(lo, hi + 1):
        sub = copy.copy(args)
        sub.increment = False
        sub.attack_arg = "".join(toks[:length])
        if args.session:
            # per-length journals: lengths are distinct keyspaces with
            # distinct fingerprints, so they cannot share one ledger
            sub.session = f"{args.session}-len{length}"
        log.info("increment", length=length, mask=sub.attack_arg)
        rc, result, n_targets = _crack_single(sub, device, log)
        if rc == 2:
            return 2
        if result is not None:
            any_found |= bool(result.found)
            if len(result.found) >= n_targets:
                break      # everything cracked; skip longer lengths
    return 0 if any_found else 1


def _crack_single(args, device: str, log: Log):
    """One crack job; returns (rc, JobResult | None, n_targets)."""
    from dprf_tpu import compilecache
    from dprf_tpu.telemetry.trace import get_tracer
    compilecache.enable(log=log)
    job = _setup_job(args, device, log)
    if job is None:
        return 2, None, 0
    engine, hl, gen = job.engine, job.hl, job.gen
    session, restored_hits = job.session, job.restored_hits
    dispatcher, spec = job.dispatcher, job.spec
    tracer = get_tracer()
    if session is not None:
        # flight-recorder stream next to the journal (attached BEFORE
        # the worker builds, so warmup-era spans land in the file too)
        tracer.attach_file(session.trace_path)

    batch, _ = _resolve_batch(args.batch, args.engine, device,
                              args.attack, log, session=session,
                              session_tuning=job.tuning,
                              hit_cap=args.hit_cap,
                              n_rules=getattr(gen, "n_rules", None))
    worker = _select_worker(args.engine, device, args.attack, gen,
                            hl.targets, batch, args.hit_cap,
                            engine, args.devices, log)
    if job.order is not None:
        # rank-ordered dispatch: unit spans are ranks; the wrapper
        # decodes each into contiguous index runs before the (device
        # or CPU) worker's unchanged index-space sweep
        from dprf_tpu.runtime.worker import OrderedWorker
        worker = OrderedWorker(worker, job.order)
    # Overlapped warmup: start the step compile now on a background
    # thread so it runs while the potfile preloads, the session
    # restores, and the coordinator takes its first leases; the
    # coordinator joins it at the first dispatch (cold start ~=
    # max(compile, setup), not their sum).  No-op for factory-warmed
    # (Pallas) workers and for the CPU oracle path.
    warmup_async = getattr(worker, "warmup_async", None)
    if warmup_async is not None:
        warmup_async()

    potfile = None if args.no_potfile else Potfile(args.potfile)

    def progress(done, total, nfound, rate):
        eta = (total - done) / rate if rate > 0 else float("inf")
        log.info("progress", pct=f"{100.0 * done / total:.2f}%",
                 found=f"{nfound}/{len(hl.targets)}",
                 rate=f"{rate:,.0f}/s",
                 eta=(f"{eta:,.0f}s" if eta != float("inf") else "?"))

    coord = Coordinator(spec, hl.targets, dispatcher, worker,
                        session=session, potfile=potfile,
                        progress_cb=None if args.quiet else progress,
                        # device jobs verify every hit against the CPU
                        # oracle before the potfile (mirrors the
                        # distributed CoordinatorState verifier); the CPU
                        # worker IS the oracle, so no double hashing there
                        oracle=engine if device != "cpu" else None)
    coord.preload_found()
    coord.restore_hits(restored_hits)
    if coord.found:
        log.info("pre-cracked targets", count=len(coord.found))

    snap = None
    devstats_poller = None
    if session is not None:
        from dprf_tpu.telemetry import (DEFAULT as _registry,
                                        TelemetrySnapshotter,
                                        snapshot_interval)
        snap = TelemetrySnapshotter(session.telemetry_path, _registry,
                                    interval=snapshot_interval()).start()
        # HBM gauges ride the same snapshots (ISSUE 13); no-op
        # ticks on backends without memory stats
        from dprf_tpu.telemetry.devstats import DevstatsPoller
        devstats_poller = DevstatsPoller(registry=_registry).start()
    try:
        if args.profile:
            # jax.profiler capture of every step the coordinator
            # drives, through the single-flight ProfileCapture (a
            # DPRF_JAX_PROFILE env trace on the same process degrades
            # to a logged no-op instead of a crash); analyze with
            # `dprf profile DIR`
            from dprf_tpu.telemetry import profiler as profiler_mod
            with profiler_mod.get_profiler().session(
                    args.profile, owner="cli", log=log):
                result = coord.run()
            log.info("profile written (analyze with `dprf profile`)",
                     dir=args.profile)
        else:
            result = coord.run()
    finally:
        if devstats_poller is not None:
            devstats_poller.stop()
        if snap is not None:
            snap.stop()
            log.info("telemetry snapshots written",
                     path=session.telemetry_path)
        if session is not None:
            tracer.detach_file()
            log.info("trace spans written (export with `dprf trace "
                     "export`)", path=session.trace_path)

    _print_results(result.found, hl.targets)
    if result.parked:
        log.warn("job finished with POISONED units parked; their "
                 "ranges were NOT swept (see "
                 "dprf_units_poisoned_total)", parked=result.parked)
    log.info("job finished",
             found=f"{len(result.found)}/{len(hl.targets)}",
             tested=result.tested, elapsed=f"{result.elapsed:.2f}s",
             rate=f"{result.rate:,.0f}/s",
             exhausted=result.exhausted)
    return (0 if result.found else 1), result, len(hl.targets)


# ---------------------------------------------------------------------------
# serve / worker (distributed)

def _parse_hostport(s: str) -> tuple:
    host, _, port = s.rpartition(":")
    return host or "127.0.0.1", int(port)


def _parse_owner_quotas(specs) -> dict:
    """--owner-quota OWNER=N (repeatable) -> {owner: int} for the
    scheduler's per-owner aggregate caps."""
    out: dict = {}
    for s in specs or ():
        owner, _, n = s.partition("=")
        if not owner or not n:
            raise ValueError(f"--owner-quota wants OWNER=N, got {s!r}")
        out[owner] = max(0, int(n))
    return out


def cmd_serve(args, log: Log) -> int:
    from dprf_tpu import compilecache
    from dprf_tpu.runtime.rpc import CoordinatorServer, CoordinatorState

    compilecache.enable(log=log)
    device = _DEVICE_ALIASES[args.device]
    job_setup = _setup_job(args, device, log,
                           lease_timeout=args.lease_timeout)
    if job_setup is None:
        return 2
    engine, hl, gen = job_setup.engine, job_setup.hl, job_setup.gen
    session, restored_hits = job_setup.session, job_setup.restored_hits
    dispatcher, spec = job_setup.dispatcher, job_setup.spec
    unit_size, max_len = job_setup.unit_size, job_setup.max_len

    potfile = None if args.no_potfile else Potfile(args.potfile)

    batch, _ = _resolve_batch(args.batch, engine.name, device,
                              args.attack, log, session=session,
                              session_tuning=job_setup.tuning,
                              hit_cap=args.hit_cap,
                              n_rules=getattr(gen, "n_rules", None))

    # Everything a worker needs to rebuild the identical job.  max_len
    # is shipped so worker-side keyspace/packing can't drift from ours.
    # batch ships RESOLVED (an int): the coordinator's tuning decision
    # applies fleet-wide unless a worker overrides with --batch.
    job = {
        "engine": engine.name,
        "attack": args.attack,
        "attack_arg": args.attack_arg,
        "customs": {str(i): v.hex() for i, v in _customs(args).items()},
        "rules": args.rules,
        "markov": args.markov,
        "max_len": max_len,
        "targets": [t.raw for t in hl.targets],
        "keyspace": gen.keyspace,
        "unit_size": unit_size,
        "batch": batch,
        "hit_cap": args.hit_cap,
        # candidate order + the resolved bijection split (pinned here
        # so workers can never fork the rank<->index map on divergent
        # DPRF_ORDER_* environments)
        "order": job_setup.order.kind if job_setup.order else "index",
        "order_split": job_setup.order.split if job_setup.order else 0,
        # sharding request: workers build the job's worker over N of
        # their local chips through the unified sharded runtime (their
        # own --devices flag overrides)
        "devices": max(1, getattr(args, "devices", 1) or 1),
        "fingerprint": spec.fingerprint,
    }

    def verify_hit(ti, plain):
        # Re-hash with the coordinator's CPU oracle before accepting: a
        # worker with a divergent device path must not poison the
        # potfile or halt the search for a target it did not crack.
        if engine.verify(plain, hl.targets[ti]):
            return True
        log.warn("rejected unverifiable hit", target=hl.targets[ti].raw[:32])
        return False

    from dprf_tpu.telemetry.trace import get_tracer
    token = args.token or envreg.get_str("DPRF_TOKEN") or None
    state = CoordinatorState(job, dispatcher, len(hl.targets),
                             verifier=verify_hit, token=token,
                             owner_quotas=_parse_owner_quotas(
                                 getattr(args, "owner_quota", None)))
    tracer = get_tracer()
    if token:
        log.info("worker authentication enabled")
    if session is not None:
        # default_job in the header lets resume fold the (now always
        # tagged) default-job lines back into the flat fields
        session.open(spec.as_dict(),
                     default_job=state.default_job_id)
        # stream the fleet's lifecycle spans (incl. the ones remote
        # workers ship back) next to the journal for dprf trace export
        tracer.attach_file(session.trace_path)
        # alert transitions land beside them (<session>.alerts.jsonl)
        state.alerts.attach_file(session.alerts_path)

    def on_progress(done, total, nfound):
        # done/total/nfound aggregate over EVERY non-cancelled job
        if not args.quiet:
            log.info("progress", pct=f"{100.0 * done / total:.2f}%",
                     found=nfound)

    # -- multi-tenant hooks (jobs/scheduler.py; all fire under
    # state.lock, so the journal writes below serialize).  ONE hit
    # path for every job including the default (ISSUE 10: the
    # untagged dual-write path is gone -- new journals tag every
    # units/hit line with its job id) -------------------------------

    def on_job_hit(job, ti, cand, plain):
        if job.job_id == state.default_job_id:
            raw = hl.targets[ti].raw
        else:
            raws = job.spec.get("targets") or []
            raw = raws[ti] if 0 <= ti < len(raws) else str(ti)
        log.info("cracked", job=job.job_id, target=str(raw)[:32],
                 lane=cand)
        if potfile is not None:
            potfile.add(raw, plain)
        if session is not None:
            session.record_hit(ti, cand, plain, job=job.job_id)

    def on_job_progress(jid, intervals, digest=None):
        if session is not None:
            session.record_units(intervals, job=jid, digest=digest)

    def on_job_event(kind, job):
        if session is None:
            return
        if kind == "submit":
            session.record_job(job.job_id, job.spec, owner=job.owner,
                               priority=job.priority, quota=job.quota,
                               rate=job.rate)
        elif kind == "gc":
            # age-based reap (DPRF_JOB_TTL_S): restore must not
            # resurrect the job
            session.record_job_gc(job.job_id)
        else:
            session.record_job_state(job.job_id, job.state)

    def on_worker_health(tr):
        # fleet health transitions -> {"type": "worker_health"}
        # journal records (fired by health_tick under state.lock, so
        # these writes serialize with the hit/progress writers)
        log.info("worker health", worker=tr.get("worker"),
                 frm=tr.get("from"), to=tr.get("to"))
        if session is not None:
            session.record_worker_health(
                tr.get("worker"), tr.get("from"), tr.get("to"),
                ts=tr.get("ts"), age_s=tr.get("age_s"))

    def on_profile(worker, summary):
        # kernel-profile summaries -> {"type": "profile"} journal
        # records (fired under state.lock by op_profile_push, so the
        # writes serialize with the other journal writers); `dprf
        # report` renders them post-mortem
        if session is not None:
            session.record_profile(worker, summary)

    state.on_progress = on_progress
    state.on_job_hit = on_job_hit
    state.on_job_progress = on_job_progress
    state.on_job_event = on_job_event
    state.on_worker_health = on_worker_health
    state.on_profile = on_profile
    from dprf_tpu.runtime.coordinator import preload_potfile
    # restored hits go through the default job's hit BUFFER (not just
    # the found dict) so op_hits_pull clients see them too
    state.seed_found(restored_hits)
    # the server is not up yet, but taking the lock costs nothing and
    # keeps the guarded-by invariant unconditional (dprf check locks)
    with state.lock:
        preload_potfile(state.found, hl.targets, potfile)
        preloaded = len(state.found)
    state.refresh_found_gauge()
    if preloaded:
        log.info("pre-cracked targets", count=preloaded)
    if job_setup.restored_jobs:
        # scheduler-submitted tenants from the journal: rebuild each
        # job's ledger/hits/state so the restart loses no coverage
        from dprf_tpu.jobs.build import restore_jobs
        restore_jobs(state, job_setup.restored_jobs, log=log,
                     lease_timeout=args.lease_timeout)

    host, port = _parse_hostport(args.bind)
    server = CoordinatorServer(state, host, port)
    log.info("serving job", bind=f"{server.address[0]}:{server.address[1]}",
             fingerprint=spec.fingerprint, keyspace=gen.keyspace)
    log.info("metrics endpoint",
             url=f"http://{server.address[0]}:{server.address[1]}/metrics")
    snap = None
    if session is not None:
        from dprf_tpu.telemetry import (TelemetrySnapshotter,
                                        snapshot_interval)
        snap = TelemetrySnapshotter(session.telemetry_path,
                                    state.registry,
                                    interval=snapshot_interval()).start()
    # the fleet health plane's evaluation loop (ISSUE 10): worker
    # state machine + stragglers + per-job SLOs + alert rules, every
    # DPRF_ALERT_EVAL_S seconds
    from dprf_tpu.telemetry.health import HealthMonitor
    monitor = HealthMonitor(state.health_tick).start()
    # device-memory polling (ISSUE 13): HBM gauges for /metrics, the
    # telemetry snapshots, and `dprf report`'s memory section; a
    # backend without memory stats makes every tick a no-op
    from dprf_tpu.telemetry.devstats import DevstatsPoller
    devstats_poller = DevstatsPoller(registry=state.registry).start()
    try:
        server.serve_until_done()
    finally:
        monitor.stop()
        devstats_poller.stop()
        if snap is not None:
            snap.stop()
            log.info("telemetry snapshots written",
                     path=session.telemetry_path)
        if session is not None:
            tracer.detach_file()
            log.info("trace spans written (export with `dprf trace "
                     "export`)", path=session.trace_path)
    # one snapshot under the lock: the server just shut down, but a
    # worker connection thread may still be unwinding its last op
    with state.lock:
        found = dict(state.found)
        summaries = state.scheduler.summaries()
        per_job = [(j.job_id, j.dispatcher.completed_intervals(),
                    j.dispatcher.parked_count(),
                    j.dispatcher.parked_indices(),
                    j.dispatcher.coverage_digest())
                   for j in state.scheduler.jobs()]
    if session is not None:
        for jid, intervals, _, _, digest in per_job:
            session.snapshot(intervals, job=jid, digest=digest)
        session.close()
    _print_results(found, hl.targets)
    for jid, _, parked, parked_idx, _ in per_job:
        if parked:
            log.warn("job finished with POISONED units parked; their "
                     "ranges were NOT swept", job=jid, parked=parked,
                     indices=parked_idx)
    if len(summaries) > 1:
        # tenants beyond the CLI-invoked default job: their hits
        # streamed via op_hits_pull, but leave a closing audit line
        for s in summaries:
            if s["id"] != state.default_job_id:
                log.info("tenant job finished", job=s["id"],
                         owner=s["owner"], state=s["state"],
                         found=f"{s['found']}/{s['targets']}",
                         covered=f"{s['done']}/{s['total']}")
    log.info("job finished",
             found=f"{len(found)}/{len(hl.targets)}")
    return 0 if found else 1


def cmd_worker(args, log: Log) -> int:
    import os
    import socket as _socket

    from dprf_tpu import compilecache
    from dprf_tpu.runtime.rpc import CoordinatorClient, worker_loop

    compilecache.enable(log=log)
    device = _DEVICE_ALIASES[args.device]
    host, port = _parse_hostport(args.connect)
    token = args.token or envreg.get_str("DPRF_TOKEN") or None
    client = CoordinatorClient(host, port, token=token)
    hello = client.hello()
    job = hello["job"]
    default_jid = hello.get("job_id")
    log.info("job received", engine=job["engine"], attack=job["attack"],
             keyspace=job["keyspace"], targets=len(job["targets"]),
             job=default_jid)

    def build_worker(spec: dict, jid):
        """Rebuild one job's worker from its wire spec, fingerprint-
        checked: a wordlist or rules file that differs in CONTENT (not
        just size) on this host would silently leave coverage holes --
        the unit ledger marks ranges done that this worker decoded to
        different candidates."""
        engine = get_engine(spec["engine"], device="cpu")
        targets = [engine.parse_target(raw) for raw in spec["targets"]]
        customs = {int(i): bytes.fromhex(v)
                   for i, v in spec.get("customs", {}).items()}
        gen, attack_desc, _ = _build_gen(
            spec["attack"], spec["attack_arg"], customs,
            spec.get("rules"), spec.get("max_len"), engine, device,
            log, markov=spec.get("markov"))
        ours = job_fingerprint(engine.name, attack_desc, gen.keyspace,
                               [t.digest for t in targets])
        if ours != spec["fingerprint"]:
            raise RpcError(
                f"local job {jid} disagrees with coordinator "
                "(different wordlist/rules file content on this "
                f"host?): ours={ours} theirs={spec['fingerprint']}")
        # the worker's own --devices wins (including an explicit 1 --
        # pin to a single chip); otherwise honor the job's sharding
        # request (serve/jobs submit carry "devices")
        n_dev = (args.devices if args.devices
                 else int(spec.get("devices") or 1))
        w = _select_worker(spec["engine"], device, spec["attack"], gen,
                           targets, args.batch or spec["batch"],
                           spec["hit_cap"], engine, n_dev, log)
        if (spec.get("order") or "index") != "index":
            # rank-ordered job: rebuild the EXACT bijection from the
            # wire spec (kind + pinned split -- local DPRF_ORDER_*
            # knobs must not fork the map) and decode leased rank
            # spans before the index-space sweep
            from dprf_tpu.generators.order import build_order
            from dprf_tpu.runtime.worker import OrderedWorker
            order = build_order(spec["order"], gen,
                                split=int(spec.get("order_split") or 0)
                                or None)
            w = OrderedWorker(w, order)
        # overlapped warmup: the step compile runs while leases
        # round-trip to the coordinator; worker_loop joins it before
        # the first dispatch
        warmup_async = getattr(w, "warmup_async", None)
        if warmup_async is not None:
            warmup_async()
        return w

    try:
        worker = build_worker(job, default_jid)
    except RpcError as e:
        log.error(str(e))
        return 2

    # multi-tenant fleets (jobs/scheduler.py): lease entries name
    # their job; an unfamiliar id fetches the spec over op_job_status,
    # rebuilds + fingerprint-checks it, and caches the worker.  A job
    # this host CANNOT build (wordlist missing here, divergent file
    # content) caches as None: worker_loop releases its leases and
    # keeps serving every other tenant -- one bad submission must not
    # kill the fleet.
    workers = {default_jid: worker} if default_jid is not None else {}

    def worker_for(jid):
        if jid in workers:
            return workers[jid]
        try:
            resp = client.call("job_status", job=jid)
            spec = resp["spec"]
            log.info("job received", engine=spec["engine"],
                     attack=spec["attack"], keyspace=spec["keyspace"],
                     job=jid)
            w = build_worker(spec, jid)
        except (RpcError, OSError, ValueError, KeyError) as e:
            log.error("job cannot run on this host; refusing its "
                      "leases", job=jid, error=str(e))
            w = None
        workers[jid] = w
        return w

    worker_id = args.id or f"{_socket.gethostname()}:{os.getpid()}"
    # worker_loop exits cleanly only on an explicit stop signal; any
    # bare connection drop (coordinator crash) or quarantine raises and
    # surfaces through main()'s error handler as a nonzero exit.
    done = worker_loop(client, worker, worker_id, log=log,
                       depth=args.pipeline_depth,
                       worker_for=worker_for)
    log.info("worker done", units=done)
    client.close()
    return 0


# ---------------------------------------------------------------------------

def cmd_bench(args, log: Log) -> int:
    import contextlib
    import json

    from dprf_tpu import compilecache
    from dprf_tpu.bench import run_bench, run_config
    from dprf_tpu.perfreport import compare as compare_mod

    baseline_dir = args.baseline_dir or compare_mod.repo_root()
    if args.gate_dry:
        # CI mode: audit the committed trajectory, measure nothing.
        # --ttfh redirects the audit at the TTFH_r*.json records
        verdict = compare_mod.gate_dry(
            baseline_dir, window=args.gate_window,
            pattern=(compare_mod.TTFH_PATTERN if args.ttfh
                     else "BENCH_r*.json"))
        print(json.dumps({"gate": verdict}))
        if verdict["verdict"] == "regression":
            log.error("bench gate: REGRESSION in the committed "
                      "trajectory", ratio=verdict["ratio"],
                      tolerance=verdict["tolerance"])
            return 1
        log.info("bench gate", verdict=verdict["verdict"],
                 window=verdict["window"])
        return 0
    compilecache.enable(log=log)
    ctx = contextlib.nullcontext()
    if args.profile:
        # kernel profile of the measurement window, through the
        # single-flight capture owner; the analyzed top-ops +
        # fractions fold into the result JSON below
        from dprf_tpu.telemetry import profiler as profiler_mod
        ctx = profiler_mod.get_profiler().session(
            args.profile, owner="bench", log=log)
    with ctx:
        if args.ttfh:
            from dprf_tpu.bench import run_ttfh
            res = run_ttfh(engine=args.engine, mask=args.mask,
                           plants=args.plants, log=log)
        elif args.targets_sweep:
            from dprf_tpu.bench import run_targets_sweep
            sizes = [int(s) for s in
                     args.targets_sizes.split(",") if s.strip()]
            res = run_targets_sweep(engine=args.engine, mask=args.mask,
                                    sizes=sizes, batch=args.batch,
                                    seconds=args.seconds, log=log)
        elif args.devices > 1:
            from dprf_tpu.bench import run_scaling
            res = run_scaling(engine=args.engine, mask=args.mask,
                              n_devices=args.devices,
                              batch_per_device=args.batch,
                              seconds=args.seconds, inner=args.inner,
                              impl=args.impl, ablate=args.ablate,
                              log=log)
        elif args.config is not None:
            res = run_config(args.config,
                             device=_DEVICE_ALIASES[args.device],
                             seconds=args.seconds, batch=args.batch,
                             bcrypt_cost=args.bcrypt_cost,
                             unit_strides=args.unit_strides, log=log)
        else:
            res = run_bench(engine=args.engine,
                            device=_DEVICE_ALIASES[args.device],
                            mask=args.mask, batch=args.batch,
                            seconds=args.seconds, impl=args.impl, log=log)
    if args.profile:
        # fold the kernel view into the BENCH record: top ops,
        # class fractions, phase split, and the measured-vs-analyzed
        # cost divergence (the bench knows its candidate count).
        # --config/--devices results carry the engine + "tested"
        # count instead of the single-run batch fields
        cands = res.get("batches", 0) * res.get("batch", 0) \
            * max(1, res.get("inner", 1)) or res.get("tested", 0)
        summary = profiler_mod.analyze_trace(
            args.profile, engine=res.get("engine") or args.engine,
            candidates=cands or None)
        res["profile"] = {
            "top_ops": (summary.get("top_ops") or [])[:10],
            "fractions": summary.get("fractions"),
            "phases": summary.get("phases"),
            "device_s": summary.get("device_s"),
            "divergence": summary.get("divergence"),
            "error": summary.get("error"),
        }
    if args.gate:
        # regression sentinel: the verdict rides the result JSON (CI
        # parses it) and a regression exits non-zero.  Scaling mode
        # gates against the SCALING_r*.json efficiency trajectory, so
        # a multichip regression alarms exactly like a throughput one.
        if args.ttfh:
            pattern = compare_mod.TTFH_PATTERN
        elif args.targets_sweep:
            pattern = compare_mod.TARGETS_PATTERN
        elif args.devices > 1:
            pattern = compare_mod.SCALING_PATTERN
        else:
            pattern = "BENCH_r*.json"
        res["gate"] = compare_mod.gate_repo(res, baseline_dir,
                                            window=args.gate_window,
                                            pattern=pattern)
    print(json.dumps(res))
    if args.gate and res["gate"]["verdict"] == "regression":
        log.error("bench gate: REGRESSION vs the baseline window",
                  ratio=res["gate"]["ratio"],
                  tolerance=res["gate"]["tolerance"])
        return 1
    return 0


def _tune_generator(attack: str, args):
    """Generator shaping a tuning probe.  wordlist/combinator reuse
    bench's synthetic in-memory word source (config 3's trick) so the
    sweep measures the device pipeline, not disk I/O; the source is
    deterministic, so cache records stay comparable across runs."""
    if attack == "mask":
        return MaskGenerator(args.mask)
    from dprf_tpu.bench import _synthetic_words
    if attack == "wordlist":
        from dprf_tpu.generators.wordlist import WordlistRulesGenerator
        from dprf_tpu.rules.parser import load_rules
        return WordlistRulesGenerator(_synthetic_words(args.words),
                                      load_rules(args.rules),
                                      max_len=24)
    from dprf_tpu.generators.combinator import CombinatorGenerator
    words = _synthetic_words(args.words)
    return CombinatorGenerator(words, words, max_len=24)


#: superstep `inner` fusion-window rungs (dprf tune --rungs inner) --
#: unordered knob values, so sweep_values probes them all
_INNER_RUNGS = (4, 8, 16, 32, 64, 128, 256)
#: Pallas kernel tile-size rungs (sublanes per tile; tile = sub * 128)
_SUB_RUNGS = (8, 16, 32, 64, 128)


def _tune_one(engine_name: str, args, device: str, log: Log) -> dict:
    """Sweep one engine's rungs and record the winner; returns the
    result JSON dict.  ``--rungs batch`` climbs the geometric batch
    ladder; ``--rungs inner`` sweeps the multi-batch superstep fusion
    window (workers' SUPER_CAP); ``--rungs sub`` sweeps the Pallas
    kernel tile size.  Raises ValueError for engines this invocation
    cannot tune (salted targets without --hashfile, every rung
    failing) -- `--all` reports those as skipped."""
    from dprf_tpu import tune as tune_mod
    from dprf_tpu.tune import (geometric_ladder, record_tuned_batch,
                               record_tuned_value, sweep, sweep_values)

    attack = getattr(args, "attack", "mask")
    rungs = getattr(args, "rungs", "batch")
    oracle = get_engine(engine_name, device="cpu")
    gen = _tune_generator(attack, args)
    if args.hashfile:
        hl = _load_targets(oracle, args.hashfile, log)
        if hl is None:
            raise ValueError("no valid targets in hashfile")
        targets = hl.targets
    else:
        try:
            # unmatchable digest (bench's trick): tuning needs load,
            # not cracks
            targets = [oracle.parse_target("ff" * oracle.digest_size)]
        except Exception:
            raise ValueError(
                "targets need salts/params; pass --hashfile with real "
                "target lines to tune against") from None

    extras = _tune_extras(attack, hit_cap=args.hit_cap,
                          n_rules=getattr(gen, "n_rules", None))

    def make_worker(batch: int):
        if device == "cpu":
            return CpuWorker(oracle, gen, targets, chunk=batch)
        return _select_worker(engine_name, device, attack, gen, targets,
                              batch, args.hit_cap, oracle, 1, log)

    knob = None
    if rungs == "batch":
        ladder = geometric_ladder(args.min_batch, args.max_batch,
                                  args.ladder_factor)
        log.info("tuning", engine=engine_name, device=device,
                 attack=attack,
                 ladder=",".join(str(b) for b in ladder))
        result = sweep(make_worker, gen.keyspace, ladder,
                       probe_seconds=args.seconds,
                       compile_budget_s=args.compile_budget, log=log)
        path = record_tuned_batch(engine_name, attack, device, result,
                                  extras=extras)
        key = tune_mod.make_key(engine_name, attack=attack,
                                device=device, **extras)
    else:
        knob = rungs
        # knob sweeps run at the already-tuned (or default) batch, so
        # the winner composes with a prior `--rungs batch` record;
        # --max-batch still caps it (CI smokes keep probe units small)
        batch = min(args.max_batch,
                    tune_mod.lookup_tuned_batch(
                        engine_name, attack=attack, device=device,
                        extras=extras)
                    or DEFAULT_BATCH)
        if rungs == "inner":
            values = [v for v in _INNER_RUNGS]

            def mk_inner(v: int):
                w = make_worker(batch)
                # SUPER_CAP bounds _super_inner's window; the instance
                # override beats the class default / env knob for this
                # probe only
                w.SUPER_CAP = int(v)
                return w

            log.info("tuning", engine=engine_name, device=device,
                     attack=attack, knob="inner", batch=batch,
                     values=",".join(str(v) for v in values))
            result = sweep_values(
                mk_inner, values, gen.keyspace,
                probe_seconds=args.seconds,
                compile_budget_s=args.compile_budget,
                unit_strides=max(values), log=log, label="inner")
        else:                    # rungs == "sub"
            if attack != "mask" or device == "cpu":
                raise ValueError("--rungs sub tunes the Pallas mask "
                                 "kernel tile; use --attack mask with "
                                 "a device backend")
            from dprf_tpu.ops.pallas_mask import pallas_mode
            mode = pallas_mode()
            if mode is None:
                raise ValueError("Pallas kernels unavailable on this "
                                 "backend (see DPRF_PALLAS)")
            try:
                dev_engine = get_engine(engine_name, device="jax")
            except KeyError:
                raise ValueError(
                    f"no jax engine named {engine_name!r}") from None
            from dprf_tpu.runtime.worker import PallasMaskWorker
            values = [v for v in _SUB_RUNGS if v * 128 <= batch]

            def mk_sub(v: int):
                w = PallasMaskWorker(dev_engine, gen, targets,
                                     batch=batch,
                                     hit_capacity=args.hit_cap,
                                     oracle=oracle, sub=v, **mode)
                w.warmup()
                return w

            log.info("tuning", engine=engine_name, device=device,
                     attack=attack, knob="sub", batch=batch,
                     values=",".join(str(v) for v in values))
            result = sweep_values(
                mk_sub, values, gen.keyspace,
                probe_seconds=args.seconds,
                compile_budget_s=args.compile_budget, log=log,
                label="sub")
        path = record_tuned_value(engine_name, knob, attack, device,
                                  result, extras=extras)
        key = tune_mod.make_key(engine_name, attack=attack,
                                device=device, knob=knob, **extras)
    log.info("tuned", engine=engine_name,
             **{knob or "batch": result.batch},
             rate=f"{result.rate_hs:,.0f}/s", cache=path)
    out = {
        "engine": engine_name,
        "device": device,
        "attack": attack,
        "env": tune_mod.env_fingerprint(engine_name, device),
        "key": key,
        "batch": result.batch,
        "rate_hs": result.rate_hs,
        "compile_s": round(result.compile_s, 3),
        "swept": [p.as_dict() for p in result.swept],
        "cache": path,
    }
    if knob:
        out["knob"] = knob
        out["value"] = result.batch
    return out


def cmd_tune(args, log: Log) -> int:
    """Sweep the batch ladder through the REAL worker path and record
    the winner in the persistent tuning cache, where `--batch auto`
    jobs and bench warm-start from it.  ``--all`` sweeps every
    registered device engine (the fleet-image pre-population pass);
    analyzed program costs land in the program registry as a side
    effect of each rung (telemetry/programs.py)."""
    import json as _json

    from dprf_tpu import compilecache

    if not args.all and not args.engine:
        log.error("pass --engine NAME (or --all to sweep every "
                  "registered engine)")
        return 2
    device = _DEVICE_ALIASES[args.device]
    if args.tune_dir:
        os.environ["DPRF_TUNE_DIR"] = args.tune_dir
    compilecache.enable(log=log)
    if not args.all:
        try:
            print(_json.dumps(_tune_one(args.engine, args, device, log)))
        except ValueError as e:
            log.error(str(e), engine=args.engine)
            return 2
        return 0
    # --all: one sweep per registered engine; a skipped or failed
    # engine is a report line, never the end of the fleet bake
    results, skipped = [], []
    names = sorted(engine_names("jax" if device == "jax" else "cpu"))
    for name in names:
        try:
            results.append(_tune_one(name, args, device, log))
        except Exception as e:   # noqa: BLE001 -- per-engine isolation
            log.warn("tune skipped", engine=name, error=str(e))
            skipped.append({"engine": name, "error": str(e)})
    from dprf_tpu.telemetry import programs as programs_mod
    programs_mod.analyze_pending()
    print(_json.dumps({
        "tuned": len(results),
        "skipped": len(skipped),
        "engines": len(names),
        "programs_analyzed": len(programs_mod.get_programs().snapshot()),
        "results": results,
        "skips": skipped,
    }))
    return 0 if results else 1


def cmd_prewarm(args, log: Log) -> int:
    """Populate the persistent compile cache ahead of time: iterate
    (engine, attack, batch) specs -- tune-cache-seeded and/or an
    explicit --engines/--attacks list -- build each worker's step
    through the real factory path, and lower+compile it WITHOUT
    dispatching.  Bake the cache dir into a fleet image and every
    worker's warmup becomes a cache load."""
    import json as _json

    from dprf_tpu import compilecache, engine_names
    from dprf_tpu.compilecache.prewarm import (RESULT_MARKER,
                                               PrewarmSpec,
                                               explicit_specs,
                                               render_table,
                                               run_prewarm,
                                               tune_seeded_specs)

    d = compilecache.enable(dir=args.cache_dir, log=log)
    if d is None:
        log.error("persistent compile cache unavailable (disabled or "
                  "unwritable dir); nothing to prewarm into")
        return 2
    if args.spec_json:
        # child-process mode (prewarm --jobs fan-out): compile exactly
        # these specs, report one marker line each
        from dprf_tpu.compilecache.prewarm import prewarm_one
        specs = [PrewarmSpec.from_dict(s)
                 for s in _json.loads(args.spec_json)]
        for spec in specs:
            res = prewarm_one(spec, log=log)
            print(RESULT_MARKER + _json.dumps(res.as_dict()), flush=True)
        return 0
    attacks = [a.strip() for a in args.attacks.split(",") if a.strip()]
    for a in attacks:
        if a not in ("mask", "wordlist", "combinator", "hybrid-wm",
                     "hybrid-mw"):
            log.error(f"unknown attack shape {a!r} (mask, wordlist, "
                      "combinator, hybrid-wm, hybrid-mw)")
            return 2
    if args.engines:
        engines = (sorted(engine_names("jax"))
                   if args.engines == "all"
                   else [e.strip() for e in args.engines.split(",")
                         if e.strip()])
        specs = explicit_specs(engines, attacks, hit_cap=args.hit_cap,
                               mask=args.mask, rules=args.rules,
                               wordlist=args.wordlist,
                               combinator=args.combinator,
                               batch=args.batch,
                               devices=args.devices)
    else:
        specs = tune_seeded_specs("jax", hit_cap=args.hit_cap,
                                  mask=args.mask, rules=args.rules,
                                  wordlist=args.wordlist,
                                  devices=args.devices, log=log)
        if not specs:
            log.error("tuning cache has no device entries to seed "
                      "from; pass --engines (e.g. --engines md5,ntlm "
                      "or --engines all)")
            return 2
    log.info("prewarming", specs=len(specs), jobs=args.jobs, cache=d)
    results = run_prewarm(specs, jobs=args.jobs, log=log)
    if not args.quiet:
        print(render_table(results), file=sys.stderr)
    skipped = [r for r in results if r.skipped]
    ok = [r for r in results if not r.error and not r.skipped]
    print(_json.dumps({
        "cache_dir": d,
        "specs": len(results),
        "compiled": len(ok),
        "hits": sum(1 for r in ok if r.cache == "hit"),
        "misses": sum(1 for r in ok if r.cache == "miss"),
        "skipped": len(skipped),
        "errors": len(results) - len(ok) - len(skipped),
        "results": [r.as_dict() for r in results],
    }))
    return 0 if ok or skipped or not results else 1


def cmd_retry_parked(args, log: Log) -> int:
    """Admin client for rpc.op_retry_parked: requeue a live job's
    poisoned/parked units with a fresh retry budget."""
    import json as _json

    from dprf_tpu.runtime.rpc import CoordinatorClient

    host, port = _parse_hostport(args.connect)
    token = args.token or envreg.get_str("DPRF_TOKEN") or None
    client = CoordinatorClient(host, port, timeout=args.timeout,
                               token=token)
    try:
        client.hello()             # answers the auth challenge if any
        resp = client.call("retry_parked")
    finally:
        client.close()
    retried = int(resp.get("retried", 0))
    log.info("parked units requeued", retried=retried)
    print(_json.dumps({"retried": retried}))
    return 0


def cmd_top(args, log: Log) -> int:
    """Live fleet view (`dprf top --connect host:port`): renders the
    coordinator's flight recorder + lease table every --interval
    seconds -- per-worker state, current unit, lease deadline
    countdown, and recent lifecycle spans."""
    import time as _time

    from dprf_tpu.runtime.rpc import CoordinatorClient
    from dprf_tpu.telemetry.trace import render_top

    host, port = _parse_hostport(args.connect)
    token = args.token or envreg.get_str("DPRF_TOKEN") or None
    client = CoordinatorClient(host, port, timeout=args.timeout,
                               token=token)
    try:
        if token:
            client.hello()     # answer the auth challenge first
        prev = None
        frames = 0
        cursor = None
        # --follow keeps a client-side span buffer and asks only for
        # spans past the cursor; a resync (cursor fell off the
        # coordinator's ring) replaces the buffer with the full tail
        from collections import deque
        buf: deque = deque(maxlen=max(args.spans, 64))
        while True:
            if args.follow:
                resp = client.call("trace_tail", n=args.spans,
                                   since=cursor, trace=args.trace)
                if resp.get("resync") or "cursor" not in resp:
                    # resync, or a pre-cursor coordinator that ignored
                    # `since` and sent the full tail: REPLACE the
                    # buffer (appending would duplicate every span)
                    buf.clear()
                buf.extend(resp.get("spans") or [])
                cursor = resp.get("cursor") or cursor
                resp = dict(resp, spans=list(buf))
            else:
                resp = client.call("trace_tail", n=args.spans,
                                   trace=args.trace)
            text = render_top(resp, prev)
            if not args.no_clear and sys.stdout.isatty():
                sys.stdout.write("\x1b[H\x1b[2J")
            print(text)
            sys.stdout.flush()
            prev = (_time.monotonic(), resp.get("status") or {})
            frames += 1
            if args.iterations and frames >= args.iterations:
                break
            if (resp.get("status") or {}).get("stop"):
                log.info("job finished")
                break
            _time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        pass
    finally:
        client.close()
    return 0


def _jobs_client(args, log: Log):
    """Authenticated client for the jobs/trace admin commands."""
    from dprf_tpu.runtime.rpc import CoordinatorClient

    host, port = _parse_hostport(args.connect)
    token = args.token or envreg.get_str("DPRF_TOKEN") or None
    client = CoordinatorClient(host, port, timeout=args.timeout,
                               token=token)
    if token:
        client.hello()             # answer the auth challenge first
    return client


def cmd_jobs(args, log: Log) -> int:
    """`dprf jobs submit/list/status/cancel/pause/resume/hits`: the
    multi-tenant admin surface over a running coordinator's job
    scheduler (rpc.op_job_* / op_hits_pull).  One helper per
    subcommand: each RPC op's response lives in its own scope, so the
    protocol checker's per-op key dataflow stays exact."""
    client = _jobs_client(args, log)
    try:
        if args.jobs_cmd == "submit":
            return _jobs_submit(client, args, log)
        if args.jobs_cmd == "list":
            return _jobs_list(client, args)
        if args.jobs_cmd == "hits":
            return _jobs_hits(client, args, log)
        return _jobs_admin(client, args, log)
    finally:
        client.close()


def _jobs_submit(client, args, log: Log) -> int:
    import json as _json

    tf = getattr(args, "targets_file", None)
    targets_fingerprint = None
    if tf is not None:
        if args.hashfile is not None:
            log.error("pass a hashfile positional OR --targets-file, "
                      "not both")
            return 2
        from dprf_tpu.targets import TargetStore
        store = TargetStore.from_file(
            get_engine(args.engine, device="cpu"), tf, log=log)
        if not store.targets:
            log.error("no valid targets in targets file", path=tf)
            return 2
        lines = store.lines()
        targets_fingerprint = store.fingerprint
    elif args.hashfile is None:
        log.error("no target hashes: pass a hashfile or --targets-file")
        return 2
    else:
        with open(args.hashfile, encoding="utf-8",
                  errors="replace") as fh:
            lines = [ln.strip() for ln in fh if ln.strip()]
    spec = {
        "engine": args.engine,
        "attack": args.attack,
        "attack_arg": args.attack_arg,
        "customs": {str(i): v.hex()
                    for i, v in _customs(args).items()},
        "rules": args.rules,
        "markov": args.markov,
        "order": getattr(args, "order", "index"),
        "targets": lines,
        "targets_fingerprint": targets_fingerprint,
        "unit_size": args.unit_size,
        "unit_seconds": args.unit_seconds,
        "batch": args.batch or DEFAULT_BATCH,
        "hit_cap": args.hit_cap,
        "devices": max(1, args.devices or 1),
    }
    owner = args.owner or os.environ.get("USER") or "?"
    resp = client.call("job_submit", spec=spec, owner=owner,
                       priority=args.priority,
                       quota=args.quota, rate=args.rate)
    log.info("job submitted", job=resp.get("job_id"),
             keyspace=resp.get("keyspace"),
             fingerprint=resp.get("fingerprint"))
    print(_json.dumps({"job": resp.get("job_id"),
                       "keyspace": resp.get("keyspace"),
                       "fingerprint": resp.get("fingerprint")}))
    return 0


def _jobs_list(client, args) -> int:
    import json as _json

    resp = client.call("job_list")
    jobs = resp.get("jobs") or []
    if not args.quiet:
        print(f"{'JOB':6s} {'OWNER':12s} {'PRIO':>4s} "
              f"{'STATE':10s} {'COVERED':>18s} {'FOUND':>9s} "
              f"{'LEASES':>7s}", file=sys.stderr)
        for j in jobs:
            cov = f"{j['done']}/{j['total']}"
            print(f"{j['id']:6s} {j['owner'][:12]:12s} "
                  f"{j['priority']:>4d} {j['state']:10s} "
                  f"{cov:>18s} "
                  f"{j['found']}/{j['targets']:>4d} "
                  f"{j['leases']:>7d}", file=sys.stderr)
    print(_json.dumps(jobs))
    return 0


def _jobs_admin(client, args, log: Log) -> int:
    """status / cancel / pause / resume: one job in, its summary out."""
    import json as _json

    cmd = args.jobs_cmd
    if cmd == "status":
        resp = client.call("job_status", job=args.job)
    elif cmd == "cancel":
        resp = client.call("job_cancel", job=args.job)
    else:
        resp = client.call("job_pause", job=args.job,
                           resume=cmd == "resume")
    summary = resp.get("job") or {}
    log.info(f"job {cmd}", job=summary.get("id"),
             state=summary.get("state"))
    print(_json.dumps(summary))
    return 0


def _jobs_hits(client, args, log: Log) -> int:
    """Cursor-based per-job hit pull; --follow keeps polling until the
    job reaches a terminal state."""
    import time as _time

    spec = _jobs_client_spec(client, args.job)
    raws = (spec or {}).get("targets") or []
    cursor = max(0, args.cursor)
    while True:
        resp = client.call("hits_pull", job=args.job, cursor=cursor)
        for h in resp.get("hits") or ():
            ti = h.get("target")
            raw = (raws[ti] if isinstance(ti, int)
                   and 0 <= ti < len(raws) else str(ti))
            from dprf_tpu.runtime.potfile import encode_plain
            print(f"{raw}:"
                  f"{encode_plain(bytes.fromhex(h['plaintext']))}",
                  flush=True)
        cursor = resp.get("cursor") or cursor
        state = resp.get("state")
        if not args.follow or state in ("done", "cancelled"):
            log.info("hits pulled", job=args.job, cursor=cursor,
                     found=resp.get("found"),
                     targets=resp.get("targets"), state=state)
            return 0
        _time.sleep(max(0.1, args.interval))


def _jobs_client_spec(client, job_id: str):
    """The job's wire spec via op_job_status (target raws for
    rendering pulled hits); None when the job is unknown."""
    from dprf_tpu.runtime.rpc import RpcError
    try:
        resp = client.call("job_status", job=job_id)
    except RpcError:
        return None
    return resp.get("spec")


def cmd_trace(args, log: Log) -> int:
    """`dprf trace export SESSION`: session span stream -> Chrome-trace
    JSON (Perfetto-loadable), plus a lifecycle summary -- how many unit
    traces, reissues, orphan spans (there should be none), and
    incomplete lifecycles.  `dprf trace pull --connect` is the
    incident-response path: collect the fleet's flight-recorder rings
    from a live coordinator into a file export understands."""
    import json as _json

    from dprf_tpu.telemetry import trace as trace_mod

    if args.trace_cmd == "pull":
        return _trace_pull(args, log)

    path = trace_mod.trace_path(args.session)
    spans = trace_mod.load_trace(path)
    if not spans:
        log.error("no spans found (did the job run with --session?)",
                  path=path)
        return 2
    doc = trace_mod.export_chrome_trace(spans)
    base = (args.session[:-len(trace_mod.TRACE_SUFFIX)]
            if args.session.endswith(trace_mod.TRACE_SUFFIX)
            else args.session)
    out = args.out or base + ".perfetto.json"
    with open(out, "w", encoding="utf-8") as fh:
        _json.dump(doc, fh)
    report = trace_mod.lifecycle_report(spans)
    reissued = sum(1 for d in report["details"].values()
                   if d["reissues"])
    log.info("trace exported", out=out, spans=report["spans"],
             traces=report["traces"], reissued_units=reissued,
             orphans=report["orphans"],
             incomplete=len(report["incomplete"]))
    if report["orphans"]:
        log.warn("orphan spans present: a parent link crossed the RPC "
                 "boundary without its context (bug?)")
    print(_json.dumps({
        "out": out,
        "spans": report["spans"],
        "traces": report["traces"],
        "reissued_units": reissued,
        "orphans": report["orphans"],
        "incomplete": len(report["incomplete"]),
    }))
    return 0


def _trace_pull(args, log: Log) -> int:
    """`dprf trace pull --connect`: arm a fleet-wide ring pull (each
    live worker ships its local flight recorder with its next lease
    round trip), wait, then page the coordinator's merged ring out
    through op_trace_pull and write a .trace.jsonl stream."""
    import json as _json
    import time as _time

    client = _jobs_client(args, log)
    try:
        first = client.call("trace_pull", arm=not args.no_arm,
                            since=None, n=args.spans)
        if not args.no_arm:
            log.info("pull armed; waiting for worker rings",
                     epoch=first.get("epoch"), wait_s=args.wait)
            _time.sleep(max(0.0, args.wait))
        # page the ring: span-id cursor, stop when a page comes back
        # short (tail reached)
        spans: list = []
        cursor = None
        while True:
            resp = client.call("trace_pull", arm=False, since=cursor,
                               n=args.spans)
            page = resp.get("spans") or []
            if resp.get("resync"):
                spans = []        # cursor fell off the ring: restart
            spans.extend(page)
            cursor = resp.get("cursor") or cursor
            if len(page) < args.spans:
                break
        with open(args.out, "w", encoding="utf-8") as fh:
            for s in spans:
                fh.write(_json.dumps(s, separators=(",", ":"),
                                     default=str) + "\n")
        procs = sorted({str(s.get("proc")) for s in spans})
        log.info("trace pulled", out=args.out, spans=len(spans),
                 procs=len(procs))
        print(_json.dumps({"out": args.out, "spans": len(spans),
                           "procs": procs}))
        return 0
    finally:
        client.close()


def cmd_report(args, log: Log) -> int:
    """`dprf report SESSION`: render the performance-attribution
    report from the session's artifacts (perfreport/report.py) --
    a post-mortem needs no live coordinator."""
    import json as _json

    from dprf_tpu.perfreport import build_report, render_report

    doc = build_report(args.session)
    if doc is None:
        log.error("no session artifacts found (journal, .trace.jsonl "
                  "or .telemetry.jsonl)", session=args.session)
        return 2
    if args.json:
        print(_json.dumps(doc, sort_keys=True))
    else:
        print(render_report(doc))
    return 0


def cmd_audit(args, log: Log) -> int:
    """`dprf audit SESSION`: reconstruct the coverage story from the
    session's artifacts (perfreport/audit.py) and gate on it -- exit
    0 only when the verdict is clean, so CI and the chaos harness can
    use the exit code directly."""
    import json as _json

    from dprf_tpu.perfreport import build_audit, render_audit

    doc = build_audit(args.session)
    if doc is None:
        log.error("no session artifacts found (journal or "
                  ".trace.jsonl)", session=args.session)
        return 2
    if args.json:
        print(_json.dumps(doc, sort_keys=True))
    else:
        print(render_audit(doc))
    return 0 if doc["verdict"] == "clean" else 3


def _fmt_eta(v) -> str:
    if v is None:
        return "?"
    if v >= 3600:
        return f"{v / 3600:.1f}h"
    if v >= 120:
        return f"{v / 60:.1f}m"
    return f"{v:.0f}s"


def cmd_health(args, log: Log) -> int:
    """`dprf health --connect`: the fleet health plane's live view --
    per-worker state machine + payloads, per-job SLOs, active alerts
    (rpc.op_health)."""
    import json as _json

    client = _jobs_client(args, log)
    try:
        resp = client.call("health")
    finally:
        client.close()
    workers = resp.get("workers") or {}
    jobs = resp.get("jobs") or []
    active = resp.get("alerts") or []
    if args.json:
        print(_json.dumps({"workers": workers, "jobs": jobs,
                           "alerts": active}, sort_keys=True))
        return 0
    firing = [a for a in active if a.get("state") == "firing"]
    if firing:
        print(f"FIRING: {', '.join(a['rule'] for a in firing)}")
    print(f"{'WORKER':20s} {'STATE':>9s} {'AGE':>6s} {'RATE':>12s} "
          f"{'STRAG':>5s} {'ENGINE':>8s} {'Q':>3s}")
    for w in sorted(workers):
        rec = workers[w]
        pl = rec.get("payload") or {}
        rate = rec.get("rate_hs")
        print(f"{w[:20]:20s} {str(rec.get('state'))[:9]:>9s} "
              f"{rec.get('age_s', 0):>5.0f}s "
              f"{(f'{rate:,.0f}/s' if rate else '-'):>12s} "
              f"{('yes' if rec.get('straggler') else '-'):>5s} "
              f"{str(pl.get('engine') or '-')[:8]:>8s} "
              f"{str(pl.get('queue') if pl.get('queue') is not None else '-'):>3s}")
    print()
    print(f"{'JOB':6s} {'STATE':>9s} {'COVERED':>20s} {'RATE':>12s} "
          f"{'ETA':>7s} {'TTFH':>7s} {'STALL':>5s}")
    for j in jobs:
        cov = f"{j.get('covered', 0)}/{j.get('total', 0)}"
        rate = j.get("rate_ips")
        ttfh = j.get("ttfh_s")
        print(f"{str(j.get('job'))[:6]:6s} "
              f"{str(j.get('state'))[:9]:>9s} {cov:>20s} "
              f"{(f'{rate:,.0f}/s' if rate else '-'):>12s} "
              f"{_fmt_eta(j.get('eta_s')):>7s} "
              f"{(f'{ttfh:.1f}s' if ttfh is not None else '-'):>7s} "
              f"{('YES' if j.get('stalled') else '-'):>5s}")
    log.info("fleet health", workers=len(workers), jobs=len(jobs),
             firing=len(firing))
    return 0


def cmd_alerts(args, log: Log) -> int:
    """`dprf alerts --connect`: active alerts + the recent
    pending/firing/resolved transition history (rpc.op_alerts)."""
    import json as _json

    client = _jobs_client(args, log)
    try:
        resp = client.call("alerts", n=args.history)
    finally:
        client.close()
    active = resp.get("alerts") or []
    history = resp.get("history") or []
    if args.json:
        print(_json.dumps({"alerts": active, "history": history},
                          sort_keys=True))
        return 0
    if not active:
        print("no active alerts")
    else:
        print(f"{'RULE':20s} {'STATE':>8s} {'SEV':>8s} {'FOR':>7s} "
              f"{'VALUE':>10s} {'LABELS'}")
        for a in active:
            lv = ",".join(f"{k}={v}" for k, v in
                          sorted((a.get("labels") or {}).items()))
            print(f"{str(a.get('rule'))[:20]:20s} "
                  f"{str(a.get('state')):>8s} "
                  f"{str(a.get('severity'))[:8]:>8s} "
                  f"{a.get('since_s', 0):>6.0f}s "
                  f"{a.get('value', 0):>10.3g} {lv}")
    if history:
        print()
        print("recent transitions:")
        for e in history[-args.history:]:
            lv = ",".join(str(v) for _, v in
                          sorted((e.get("labels") or {}).items()))
            print(f"  {e.get('rule')}({lv}) -> {e.get('state')} "
                  f"value={e.get('value')}")
    log.info("alerts", active=len(active), history=len(history))
    return 0


def cmd_token(args, log: Log) -> int:
    """`dprf token --owner NAME`: mint a tenant token from the admin
    secret (rpc.owner_token).  Hand the printed token to the tenant;
    the coordinator re-derives it from the admin secret at hello, so
    no token table exists anywhere."""
    from dprf_tpu.runtime.rpc import owner_token

    secret = args.token or envreg.get_str("DPRF_TOKEN") or None
    if not secret:
        log.error("minting needs the coordinator's admin secret "
                  "(--token or $DPRF_TOKEN)")
        return 2
    print(owner_token(secret, args.owner))
    return 0


def cmd_programs(args, log: Log) -> int:
    """`dprf programs --connect`: the fleet's compiled-program table
    (op_programs) -- XLA-derived cost/memory per executable, merged
    from the coordinator's compile sites and worker heartbeats."""
    import json as _json

    from dprf_tpu.telemetry import programs as programs_mod

    client = _jobs_client(args, log)
    try:
        resp = client.call("programs")
    finally:
        client.close()
    records = resp.get("programs") or []
    if args.json:
        print(_json.dumps(records, sort_keys=True))
    else:
        print(programs_mod.render_table(records))
    log.info("programs", records=len(records))
    return 0


def cmd_profile(args, log: Log) -> int:
    """`dprf profile`: kernel-level profiling (ISSUE 15).  Local mode
    analyzes an existing capture (dependency-free perfetto parse);
    --connect requests a bounded capture window on a fleet worker
    over op_profile and polls until the analyzed summary arrives."""
    import json as _json

    from dprf_tpu.telemetry import profiler as profiler_mod

    if args.connect:
        return _profile_connect(args, log, profiler_mod, _json)
    if not args.target:
        log.error("profile: give a capture dir / trace file to "
                  "analyze, or --connect for a live capture")
        return 2
    doc = profiler_mod.analyze_trace(args.target, engine=args.engine,
                                     top=args.top)
    if args.json:
        print(_json.dumps(doc, sort_keys=True))
    else:
        print(profiler_mod.render_summary(doc))
    return 1 if doc.get("error") else 0


def _profile_connect(args, log: Log, profiler_mod, _json) -> int:
    """The capture+pull flow: op_profile request -> the worker's next
    lease/heartbeat carries the window -> it sweeps through the
    window, analyzes locally, pushes the summary -> we poll the
    coordinator's summary table for our request id."""
    import time as _time

    client = _jobs_client(args, log)
    try:
        if args.fetch:
            resp = client.call("profile", worker=args.worker)
            summaries = resp.get("summaries") or {}
            if args.json:
                print(_json.dumps(summaries, sort_keys=True))
            else:
                for w in sorted(summaries):
                    for s in summaries[w]:
                        print(f"--- {w}")
                        print(profiler_mod.render_summary(s))
            log.info("profile summaries",
                     workers=len(summaries))
            return 0
        resp = client.call("profile", action="request",
                           worker=args.worker, seconds=args.seconds)
        rid = resp.get("request_id")
        worker = resp.get("worker")
        log.info("capture requested", worker=worker, request=rid)
        deadline = _time.monotonic() + max(1.0, args.wait)
        summary = None
        while _time.monotonic() < deadline:
            try:
                st = client.call("profile", worker=worker)
            except (OSError, RpcError):
                # the serve session can legitimately end mid-poll
                # (short job: the drain's read-grace covers the
                # normal push->read window, but a killed or crashed
                # coordinator shouldn't turn into a CLI traceback)
                log.warn("coordinator went away mid-poll",
                         worker=worker, request=rid)
                break
            for s in (st.get("summaries") or {}).get(worker, []):
                if s.get("request_id") == rid:
                    summary = s
                    break
            if summary is not None:
                break
            _time.sleep(0.5)
    finally:
        client.close()
    if summary is None:
        log.error("no summary arrived in time (worker still "
                  "compiling/warming the profiler deps, dead, or "
                  "never leasing?)", worker=worker,
                  waited=f"{args.wait:.0f}s")
        return 1
    if args.json:
        print(_json.dumps(summary, sort_keys=True))
    else:
        print(profiler_mod.render_summary(summary))
    return 1 if summary.get("error") else 0


def cmd_metrics(args, log: Log) -> int:
    """Scrape a running coordinator: plain HTTP GET on the RPC port
    (no client library; works for curl/Prometheus too).  --json asks
    the authenticated RPC op for the structured snapshot instead."""
    host, port = _parse_hostport(args.connect)
    if args.json:
        import json as _json

        from dprf_tpu.runtime.rpc import CoordinatorClient
        token = args.token or envreg.get_str("DPRF_TOKEN") or None
        client = CoordinatorClient(host, port, timeout=args.timeout,
                                   token=token)
        try:
            if token:
                client.hello()       # answer the auth challenge first
            resp = client.call("metrics", format="json")
        finally:
            client.close()
        print(_json.dumps(resp.get("metrics", {}), indent=2,
                          sort_keys=True))
        return 0
    from dprf_tpu.telemetry import scrape_metrics
    sys.stdout.write(scrape_metrics(host, port, timeout=args.timeout))
    return 0


def cmd_show(args, log: Log) -> int:
    """hashcat --show parity: hash:plain for every potfile-cracked
    target of the hashlist."""
    from dprf_tpu.runtime.potfile import encode_plain

    engine = get_engine(args.engine, device="cpu")
    hl = _load_targets(engine, args.hashfile, log)
    if hl is None:
        return 2
    pot = Potfile(args.potfile)
    n = 0
    for t in hl.targets:
        plain = pot.get(t.raw)
        if plain is not None:
            print(f"{t.raw}:{encode_plain(plain)}")
            n += 1
    log.info("cracked", count=f"{n}/{len(hl.targets)}")
    return 0


def cmd_left(args, log: Log) -> int:
    """hashcat --left parity: targets still missing from the potfile."""
    engine = get_engine(args.engine, device="cpu")
    hl = _load_targets(engine, args.hashfile, log)
    if hl is None:
        return 2
    pot = Potfile(args.potfile)
    n = 0
    for t in hl.targets:
        if pot.get(t.raw) is None:
            print(t.raw)
            n += 1
    log.info("uncracked", count=f"{n}/{len(hl.targets)}")
    return 0


def cmd_check(args, log: Log) -> int:
    from dprf_tpu import analysis
    argv = []
    if args.root:
        argv += ["--root", args.root]
    for v in args.only or ():
        argv += ["--only", v]
    for v in args.skip or ():
        argv += ["--skip", v]
    if args.explain:
        argv += ["--explain", args.explain]
    for flag in ("json", "list", "show_suppressed", "write_env_docs",
                 "fix_skeletons"):
        if getattr(args, flag):
            argv.append("--" + flag.replace("_", "-"))
    return analysis.main(argv)


def cmd_engines(args, log: Log) -> int:
    devices = [args.device] if args.device else ["cpu", "jax"]
    for dev in devices:
        try:
            names = engine_names(dev)
        except KeyError:
            names = []
        if not getattr(args, "verbose", False):
            print(f"{dev}: {', '.join(names)}")
            continue
        from dprf_tpu.engines import engine_class
        print(f"{dev}:")
        for n in names:
            doc = (engine_class(n, dev).__doc__ or "").strip()
            first = doc.splitlines()[0] if doc else ""
            print(f"  {n:14s} {first}")
    return 0


def _attack_gen(args, log: Log):
    """Engine-free generator from an attack spec (keyspace / stdout)."""
    customs = _customs(args)
    if args.attack == "mask":
        counts = None
        if getattr(args, "markov", None):
            from dprf_tpu.generators.markov import load_stats
            counts = load_stats(args.markov)
        return MaskGenerator(args.attack_arg, custom=customs or None,
                             markov_counts=counts)
    if getattr(args, "markov", None):
        # same contract as crack: silently unordered output would be
        # worse than the error
        raise ValueError("--markov applies to mask attacks only")
    if args.attack == "wordlist":
        from dprf_tpu.generators.wordlist import WordlistRulesGenerator
        return WordlistRulesGenerator.from_files(
            args.attack_arg, args.rules, max_len=args.max_len)
    gen, _, _ = _build_combinator_gen(
        args.attack, args.attack_arg, customs, args.max_len,
        None, "cpu", log)
    return gen


def cmd_keyspace(args, log: Log) -> int:
    print(_attack_gen(args, log).keyspace)
    return 0


def cmd_markov(args, log: Log) -> int:
    from dprf_tpu.generators.markov import (save_stats, stats_digest,
                                            train_file)
    counts = train_file(args.wordlist, max_len=args.max_len)
    save_stats(args.out, counts)
    log.info("markov stats written", out=args.out,
             words_weight=int(counts[0].sum()),
             digest=stats_digest(counts))
    return 0


def cmd_stdout(args, log: Log) -> int:
    """Stream the attack's candidate bytes, one per line, without
    hashing -- for piping into other tools and for debugging what a
    mask/rule spec actually expands to (hashcat's --stdout)."""
    gen = _attack_gen(args, log)
    start = max(0, args.skip)
    end = gen.keyspace if args.limit is None else \
        min(gen.keyspace, start + args.limit)
    out = sys.stdout.buffer
    try:
        for s in range(start, end, 8192):
            n = min(8192, end - s)
            for c in gen.candidates(s, n):
                if c is None:        # rule-rejected keyspace hole
                    continue
                out.write(c)
                out.write(b"\n")
        out.flush()
    except BrokenPipeError:          # |head is normal use, not an error
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), out.fileno())
    return 0


_COMMANDS = {
    "crack": cmd_crack,
    "serve": cmd_serve,
    "worker": cmd_worker,
    "bench": cmd_bench,
    "tune": cmd_tune,
    "prewarm": cmd_prewarm,
    "jobs": cmd_jobs,
    "retry-parked": cmd_retry_parked,
    "top": cmd_top,
    "trace": cmd_trace,
    "health": cmd_health,
    "alerts": cmd_alerts,
    "token": cmd_token,
    "report": cmd_report,
    "audit": cmd_audit,
    "programs": cmd_programs,
    "profile": cmd_profile,
    "metrics": cmd_metrics,
    "check": cmd_check,
    "show": cmd_show,
    "left": cmd_left,
    "engines": cmd_engines,
    "keyspace": cmd_keyspace,
    "stdout": cmd_stdout,
    "markov": cmd_markov,
}


def main(argv: Optional[list] = None) -> int:
    # Honor an explicit JAX_PLATFORMS before any backend initializes:
    # some environments (the axon TPU tunnel) force-register their
    # platform via sitecustomize + jax.config, which silently overrides
    # the env var -- so `JAX_PLATFORMS=cpu dprf bench --devices 8`
    # would grab the real TPU instead of the virtual CPU mesh.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms is not None:   # "" = JAX auto-selection, honor it
        import jax
        jax.config.update("jax_platforms", env_platforms or None)
    args = _build_parser().parse_args(argv)
    log = Log(quiet=getattr(args, "quiet", False))
    # library code logs through the module-level DEFAULT; mirror -q
    from dprf_tpu.utils.logging import DEFAULT
    DEFAULT.quiet = log.quiet
    try:
        return _COMMANDS[args.command](args, log)
    except (ValueError, KeyError, OSError, RpcError) as e:
        log.error(str(e))
        return 2


if __name__ == "__main__":
    sys.exit(main())
