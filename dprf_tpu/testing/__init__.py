"""Fault-injection harnesses (ISSUE 19).

Not shipped runtime -- these drive the REAL coordinator-side pieces
(dispatcher, session journal, trace recorder, coverage ledger) through
failure schedules no polite test reaches, then hand the wreckage to
the offline auditor (``dprf audit``) and gate on its verdict.  The CI
``audit`` tier and tests/test_chaos.py are the consumers.
"""

from dprf_tpu.testing.chaos import FAULTS, run_chaos

__all__ = ["FAULTS", "run_chaos"]
