"""Chaos harness: inject coordinator-plane faults mid-sweep, then
prove the coverage story survived (ISSUE 19).

The harness runs a whole "crack" in process against the REAL pieces a
coordinator is made of -- Dispatcher (lease/complete/reissue/park),
SessionJournal (units snapshots + coverage digests + hits),
TraceRecorder (lifecycle spans), CoverageLedger -- with the sweep
itself simulated: "hashing" a unit means checking which planted
candidate indices fall inside its range, so the run is deterministic,
hardware-free, and finishes in well under a second.  What is NOT
simulated is everything this PR audits: the unit lifecycle, the
journal stream, and the ledger.

Faults injected (``FAULTS``), each on a unit carrying a planted hit
so the exactly-once invariant is exercised through every path:

  - ``worker_kill``      a worker leases a unit and dies silently;
                         the unit is still outstanding at ...
  - ``coordinator_restart``  the journal is closed mid-sweep, loaded
                         back, and the dispatcher rebuilt with
                         ``from_completed(expect_digest=...)`` -- the
                         journaled digest must verify, and ...
  - ``resplit``          ... the un-covered remainder (including the
                         dead worker's unit) is resplit into fresh
                         units;
  - ``lease_expiry``     a worker goes quiet holding a lease; the
                         fake clock advances past the timeout and the
                         reaper reissues the unit;
  - ``stale_complete``   the quiet worker comes BACK after the unit
                         was reissued and completed by another -- its
                         late completion must bounce off the
                         stale-lease guard (a dropped/duplicated
                         completion RPC), and its duplicate hit
                         sighting must be deduped;
  - ``poison_park``      a unit fails repeatedly until parked, then a
                         ``retry_parked`` admin op requeues it and it
                         finally lands.

After the sweep drains, the harness snapshots the journal and runs
the OFFLINE auditor (perfreport/audit.py) over the artifacts.  The
gate is the auditor's verdict plus the harness's own live checks:
fraction 1.0, zero overlap, zero gaps, every planted hit found
exactly once, every stale report rejected.  ``main()`` is the CI
``audit`` tier entry point (exit 0 iff clean).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Optional

from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import TraceRecorder

FAULTS = ("worker_kill", "coordinator_restart", "resplit",
          "lease_expiry", "stale_complete", "poison_park")

#: parked after this many failures -- keeps poison_park quick
MAX_RETRIES = 2

#: loop backstop: the schedule converges in ~60 iterations; hitting
#: this means a fault path wedged the sweep, which IS a finding
MAX_STEPS = 10_000


class _Clock:
    """Manual monotonic clock: lease expiry on demand, no sleeping."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _Run:
    """One chaos sweep's mutable state (split out so the restart
    fault can tear half of it down and rebuild it)."""

    def __init__(self, session_path: str, keyspace: int,
                 unit_size: int, lease_timeout: float,
                 order=None) -> None:
        self.session_path = session_path
        self.keyspace = keyspace
        self.unit_size = unit_size
        self.lease_timeout = lease_timeout
        #: rank<->index bijection (generators/order.py) or None; when
        #: set, every dispatcher runs in rank space and the harness
        #: proves the SAME exactly-once story under reordering
        self.order = order
        self.clock = _Clock()
        self.registry = MetricsRegistry()
        self.recorder = TraceRecorder(proc="coordinator",
                                      enabled=True,
                                      registry=self.registry)
        self.spec = {"engine": "chaos", "attack": "mask",
                     "keyspace": keyspace}
        self.journal: Optional[SessionJournal] = None
        self.dispatcher: Optional[Dispatcher] = None
        self.found: dict = {}       # target -> index (exactly-once)
        self.injected: list = []
        self.violations: list = []

    # -- coordinator lifecycle -------------------------------------------

    def boot(self) -> None:
        """Fresh coordinator: new journal + dispatcher over the whole
        keyspace."""
        self.journal = SessionJournal(self.session_path,
                                      snapshot_every=4)
        self.journal.open(self.spec)
        self.recorder.attach_file(self.journal.trace_path)
        self.dispatcher = Dispatcher(
            self.keyspace, self.unit_size, order=self.order,
            lease_timeout=self.lease_timeout, clock=self.clock,
            registry=self.registry, recorder=self.recorder,
            max_unit_retries=MAX_RETRIES)

    def restart(self) -> None:
        """The coordinator_restart fault: drop the live dispatcher
        (outstanding leases and all), close the journal, load it
        back, and rebuild -- the journaled digest must verify against
        the rebuilt ledger, and every un-journaled range (including
        units that were leased out when the lights went off) must be
        resplit into fresh pending units."""
        self.journal.close()
        self.recorder.detach_file()
        state = SessionJournal.load(self.session_path)
        self.journal = SessionJournal(self.session_path,
                                      snapshot_every=4)
        self.journal.open(self.spec)
        self.recorder.attach_file(self.journal.trace_path)
        self.dispatcher = Dispatcher.from_completed(
            self.keyspace, self.unit_size, state.completed,
            expect_digest=state.coverage.get(state.default_job),
            order=self.order,
            lease_timeout=self.lease_timeout, clock=self.clock,
            registry=self.registry, recorder=self.recorder,
            max_unit_retries=MAX_RETRIES)
        # the hit ledger survives the restart the same way the
        # coordinator's does: replayed from the journal
        self.found = {h["target"]: h["index"] for h in state.hits}

    # -- the simulated worker --------------------------------------------

    def sweep_hits(self, unit, plants: dict) -> list:
        """(target, index) planted inside the unit's range -- the
        whole 'device' side of this harness.  Unit spans are RANKS
        under an order, so membership goes through the bijection's
        point map, exactly like an OrderedWorker's decode does."""
        out = []
        for t, idx in plants.items():
            pos = (self.order.index_to_rank(idx)
                   if self.order is not None else idx)
            if unit.start <= pos < unit.end:
                out.append((t, idx))
        return out

    def land(self, unit, worker: str, plants: dict) -> bool:
        """A worker's completion report: mark the unit done, journal
        coverage + any NEW hits (the coordinator's dedupe -- a hit
        re-sighted by a redundant sweep is dropped, not re-recorded)."""
        ok = self.dispatcher.complete(unit.unit_id, elapsed=0.01,
                                      worker_id=worker)
        if not ok:
            return False
        self.journal.record_units(
            self.dispatcher.completed_intervals(),
            digest=self.dispatcher.coverage_digest())
        for t, idx in self.sweep_hits(unit, plants):
            if t not in self.found:
                self.found[t] = idx
                self.journal.record_hit(t, idx, f"pw{t}".encode())
        return True


def _chaos_order(kind: str, keyspace: int):
    """The harness's rank order: a MarkovOrder over a synthetic
    mixed-radix factorization of the keyspace (hardware-free, no
    generator needed).  Built directly -- not via build_order -- so
    the chaos schedule can pin a split with a nontrivial block."""
    if kind in (None, "", "index"):
        return None
    from dprf_tpu.generators.order import MarkovOrder
    radices, k = [], keyspace
    while k % 10 == 0 and k > 10 and len(radices) < 3:
        radices.append(10)
        k //= 10
    if len(radices) < 2 or k < 2:
        raise ValueError(
            f"--order markov chaos needs a keyspace divisible by 100 "
            f"with a cofactor >= 2, got {keyspace}")
    return MarkovOrder((k, *radices), split=2)


def run_chaos(session_path: str, keyspace: int = 20_000,
              unit_size: int = 512, n_hits: int = 4,
              lease_timeout: float = 30.0,
              order: str = "index") -> dict:
    """Run the full fault schedule over a small keyspace; returns the
    result dict (verdict, fraction, per-fault record, violations).
    Artifacts are left at ``session_path`` (+ .trace.jsonl) so ``dprf
    audit`` can be pointed at the wreckage afterwards.

    ``order="markov"`` reruns the identical schedule in RANK space:
    the dispatcher leases rank spans, plants are journaled as
    indices, and restart-resume rides the rank_image of the
    journal's index intervals -- exactly-once must hold bit-for-bit
    under reordering."""
    ord_obj = _chaos_order(order, keyspace)
    run = _Run(session_path, keyspace, unit_size, lease_timeout,
               order=ord_obj)
    run.boot()
    # planted hits, spread so the fault-carrying units each hold one.
    # The schedule MARKS are positions along the dispatch axis (ranks
    # under an order); each plant's journaled identity is its INDEX,
    # like a production hit's cand_index
    marks = {t: (t + 1) * keyspace // (n_hits + 1)
             for t in range(n_hits)}
    plants = ({t: ord_obj.rank_to_index(m) for t, m in marks.items()}
              if ord_obj is not None else dict(marks))
    kill_idx = marks.get(0, keyspace // 5)
    stale_idx = marks.get(1, 2 * keyspace // 5)
    park_idx = marks.get(2, 3 * keyspace // 5)

    # restart when the sweep reaches the midpoint between the kill
    # and stale plants -- after worker_kill, before lease_expiry --
    # so the schedule holds at any keyspace/unit_size shape
    restart_idx = (kill_idx + stale_idx) // 2

    killed = restarted = parked_retried = False
    stale: Optional[dict] = None    # {"uid", "worker"} once injected
    park_fails = 0
    completes = 0
    leases = 0

    for _ in range(MAX_STEPS):
        d = run.dispatcher
        # while a stale report is pending, the reissued unit is the
        # next lease out -- hand it to a DIFFERENTLY-named worker so
        # the late report exercises the lease-moved guard
        worker = ("w-rescue" if stale is not None
                  else f"w-{leases % 2}")
        unit = d.lease(worker_id=worker)
        leases += 1
        if unit is None:
            if d.parked_count() and not parked_retried:
                # the admin op: fresh retry budget for poisoned units
                parked_retried = True
                d.retry_parked()
                run.injected.append("poison_park")
                continue
            if d.outstanding_count():
                # quiet workers: let their leases expire and reap
                run.clock.advance(run.lease_timeout + 1.0)
                continue
            break    # drained: nothing pending, outstanding, parked
        uid = unit.unit_id

        if not killed and unit.start <= kill_idx < unit.end:
            # worker_kill: "w-dead" holds the lease and says nothing
            # more; resolved by restart-resplit or the reaper below
            killed = True
            run.injected.append("worker_kill")
            continue

        if (not restarted and killed
                and unit.start <= restart_idx < unit.end):
            # coordinator_restart (+ resplit): current lease and the
            # dead worker's unit are both lost with the process
            restarted = True
            run.injected.extend(["coordinator_restart", "resplit"])
            run.restart()
            continue

        if (restarted and stale is None
                and unit.start <= stale_idx < unit.end):
            # lease_expiry: this worker goes quiet mid-unit; the
            # reaper will reissue after the clock advance
            stale = {"uid": uid, "worker": worker, "unit": unit}
            run.injected.append("lease_expiry")
            run.clock.advance(run.lease_timeout + 1.0)
            continue

        if stale is not None and uid == stale["uid"]:
            # the reissued unit is now leased to a rescue worker --
            # and the quiet worker's completion RPC finally arrives
            # FIRST: the lease moved, so the stale-lease guard must
            # drop it, and its duplicate hit sighting must dedupe
            if run.dispatcher.complete(uid, elapsed=0.01,
                                       worker_id=stale["worker"]):
                run.violations.append(
                    f"stale completion of unit {uid} accepted -- "
                    "double coverage")
            if not run.land(unit, "w-rescue", plants):
                run.violations.append(
                    f"rescue completion of unit {uid} rejected")
            for t, idx in run.sweep_hits(stale["unit"], plants):
                if t not in run.found:
                    run.violations.append(
                        f"hit {t} lost in stale-complete path")
            run.injected.append("stale_complete")
            stale = None
            completes += 1
            continue

        if (restarted and park_fails < MAX_RETRIES
                and not parked_retried
                and unit.start <= park_idx < unit.end):
            # poison_park: fail until the retry budget parks it; the
            # retry_parked branch above requeues it later
            park_fails += 1
            d.fail(uid, worker_id=worker)
            continue

        if not run.land(unit, worker, plants):
            run.violations.append(
                f"live completion of unit {uid} rejected")
        completes += 1
    else:
        run.violations.append(
            f"sweep did not drain within {MAX_STEPS} steps")

    d = run.dispatcher
    run.journal.snapshot(d.completed_intervals(),
                         digest=d.coverage_digest())
    run.journal.close()
    run.recorder.detach_file()

    for name in FAULTS:
        if name not in run.injected:
            run.violations.append(f"fault {name} never injected")
    if len(run.found) != n_hits:
        run.violations.append(
            f"{len(run.found)}/{n_hits} planted hits found")

    from dprf_tpu.perfreport.audit import build_audit
    audit = build_audit(session_path)
    ledger = d.coverage
    result = {
        "session": session_path,
        "keyspace": keyspace,
        "order": order or "index",
        "faults": run.injected,
        "completes": completes,
        "fraction": ledger.fraction(),
        "overlap": ledger.overlap_total,
        "gap_total": ledger.gap_total(),
        "digest": d.coverage_digest(),
        "hits_planted": n_hits,
        "hits_found": len(run.found),
        "violations": run.violations,
        "audit_verdict": audit["verdict"] if audit else "missing",
        "audit_problems": audit["problems"] if audit else [],
    }
    result["clean"] = (not run.violations
                       and result["audit_verdict"] == "clean"
                       and result["fraction"] >= 1.0
                       and result["overlap"] == 0
                       and result["gap_total"] == 0)
    return result


def main(argv=None) -> int:
    """CI audit-tier entry point: run the schedule, print the result
    as JSON, exit 0 iff the auditor-backed gate is clean."""
    import argparse
    p = argparse.ArgumentParser(
        description="coverage chaos harness (ISSUE 19)")
    p.add_argument("--session", default=None,
                   help="session journal path (default: a temp dir; "
                   "artifacts are LEFT for `dprf audit`)")
    p.add_argument("--keyspace", type=int, default=20_000)
    p.add_argument("--unit-size", type=int, default=512)
    p.add_argument("--order", default="index",
                   choices=["index", "markov"],
                   help="run the schedule in rank space (markov): "
                   "same faults, same exactly-once gate, dispatched "
                   "through the rank<->index bijection")
    args = p.parse_args(argv)
    session = args.session
    if session is None:
        session = os.path.join(
            tempfile.mkdtemp(prefix="dprf-chaos-"), "chaos.session")
    else:
        os.makedirs(os.path.dirname(os.path.abspath(session)),
                    exist_ok=True)
    result = run_chaos(session, keyspace=args.keyspace,
                       unit_size=args.unit_size, order=args.order)
    print(json.dumps(result, sort_keys=True))
    return 0 if result["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
