"""DPRF-TPU: a TPU-native distributed password-recovery framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the
reference DPRF (Expertasif/dprf): HashEngine plugins (MD5, SHA-1,
SHA-256, NTLM, bcrypt, WPA2-PMKID), mask and wordlist+rules candidate
generation, a Dispatcher/WorkUnit keyspace splitter, and a coordinator
that collects hits -- with the entire hot path (index -> candidate ->
digest -> compare -> hit compaction) fused into a single jitted device
program so candidates never leave HBM.

Reference parity note: the reference mount was empty at survey time
(SURVEY.md, "CRITICAL FINDING"); the public surface implemented here is
pinned to the component names in BASELINE.json's north star.
"""

__version__ = "0.1.0"

from dprf_tpu.engines import get_engine, engine_names  # noqa: F401
