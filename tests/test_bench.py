"""Benchmark-mode smoke tests on the CPU backend: every mode produces
a well-formed result dict with a positive rate.  Short runs -- these
validate plumbing and output schema, not performance."""

import jax
import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu.bench import run_bench, run_config, run_scaling


def test_run_bench_xla_schema():
    res = run_bench(engine="md5", device="jax", mask="?l?l?l?l?l?l",
                    batch=4096, seconds=0.3, impl="xla")
    assert res["value"] > 0
    assert res["impl"] == "xla"
    assert res["unit"] == "H/s"
    assert res["device"] == jax.devices()[0].platform
    assert res["batches"] >= 1


def test_run_bench_cpu_oracle():
    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l?l",
                    batch=2048, seconds=0.3)
    assert res["value"] > 0 and res["device"] == "cpu"


def test_run_config_1_worker_path():
    res = run_config(1, device="jax", seconds=0.3, batch=4096)
    assert res["config"] == 1 and res["engine"] == "md5"
    assert res["value"] > 0 and res["targets"] == 1


def test_cached_session_fallback_reads_committed_results(tmp_path):
    """bench.py's fallback chain must consult checked-in
    TPU_RESULTS_r*.json (VERDICT r3 #1): /tmp session files first, then
    the latest committed round, ignoring poisoned (>=1e12) values."""
    import importlib.util
    import json
    import os
    import shutil

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_root", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    # sandbox copy so the test controls exactly which files exist
    sandbox = tmp_path / "repo"
    sandbox.mkdir()
    shutil.copy(os.path.join(repo, "bench.py"), sandbox / "bench.py")
    (sandbox / "TPU_RESULTS_r01.json").write_text(json.dumps(
        {"stages": {"bench": {"md5-pallas": {
            "device": "tpu", "engine": "md5", "value": 1.0e9}}}}))
    (sandbox / "TPU_RESULTS_r02.json").write_text(json.dumps(
        {"sessionA": {"stages": {"bench": {
            "md5-pallas": {"device": "tpu", "engine": "md5",
                           "value": 2.0e9},
            "md5-poisoned": {"device": "tpu", "engine": "md5",
                             "value": 1.3e15},     # poisoned: ignored
            "sha1": {"device": "tpu", "engine": "sha1",
                     "value": 9.9e9}}}}}))         # wrong engine
    spec2 = importlib.util.spec_from_file_location(
        "bench_sandbox", str(sandbox / "bench.py"))
    mod2 = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(mod2)
    mod2.TMP_SESSION_GLOB = str(tmp_path / "nonexistent" / "*.json")
    res = mod2._cached_session_result()
    # newest round wins; nested session shape is scanned; caps applied
    assert res is not None and res["value"] == 2.0e9
    assert res["device"] == "tpu" and "cached session" in res["note"]

    # the real repo's committed results must be found too (tmp tier
    # neutralized so this exercises the committed-file path); only
    # schema properties are asserted -- the value belongs to whatever
    # round last measured, not to this test
    mod.TMP_SESSION_GLOB = str(tmp_path / "nonexistent" / "*.json")
    real = mod._cached_session_result()
    assert real is not None and real["device"] == "tpu"
    assert 0 < real["value"] < mod.CACHED_VALUE_CAP

    # a stale /tmp leftover (older than the newest committed file)
    # must NOT shadow the committed record -- it joins the same tier
    stale_dir = tmp_path / "stale"
    stale_dir.mkdir()
    stale = stale_dir / "tpu_session_results.json"
    stale.write_text(json.dumps({"stages": {"bench": {"md5-xla": {
        "device": "tpu", "engine": "md5", "value": 5.0e7}}}}))
    committed = sandbox / "TPU_RESULTS_r02.json"
    os.utime(stale, (os.path.getmtime(committed) - 100,) * 2)
    mod2.TMP_SESSION_GLOB = str(stale_dir / "*.json")
    res = mod2._cached_session_result()
    assert res["value"] == 2.0e9   # committed round wins the tier
    # but a FRESH /tmp session (newer than the committed file) wins
    os.utime(stale, (os.path.getmtime(committed) + 100,) * 2)
    res = mod2._cached_session_result()
    assert res["value"] == 5.0e7


def test_run_scaling_plumbing():
    assert len(jax.devices()) >= 2, "conftest fakes 8 CPU devices"
    res = run_scaling(engine="md5", mask="?l?l?l?l?l?l", n_devices=2,
                      batch_per_device=2048, seconds=0.3, inner=1)
    assert res["n_devices"] == 2
    assert res["rate_1chip"] > 0 and res["rate_ndev"] > 0
    assert res["rate_independent"] > 0
    assert res["per_chip"] == pytest.approx(res["rate_ndev"] / 2)
    # the gated number compares against the embarrassingly-parallel
    # baseline (contention-fair on a virtual mesh); the classic
    # unloaded ratio rides along as efficiency_strict
    assert res["baseline"] == "independent"
    assert res["value"] == res["efficiency"] == pytest.approx(
        min(1.0, res["rate_ndev"] / res["rate_independent"]))
    assert res["efficiency_raw"] == pytest.approx(
        res["rate_ndev"] / res["rate_independent"])
    assert res["efficiency_strict"] == pytest.approx(
        res["rate_ndev"] / (2 * res["rate_1chip"]))
    assert res["superstep"] is False       # inner=1: compat program
    assert "h2d_share" in res and "phases" in res
    assert "note" in res      # CPU mesh must be labeled plumbing-only
