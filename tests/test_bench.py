"""Benchmark-mode smoke tests on the CPU backend: every mode produces
a well-formed result dict with a positive rate.  Short runs -- these
validate plumbing and output schema, not performance."""

import jax
import pytest

from dprf_tpu.bench import run_bench, run_config, run_scaling


def test_run_bench_xla_schema():
    res = run_bench(engine="md5", device="jax", mask="?l?l?l?l?l?l",
                    batch=4096, seconds=0.3, impl="xla")
    assert res["value"] > 0
    assert res["impl"] == "xla"
    assert res["unit"] == "H/s"
    assert res["device"] == jax.devices()[0].platform
    assert res["batches"] >= 1


def test_run_bench_cpu_oracle():
    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l?l",
                    batch=2048, seconds=0.3)
    assert res["value"] > 0 and res["device"] == "cpu"


def test_run_config_1_worker_path():
    res = run_config(1, device="jax", seconds=0.3, batch=4096)
    assert res["config"] == 1 and res["engine"] == "md5"
    assert res["value"] > 0 and res["targets"] == 1


def test_run_scaling_plumbing():
    assert len(jax.devices()) >= 2, "conftest fakes 8 CPU devices"
    res = run_scaling(engine="md5", mask="?l?l?l?l?l?l", n_devices=2,
                      batch_per_device=2048, seconds=0.3)
    assert res["n_devices"] == 2
    assert res["rate_1chip"] > 0 and res["rate_ndev"] > 0
    assert res["per_chip"] == pytest.approx(res["rate_ndev"] / 2)
    assert res["efficiency"] == pytest.approx(
        res["rate_ndev"] / (2 * res["rate_1chip"]))
    assert "note" in res      # CPU mesh must be labeled plumbing-only
