"""Multi-tenant serve plane (ISSUE 8): the JobScheduler's stride
fair share, quota/rate limits and lifecycle; the per-job RPC surface
(op_job_submit/list/status/cancel/pause, op_hits_pull); the two-job
chaos test over a loopback fleet (fair-share interleave, zero
cross-job hit leakage, exact per-job coverage, per-job trace labels);
per-job session-journal resume after a coordinator restart; and the
adaptive lease-ahead depth that replaced the static pipeline knob.
"""

import hashlib
import json
import threading
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.jobs import (CANCELLED, DONE, PAUSED, RUNNING,
                           JobScheduler)
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, worker_loop)
from dprf_tpu.runtime.session import SessionJournal, job_fingerprint
from dprf_tpu.runtime.worker import AdaptiveDepth, CpuWorker
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import TraceRecorder

#: the `jobs` marker selects the multi-tenant serve-plane tier
#: (`pytest -m jobs`); everything here is loopback CPU work, so the
#: whole file also rides the smoke tier under its wall-time budget
pytestmark = [pytest.mark.smoke, pytest.mark.jobs]

UNIT = 100
KEYSPACE = 1000   # 10 units per job


def _sched(reg=None, clock=None):
    return JobScheduler(registry=reg or MetricsRegistry(),
                        clock=clock)


def _disp(reg, job_id="j0", keyspace=KEYSPACE, unit=UNIT, rec=None,
          **kw):
    return Dispatcher(keyspace, unit, registry=reg, job_id=job_id,
                      recorder=rec, **kw)


def _add(sched, reg, priority=1, keyspace=KEYSPACE, n_targets=1,
         rec=None, **kw):
    jid = sched.reserve_id()
    d = _disp(reg, job_id=jid, keyspace=keyspace, rec=rec)
    return sched.add({"engine": "md5"}, d, n_targets,
                     priority=priority, job_id=jid, **kw)


# ---------------------------------------------------------------------------
# stride fair share

def test_stride_fair_share_matches_weights_exactly():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, priority=3)
    b = _add(s, reg, priority=1)
    order = []
    for _ in range(8):
        for job, unit in s.lease_many("w0", 1):
            order.append(job.job_id)
            job.dispatcher.complete(unit.unit_id)
    # deterministic stride: over any window the lease counts approach
    # the 3:1 weight ratio exactly -- 6/2 in the first 8
    assert order.count(a.job_id) == 6
    assert order.count(b.job_id) == 2
    assert a.leases == 6 and b.leases == 2


def test_fair_share_holds_within_lease_ahead_batches():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, priority=2)
    b = _add(s, reg, priority=1)
    pairs = s.lease_many("w0", 6)
    jids = [j.job_id for j, _ in pairs]
    assert jids.count(a.job_id) == 4
    assert jids.count(b.job_id) == 2


def test_job_with_full_ledger_skipped_without_pass_penalty():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, priority=1, keyspace=UNIT)      # one unit only
    b = _add(s, reg, priority=1)
    # a's single unit goes out; its ledger is now fully outstanding
    pairs = s.lease_many("w0", 5)
    assert [j.job_id for j, _ in pairs].count(a.job_id) == 1
    pass_before = a.pass_value
    more = s.lease_many("w0", 3)
    assert all(j.job_id == b.job_id for j, _ in more)
    # no penalty accrued: a's pass did not advance while unleasable
    assert a.pass_value == pass_before


def test_late_submitted_job_starts_at_pass_frontier():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, priority=1)
    for _ in range(6):
        (job, unit), = s.lease_many("w0", 1)
        job.dispatcher.complete(unit.unit_id)
    b = _add(s, reg, priority=1)
    assert b.pass_value == a.pass_value
    # equal weights from here: the newcomer does NOT get a retroactive
    # catch-up burst, it alternates
    jids = [j.job_id for j, _ in s.lease_many("w0", 4)]
    assert jids.count(b.job_id) == 2


# ---------------------------------------------------------------------------
# quota and lease-rate limits

def test_quota_counts_outstanding_and_stops_leasing():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, quota=250)
    pairs = s.lease_many("w0", 10)
    # 3 units x 100 indices: a 4th would overshoot the 250 quota
    # because outstanding indices count against it too
    assert len(pairs) == 3
    for job, unit in pairs:
        s.complete(job, unit.unit_id)
    assert a.state == DONE and a.done_reason == "quota reached"
    assert s.lease_many("w0", 1) == []


def test_owner_aggregate_quota_caps_leases_across_jobs():
    """ISSUE 13 satellite: one owner's cap binds the SUM of its jobs'
    swept-or-leased indices -- splitting work over two jobs buys the
    owner nothing, and other owners keep leasing."""
    reg = MetricsRegistry()
    s = JobScheduler(registry=reg, owner_quotas={"alice": 150})
    a1 = _add(s, reg, owner="alice")
    a2 = _add(s, reg, owner="alice")
    b = _add(s, reg, owner="bob")
    pairs = s.lease_many("w0", 12)
    by_owner: dict = {}
    for job, _ in pairs:
        by_owner[job.owner] = by_owner.get(job.owner, 0) + 1
    # alice: first lease takes aggregate to 100 (< 150, still
    # leasable), second to 200 (capped); bob is unaffected
    assert by_owner["alice"] == 2
    assert by_owner["bob"] == 10        # bob's whole keyspace
    assert s.owner_swept("alice") == 200
    assert s.owner_quota_error("alice") is not None
    assert s.owner_quota_error("bob") is None
    assert {a1.job_id, a2.job_id} >= {
        j.job_id for j, _ in pairs if j.owner == "alice"}
    assert b.leases == 10


def test_owner_quota_rejects_submit_before_the_build():
    """The admission gate fires before the expensive server-side
    build: a capped owner's submission must not even construct the
    job runtime."""
    reg = MetricsRegistry()
    disp = Dispatcher(KEYSPACE, UNIT, registry=reg)
    state = CoordinatorState({"engine": "md5"}, disp, 1, registry=reg,
                             owner_quotas={"alice": 0})

    def exploding_builder(*a, **kw):
        raise AssertionError("build ran past the owner-quota gate")

    state.job_builder = exploding_builder
    resp = state.op_job_submit({"spec": {}, "owner": "alice"})
    assert "quota" in resp["error"]
    # an uncapped owner reaches the builder (and its real errors)
    def ok_builder(spec, jid, **kw):
        d = Dispatcher(KEYSPACE, UNIT, registry=reg, job_id=jid)
        return {"engine": "md5", "keyspace": KEYSPACE,
                "fingerprint": "f"}, d, ["t0"], None

    state.job_builder = ok_builder
    resp = state.op_job_submit({"spec": {}, "owner": "bob"})
    assert resp.get("ok")


def test_rate_token_bucket_throttles_leases():
    clock = [0.0]
    reg = MetricsRegistry()
    s = _sched(reg, clock=lambda: clock[0])
    a = _add(s, reg, rate=2.0)
    # one token in the bucket at t0
    assert len(s.lease_many("w0", 5)) == 1
    assert s.lease_many("w0", 5) == []
    clock[0] = 1.0          # 1s -> 2 tokens refilled (rate 2/s)
    pairs = s.lease_many("w0", 5)
    assert len(pairs) == 2
    assert a.leases == 3


# ---------------------------------------------------------------------------
# lifecycle: pause / cancel / done

def test_pause_blocks_leasing_but_outstanding_completes_land():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg)
    (job, unit), = s.lease_many("w0", 1)
    s.pause(a.job_id)
    assert a.state == PAUSED
    assert s.lease_many("w0", 1) == []
    # pause is not stop: the fleet keeps polling for a resume
    assert not s.idle_stop()
    assert s.complete(job, unit.unit_id)      # honestly leased: lands
    assert a.covered() == UNIT
    s.pause(a.job_id, resume=True)
    assert a.state == RUNNING
    assert len(s.lease_many("w0", 1)) == 1


def test_cancel_mid_flight_drops_stale_completes_and_hits():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg)
    (job, unit), = s.lease_many("w0", 1)
    s.cancel(a.job_id)
    assert a.state == CANCELLED and a.done_reason == "cancelled"
    # the in-flight unit was leased before the cancel: its report must
    # not land coverage (or anything else)
    assert s.complete(job, unit.unit_id) is False
    assert a.covered() == 0
    assert s.lease_many("w0", 1) == []
    # cancelled jobs are excluded from aggregate progress
    assert s.progress() == (0, 0)
    assert s.all_finished()


def test_done_reasons_targets_and_exhaustion():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, keyspace=2 * UNIT, n_targets=1)
    b = _add(s, reg, keyspace=2 * UNIT, n_targets=1)
    # a: crack the target before the keyspace ends
    (job, unit), = s.lease_many("w0", 1)
    assert job is a
    s.record_hit(a, 0, 5, b"pw")
    assert a.state == DONE and a.done_reason == "all targets found"
    # b: sweep everything without a crack
    while True:
        pairs = s.lease_many("w0", 1)
        if not pairs:
            break
        for j, u in pairs:
            s.complete(j, u.unit_id)
    assert b.state == DONE and b.done_reason == "keyspace exhausted"
    assert s.all_finished() and s.idle_stop()


def test_hit_buffer_cursor_and_dedupe():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg, n_targets=2)
    assert s.record_hit(a, 0, 11, b"x")
    assert not s.record_hit(a, 0, 99, b"y")     # duplicate target
    assert s.record_hit(a, 1, 22, b"z")
    assert [h["seq"] for h in a.hits] == [0, 1]
    assert a.hits[1]["plaintext"] == b"z".hex()
    assert a.found == {0: b"x", 1: b"z"}


def test_retry_parked_revives_done_job():
    reg = MetricsRegistry()
    s = _sched(reg)
    jid = s.reserve_id()
    d = Dispatcher(2 * UNIT, UNIT, registry=reg, job_id=jid,
                   max_unit_retries=1)
    a = s.add({"engine": "md5"}, d, 1, job_id=jid)
    (j1, u1), = s.lease_many("w0", 1)
    s.fail(j1, u1.unit_id)                      # parks (retry cap 1)
    (j2, u2), = s.lease_many("w0", 1)
    s.complete(j2, u2.unit_id)
    assert a.state == DONE and d.parked_count() == 1
    assert s.retry_parked() == 1
    assert a.state == RUNNING                   # reachable again
    (j3, u3), = s.lease_many("w0", 1)
    s.complete(j3, u3.unit_id)
    assert a.state == DONE and a.covered() == 2 * UNIT


def test_job_table_cap_and_duplicate_ids_rejected():
    reg = MetricsRegistry()
    s = _sched(reg)
    a = _add(s, reg)
    with pytest.raises(ValueError):
        s.add({"engine": "md5"}, _disp(reg, job_id=a.job_id), 1,
              job_id=a.job_id)
    s.MAX_JOBS = 1
    with pytest.raises(ValueError):
        _add(s, reg)


# ---------------------------------------------------------------------------
# adaptive lease-ahead depth (replaces the static DPRF_PIPELINE_DEPTH)

def test_adaptive_depth_tracks_rtt_to_unit_ratio():
    d = AdaptiveDepth(cap=8)
    assert d.depth == 2                 # pre-signal default
    d.observe_rtt(0.4)
    d.observe_unit(0.1)                 # want 1 + ceil(4) = 5
    steps = [d.update() for _ in range(5)]
    assert steps == [3, 4, 5, 5, 5]     # one step per update, converges
    # the link got fast / units got long: back off toward serial
    for _ in range(30):
        d.observe_rtt(0.001)
        d.observe_unit(1.0)
        d.update()
    assert d.depth == 2                 # 1 + ceil(0.001) = 2


def test_adaptive_depth_env_knob_is_the_cap():
    d = AdaptiveDepth(cap=3)
    d.observe_rtt(10.0)
    d.observe_unit(0.01)                # wants ~1001, capped
    for _ in range(10):
        d.update()
    assert d.depth == 3


def test_adaptive_depth_without_signals_stays_put():
    d = AdaptiveDepth(cap=8)
    assert [d.update() for _ in range(3)] == [2, 2, 2]


# ---------------------------------------------------------------------------
# session journal: per-job records

def test_journal_snapshot_cadence_is_per_job(tmp_path):
    # a shared counter would let one job's completions starve another
    # job's snapshots indefinitely (crash -> its coverage lost)
    path = str(tmp_path / "cadence.session")
    j = SessionJournal(path, snapshot_every=2)
    j.open({"engine": "md5"})
    j.record_units([(0, 100)])                   # default: 1 of 2
    j.record_units([(0, 50)], job="j1")          # j1: 1 of 2
    j.record_units([(0, 200)])                   # default: snapshots
    j.record_units([(0, 150)], job="j1")         # j1: snapshots
    j.close()
    st = SessionJournal.load(path)
    assert st.completed == [(0, 200)]
    assert st.jobs["j1"]["completed"] == [(0, 150)]


def test_journal_job_records_round_trip(tmp_path):
    path = str(tmp_path / "s.session")
    j = SessionJournal(path, snapshot_every=1)
    j.open({"engine": "md5"})
    j.record_units([(0, 300)])                       # default job
    j.record_hit(0, 7, b"aa")
    j.record_job("j1", {"engine": "md5", "attack": "mask"},
                 owner="alice", priority=3, quota=500, rate=1.5)
    j.record_units([(100, 500)], job="j1")
    j.record_hit(1, 42, b"bb", job="j1")
    j.record_job_state("j1", "paused")
    j.close()
    st = SessionJournal.load(path)
    assert st.completed == [(0, 300)]                # untagged: default
    assert [h["target"] for h in st.hits] == [0]
    rec = st.jobs["j1"]
    assert rec["owner"] == "alice" and rec["priority"] == 3
    assert rec["quota"] == 500 and rec["rate"] == 1.5
    assert rec["completed"] == [(100, 500)]
    assert rec["hits"][0]["plaintext"] == b"bb".hex()
    assert rec["state"] == "paused"


# ---------------------------------------------------------------------------
# the loopback fleet

def _mask_job(mask, plants, unit_size=UNIT):
    eng = get_engine("md5")
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest())
               for p in plants]
    fp = job_fingerprint("md5", f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "md5", "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": unit_size, "batch": 4096, "hit_cap": 8,
           "fingerprint": fp}
    return eng, gen, targets, job


def _serve(job, gen, targets, priority=1, rec=None, reg=None, **kw):
    reg = reg or MetricsRegistry()
    rec = rec or TraceRecorder(registry=reg)
    eng = get_engine(job["engine"])
    disp = Dispatcher(gen.keyspace, job["unit_size"], registry=reg,
                      recorder=rec, job_id="j0")
    state = CoordinatorState(
        job, disp, len(targets), registry=reg, recorder=rec,
        priority=priority,
        verifier=lambda ti, p: eng.verify(p, targets[ti]), **kw)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, disp, rec, reg


def _submit_spec(mask, plants, priority=1, **extra):
    spec = {"engine": "md5", "attack": "mask", "attack_arg": mask,
            "targets": [hashlib.md5(p).hexdigest() for p in plants],
            "unit_size": UNIT, "unit_seconds": 0}
    spec.update(extra)
    return spec


def _spec_worker(spec):
    """cmd_worker's rebuild: engine + generator + CpuWorker from a
    wire job spec."""
    eng = get_engine(spec["engine"])
    gen = MaskGenerator(spec["attack_arg"])
    targets = [eng.parse_target(raw) for raw in spec["targets"]]
    return CpuWorker(eng, gen, targets)


def test_two_jobs_chaos_fair_share_coverage_and_no_leakage():
    """The ISSUE 8 acceptance test: two tenants on one fleet --
    fair-share lease interleave matching the 3:1 weights, exact
    per-job coverage, per-job hit streams with zero cross-job
    leakage, and per-job trace labels end to end."""
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets,
                                           priority=3)
    try:
        admin = CoordinatorClient(*server.address)
        resp = admin.call("job_submit",
                          spec=_submit_spec("?d?d?d", [b"998"]),
                          owner="bob", priority=1)
        jid_b = resp["job_id"]
        assert resp["keyspace"] == KEYSPACE

        hello = admin.call("hello", worker_id="setup")
        workers = {hello["job_id"]: CpuWorker(eng, gen, targets)}

        def worker_for(jid):
            w = workers.get(jid)
            if w is None:
                spec = admin.call("job_status", job=jid)["spec"]
                workers[jid] = w = _spec_worker(spec)
            return w

        client = CoordinatorClient(*server.address)
        wrec = TraceRecorder(registry=MetricsRegistry())
        done = worker_loop(client, workers[hello["job_id"]], "w0",
                           idle_sleep=0.01, registry=MetricsRegistry(),
                           recorder=wrec, worker_for=worker_for)
        client.close()

        # every unit of both jobs completed exactly once
        assert done == 20
        with state.lock:
            sched = state.scheduler
            a = sched.get("j0")
            b = sched.get(jid_b)
            assert a.dispatcher.completed_intervals() == [(0, KEYSPACE)]
            assert b.dispatcher.completed_intervals() == [(0, KEYSPACE)]
            assert a.state == DONE and b.state == DONE
            # zero cross-job hit leakage: each job found ITS plant
            assert a.found == {0: b"999"}
            assert b.found == {0: b"998"}

        # per-job hit streams: each tenant pulls only its own crack
        ha = admin.call("hits_pull", job="j0")
        hb = admin.call("hits_pull", job=jid_b)
        assert [h["plaintext"] for h in ha["hits"]] == [b"999".hex()]
        assert [h["plaintext"] for h in hb["hits"]] == [b"998".hex()]
        assert ha["cursor"] == 1 and hb["state"] == DONE
        # the cursor never re-reads
        again = admin.call("hits_pull", job=jid_b, cursor=hb["cursor"])
        assert again["hits"] == []

        # fair-share interleave: lease order is the stride order
        # (selection happens under the coordinator lock), so the
        # first-window ratio matches the 3:1 weights within 20%
        leases = [s for s in rec.tail(4096) if s["name"] == "lease"]
        window = [s["attrs"]["job"] for s in leases[:8]]
        n_a = window.count("j0")
        n_b = window.count(jid_b)
        assert n_b > 0 and 2.4 <= n_a / n_b <= 3.6, window

        # per-job observability: every unit-lifecycle span (incl. the
        # rpc/sweep spans the worker shipped back) names its job
        spans = rec.tail(4096)
        for s in spans:
            if s["name"] in ("lease", "complete", "sweep", "rpc"):
                assert s["attrs"].get("job") in ("j0", jid_b), s
        assert {s["attrs"]["job"] for s in leases} == {"j0", jid_b}
        admin.close()
    finally:
        server.shutdown()


def test_job_cancel_mid_flight_over_rpc_drops_report():
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        admin = CoordinatorClient(*server.address)
        jid = admin.call("job_submit",
                         spec=_submit_spec("?d?d?d", [b"123"]),
                         owner="eve")["job_id"]
        w = CoordinatorClient(*server.address)
        # drain default-job units until a unit of the new job arrives
        unit = None
        for _ in range(40):
            resp = w.call("lease", worker_id="w1")
            u = resp.get("unit")
            if u is None:
                break
            if u["job"] == jid:
                unit = u
                break
            w.call("complete", unit_id=u["id"], hits=[],
                   worker_id="w1", job=u["job"])
        assert unit is not None
        admin.call("job_cancel", job=jid)
        # the stale complete -- WITH the real crack -- must bounce
        resp = w.call("complete", unit_id=unit["id"],
                      hits=[{"target": 0, "cand": 123,
                             "plaintext": b"123".hex()}],
                      worker_id="w1", job=jid)
        assert resp.get("dropped") is True
        with state.lock:
            b = state.scheduler.get(jid)
            assert b.found == {} and b.covered() == 0
            assert b.state == CANCELLED
        # no further leases from the cancelled job
        resp = w.call("lease", worker_id="w1", ahead=8)
        assert all(e["job"] != jid for e in resp.get("units") or ())
        w.close()
        admin.close()
    finally:
        server.shutdown()


def test_jobs_cli_round_trip_against_live_coordinator(tmp_path,
                                                      capsys):
    """`dprf jobs submit/list/status/pause/resume/cancel/hits` against
    a real serving coordinator."""
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    addr = "%s:%d" % server.address
    try:
        hashfile = tmp_path / "h.txt"
        hashfile.write_text(hashlib.md5(b"424").hexdigest() + "\n")
        rc = cli_main(["jobs", "submit", "?d?d?d", str(hashfile),
                       "--engine", "md5", "--owner", "alice",
                       "--priority", "2", "--quota", "800",
                       "--unit-size", str(UNIT), "--unit-seconds", "0",
                       "--connect", addr, "-q"])
        out = capsys.readouterr().out
        assert rc == 0
        jid = json.loads(out.strip().splitlines()[-1])["job"]

        rc = cli_main(["jobs", "list", "--connect", addr, "-q"])
        out = capsys.readouterr().out
        assert rc == 0
        jobs = json.loads(out.strip().splitlines()[-1])
        by_id = {j["id"]: j for j in jobs}
        assert by_id[jid]["owner"] == "alice"
        assert by_id[jid]["priority"] == 2
        assert by_id[jid]["quota"] == 800
        assert "j0" in by_id

        rc = cli_main(["jobs", "pause", jid, "--connect", addr, "-q"])
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["state"] \
            == PAUSED
        rc = cli_main(["jobs", "resume", jid, "--connect", addr, "-q"])
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["state"] \
            == RUNNING

        # crack the submitted job's target, then pull its hits
        w = CoordinatorClient(*server.address)
        for _ in range(40):
            resp = w.call("lease", worker_id="w1")
            u = resp.get("unit")
            if u is None:
                break
            hits = []
            if u["job"] == jid and u["start"] <= 424 < u["start"] \
                    + u["length"]:
                hits = [{"target": 0, "cand": 424,
                         "plaintext": b"424".hex()}]
            w.call("complete", unit_id=u["id"], hits=hits,
                   worker_id="w1", job=u["job"])
        w.close()
        rc = cli_main(["jobs", "hits", jid, "--connect", addr, "-q"])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"{hashlib.md5(b'424').hexdigest()}:424" in out

        rc = cli_main(["jobs", "status", jid, "--connect", addr,
                       "-q"])
        out = capsys.readouterr().out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["found"] == 1

        # a terminal job stays terminal: cancelling the DONE job is a
        # no-op, so round-trip cancel against a still-running one
        rc = cli_main(["jobs", "cancel", jid, "--connect", addr,
                       "-q"])
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["state"] \
            == DONE
        hashfile2 = tmp_path / "h2.txt"
        hashfile2.write_text(hashlib.md5(b"000").hexdigest() + "\n")
        rc = cli_main(["jobs", "submit", "?d?d?d", str(hashfile2),
                       "--engine", "md5", "--unit-size", str(UNIT),
                       "--unit-seconds", "0", "--connect", addr,
                       "-q"])
        out = capsys.readouterr().out
        jid2 = json.loads(out.strip().splitlines()[-1])["job"]
        rc = cli_main(["jobs", "cancel", jid2, "--connect", addr,
                       "-q"])
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["state"] \
            == CANCELLED
        with state.lock:
            assert state.scheduler.get(jid2).state == CANCELLED
    finally:
        server.shutdown()


def test_unbuildable_job_fails_leases_without_killing_worker():
    """A tenant submission this host cannot rebuild (worker_for ->
    None: missing wordlist, divergent fingerprint) must not take the
    worker down: its leases fail back in-band, the retry budget parks
    its units, and every other job still completes."""
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        admin = CoordinatorClient(*server.address)
        jid_b = admin.call("job_submit",
                           spec=_submit_spec("?d?d?d", [b"123"]),
                           owner="bob")["job_id"]
        client = CoordinatorClient(*server.address)
        done = worker_loop(client, CpuWorker(eng, gen, targets), "w0",
                           idle_sleep=0.01,
                           registry=MetricsRegistry(),
                           recorder=TraceRecorder(
                               registry=MetricsRegistry()),
                           worker_for=lambda jid:
                               CpuWorker(eng, gen, targets)
                               if jid == "j0" else None)
        client.close()
        with state.lock:
            a = state.scheduler.get("j0")
            b = state.scheduler.get(jid_b)
            # the buildable job swept to completion on this worker
            assert a.dispatcher.completed_intervals() == [(0, KEYSPACE)]
            assert a.found == {0: b"999"}
            # the unbuildable one parked every unit, swept nothing
            assert b.covered() == 0
            assert b.dispatcher.parked_count() == KEYSPACE // UNIT
            assert b.state == DONE
        assert done == KEYSPACE // UNIT     # only j0's units resolved
        admin.close()
    finally:
        server.shutdown()


def test_job_table_full_rejected_before_build():
    eng, gen, targets, job = _mask_job("?d?d", [b"42"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        with state.lock:
            state.scheduler.MAX_JOBS = 1     # the default job fills it
        c = CoordinatorClient(*server.address)
        from dprf_tpu.runtime.rpc import RpcError
        with pytest.raises(RpcError, match="job table full"):
            c.call("job_submit", spec=_submit_spec("?d?d", [b"11"]))
        # the rejected id registered no per-job metric series
        assert reg.get("dprf_keyspace_total").value(job="j1") == 0
        c.close()
    finally:
        server.shutdown()


def test_bad_job_submissions_rejected():
    eng, gen, targets, job = _mask_job("?d?d", [b"42"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        from dprf_tpu.runtime.rpc import RpcError
        for spec in (None, {}, {"engine": "md5", "attack": "mask",
                               "attack_arg": "?d", "targets": []},
                     {"engine": "nosuch-engine", "attack": "mask",
                      "attack_arg": "?d", "targets": ["00" * 16]}):
            with pytest.raises(RpcError):
                c.call("job_submit", spec=spec)
        # fingerprint disagreement (client claims a different build)
        spec = _submit_spec("?d?d", [b"11"], fingerprint="bogus")
        with pytest.raises(RpcError, match="fingerprint"):
            c.call("job_submit", spec=spec)
        with pytest.raises(RpcError, match="unknown job"):
            c.call("job_status", job="j99")
        with pytest.raises(RpcError, match="unknown job"):
            c.call("hits_pull", job="j99")
        c.close()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# per-job resume after a coordinator restart

def test_per_job_resume_exact_coverage_after_restart(tmp_path):
    """Kill a multi-tenant coordinator mid-job; a restarted one
    rebuilds every tenant's ledger from the journal and the fleet
    finishes with exact per-job coverage and no re-sweep overlap."""
    from dprf_tpu.jobs.build import restore_jobs

    path = str(tmp_path / "mt.session")
    session = SessionJournal(path, snapshot_every=1)
    session.open({"engine": "md5"})
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    # cmd_serve's journaling hooks, wired the same way
    state.on_job_progress = lambda jid, iv, dg=None: \
        session.record_units(
            iv, job=None if jid == state.default_job_id else jid,
            digest=dg)
    state.on_job_hit = (
        lambda j, ti, cand, plain: session.record_hit(
            ti, cand, plain, job=j.job_id)
        if j.job_id != state.default_job_id else None)
    state.on_job_event = (
        lambda kind, j: session.record_job(
            j.job_id, j.spec, owner=j.owner, priority=j.priority,
            quota=j.quota, rate=j.rate)
        if kind == "submit"
        else session.record_job_state(j.job_id, j.state))
    try:
        admin = CoordinatorClient(*server.address)
        # b has TWO targets: one cracks pre-crash (journaled hit),
        # the other only at the end of the keyspace -- so b is still
        # RUNNING after the restore, not DONE-by-targets
        jid_b = admin.call("job_submit",
                           spec=_submit_spec("?d?d?d",
                                             [b"111", b"999"]),
                           owner="bob")["job_id"]
        # c's plant is outside its mask space: it stays mid-flight
        # (never DONE) so the cancel below hits a RUNNING job
        jid_c = admin.call("job_submit",
                           spec=_submit_spec("?d?d?d", [b"zzz"]),
                           owner="carol")["job_id"]
        # partial progress: a few units of each, B's crack lands
        w = CoordinatorClient(*server.address)
        swept = {"j0": 0, jid_b: 0, jid_c: 0}
        for _ in range(9):
            resp = w.call("lease", worker_id="w1")
            u = resp["unit"]
            hits = []
            if u["job"] == jid_b \
                    and u["start"] <= 111 < u["start"] + u["length"]:
                hits = [{"target": 0, "cand": 111,
                         "plaintext": b"111".hex()}]
            w.call("complete", unit_id=u["id"], hits=hits,
                   worker_id="w1", job=u["job"])
            swept[u["job"]] += u["length"]
        admin.call("job_cancel", job=jid_c)
        with state.lock:
            covered_b = state.scheduler.get(jid_b).covered()
            assert state.scheduler.get(jid_b).found == {0: b"111"}
        assert covered_b == swept[jid_b] > 0
        w.close()
        admin.close()
    finally:
        server.shutdown()        # the "crash"
    session.close()

    # -- restart: rebuild default job + tenants from the journal -----
    prior = SessionJournal.load(path)
    assert set(prior.jobs) == {jid_b, jid_c}
    reg2 = MetricsRegistry()
    rec2 = TraceRecorder(registry=reg2)
    disp2 = Dispatcher.from_completed(gen.keyspace, UNIT,
                                      prior.completed, registry=reg2,
                                      recorder=rec2, job_id="j0")
    state2 = CoordinatorState(
        job, disp2, len(targets), registry=reg2, recorder=rec2,
        verifier=lambda ti, p: eng.verify(p, targets[ti]))
    state2.seed_found(prior.hits)
    assert restore_jobs(state2, prior.jobs, log=None) == 2
    server2 = CoordinatorServer(state2, "127.0.0.1", 0)
    server2.start_background()
    try:
        with state2.lock:
            b = state2.scheduler.get(jid_b)
            c = state2.scheduler.get(jid_c)
            # exact pre-crash coverage, restored hit, restored states
            assert b.covered() == swept[jid_b]
            assert b.found == {0: b"111"}
            assert b.owner == "bob" and b.state == RUNNING
            assert c.state == CANCELLED      # cancel survived restart
        hb = CoordinatorClient(*server2.address).call(
            "hits_pull", job=jid_b)
        assert [h["plaintext"] for h in hb["hits"]] == [b"111".hex()]

        # the fleet finishes the remainder; coverage is exact -- every
        # index swept once, nothing re-swept, nothing lost
        client = CoordinatorClient(*server2.address)
        workers = {"j0": CpuWorker(eng, gen, targets)}

        def worker_for(jid):
            w2 = workers.get(jid)
            if w2 is None:
                with state2.lock:
                    spec = state2.scheduler.get(jid).spec
                workers[jid] = w2 = _spec_worker(spec)
            return w2

        done = worker_loop(client, workers["j0"], "w2",
                           idle_sleep=0.01,
                           registry=MetricsRegistry(),
                           recorder=TraceRecorder(
                               registry=MetricsRegistry()),
                           worker_for=worker_for)
        client.close()
        with state2.lock:
            a2 = state2.scheduler.get("j0")
            b2 = state2.scheduler.get(jid_b)
            assert a2.dispatcher.completed_intervals() \
                == [(0, KEYSPACE)]
            assert b2.dispatcher.completed_intervals() \
                == [(0, KEYSPACE)]
            assert a2.found == {0: b"999"}
            assert b2.found == {0: b"111", 1: b"999"}
            # resumed units only: restart + finish never re-sweeps
            assert done * UNIT == 2 * KEYSPACE - swept["j0"] \
                - swept[jid_b]
    finally:
        server2.shutdown()


def test_top_view_groups_by_job():
    """op_trace_tail ships per-job summaries and render_top shows the
    admin view grouped by job."""
    from dprf_tpu.telemetry.trace import render_top

    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        c.call("job_submit", spec=_submit_spec("?d?d?d", [b"777"]),
               owner="alice", priority=2)
        c.call("lease", worker_id="w0")
        resp = c.call("trace_tail")
        jobs = resp["status"]["jobs"]
        assert {j["id"] for j in jobs} == {"j0", "j1"}
        text = render_top(resp)
        assert "JOB" in text and "alice" in text
        # the worker table names the lease's owning job
        assert "j0#" in text or "j1#" in text
        c.close()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# flight-recorder pull (op_trace_pull / op_trace_push)

def test_trace_pull_pages_ring_and_arm_bumps_epoch():
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        # some coordinator-side spans
        for _ in range(5):
            resp = c.call("lease", worker_id="w1")
            c.call("complete", unit_id=resp["unit"]["id"], hits=[],
                   worker_id="w1", job=resp["unit"]["job"])
        r0 = c.call("trace_pull", n=4)
        assert r0["epoch"] == 0
        r1 = c.call("trace_pull", arm=True, n=4)
        assert r1["epoch"] == 1
        # lease responses now carry the bumped epoch
        assert c.call("lease", worker_id="w1")["pull"] == 1
        # cursor pagination covers the whole ring without overlap
        spans, cursor = [], None
        while True:
            page = c.call("trace_pull", since=cursor, n=4)
            got = page["spans"]
            spans.extend(got)
            cursor = page["cursor"]
            if len(got) < 4:
                break
        ids = [s["span"] for s in spans]
        assert len(ids) == len(set(ids))
        assert len(ids) == len(rec.tail(4096))
        c.close()
    finally:
        server.shutdown()


def test_trace_push_ingests_worker_ring_sanitized():
    eng, gen, targets, job = _mask_job("?d?d", [b"42"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        spans = [{"name": "sweep", "span": "s1", "trace": "t1",
                  "ts": 1.0, "dur": 0.5, "proc": "liar", "unit": 1},
                 {"name": "not-a-span", "span": "s2"}]
        resp = c.call("trace_push", worker_id="w7", spans=spans,
                      clock=time.time())
        assert resp["ingested"] == 1      # undeclared name dropped
        got = [s for s in rec.tail(100) if s.get("span") == "s1"]
        # proc forced to the server-known worker id: no impersonation
        assert got and got[0]["proc"] == "w7"
        c.close()
    finally:
        server.shutdown()


def test_worker_loop_ships_ring_when_pull_armed():
    """The fleet-wide incident pull: arming bumps the lease epoch and
    a polling worker ships its LOCAL ring via op_trace_push."""
    eng, gen, targets, job = _mask_job("?d?d", [b"99"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    try:
        admin = CoordinatorClient(*server.address)
        # a second, PAUSED job keeps the worker polling after the
        # default job drains (pause is not stop)
        jid = admin.call("job_submit",
                         spec=_submit_spec("?d?d", [b"11"]),
                         owner="bob")["job_id"]
        admin.call("job_pause", job=jid)

        wrec = TraceRecorder(registry=MetricsRegistry())
        client = CoordinatorClient(*server.address)
        t = threading.Thread(
            target=worker_loop,
            args=(client, CpuWorker(eng, gen, targets), "w0"),
            kwargs={"idle_sleep": 0.01,
                    "registry": MetricsRegistry(), "recorder": wrec})
        t.start()
        # wait until the default job drained and the worker idles
        deadline = time.time() + 30
        while time.time() < deadline:
            with state.lock:
                if state.scheduler.get("j0").state == DONE:
                    break
            time.sleep(0.01)
        # plant a marker span in the worker's LOCAL ring: it rode no
        # complete message, only a push can deliver it
        marker = wrec.record("warmup", dur=0.0, proc="w0",
                             engine="md5-marker")
        admin.call("trace_pull", arm=True)
        mid = marker["span"]
        found = None
        while time.time() < deadline and found is None:
            found = next((s for s in rec.tail(4096)
                          if s.get("span") == mid), None)
            time.sleep(0.02)
        admin.call("job_cancel", job=jid)    # lets the worker stop
        t.join(timeout=30)
        assert not t.is_alive()
        client.close()
        assert found is not None, "armed pull never delivered the ring"
        assert found["proc"] == "w0"
        admin.close()
    finally:
        server.shutdown()


def test_trace_pull_cli_writes_export_compatible_file(tmp_path,
                                                      capsys):
    """`dprf trace pull --connect` -> file -> `dprf trace export`."""
    eng, gen, targets, job = _mask_job("?d?d?d", [b"999"])
    state, server, disp, rec, reg = _serve(job, gen, targets)
    addr = "%s:%d" % server.address
    try:
        c = CoordinatorClient(*server.address)
        for _ in range(3):
            resp = c.call("lease", worker_id="w1")
            c.call("complete", unit_id=resp["unit"]["id"], hits=[],
                   worker_id="w1", job=resp["unit"]["job"])
        c.close()
        out = str(tmp_path / "pulled.trace.jsonl")
        rc = cli_main(["trace", "pull", "--connect", addr, "-o", out,
                       "--no-arm", "-q"])
        got = capsys.readouterr().out
        assert rc == 0
        info = json.loads(got.strip().splitlines()[-1])
        assert info["spans"] == len(rec.tail(4096)) > 0
        # the pulled stream feeds straight into trace export
        perfetto = str(tmp_path / "out.json")
        rc = cli_main(["trace", "export", out, "-o", perfetto, "-q"])
        assert rc == 0
        events = json.loads(open(perfetto).read())["traceEvents"]
        assert any(e.get("ph") == "X" for e in events)
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# age-based job GC (ISSUE 9 satellite): DPRF_JOB_TTL_S reaps terminal
# jobs so long-lived fleets never wedge at the MAX_JOBS cap

def test_job_gc_reaps_terminal_jobs_after_ttl(monkeypatch):
    monkeypatch.setenv("DPRF_JOB_TTL_S", "100")
    clk = [0.0]
    reg = MetricsRegistry()
    s = _sched(reg, clock=lambda: clk[0])
    default = _add(s, reg)
    a = _add(s, reg)
    b = _add(s, reg)
    s.cancel(a.job_id)
    clk[0] = 50.0
    # terminal but younger than the TTL: stays
    assert s.maybe_gc(keep=(default.job_id,), force=True) == []
    clk[0] = 200.0
    reaped = s.maybe_gc(keep=(default.job_id,), force=True)
    assert [j.job_id for j in reaped] == [a.job_id]
    assert s.get(a.job_id) is None
    assert s.get(b.job_id) is b          # running jobs never reaped
    # the protected (default) job survives even terminal and ancient
    s.cancel(default.job_id)
    s.cancel(b.job_id)
    clk[0] = 1000.0
    reaped = s.maybe_gc(keep=(default.job_id,), force=True)
    assert [j.job_id for j in reaped] == [b.job_id]
    assert s.get(default.job_id) is default


def test_job_gc_rate_limited_unless_forced(monkeypatch):
    monkeypatch.setenv("DPRF_JOB_TTL_S", "10")
    clk = [0.0]
    reg = MetricsRegistry()
    s = _sched(reg, clock=lambda: clk[0])
    default = _add(s, reg)
    a = _add(s, reg)
    s.cancel(a.job_id)
    clk[0] = 1.0
    assert s.maybe_gc(keep=(default.job_id,)) == []   # young; scans
    clk[0] = 20.0
    # within the 30 s scan interval of the last scan: unforced no-op,
    # forced reaps
    assert s.maybe_gc(keep=(default.job_id,)) == []
    reaped = s.maybe_gc(keep=(default.job_id,), force=True)
    assert [j.job_id for j in reaped] == [a.job_id]


def test_job_gc_disabled_with_zero_ttl(monkeypatch):
    monkeypatch.setenv("DPRF_JOB_TTL_S", "0")
    clk = [0.0]
    reg = MetricsRegistry()
    s = _sched(reg, clock=lambda: clk[0])
    default = _add(s, reg)
    a = _add(s, reg)
    s.cancel(a.job_id)
    clk[0] = 1e9
    assert s.maybe_gc(keep=(default.job_id,), force=True) == []
    assert s.get(a.job_id) is a


def test_job_gc_on_lease_path_fires_journal_hook(monkeypatch):
    monkeypatch.setenv("DPRF_JOB_TTL_S", "5")
    reg = MetricsRegistry()
    rec = TraceRecorder(enabled=False, registry=reg)
    clk = [0.0]
    sched = _sched(reg, clock=lambda: clk[0])
    disp = _disp(reg, rec=rec)
    events = []
    state = CoordinatorState(
        {"engine": "md5"}, disp, 1, registry=reg, recorder=rec,
        scheduler=sched,
        on_job_event=lambda kind, job: events.append((kind,
                                                      job.job_id)))
    tenant = _add(sched, reg, rec=rec)
    sched.cancel(tenant.job_id)
    clk[0] = 100.0
    state.op_lease({"worker_id": "w0", "ahead": 1})
    assert state.scheduler.get(tenant.job_id) is None
    assert ("gc", tenant.job_id) in events


def test_session_journal_job_gc_record_drops_job(tmp_path):
    path = str(tmp_path / "s.session")
    j = SessionJournal(path)
    j.open({"fingerprint": "x"})
    j.record_job("j1", {"engine": "md5"}, owner="alice")
    j.record_job("j2", {"engine": "md5"}, owner="bob")
    j.record_job_state("j1", "cancelled")
    j.record_job_gc("j1")
    j.close()
    st = SessionJournal.load(path)
    assert "j1" not in st.jobs          # GC'd: restore must skip it
    assert "j2" in st.jobs
