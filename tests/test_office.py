"""MS Office 2007 (hashcat 9400): AES reference vectors, the
MS-OFFCRYPTO derivation, and device workers (spin count lowered so the
CPU-mesh suite stays fast)."""

import hashlib
import os

import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops.aes import (aes128_decrypt_block, aes128_encrypt_block,
                              aes128_decrypt_blocks)
from dprf_tpu.runtime.workunit import WorkUnit


def test_aes_fips_vector():
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    pt = bytes.fromhex("3243f6a8885a308d313198a2e0370734")
    ct = aes128_encrypt_block(key, pt)
    assert ct.hex() == "3925841d02dc09fbdc118597196a0b32"
    assert aes128_decrypt_block(key, ct) == pt


def test_batched_decrypt_matches_scalar():
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    keys = rng.randint(0, 256, (32, 16), dtype=np.uint8)
    blocks = rng.randint(0, 256, (2, 16), dtype=np.uint8)
    got = np.asarray(aes128_decrypt_blocks(jnp.asarray(keys), blocks))
    for j in range(32):
        for n in range(2):
            assert bytes(got[j, n]) == \
                aes128_decrypt_block(bytes(keys[j]), bytes(blocks[n]))


def _line(pw: bytes, spin: int, salt: bytes = bytes(range(16))) -> str:
    eng = get_engine("office2007")
    eng.spin_count = spin
    key = eng._derive_key(pw, salt)
    verifier = os.urandom(16)
    vh = hashlib.sha1(verifier).digest() + os.urandom(12)
    ev = aes128_encrypt_block(key, verifier)
    evh = (aes128_encrypt_block(key, vh[:16])
           + aes128_encrypt_block(key, vh[16:]))
    return "$office$*2007*20*128*16*%s*%s*%s" % (
        salt.hex(), ev.hex(), evh.hex())


def test_parse_and_oracle():
    eng = get_engine("office2007")
    eng.spin_count = 100
    t = eng.parse_target(_line(b"secret", 100))
    assert eng.hash_batch([b"secret"], params=t.params)[0] == b"\x01"
    assert eng.hash_batch([b"wrong"], params=t.params)[0] == b"\x00"
    with pytest.raises(ValueError):
        eng.parse_target("$office$*2013*20*128*16*aa*bb*cc")
    with pytest.raises(ValueError):
        eng.parse_target("not an office line")


def test_device_mask_worker_cracks():
    cpu = get_engine("office2007")
    dev = get_engine("office2007", device="jax")
    cpu.spin_count = dev.spin_count = 100
    gen = MaskGenerator("?l?l")
    t = cpu.parse_target(_line(b"fx", 100))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fx"]


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("office2007")
    dev = get_engine("office2007", device="jax")
    cpu.spin_count = dev.spin_count = 80
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")], max_len=16)
    t = cpu.parse_target(_line(b"banana", 80))
    w = dev.make_wordlist_worker(gen, [t], batch=128, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}


def _agile_line(version: str, pw: bytes, spin: int) -> str:
    from dprf_tpu.engines.cpu.engines import (OFFICE_BK_INPUT,
                                              OFFICE_BK_VALUE)

    eng = get_engine(f"office{version}")
    salt = bytes(range(16))
    ki = eng._agile_key(pw, salt, spin, OFFICE_BK_INPUT)
    kv = eng._agile_key(pw, salt, spin, OFFICE_BK_VALUE)
    inp = os.urandom(16)
    want = hashlib.new(eng._hash, inp).digest()[:32].ljust(32, b"\x00")
    c_inp = aes128_encrypt_block(ki, bytes(a ^ b for a, b in
                                           zip(inp, salt)))
    cv1 = aes128_encrypt_block(kv, bytes(a ^ b for a, b in
                                         zip(want[:16], salt)))
    cv2 = aes128_encrypt_block(kv, bytes(a ^ b for a, b in
                                         zip(want[16:], cv1)))
    return "$office$*%s*%d*%d*16*%s*%s*%s" % (
        version, spin, eng._keybits, salt.hex(), c_inp.hex(),
        (cv1 + cv2).hex())


def test_aes256_fips_vector():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f"
                        "101112131415161718191a1b1c1d1e1f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    ct = aes128_encrypt_block(key, pt)     # generic dispatch by keylen
    assert ct.hex() == "8ea2b7ca516745bfeafc49904b496089"
    assert aes128_decrypt_block(key, ct) == pt


@pytest.mark.parametrize("version", ["2010", "2013"])
def test_agile_oracle(version):
    eng = get_engine(f"office{version}")
    t = eng.parse_target(_agile_line(version, b"secret", 60))
    assert eng.hash_batch([b"secret"], params=t.params)[0] == b"\x01"
    assert eng.hash_batch([b"wrong"], params=t.params)[0] == b"\x00"
    with pytest.raises(ValueError):
        eng.parse_target("$office$*2007*20*128*16*aa*bb*cc")


@pytest.mark.parametrize("version", ["2010", "2013"])
def test_agile_device_mask_cracks(version):
    cpu = get_engine(f"office{version}")
    dev = get_engine(f"office{version}", device="jax")
    t = cpu.parse_target(_agile_line(version, b"fx", 60))
    gen = MaskGenerator("?l?l")
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fx"]


def test_agile_device_wordlist_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("office2013")
    dev = get_engine("office2013", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")], max_len=16)
    t = cpu.parse_target(_agile_line("2013", b"banana", 50))
    w = dev.make_wordlist_worker(gen, [t], batch=128, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}
