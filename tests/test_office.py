"""MS Office 2007 (hashcat 9400): AES reference vectors, the
MS-OFFCRYPTO derivation, and device workers (spin count lowered so the
CPU-mesh suite stays fast)."""

import hashlib
import os

import numpy as np
import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops.aes import (aes128_decrypt_block, aes128_encrypt_block,
                              aes128_decrypt_blocks)
from dprf_tpu.runtime.workunit import WorkUnit


def test_aes_fips_vector():
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    pt = bytes.fromhex("3243f6a8885a308d313198a2e0370734")
    ct = aes128_encrypt_block(key, pt)
    assert ct.hex() == "3925841d02dc09fbdc118597196a0b32"
    assert aes128_decrypt_block(key, ct) == pt


def test_batched_decrypt_matches_scalar():
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    keys = rng.randint(0, 256, (32, 16), dtype=np.uint8)
    blocks = rng.randint(0, 256, (2, 16), dtype=np.uint8)
    got = np.asarray(aes128_decrypt_blocks(jnp.asarray(keys), blocks))
    for j in range(32):
        for n in range(2):
            assert bytes(got[j, n]) == \
                aes128_decrypt_block(bytes(keys[j]), bytes(blocks[n]))


def _line(pw: bytes, spin: int, salt: bytes = bytes(range(16))) -> str:
    eng = get_engine("office2007")
    eng.spin_count = spin
    key = eng._derive_key(pw, salt)
    verifier = os.urandom(16)
    vh = hashlib.sha1(verifier).digest() + os.urandom(12)
    ev = aes128_encrypt_block(key, verifier)
    evh = (aes128_encrypt_block(key, vh[:16])
           + aes128_encrypt_block(key, vh[16:]))
    return "$office$*2007*20*128*16*%s*%s*%s" % (
        salt.hex(), ev.hex(), evh.hex())


def test_parse_and_oracle():
    eng = get_engine("office2007")
    eng.spin_count = 100
    t = eng.parse_target(_line(b"secret", 100))
    assert eng.hash_batch([b"secret"], params=t.params)[0] == b"\x01"
    assert eng.hash_batch([b"wrong"], params=t.params)[0] == b"\x00"
    with pytest.raises(ValueError):
        eng.parse_target("$office$*2013*20*128*16*aa*bb*cc")
    with pytest.raises(ValueError):
        eng.parse_target("not an office line")


def test_device_mask_worker_cracks():
    cpu = get_engine("office2007")
    dev = get_engine("office2007", device="jax")
    cpu.spin_count = dev.spin_count = 100
    gen = MaskGenerator("?l?l")
    t = cpu.parse_target(_line(b"fx", 100))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fx"]


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("office2007")
    dev = get_engine("office2007", device="jax")
    cpu.spin_count = dev.spin_count = 80
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")], max_len=16)
    t = cpu.parse_target(_line(b"banana", 80))
    w = dev.make_wordlist_worker(gen, [t], batch=128, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}
