"""WPA2 handshake MIC (hc22000 WPA*02): reference cross-check against
an independent stdlib construction, parsing (key versions, SNonce
extraction), device cracks for both key versions, wordlist path, CLI."""

import hashlib
import hmac as hmac_mod

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.wpa2 import (make_wpa02_line, parse_wpa02,
                                       wpa2_mic)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit

AP = bytes.fromhex("aabbccddeeff")
STA = bytes.fromhex("112233445566")
AN = bytes(range(32))
SN = bytes(range(32, 64))


def test_reference_matches_independent_construction():
    """Re-derive the MIC with inline stdlib calls (802.11i PRF spelled
    out) and compare against wpa2_mic."""
    pw, essid = b"correcthorse", b"MyWifi"
    line = make_wpa02_line(pw, essid, AP, STA, AN, SN, keyver=2)
    f = parse_wpa02(line)
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    data = (min(AP, STA) + max(AP, STA) + min(AN, SN) + max(AN, SN))
    kck = hmac_mod.new(pmk, b"Pairwise key expansion\x00" + data
                       + b"\x00", hashlib.sha1).digest()[:16]
    want = hmac_mod.new(kck, f["eapol"], hashlib.sha1).digest()[:16]
    assert f["mic"] == want
    assert wpa2_mic(pw, essid, AP, STA, AN, f["eapol"], 2) == want


def test_parse_extracts_snonce_and_keyver():
    line = make_wpa02_line(b"x", b"Net", AP, STA, AN, SN, keyver=1)
    f = parse_wpa02(line)
    assert f["eapol"][17:49] == SN
    assert f["keyver"] == 1
    with pytest.raises(ValueError):
        parse_wpa02("WPA*01*aa*bb*cc*dd")        # PMKID line, not 02


@pytest.mark.parametrize("keyver", [2, 1])
def test_device_mask_crack(keyver):
    dev = get_engine("wpa2-eapol", "jax")
    cpu = get_engine("wpa2-eapol", "cpu")
    dev.iterations = cpu.iterations = 64
    try:
        gen = MaskGenerator("pw?d?d")
        line = make_wpa02_line(b"pw73", b"CoffeeShop", AP, STA, AN, SN,
                               keyver, iterations=64)
        t = dev.parse_target(line)
        w = dev.make_mask_worker(gen, [t], batch=32, hit_capacity=8,
                                 oracle=cpu)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        assert [(h.target_index, h.plaintext)
                for h in hits] == [(0, b"pw73")]
    finally:
        del dev.iterations, cpu.iterations


def test_device_wordlist_crack_mixed_keyvers():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("wpa2-eapol", "jax")
    cpu = get_engine("wpa2-eapol", "cpu")
    dev.iterations = cpu.iterations = 64
    try:
        words = [b"dragonfly", b"wintersun"]
        rules = [parse_rule(":"), parse_rule("$1")]
        gen = WordlistRulesGenerator(words, rules, max_len=12)
        t1 = dev.parse_target(make_wpa02_line(
            b"wintersun1", b"NetA", AP, STA, AN, SN, 2, iterations=64))
        t2 = dev.parse_target(make_wpa02_line(
            b"dragonfly", b"NetB", AP, STA, AN, SN, 1, iterations=64))
        w = dev.make_wordlist_worker(gen, [t1, t2], batch=8,
                                     hit_capacity=8, oracle=cpu)
        hits = sorted((h.target_index, h.plaintext)
                      for h in w.process(WorkUnit(0, 0, gen.keyspace)))
        assert hits == [(0, b"wintersun1"), (1, b"dragonfly")]
    finally:
        del dev.iterations, cpu.iterations


def test_cli_wpa2_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    cpu = get_engine("wpa2-eapol", "cpu")
    type(cpu).iterations = 64
    try:
        line = make_wpa02_line(b"pw9z", b"HomeNet", AP, STA, AN, SN, 2,
                               iterations=64)
        hf = tmp_path / "h.txt"
        hf.write_text(line + "\n")
        rc = main(["crack", "pw?d?l", str(hf), "--engine", "wpa2-eapol",
                   "--device", "tpu", "--no-potfile", "--batch", "64",
                   "--unit-size", "260", "-q"])
        out = capsys.readouterr().out
        assert rc == 0 and ":pw9z" in out
    finally:
        type(cpu).iterations = 4096
