"""Rank-ordered keyspace dispatch (ISSUE 20): the rank<->index
bijection, rank-space dispatcher resume/resplit, the OrderedWorker
decode path end to end, the chaos schedule under reordering, and the
time-to-first-hit win the whole plane exists to buy.

Pure CPU-oracle sweeps -- the ordering story is a dispatch property,
not a backend property -- so the file lands early in the tier-1
alphabet and inside the smoke/audit tiers.
"""

import hashlib

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.order import (IdentityOrder, MarkovOrder,
                                       build_order)
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.runtime.worker import CpuWorker, OrderedWorker
from dprf_tpu.telemetry.coverage import coverage_digest
from dprf_tpu.testing.chaos import run_chaos

pytestmark = [pytest.mark.smoke, pytest.mark.audit]

#: small mixed-radix keyspace (120) -- every property below is checked
#: exhaustively over it
RADICES = (5, 4, 3, 2)


def test_markov_order_is_a_bijection():
    order = MarkovOrder(RADICES, split=2)
    ks = order.keyspace
    assert ks == 120 and order.block == 6 and order.blocks == 20
    seen = set()
    for r in range(ks):
        ix = order.rank_to_index(r)
        assert order.index_to_rank(ix) == r
        seen.add(ix)
    assert seen == set(range(ks))
    with pytest.raises(IndexError):
        order.rank_to_index(ks)
    with pytest.raises(IndexError):
        order.index_to_rank(-1)


def test_rank_order_front_loads_small_level_sums():
    order = MarkovOrder(RADICES, split=2)
    sums = []
    for pr in range(order.blocks):
        pidx = order.rank_to_index(pr * order.block) // order.block
        sums.append(sum(order._prefix_digits_of_index(pidx)))
    # digit == frequency level: rank order must sweep prefixes in
    # non-decreasing level-sum order, starting from the all-most-
    # frequent vector
    assert sums[0] == 0
    assert sums == sorted(sums)


def test_interval_calculus_tiles_and_inverts():
    order = MarkovOrder(RADICES, split=2)
    ks = order.keyspace
    spans = order.index_spans(7, 95)
    assert sum(e - s for s, e in spans) == 95 - 7
    # the spans ARE the rank interval, point for point
    covered = {ix for s, e in spans for ix in range(s, e)}
    assert covered == {order.rank_to_index(r) for r in range(7, 95)}
    # canonical images invert exactly, and the full keyspace is fixed
    assert order.rank_image(order.index_image([(7, 95)])) == [(7, 95)]
    assert order.index_image([(0, ks)]) == [(0, ks)]
    ident = IdentityOrder(ks)
    assert ident.index_spans(7, 95) == [(7, 95)]
    assert ident.rank_image([(3, 9), (9, 20)]) == [(3, 20)]


def test_split_choice_env_knobs(monkeypatch):
    monkeypatch.setenv("DPRF_ORDER_BLOCK_MIN", "1")
    monkeypatch.setenv("DPRF_ORDER_PREFIX_MAX", "25")
    assert MarkovOrder(RADICES).split == 2      # 5*4 <= 25
    monkeypatch.setenv("DPRF_ORDER_PREFIX_MAX", "5")
    assert MarkovOrder(RADICES).split == 1
    monkeypatch.setenv("DPRF_ORDER_BLOCK_MIN", "7")
    assert MarkovOrder(RADICES).split == 1      # block must reach 24
    with pytest.raises(ValueError):
        MarkovOrder(RADICES, split=5)


def test_build_order_factory():
    gen = MaskGenerator("?l?l?l")
    assert build_order("index", gen) is None
    assert build_order(None, gen) is None
    order = build_order("markov", gen, split=1)
    assert order.kind == "markov" and order.keyspace == gen.keyspace
    with pytest.raises(ValueError):
        build_order("markov", object())         # no radices: wordlist
    with pytest.raises(ValueError):
        build_order("bogus", gen)


def test_rank_resume_resplit_different_unit_size():
    order = MarkovOrder(RADICES, split=2)
    ks = order.keyspace
    d1 = Dispatcher(ks, 16, order=order)
    for _ in range(4):
        unit = d1.lease()
        assert unit.order == "markov"
        d1.complete(unit.unit_id)
    completed = d1.completed_intervals()
    digest = d1.coverage_digest()
    # the journal view is the INDEX image of rank span [0, 64): same
    # mass, scattered runs, digest computable from intervals alone
    assert sum(e - s for s, e in completed) == 64
    assert digest == coverage_digest(ks, completed)
    # resume with a DIFFERENT unit size: the journaled index intervals
    # map back through rank_image, the digest must verify, and the
    # rank-space remainder resplits exactly -- no hole, no overlap
    d2 = Dispatcher.from_completed(ks, 10, completed,
                                   expect_digest=digest, order=order)
    assert d2.coverage_digest() == digest
    while True:
        unit = d2.lease()
        if unit is None:
            break
        d2.complete(unit.unit_id)
    assert d2.progress() == (ks, ks)
    assert d2.completed_intervals() == [(0, ks)]
    assert d2.coverage.overlap_total == 0
    assert d2.coverage.gap_total() == 0
    # a corrupted journal must still be refused under an order
    with pytest.raises(ValueError):
        Dispatcher.from_completed(ks, 10, completed,
                                  expect_digest="0" * 16, order=order)


def test_ordered_crack_end_to_end(tmp_path):
    """Full Coordinator run in rank space: planted hit recovered with
    its index-space cand_index, the sweep exhausts, and the journal
    digest is byte-identical to what a linear sweep would record."""
    gen = MaskGenerator("?l?l?l")
    pw = b"fox"
    eng = get_engine("md5", device="cpu")
    targets = [eng.parse_target(hashlib.md5(pw).hexdigest()),
               eng.parse_target("ff" * 16)]     # unmatchable: run out
    order = MarkovOrder(gen.radices, split=2)
    dispatcher = Dispatcher(gen.keyspace, 1 << 10, order=order)
    worker = OrderedWorker(CpuWorker(eng, gen, targets), order)
    session = SessionJournal(str(tmp_path / "ordered.session"))
    spec = JobSpec("md5", "cpu", "mask", "?l?l?l", gen.keyspace, "fp")
    result = Coordinator(spec, targets, dispatcher, worker,
                         session=session).run()
    assert result.found == {0: pw}
    assert result.exhausted and result.tested == gen.keyspace
    assert result.coverage_digest == coverage_digest(
        gen.keyspace, [(0, gen.keyspace)])
    assert dispatcher.coverage.overlap_total == 0


def test_chaos_schedule_under_markov_order(tmp_path):
    """The identical fault schedule (ISSUE 19) dispatched in rank
    space: every planted hit exactly once, digest-verified restart
    resume, auditor verdict clean from the artifacts alone."""
    result = run_chaos(str(tmp_path / "chaos.session"), order="markov")
    assert result["clean"], result
    assert result["order"] == "markov"
    assert result["audit_verdict"] == "clean"
    assert result["hits_found"] == result["hits_planted"]
    assert result["fraction"] == 1.0 and result["overlap"] == 0


def test_ttfh_ordered_beats_linear():
    """The acceptance property itself: rank-ordered dispatch reaches
    the planted first hit in >= 10x fewer candidates than index
    order.  Candidate counts are deterministic; the steady-state H/s
    penalty is wall-clock and CI-noisy, so the tight <10% gate rides
    the committed TTFH_r01.json record and this live check only
    guards against a pathological decode cost."""
    from dprf_tpu.bench import run_ttfh
    result = run_ttfh(engine="md5", plants=4)
    assert result["value"] >= 10.0, result
    assert result["ordered"]["candidates_to_first_hit"] * 10 <= \
        result["linear"]["candidates_to_first_hit"]
    assert result["penalty"] <= 0.30, result
