"""RAR5 (hashcat 13000): check-value construction, parse, device
workers over the pbkdf2-sha256 fold."""

import hashlib

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.engines import rar5_pswcheck
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(pw: bytes, n: int = 6, salt: bytes = bytes(range(16))) -> str:
    dk = hashlib.pbkdf2_hmac("sha256", pw, salt, (1 << n) + 32, 32)
    return "$rar5$16$%s$%d$%s$8$%s" % (
        salt.hex(), n, bytes(16).hex(), rar5_pswcheck(dk).hex())


def test_parse_and_oracle():
    eng = get_engine("rar5")
    t = eng.parse_target(_line(b"password"))
    assert t.params["iterations"] == (1 << 6) + 32
    assert eng.hash_batch([b"password"], params=t.params)[0] == t.digest
    assert not eng.verify(b"nope", t)
    with pytest.raises(ValueError):
        eng.parse_target("$rar5$16$aa$99$bb$8$cc")   # absurd exponent
    with pytest.raises(ValueError):
        eng.parse_target("not rar5")


def test_device_mask_worker_cracks():
    cpu = get_engine("rar5")
    dev = get_engine("rar5", device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(_line(b"fox"))
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("rar5")
    dev = get_engine("rar5", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")])
    t = cpu.parse_target(_line(b"banana"))
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}
