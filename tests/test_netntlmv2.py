"""NetNTLMv2 (hashcat 5600): reference vs stdlib hmac, device vs
reference (multi-block constant-message HMAC chains), workers, CLI."""

import hashlib
import hmac as hmac_mod

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.engines import netntlmv2_proof, parse_netntlmv2
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(pw, user, domain, chal, blob):
    proof = netntlmv2_proof(pw, user, domain, chal, blob)
    return f"{user}::{domain}:{chal.hex()}:{proof.hex()}:{blob.hex()}"


CHAL = bytes(range(8))
BLOB = bytes((i * 31 + 5) % 256 for i in range(200))


def test_reference_matches_stdlib_construction():
    from dprf_tpu.engines.cpu.md4 import md4

    pw, user, domain = b"Secret1", "alice", "EXAMPLE"
    nt = md4(pw.decode("latin-1").encode("utf-16-le"))
    key2 = hmac_mod.new(nt, (user.upper() + domain).encode("utf-16-le"),
                        "md5").digest()
    want = hmac_mod.new(key2, CHAL + BLOB, "md5").digest()
    assert netntlmv2_proof(pw, user, domain, CHAL, BLOB) == want


def test_parse_and_verify():
    cpu = get_engine("netntlmv2", "cpu")
    line = _line(b"hunter2", "Bob", "CORP", CHAL, BLOB)
    t = cpu.parse_target(line)
    assert t.params["user"] == "Bob" and t.params["domain"] == "CORP"
    assert cpu.verify(b"hunter2", t)
    assert not cpu.verify(b"hunter3", t)
    with pytest.raises(ValueError):
        parse_netntlmv2("no-double-colon:here")


def test_mask_worker_end_to_end():
    dev = get_engine("netntlmv2", "jax")
    cpu = get_engine("netntlmv2", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"k3z"
    t = dev.parse_target(_line(secret, "admin", "WORKGROUP", CHAL, BLOB))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_wordlist_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("netntlmv2", "jax")
    cpu = get_engine("netntlmv2", "cpu")
    words = [b"winter", b"summer"]
    rules = [parse_rule(":"), parse_rule("c $1")]
    gen = WordlistRulesGenerator(words, rules, max_len=20)
    secret = b"Summer1"
    t = dev.parse_target(_line(secret, "eve", "LAB", CHAL, BLOB))
    w = dev.make_wordlist_worker(gen, [t], batch=16, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_sharded_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("netntlmv2", "jax")
    cpu = get_engine("netntlmv2", "cpu")
    gen = MaskGenerator("?d?l")
    secret = b"7q"
    t = dev.parse_target(_line(secret, "svc", "NT", CHAL, BLOB))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=32, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_netntlmv2_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = _line(b"za9", "user1", "HOME", CHAL, BLOB)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "netntlmv2",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and ":za9" in out
