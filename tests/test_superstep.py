"""Super-step dispatch: fused many-batch device dispatches must be
bit-identical to the per-batch path (hits, overflow semantics, unit
boundaries), and the pipelined Coordinator must behave like the serial
one.

SURVEY.md section 3: the hot loop's host<->device link cost is part of
the production path; these tests pin the correctness of the machinery
that amortizes it (ops/superstep.py + worker submit/resolve +
Coordinator depth-2 pipelining).
"""

import hashlib

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops.superstep import make_super_step, max_inner
from dprf_tpu.runtime.worker import (DeviceMaskWorker,
                                     DeviceWordlistWorker,
                                     submit_or_process)
from dprf_tpu.runtime.workunit import WorkUnit

pytestmark = pytest.mark.smoke


def _hits_tuple(hits):
    return sorted((h.target_index, h.cand_index, h.plaintext)
                  for h in hits)


def _md5_targets(eng, plants):
    return [eng.parse_target(hashlib.md5(p).hexdigest()) for p in plants]


# -- factory ----------------------------------------------------------------

def test_max_inner_int32_budget():
    assert max_inner(1 << 22, 512) == 256       # 512 * 4M > 2^31
    assert max_inner(1 << 18, 512) == 512
    assert max_inner(1 << 31, 512) == 0


def test_super_step_stacks_and_clips():
    """A fake step records its (x, nv) arguments via its outputs; the
    wrapper must slice xs per iteration, clip n_valid exactly, and sum
    the flag function over iterations."""
    batch = 10

    def step(x, nv):
        return jnp.asarray(nv), x * 2, jnp.stack([x[0], nv])

    ss = make_super_step(step, inner=4, batch=batch)
    xs = jnp.arange(8, dtype=jnp.int32).reshape(4, 2)
    flag, (nvs, x2, pairs) = ss(xs, jnp.int32(25))
    # nv per iteration: 10, 10, 5, 0 -- flag (default out[0]) sums them
    assert int(flag) == 25
    assert [int(v) for v in np.asarray(nvs)] == [10, 10, 5, 0]
    assert np.asarray(x2).tolist() == (np.arange(8).reshape(4, 2) * 2).tolist()
    assert np.asarray(pairs)[:, 0].tolist() == [0, 2, 4, 6]


def test_super_step_custom_flag():
    def step(x, nv):
        return jnp.int32(0), jnp.asarray(nv)

    ss = make_super_step(step, inner=3, batch=5,
                         flag_fn=lambda out: out[1])
    flag, _ = ss(jnp.zeros((3, 1), jnp.int32), jnp.int32(12))
    assert int(flag) == 12


def test_super_step_rejects_int32_overflow():
    with pytest.raises(ValueError):
        make_super_step(lambda x, nv: (nv,), inner=512, batch=1 << 22)


# -- mask workers -----------------------------------------------------------

@pytest.fixture
def md5_jax():
    return get_engine("md5", device="jax")


def _mask_worker(eng, gen, targets, batch, **kw):
    return DeviceMaskWorker(eng, gen, targets,
                            oracle=get_engine("md5"), batch=batch, **kw)


def test_mask_super_matches_per_batch(md5_jax, monkeypatch):
    """Plants inside super chunks, in the per-batch tail, and across
    chunk boundaries must decode to identical hits either way."""
    gen = MaskGenerator("?l?l?l?l")          # keyspace 456976
    batch = 1 << 12
    # 40 strides: super chunks 32 + per-batch tail 8 (SUPER_MIN=8)
    unit = WorkUnit(0, 0, 40 * batch)
    plants = [b"aaaa",                       # index 0
              gen.candidate(32 * batch - 1),  # last lane of chunk
              gen.candidate(32 * batch),      # first tail batch lane
              gen.candidate(40 * batch - 1)]  # very last unit lane
    targets = _md5_targets(md5_jax, plants)
    w_super = _mask_worker(md5_jax, gen, targets, batch)
    got = _hits_tuple(w_super.process(unit))
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w_plain = _mask_worker(md5_jax, gen, targets, batch)
    assert got == _hits_tuple(w_plain.process(unit))
    assert {h[2] for h in got} == set(plants)


def test_mask_super_partial_tail(md5_jax):
    """Unit end mid-batch after super chunks: n_valid masking must
    exclude out-of-unit candidates."""
    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    end = 8 * batch + 100
    inside = gen.candidate(end - 1)
    outside = gen.candidate(end)             # 1 past the unit
    targets = _md5_targets(md5_jax, [inside, outside])
    w = _mask_worker(md5_jax, gen, targets, batch)
    hits = w.process(WorkUnit(0, 0, end))
    assert _hits_tuple(hits) == [(0, end - 1, inside)]


def test_mask_super_offset_unit(md5_jax, monkeypatch):
    """Units not starting at 0 decode global indices correctly."""
    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    start = 13 * batch + 7
    unit = WorkUnit(3, start, 16 * batch)
    plant = gen.candidate(start + 9 * batch + 5)
    targets = _md5_targets(md5_jax, [plant])
    w = _mask_worker(md5_jax, gen, targets, batch)
    got = _hits_tuple(w.process(unit))
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = _mask_worker(md5_jax, gen, targets, batch)
    assert got == _hits_tuple(w2.process(unit)) != []


def test_mask_super_multi_target(md5_jax, monkeypatch):
    """1k-list-style multi-target compare through the super path."""
    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    plants = [gen.candidate(i * 37777) for i in range(5)]
    targets = _md5_targets(md5_jax, plants) + _md5_targets(
        md5_jax, [b"zzzz"])
    unit = WorkUnit(0, 0, 48 * batch)
    w = _mask_worker(md5_jax, gen, targets, batch)
    got = _hits_tuple(w.process(unit))
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = _mask_worker(md5_jax, gen, targets, batch)
    assert got == _hits_tuple(w2.process(unit))
    assert len(got) == sum(gen.index_of(p) < unit.end for p in plants)


def test_mask_super_overflow_rescan(md5_jax):
    """count > hit_capacity inside a super ROW falls back to the exact
    oracle rescan of that one batch -- same granularity as per-batch."""
    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    # 3 plants inside one batch of a super chunk, capacity 2
    base = 17 * batch
    plants = [gen.candidate(base + i) for i in (1, 2, 3)]
    targets = _md5_targets(md5_jax, plants)
    w = _mask_worker(md5_jax, gen, targets, batch, hit_capacity=2)
    hits = w.process(WorkUnit(0, 0, 32 * batch))
    assert {h.plaintext for h in hits} == set(plants)


def test_superstep_disabled_env(md5_jax, monkeypatch):
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    gen = MaskGenerator("?l?l?l")
    w = _mask_worker(md5_jax, gen, _md5_targets(md5_jax, [b"cat"]),
                     1 << 10)
    pu = w.submit(WorkUnit(0, 0, gen.keyspace))
    assert all(kind == "batch" for kind, _, _ in pu.queued)
    assert _hits_tuple(pu.resolve()) == [(0, gen.index_of(b"cat"), b"cat")]


def test_super_build_failure_degrades_to_per_batch(md5_jax):
    """A backend that rejects the scan-wrapped program must degrade
    the worker to per-batch dispatch, not kill the job."""
    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    plant = gen.candidate(9 * batch + 4)
    w = _mask_worker(md5_jax, gen, _md5_targets(md5_jax, [plant]), batch)

    def broken_super_step(inner):
        raise RuntimeError("mosaic says no")

    w._super_step = broken_super_step
    hits = w.process(WorkUnit(0, 0, 16 * batch))
    assert [h.plaintext for h in hits] == [plant]
    assert w._super_disabled
    # and the flag sticks: no further super attempts
    assert w._super_inner(64) == 0


def test_submit_or_process_wraps_sync_workers():
    from dprf_tpu.runtime.worker import CpuWorker

    gen = MaskGenerator("?l?l?l")
    oracle = get_engine("md5")
    w = CpuWorker(oracle, gen, _md5_targets(oracle, [b"dog"]))
    p = submit_or_process(w, WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in p.resolve()] == [b"dog"]


# -- pallas kernel path -----------------------------------------------------

def test_pallas_super_matches_plain(md5_jax, monkeypatch):
    from dprf_tpu.ops.pallas_mask import TILE
    from dprf_tpu.runtime.worker import PallasMaskWorker

    gen = MaskGenerator("?l?l?l?l")
    plants = [gen.candidate(5), gen.candidate(9 * TILE + 17)]
    targets = _md5_targets(md5_jax, plants)
    unit = WorkUnit(0, 0, 10 * TILE)
    w = PallasMaskWorker(md5_jax, gen, targets[:1], batch=TILE,
                         oracle=get_engine("md5"), interpret=True)
    got = _hits_tuple(w.process(unit))
    assert got == [(0, 5, plants[0])]
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = PallasMaskWorker(md5_jax, gen, targets[:1], batch=TILE,
                          oracle=get_engine("md5"), interpret=True)
    assert got == _hits_tuple(w2.process(unit))


# -- wordlist workers -------------------------------------------------------

def _words(n, length=6):
    rng = np.random.default_rng(7)
    alpha = np.frombuffer(b"abcdefghijklmnopqrstuvwxyz", np.uint8)
    return [bytes(alpha[rng.integers(0, 26, length)]) for _ in range(n)]


def test_wordlist_super_matches_per_batch(monkeypatch):
    from dprf_tpu.rules.parser import parse_rules

    eng = get_engine("md5", device="jax")
    oracle = get_engine("md5")
    words = _words(4096)
    rules = parse_rules([":", "u", "$1", "r"])
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    # plant: word 3000 under rule 1 (uppercase)
    plant = words[3000].upper()
    targets = _md5_targets(eng, [plant, b"nope.."])
    # word_batch 128 -> 32 windows; super covers 32, unit = whole space
    w = DeviceWordlistWorker(eng, gen, targets, batch=128 * gen.n_rules,
                             oracle=oracle)
    unit = WorkUnit(0, 0, gen.keyspace)
    got = _hits_tuple(w.process(unit))
    assert (0, 3000 * gen.n_rules + 1, plant) in got
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = DeviceWordlistWorker(eng, gen, targets, batch=128 * gen.n_rules,
                              oracle=oracle)
    assert got == _hits_tuple(w2.process(unit))


def test_wordlist_super_unaligned_unit(monkeypatch):
    """Rule-unaligned unit boundaries: out-of-unit hits filtered the
    same way on both paths."""
    from dprf_tpu.rules.parser import parse_rules

    eng = get_engine("md5", device="jax")
    words = _words(2048)
    rules = parse_rules([":", "l", "u"])
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    plant_g = 500 * 3 + 2
    targets = _md5_targets(eng, [gen.candidate(plant_g)])
    unit = WorkUnit(0, 100, plant_g + 2 - 100)
    w = DeviceWordlistWorker(eng, gen, targets, batch=64 * 3,
                             oracle=get_engine("md5"))
    got = _hits_tuple(w.process(unit))
    assert [g for _, g, _ in got] == [plant_g]
    monkeypatch.setenv("DPRF_SUPERSTEP", "0")
    w2 = DeviceWordlistWorker(eng, gen, targets, batch=64 * 3,
                              oracle=get_engine("md5"))
    assert got == _hits_tuple(w2.process(unit))


# -- pipelined coordinator --------------------------------------------------

def test_coordinator_pipelined_run(md5_jax, tmp_path):
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.dispatcher import Dispatcher

    gen = MaskGenerator("?l?l?l?l")
    batch = 1 << 12
    plants = [gen.candidate(i) for i in (3, 99999, 420000)]
    targets = _md5_targets(md5_jax, plants)
    worker = _mask_worker(md5_jax, gen, targets, batch)
    disp = Dispatcher(gen.keyspace, unit_size=16 * batch)
    spec = JobSpec("md5", "jax", "mask", "?l?l?l?l", gen.keyspace, "t")
    coord = Coordinator(spec, targets, disp, worker,
                        oracle=get_engine("md5"))
    res = coord.run()
    assert sorted(res.found.values()) == sorted(plants)
    # stopped early (all found) or exhausted -- either way every
    # completed unit is journaled consistently
    assert res.tested <= gen.keyspace


def test_coordinator_pipeline_depth_overlap(md5_jax):
    """The coordinator must submit ahead: at least two units in flight
    before the first resolve (observable via submit call order)."""
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.dispatcher import Dispatcher

    gen = MaskGenerator("?l?l?l")
    worker = _mask_worker(md5_jax, gen,
                          _md5_targets(md5_jax, [b"zzz"]), 1 << 10)
    events = []
    orig_submit = worker.submit

    class _Spy:
        def __init__(self, pu, start):
            self.pu, self.start = pu, start

        def resolve(self):
            events.append(("resolve", self.start))
            return self.pu.resolve()

    def spy_submit(unit):
        events.append(("submit", unit.start))
        return _Spy(orig_submit(unit), unit.start)

    worker.submit = spy_submit
    disp = Dispatcher(gen.keyspace, unit_size=1 << 12)
    spec = JobSpec("md5", "jax", "mask", "?l?l?l", gen.keyspace, "t")
    Coordinator(spec, _md5_targets(md5_jax, [b"zzz"]), disp, worker,
                oracle=get_engine("md5")).run()
    kinds = [k for k, _ in events]
    assert kinds[:3] == ["submit", "submit", "resolve"]
