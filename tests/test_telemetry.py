"""Telemetry subsystem: registry semantics, /metrics over the RPC
port, JSONL snapshot round-trip, and a planted-crack integration test
asserting the scraped counters match coordinator state."""

import json
import threading

import pytest

from dprf_tpu.telemetry import (MetricsRegistry, TelemetrySnapshotter,
                                load_snapshots, scrape_metrics,
                                telemetry_path)

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# registry semantics

def test_counter_labels_and_values():
    r = MetricsRegistry()
    c = r.counter("dprf_test_total", "a counter", labelnames=("engine",))
    c.inc(engine="md5")
    c.inc(41, engine="md5")
    c.inc(7, engine="sha1")
    assert c.value(engine="md5") == 42
    assert c.value(engine="sha1") == 7
    with pytest.raises(ValueError):
        c.inc(-1, engine="md5")          # counters only go up
    with pytest.raises(ValueError):
        c.inc(1, wrong="label")          # undeclared label set
    # get-or-create: same declaration returns the same metric
    assert r.counter("dprf_test_total", "x", labelnames=("engine",)) is c
    # conflicting re-declaration is an error, not silent shadowing
    with pytest.raises(ValueError):
        r.counter("dprf_test_total", "x", labelnames=("other",))
    with pytest.raises(ValueError):
        r.gauge("dprf_test_total", "x", labelnames=("engine",))


def test_histogram_bucket_redeclaration_conflicts():
    r = MetricsRegistry()
    h = r.histogram("dprf_rb_seconds", "x", buckets=(1, 10))
    assert r.histogram("dprf_rb_seconds", "x", buckets=(10, 1)) is h
    with pytest.raises(ValueError):
        r.histogram("dprf_rb_seconds", "x", buckets=(2, 20))


def test_worker_liveness_label_cap():
    """worker_id is client-controlled; id churn past the cap shares
    one overflow child instead of growing the registry forever."""
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.rpc import CoordinatorState

    reg = MetricsRegistry()
    state = CoordinatorState({}, Dispatcher(10, 5, registry=reg), 1,
                             registry=reg)
    state.MAX_WORKER_LABELS = 4
    for i in range(10):
        state._touch_worker(f"w{i}")
    g = reg.get("dprf_worker_last_seen_timestamp")
    assert g.child_count() == 5         # 4 real ids + _overflow
    assert g.has_labels(worker="_overflow")
    assert not g.has_labels(worker="w9")


def test_gauge_set_inc_dec():
    r = MetricsRegistry()
    g = r.gauge("dprf_g", "a gauge")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value() == 6


def test_histogram_buckets_sum_count_and_timer():
    r = MetricsRegistry()
    h = r.histogram("dprf_h_seconds", "latency", buckets=(0.1, 1, 10))
    for v in (0.05, 0.5, 0.5, 5, 100):
        h.observe(v)
    assert h.count() == 5
    assert h.sum() == pytest.approx(106.05)
    text = r.render()
    # cumulative bucket counts in the exposition
    assert 'dprf_h_seconds_bucket{le="0.1"} 1' in text
    assert 'dprf_h_seconds_bucket{le="1"} 3' in text
    assert 'dprf_h_seconds_bucket{le="10"} 4' in text
    assert 'dprf_h_seconds_bucket{le="+Inf"} 5' in text
    assert "dprf_h_seconds_count 5" in text
    with h.time():
        pass
    assert h.count() == 6


def test_render_prometheus_shape():
    r = MetricsRegistry()
    r.counter("b_total", "second").inc(2)
    r.counter("a_total", "first", labelnames=("x",)).inc(x='we"ird\n')
    text = r.render()
    # HELP/TYPE headers precede samples; label values are escaped
    lines = text.splitlines()
    assert lines[0] == "# HELP a_total first"
    assert lines[1] == "# TYPE a_total counter"
    assert lines[2] == 'a_total{x="we\\"ird\\n"} 1'
    assert "b_total 2" in lines
    # snapshot is JSON-serializable and value-faithful
    snap = json.loads(r.snapshot_json())
    assert snap["b_total"]["kind"] == "counter"
    assert snap["b_total"]["values"][0]["value"] == 2


def test_registry_thread_safety():
    """Exact totals under the RPC server's handler-thread concurrency
    (and the worker's async submit): no lost increments."""
    r = MetricsRegistry()
    c = r.counter("dprf_t_total", "t", labelnames=("w",))
    h = r.histogram("dprf_t_seconds", "t")

    def work(i):
        for _ in range(5000):
            c.inc(w=f"w{i % 2}")
            h.observe(0.01)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value(w="w0") + c.value(w="w1") == 40000
    assert h.count() == 40000


# ---------------------------------------------------------------------------
# snapshot JSONL round-trip

def test_snapshot_jsonl_roundtrip(tmp_path):
    r = MetricsRegistry()
    r.counter("dprf_hits_total", "hits").inc(3)
    path = telemetry_path(str(tmp_path / "job.session"))
    snap = TelemetrySnapshotter(path, r, interval=60.0)
    snap.write_once()
    r.counter("dprf_hits_total", "hits").inc(2)
    snap.write_once()
    docs = load_snapshots(path)
    assert len(docs) == 2
    assert docs[0]["metrics"]["dprf_hits_total"]["values"][0]["value"] == 3
    assert docs[1]["metrics"]["dprf_hits_total"]["values"][0]["value"] == 5
    assert docs[1]["ts"] >= docs[0]["ts"]
    assert docs[1]["elapsed_s"] >= docs[0]["elapsed_s"]
    # torn tail line (killed run) is skipped, not fatal
    with open(path, "a") as fh:
        fh.write('{"ts": 1, "metr')
    assert len(load_snapshots(path)) == 2


def test_snapshotter_background_thread(tmp_path):
    r = MetricsRegistry()
    g = r.gauge("dprf_live", "liveness")
    g.set(1)
    path = str(tmp_path / "t.jsonl")
    snap = TelemetrySnapshotter(path, r, interval=0.3).start()
    import time
    time.sleep(1.0)
    snap.stop()                  # final line always written
    docs = load_snapshots(path)
    assert len(docs) >= 2
    assert docs[-1]["metrics"]["dprf_live"]["values"][0]["value"] == 1


# ---------------------------------------------------------------------------
# /metrics endpoint on the RPC port + planted-crack integration

def _planted_job(mask, plants, unit_size, registry):
    import hashlib

    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.rpc import CoordinatorServer, CoordinatorState
    from dprf_tpu.runtime.session import job_fingerprint

    eng = get_engine("md5")
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest())
               for p in plants]
    fp = job_fingerprint("md5", f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "md5", "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": unit_size, "batch": 4096, "hit_cap": 8,
           "fingerprint": fp}
    dispatcher = Dispatcher(gen.keyspace, unit_size, registry=registry)
    state = CoordinatorState(job, dispatcher, len(targets),
                             registry=registry)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return eng, gen, targets, state, server, dispatcher


def test_metrics_endpoint_and_counters_match_state():
    """Two workers crack a planted job; the scraped /metrics endpoint
    must agree with coordinator state: hits, units, candidates, and
    coverage (the ISSUE 1 acceptance criterion)."""
    from dprf_tpu.runtime.rpc import CoordinatorClient, worker_loop
    from dprf_tpu.runtime.worker import CpuWorker

    reg = MetricsRegistry()
    # "zz" is the LAST candidate, so no early stop: every unit runs
    eng, gen, targets, state, server, dispatcher = _planted_job(
        "?l?l", [b"ca", b"zz"], unit_size=100, registry=reg)
    try:
        def run_worker(wid):
            client = CoordinatorClient(*server.address)
            w = CpuWorker(eng, gen, targets)
            worker_loop(client, w, wid, idle_sleep=0.01, registry=reg)
            client.close()

        ts = [threading.Thread(target=run_worker, args=(f"w{i}",))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert state.finished()
        assert state.found == {0: b"ca", 1: b"zz"}

        n_units = -(-gen.keyspace // 100)
        assert reg.get("dprf_hits_total").value() == len(state.found)
        assert reg.get("dprf_units_completed_total").value(job="j0") == n_units
        assert reg.get("dprf_units_leased_total").value(job="j0") == n_units
        assert reg.get("dprf_keyspace_covered").value(job="j0") == gen.keyspace
        cands = reg.get("dprf_candidates_hashed_total")
        assert cands.value(engine="md5", device="cpu") == gen.keyspace
        # the coordinator ALSO attributes completed units (its registry
        # is the scrapeable one; remote workers hash in other processes)
        assert cands.value(engine="md5", device="remote") == gen.keyspace
        assert reg.get("dprf_targets_found").value() == 2

        # scrape over the SAME port the RPC protocol uses
        text = scrape_metrics(*server.address)
        assert "dprf_hits_total 2" in text
        assert ('dprf_units_completed_total{job="j0"} '
                f"{n_units}") in text
        assert ('dprf_candidates_hashed_total{engine="md5",'
                f'device="cpu"}} {gen.keyspace}') in text
        assert 'dprf_worker_last_seen_timestamp{worker="w0"}' in text
        # op accounting saw the lease/complete traffic
        assert 'dprf_rpc_requests_total{op="lease"}' in text
    finally:
        server.shutdown()


def test_metrics_http_404_and_rpc_op():
    from dprf_tpu.runtime.rpc import CoordinatorClient

    reg = MetricsRegistry()
    *_, state, server, _ = _planted_job("?d", [b"7"], 5, reg)
    try:
        with pytest.raises(ValueError):
            scrape_metrics(*server.address, path="/nope")
        # the authenticated-protocol read of the same registry
        client = CoordinatorClient(*server.address)
        resp = client.call("metrics")
        assert "dprf_units_leased_total" in resp["text"]
        resp = client.call("metrics", format="json")
        assert resp["metrics"]["dprf_keyspace_total"]["values"][0][
            "value"] == 10
        client.close()
    finally:
        server.shutdown()


def test_metrics_endpoint_served_with_token_auth():
    """Read-only scrape needs no shared secret even when the RPC
    protocol is token-authenticated (it exposes counts, never the job
    or hits); the JSON protocol still challenges."""
    import hashlib

    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                      CoordinatorState, RpcError)

    reg = MetricsRegistry()
    eng = get_engine("md5")
    gen = MaskGenerator("?d")
    targets = [eng.parse_target(hashlib.md5(b"3").hexdigest())]
    job = {"engine": "md5"}
    state = CoordinatorState(job, Dispatcher(gen.keyspace, 5,
                                             registry=reg),
                             len(targets), token="s3cret", registry=reg)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        text = scrape_metrics(*server.address)
        assert 'dprf_keyspace_total{job="j0"} 10' in text
        client = CoordinatorClient(*server.address)   # no token
        with pytest.raises(RpcError):
            client.hello()
        client.close()
    finally:
        server.shutdown()


def test_local_coordinator_publishes(tmp_path):
    """The in-process Coordinator path publishes the same metric names
    the distributed path does (one dashboard for both)."""
    import hashlib

    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.worker import CpuWorker

    reg = MetricsRegistry()
    eng = get_engine("md5")
    gen = MaskGenerator("?l?l")
    targets = [eng.parse_target(hashlib.md5(b"zz").hexdigest())]
    spec = JobSpec(engine="md5", device="cpu", attack="mask",
                   attack_arg="?l?l", keyspace=gen.keyspace,
                   fingerprint="t")
    coord = Coordinator(spec, targets,
                        Dispatcher(gen.keyspace, 100, registry=reg),
                        CpuWorker(eng, gen, targets), registry=reg)
    result = coord.run()
    assert result.found == {0: b"zz"}
    assert reg.get("dprf_hits_total").value() == 1
    assert reg.get("dprf_candidates_hashed_total").value(
        engine="md5", device="cpu") == result.tested
    assert reg.get("dprf_unit_seconds").count() == \
        reg.get("dprf_units_completed_total").value(job="j0")
    assert reg.get("dprf_targets_found").value() == 1


# ---------------------------------------------------------------------------
# bench freshness contract (driver bench.py)

def _load_driver_bench():
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_driver", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_freshness_gate(tmp_path):
    """The cached-session tier may not be reported twice in a row: the
    first cached report flips the state file, and the next driver run
    must refuse the tier until a fresh measurement lands."""
    mod = _load_driver_bench()
    wd = str(tmp_path)
    assert mod._cached_tier_allowed(wd)          # no state yet
    mod._record_freshness(wd, True, 3.0e9)       # fresh report
    assert mod._cached_tier_allowed(wd)
    mod._record_freshness(wd, False, 2.0e9)      # cached report
    assert not mod._cached_tier_allowed(wd)      # refuse a second
    mod._record_freshness(wd, True, 3.1e9)       # fresh again
    assert mod._cached_tier_allowed(wd)
    doc = json.load(open(mod._freshness_state_path(wd)))
    assert doc["last_fresh"] is True and doc["last_value"] == 3.1e9


def test_bench_publishes_to_registry():
    """dprf_tpu.bench runs report through the shared registry."""
    from dprf_tpu.bench import run_bench
    from dprf_tpu.telemetry import DEFAULT

    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l",
                    batch=1024, seconds=0.1)
    g = DEFAULT.get("dprf_bench_rate_hs")
    assert g is not None
    assert g.value(engine="md5", impl="xla",
                   device="cpu", mode="bench") == res["value"]
    assert DEFAULT.get("dprf_bench_runs_total").value(mode="bench") >= 1
