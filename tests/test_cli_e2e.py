"""End-to-end CLI tests: plant a password, crack it, resume a session.

SURVEY.md section 4: "plant a known password in a tiny keyspace; assert
it is found and the session resumes correctly after a simulated kill."
"""

import hashlib
import io
import json

import pytest

from dprf_tpu.cli import main
from dprf_tpu.runtime.potfile import Potfile


def run_cli(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr().out
    return rc, out


def _mk_hashfile(tmp_path, digests, name="hashes.txt"):
    p = tmp_path / name
    p.write_text("\n".join(digests) + "\n")
    return str(p)


@pytest.fixture
def md5_of():
    return lambda b: hashlib.md5(b).hexdigest()


@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_crack_planted_password(tmp_path, capsys, md5_of, device):
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"xyz")])
    pot = str(tmp_path / "t.pot")
    rc, out = run_cli(["crack", "?l?l?l", hashfile, "--engine", "md5",
                       "--device", device, "--potfile", pot,
                       "--unit-size", "4096", "--batch", "1024", "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(b'xyz')}:xyz" in out
    assert Potfile(pot).get(md5_of(b"xyz")) == b"xyz"


def test_crack_multi_hash_list(tmp_path, capsys, md5_of):
    words = [b"aa", b"mz", b"zz"]
    digests = [md5_of(w) for w in words] + [md5_of(b"too-long-not-in-space")]
    hashfile = _mk_hashfile(tmp_path, digests)
    rc, out = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                       "--device", "tpu", "--no-potfile",
                       "--unit-size", "256", "--batch", "128", "-q"], capsys)
    # one target is uncrackable -> exhausted, rc 0 because others found
    assert rc == 0
    for w in words:
        assert f"{md5_of(w)}:{w.decode()}" in out
    assert md5_of(b"too-long-not-in-space") + ":" not in out


def test_no_match_exhausts_with_rc1(tmp_path, capsys, md5_of):
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"NOPE")])
    rc, out = run_cli(["crack", "?d?d", hashfile, "--engine", "md5",
                       "--device", "cpu", "--no-potfile", "-q"], capsys)
    assert rc == 1
    assert out.strip() == ""


def test_session_resume_skips_completed(tmp_path, capsys, md5_of):
    # Plant the password near the END of the keyspace; first run covers
    # only the beginning (simulated kill via tiny keyspace slicing is
    # awkward, so instead resume from a synthetic journal that claims
    # the first 60% is done).
    from dprf_tpu.runtime.session import SessionJournal, job_fingerprint
    from dprf_tpu.generators.mask import MaskGenerator

    secret = b"zz"
    gen = MaskGenerator("?l?l")
    hashfile = _mk_hashfile(tmp_path, [md5_of(secret)])
    session = str(tmp_path / "s.json")
    fp = job_fingerprint("md5", "mask:?l?l", gen.keyspace,
                         [hashlib.md5(secret).digest()])
    j = SessionJournal(session)
    j.open({"engine": "md5", "device": "cpu", "attack": "mask",
            "attack_arg": "?l?l", "keyspace": gen.keyspace,
            "fingerprint": fp})
    j.snapshot([(0, 400)])
    j.close()

    rc, out = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                       "--device", "cpu", "--no-potfile",
                       "--session", session, "--restore",
                       "--unit-size", "64", "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(secret)}:zz" in out
    # journal now shows full coverage
    st = SessionJournal.load(session)
    assert st.completed == [(0, gen.keyspace)]


def test_session_wrong_job_rejected(tmp_path, capsys, md5_of):
    from dprf_tpu.runtime.session import SessionJournal

    hashfile = _mk_hashfile(tmp_path, [md5_of(b"aa")])
    session = str(tmp_path / "s.json")
    j = SessionJournal(session)
    j.open({"fingerprint": "something-else"})
    j.close()
    rc, _ = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                     "--device", "cpu", "--no-potfile",
                     "--session", session, "--restore", "-q"], capsys)
    assert rc == 2


def test_potfile_precracked_skips_work(tmp_path, capsys, md5_of):
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"ab")])
    pot = str(tmp_path / "t.pot")
    Potfile(pot).add(md5_of(b"ab"), b"ab")
    rc, out = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                       "--device", "cpu", "--potfile", pot, "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(b'ab')}:ab" in out


@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_crack_wordlist_rules_sha256(tmp_path, capsys, device):
    """Benchmark config 3: SHA-256 raw, wordlist + best64 rules."""
    wl = tmp_path / "wl.txt"
    wl.write_text("winter\nflower\ndragon\nsunshine\n")
    secret = b"dragon123"      # "dragon" via best64's "$1 $2 $3"
    digest = hashlib.sha256(secret).hexdigest()
    hashfile = _mk_hashfile(tmp_path, [digest])
    rc, out = run_cli(["crack", str(wl), hashfile, "--engine", "sha256",
                       "-a", "wordlist", "--rules", "best64",
                       "--device", device, "--no-potfile",
                       "--batch", "256", "-q"], capsys)
    assert rc == 0
    assert f"{digest}:dragon123" in out


def test_crack_wordlist_no_rules_ntlm(tmp_path, capsys):
    from dprf_tpu.engines.cpu.md4 import md4

    wl = tmp_path / "wl.txt"
    wl.write_text("alpha\nhunter2\nzulu\n")
    ntlm = md4(bytes(b for ch in b"hunter2" for b in (ch, 0))).hex()
    hashfile = _mk_hashfile(tmp_path, [ntlm])
    rc, out = run_cli(["crack", str(wl), hashfile, "--engine", "ntlm",
                       "-a", "wordlist", "--device", "tpu",
                       "--no-potfile", "-q"], capsys)
    assert rc == 0
    assert f"{ntlm}:hunter2" in out


def test_wordlist_session_resume(tmp_path, capsys):
    """Kill-and-resume over a wordlist+rules keyspace: second run only
    covers the remainder and still finds the planted password."""
    from dprf_tpu.runtime.session import SessionJournal

    wl = tmp_path / "wl.txt"
    words = [f"word{i:03d}" for i in range(50)] + ["secret"]
    wl.write_text("\n".join(words))
    digest = hashlib.md5(b"SECRET").hexdigest()     # via rule "u"
    hashfile = _mk_hashfile(tmp_path, [digest])
    session = str(tmp_path / "s.json")
    base = ["crack", str(wl), hashfile, "--engine", "md5",
            "-a", "wordlist", "--rules", "toggle",
            "--device", "cpu", "--no-potfile", "--session", session,
            "--unit-size", "64", "-q"]
    rc, out = run_cli(base, capsys)
    assert rc == 0 and f"{digest}:SECRET" in out
    st = SessionJournal.load(session)
    keyspace = 51 * 17          # 51 words x 17 toggle rules
    assert st.completed == [(0, keyspace)]
    # resume a completed session: no work left, hit restored
    rc, out = run_cli(base + ["--restore"], capsys)
    assert rc == 0 and f"{digest}:SECRET" in out


def test_keyspace_and_engines_commands(capsys):
    rc, out = run_cli(["keyspace", "?l?l?l?l?l?l"], capsys)
    assert rc == 0 and out.strip() == str(26 ** 6)
    rc, out = run_cli(["engines"], capsys)
    assert rc == 0 and "md5" in out


def test_malformed_hashlist_line_skipped(tmp_path, capsys, md5_of):
    p = tmp_path / "h.txt"
    p.write_text(f"# comment\nnot-a-hash\n{md5_of(b'ok')}\n\n")
    rc, out = run_cli(["crack", "?l?l", str(p), "--engine", "md5",
                       "--device", "cpu", "--no-potfile", "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(b'ok')}:ok" in out


def test_crack_mask_multichip(tmp_path, capsys, md5_of):
    """--devices 8 shards the mask job over the virtual CPU mesh."""
    hashes = _mk_hashfile(tmp_path, [md5_of(b"pod")])
    rc, out = run_cli(["crack", "-m", "md5", "-a", "mask", "?l?l?l",
                       str(hashes), "--device", "tpu", "--devices", "8",
                       "--no-potfile", "--batch", "512", "-q"], capsys)
    assert rc == 0
    assert ":pod" in out


def test_crack_wordlist_multichip(tmp_path, capsys):
    """--devices 8 shards a wordlist+rules job over the mesh."""
    import hashlib
    wl = tmp_path / "w.txt"
    wl.write_bytes(b"alpha\nbravo\nsecret\ndelta\n")
    hashes = tmp_path / "h.txt"
    hashes.write_text(hashlib.sha256(b"SECRET").hexdigest() + "\n")
    rc, out = run_cli(["crack", "-m", "sha256", "-a", "wordlist",
                       str(wl), str(hashes), "--rules", "toggle",
                       "--device", "tpu", "--devices", "8",
                       "--no-potfile", "--batch", "512", "-q"], capsys)
    assert rc == 0
    assert ":SECRET" in out


def test_wordlist_max_len_is_engine_specific():
    """The 55-byte device packing limit binds only on single-block
    digest_packed engines; bcrypt's device path accepts its full
    72-byte limit (ADVICE r1)."""
    from dprf_tpu.cli import _wordlist_max_len
    from dprf_tpu.engines import get_engine

    md5 = get_engine("md5")
    assert _wordlist_max_len("md5", md5, "jax") == 55
    bc = get_engine("bcrypt")
    assert _wordlist_max_len("bcrypt", bc, "jax") == 72
    pk = get_engine("wpa2-pmkid")
    assert _wordlist_max_len("wpa2-pmkid", pk, "cpu") == 63


def test_crack_increment_sweeps_lengths(tmp_path, capsys, md5_of):
    """--increment cracks targets of different lengths from one mask and
    stops early once everything is found."""
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"ab"), md5_of(b"abcd")])
    pot = str(tmp_path / "t.pot")
    rc, out = run_cli(["crack", "?l?l?l?l?l", hashfile, "--engine", "md5",
                       "--device", "cpu", "--potfile", pot, "--increment",
                       "--increment-min", "2",
                       "--unit-size", "4096", "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(b'ab')}:ab" in out
    assert f"{md5_of(b'abcd')}:abcd" in out
    # early stop: the length-5 keyspace (26^5) was never swept -- both
    # targets crack by length 4 (verified indirectly by runtime: a -q
    # cpu sweep of 26^5 would dominate; rely on potfile contents here)
    assert Potfile(pot).get(md5_of(b"abcd")) == b"abcd"


def test_crack_increment_rejects_bad_range(tmp_path, capsys, md5_of):
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"ab")])
    rc, _ = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                     "--device", "cpu", "--increment",
                     "--increment-min", "3", "-q"], capsys)
    assert rc == 2


def test_show_and_left(tmp_path, capsys, md5_of):
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"ab"), md5_of(b"zz")])
    pot = str(tmp_path / "t.pot")
    rc, _ = run_cli(["crack", "a?l", hashfile, "--engine", "md5",
                     "--device", "cpu", "--potfile", pot,
                     "--unit-size", "64", "-q"], capsys)
    assert rc == 0          # cracked "ab" only ("zz" not in a?l)
    rc, out = run_cli(["show", hashfile, "--engine", "md5",
                       "--potfile", pot, "-q"], capsys)
    assert rc == 0
    assert out.strip() == f"{md5_of(b'ab')}:ab"
    rc, out = run_cli(["left", hashfile, "--engine", "md5",
                       "--potfile", pot, "-q"], capsys)
    assert rc == 0
    assert out.strip() == md5_of(b"zz")


def test_skip_limit_restricts_sweep(tmp_path, capsys, md5_of):
    """--skip/--limit sweep only the requested index window."""
    # "ab" is index 0*26+1 = 1; "zz" is index 675 in ?l?l
    hashfile = _mk_hashfile(tmp_path, [md5_of(b"ab"), md5_of(b"zz")])
    rc, out = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                       "--device", "cpu", "--no-potfile",
                       "--skip", "0", "--limit", "100",
                       "--unit-size", "32", "-q"], capsys)
    assert rc == 0
    assert f"{md5_of(b'ab')}:ab" in out
    assert "zz" not in out                    # index 675 outside limit
    rc, out = run_cli(["crack", "?l?l", hashfile, "--engine", "md5",
                       "--device", "cpu", "--no-potfile",
                       "--skip", "600", "--unit-size", "32", "-q"],
                      capsys)
    assert rc == 0
    assert f"{md5_of(b'zz')}:zz" in out
    assert ":ab" not in out                   # index 1 skipped


def test_keyspace_modes(tmp_path, capsys):
    rc, out = run_cli(["keyspace", "?l?d"], capsys)
    assert rc == 0 and out.strip() == "260"
    wl = tmp_path / "w.txt"
    wl.write_text("a\nb\nc\n")
    rc, out = run_cli(["keyspace", str(wl), "-a", "wordlist",
                       "--rules", "best64"], capsys)
    assert rc == 0 and out.strip() == str(3 * 64)
    rc, out = run_cli(["keyspace", f"{wl},?d?d", "-a", "hybrid-wm"],
                      capsys)
    assert rc == 0 and out.strip() == "300"


def test_stdout_mode(tmp_path, capsys):
    """stdout streams candidates without hashing (hashcat --stdout)."""
    rc, out = run_cli(["stdout", "?d?d", "--limit", "3"], capsys)
    assert rc == 0 and out.split() == ["00", "01", "02"]
    rc, out = run_cli(["stdout", "?l?l", "--skip", "2", "--limit", "2"],
                      capsys)
    assert rc == 0 and out.split() == ["ac", "ad"]
    wl = tmp_path / "w.txt"
    wl.write_text("cat\ndog\n")
    rules = tmp_path / "r.rule"
    rules.write_text("$1\nu\n")
    rc, out = run_cli(["stdout", str(wl), "-a", "wordlist",
                       "--rules", str(rules)], capsys)
    assert rc == 0 and out.split() == ["cat1", "CAT", "dog1", "DOG"]
    rc, out = run_cli(["stdout", f"{wl},?d", "-a", "hybrid-wm",
                       "--limit", "2"], capsys)
    assert rc == 0 and out.split() == ["cat0", "cat1"]
