"""7-Zip AES-256 (hashcat 11600): KDF construction, encrypt-forward
round trips, parsing, device-vs-oracle, workers."""

import hashlib
import random
import struct
import zlib

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.sevenzip import (parse_7z, sevenzip_decrypt,
                                           sevenzip_key)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.aes import aes_encrypt_block
from dprf_tpu.runtime.workunit import WorkUnit

#: tiny KDF for tests -- the real default is 19 (0.5M units); the
#: stream walker's group math is identical at any power.
CYCLES = 8


def test_kdf_matches_streaming_construction():
    pw, salt = b"pass7", b"NaCl"
    h = hashlib.sha256()
    for i in range(1 << CYCLES):
        h.update(salt + pw.decode("latin-1").encode("utf-16-le")
                 + struct.pack("<Q", i))
    assert sevenzip_key(pw, salt, CYCLES) == h.digest()


def _line(password: bytes, content: bytes, salt: bytes = b"",
          cycles: int = CYCLES, seed: int = 9) -> str:
    """Encrypt `content` forward with the true password's key."""
    rng = random.Random(seed)
    iv = bytes(rng.randrange(256) for _ in range(16))
    key = sevenzip_key(password, salt, cycles)
    padded = content + bytes(-len(content) % 16 or 0)
    ct, prev = b"", iv
    for off in range(0, len(padded), 16):
        block = aes_encrypt_block(
            key, bytes(p ^ v for p, v in
                       zip(padded[off:off + 16], prev)))
        ct += block
        prev = block
    crc = zlib.crc32(content) & 0xFFFFFFFF
    return (f"$7z$0${cycles}${len(salt)}${salt.hex()}$16${iv.hex()}$"
            f"{crc}${len(ct)}${len(content)}${ct.hex()}")


def test_oracle_roundtrip_and_parse():
    pw, content = b"s3vn", b"The quick brown fox jumps over it."
    cpu = get_engine("7z", "cpu")
    t = cpu.parse_target(_line(pw, content, salt=b"sa"))
    assert cpu.verify(pw, t) and not cpu.verify(b"nope", t)
    # aliases resolve on both devices
    assert type(get_engine("sevenzip", "cpu")) is type(cpu)


def test_parse_errors():
    with pytest.raises(ValueError):          # compressed coder
        parse_7z("$7z$1$19$0$$16$" + "00" * 16 + "$1$16$10$" + "00" * 16)
    with pytest.raises(ValueError):
        parse_7z("$zip$not-7z")
    with pytest.raises(ValueError):          # data not block-aligned
        parse_7z("$7z$0$19$0$$16$" + "00" * 16 + "$1$15$10$" + "00" * 15)


def test_decrypt_roundtrip():
    key = bytes(range(32))
    iv = bytes(range(16, 32))
    content = b"sixteen byte blk" * 3
    ct, prev = b"", iv
    for off in range(0, len(content), 16):
        block = aes_encrypt_block(
            key, bytes(p ^ v for p, v in
                       zip(content[off:off + 16], prev)))
        ct += block
        prev = block
    assert sevenzip_decrypt(key, iv, ct) == content


@pytest.mark.smoke
@pytest.mark.compileheavy    # iterated SHA-256 KDF step compile
def test_mask_worker_end_to_end():
    dev = get_engine("7z", "jax")
    cpu = get_engine("7z", "cpu")
    gen = MaskGenerator("?l?d")
    secret = gen.candidate(155)
    t = dev.parse_target(_line(secret, b"archive payload bytes!",
                               salt=b"Qz"))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 155, secret)]


def test_mask_worker_unaligned_group():
    """A mask length whose stream unit does NOT divide 64 exercises
    the multi-block group walker (unit = 2*3+8 = 14 -> 7-block,
    32-unit groups)."""
    dev = get_engine("7z", "jax")
    cpu = get_engine("7z", "cpu")
    gen = MaskGenerator("?d?d?d")
    secret = gen.candidate(421)
    t = dev.parse_target(_line(secret, b"x" * 20))
    w = dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index) for h in hits] == [(0, 421)]


def test_short_iv_field_accepted():
    """Real 7z2hashcat lines zero-pad the IV field to 16 bytes while
    iv_len records the true (often 8-byte) length."""
    pw = b"v8"
    cpu = get_engine("7z", "cpu")
    line = _line(pw, b"iv padding check")
    f = line.split("$")
    # rewrite: iv_len 8, field still 32 hex chars (true iv + zeros)
    true_iv = bytes.fromhex(f[7])[:8]
    key = sevenzip_key(pw, b"", CYCLES)
    content = b"iv padding check"
    ct, prev = b"", (true_iv + bytes(8))
    for off in range(0, len(content), 16):
        block = aes_encrypt_block(
            key, bytes(p ^ v for p, v in
                       zip(content[off:off + 16], prev)))
        ct += block
        prev = block
    crc = zlib.crc32(content) & 0xFFFFFFFF
    line8 = (f"$7z$0${CYCLES}$0$$8${(true_iv + bytes(8)).hex()}$"
             f"{crc}${len(ct)}${len(content)}${ct.hex()}")
    t = cpu.parse_target(line8)
    assert t.params["iv"] == true_iv
    assert cpu.verify(pw, t) and not cpu.verify(b"xx", t)


def test_device_payload_cap_falls_back_to_cpu():
    from dprf_tpu.runtime.worker import CpuWorker

    dev = get_engine("7z", "jax")
    cpu = get_engine("7z", "cpu")
    gen = MaskGenerator("?d?d")
    secret = gen.candidate(77)
    big = bytes(range(256)) * 8          # 2048 B > the 1024 B cap
    t = dev.parse_target(_line(secret, big))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert isinstance(w, CpuWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_kdf_pallas_kernel_matches_oracle():
    """Interpret-mode KDF kernel vs the streaming oracle, lane for
    lane (the kernel emits raw key states; AES+CRC stay in XLA)."""
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.ops.pallas_7z import make_7z_kdf_pallas_fn

    gen = MaskGenerator("?l?d")
    salt = b"Na"
    fn = make_7z_kdf_pallas_fn(gen, batch=1024, salt=salt, cycles=CYCLES,
                               sub=8, interpret=True)
    keys = np.asarray(fn(jnp.asarray(gen.digits(0), jnp.int32)))
    for idx in (0, 7, 259):
        want = sevenzip_key(gen.candidate(idx), salt, CYCLES)
        got = b"".join(int(w).to_bytes(4, "big") for w in keys[idx])
        assert got == want, idx


def test_kernel_worker_planted(monkeypatch):
    """DPRF_PALLAS=1 routes the per-target step onto the KDF kernel
    (interpret off-TPU); planted crack through the production sweep."""
    monkeypatch.setenv("DPRF_PALLAS", "1")
    dev = get_engine("7z", "jax")
    cpu = get_engine("7z", "cpu")
    gen = MaskGenerator("?l?d")
    secret = gen.candidate(201)
    t = dev.parse_target(_line(secret, b"kernel path payload!"))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert w.batch >= 64        # rounded up to the kernel tile
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 201, secret)]


def test_sharded_worker():
    import jax

    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("7z", "jax")
    cpu = get_engine("7z", "cpu")
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(101)
    t = dev.parse_target(_line(secret, b"sharded 7z check"))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=16, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
