"""`dprf check` analyzer tests: every analyzer against fixture trees
with planted violations (each must be caught at the planted line) and
clean twins (no false positives), the suppression framework, the CLI,
and the real repo staying clean inside its budget.

Fixture trees are written under tmp_path with the same shape the
AnalysisContext walks (dprf_tpu/, tests/, tools/, README.md); the
analyzers are pure AST so nothing in a fixture is ever imported
(except the env registry, which is exec'd standalone by design).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from dprf_tpu import analysis

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return str(tmp_path)


def check(root, only):
    findings, _ = analysis.run(root, only=[only])
    return findings


def bad(findings):
    return analysis.unsuppressed(findings)


# ---------------------------------------------------------------------------
# locks: guarded-by discipline

LOCKS_DECL = """\
    import threading
    import time

    GUARDED_BY = {
        "State": {"lock": ("found", "count")},
    }

    class State:
        def __init__(self):
            self.lock = threading.Lock()
            self.found = {}
            self.count = 0
"""


def test_locks_unguarded_write_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKS_DECL + """\

        def racy(self):
            self.found["x"] = 1
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1
    assert "guarded by" in f[0].message and "found" in f[0].message
    assert f[0].path.endswith("state.py")


def test_locks_unguarded_read_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKS_DECL + """\

        def racy_read(self):
            return len(self.found)
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1 and "found" in f[0].message


def test_locks_blocking_call_under_lock_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKS_DECL + """\

        def slow(self):
            with self.lock:
                self.count += 1
                time.sleep(1)
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1
    assert "blocking call time.sleep" in f[0].message


def test_locks_order_inversion_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/pair.py": """\
        import threading

        GUARDED_BY = {"Pair": {"l1": ("x",), "l2": ("y",)}}

        class Pair:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()
                self.x = 0
                self.y = 0

            def fwd(self):
                with self.l1:
                    with self.l2:
                        self.x = 1
                        self.y = 1

            def rev(self):
                with self.l2:
                    with self.l1:
                        self.x = 2
                        self.y = 2
"""})
    f = bad(check(root, "locks"))
    assert any("lock-order cycle" in x.message for x in f), \
        [x.message for x in f]
    # the guarded accesses themselves are all inside both locks: the
    # cycle must be the ONLY finding
    assert all("lock-order cycle" in x.message for x in f)


def test_locks_inversion_through_method_call_cycle(tmp_path):
    # m1 <-> m2 call each other; an early query while holding l1 must
    # not poison the transitive-acquires cache for m2 (a cached
    # mid-cycle placeholder would hide m1's l1 from b(), dropping the
    # l2->l1 edge and missing the inversion against inv())
    root = make_repo(tmp_path, {"dprf_tpu/cyc.py": """\
        import threading

        GUARDED_BY = {"S": {"l1": ("x",), "l2": ("y",)}}

        class S:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()
                self.x = 0
                self.y = 0

            def m1(self, depth):
                with self.l1:
                    self.x = 1
                self.m2(depth)

            def m2(self, depth):
                if depth:
                    self.m1(depth - 1)

            def a(self):
                with self.l1:
                    self.m1(1)

            def b(self):
                with self.l2:
                    self.m2(1)

            def inv(self):
                with self.l1:
                    with self.l2:
                        pass
"""})
    f = bad(check(root, "locks"))
    assert any("lock-order cycle" in x.message for x in f), \
        [x.message for x in f]


def test_locks_clean_fixture_no_false_positives(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKS_DECL + """\

        def good(self):
            with self.lock:
                self.count += 1
                self.found["x"] = self.count

        def _peek(self):
            return len(self.found)
        _peek._holds_lock = "lock"

        def slow_ok(self):
            with self.lock:
                n = self.count
            time.sleep(n)
"""})
    assert bad(check(root, "locks")) == []


def test_locks_atomic_multi_writer_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/flag.py": """\
        GUARDED_BY = {"Flag": {"<atomic>": ("error",)}}

        class Flag:
            def __init__(self):
                self.error = None

            def latch(self, e):
                self.error = e

            def second_writer(self):
                self.error = None
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1 and "single-writer" in f[0].message


def test_locks_extern_acquiring_lock_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/ext.py": LOCKS_DECL + """\

    GUARDED_BY_MORE = None   # (decl for Helper lives in the real table)

    class Helper:
        def __init__(self, state: "State"):
            self.state = state

        def sneaky(self):
            with self.state.lock:
                pass
""", "dprf_tpu/decl.py": """\
    GUARDED_BY = {"Helper": {"<extern>": ()}}
"""})
    f = bad(check(root, "locks"))
    assert any("<extern>" in x.message and "acquires" in x.message
               for x in f), [x.message for x in f]


def test_locks_undeclared_class_in_table_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/empty.py": """\
        GUARDED_BY = {"Ghost": {"lock": ("x",)}}
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1 and "unknown class" in f[0].message


def test_locks_lock_never_assigned_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": """\
        GUARDED_BY = {"State": {"lock": ("found",)}}

        class State:
            def __init__(self):
                self.found = {}
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1 and "never assigned in __init__" in f[0].message


# ---------------------------------------------------------------------------
# protocol: RPC contract

def test_protocol_one_sided_keys_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                wid = msg["worker_id"]
                count = msg.get("count")
                return {"unit": wid}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3, extra=1)
                u = resp["unit"]
                t = resp["trace"]
                self.call("nosuch")
                return u, t
"""})
    msgs = [x.message for x in bad(check(root, "protocol"))]
    assert len(msgs) == 4, msgs
    assert any("reads request key 'count'" in m for m in msgs)
    assert any("sends key 'extra'" in m for m in msgs)
    assert any("response read of key 'trace'" in m for m in msgs)
    assert any("no op_nosuch handler" in m for m in msgs)


def test_protocol_clean_fixture_no_false_positives(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                wid = msg["worker_id"]
                return {"unit": wid, "nested": {"trace": 1}}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                # transport keys are free; nested payload keys count
                # as returned (over-approximation, documented)
                if "error" in resp:
                    return None
                return resp["unit"], resp.get("trace")
"""})
    assert bad(check(root, "protocol")) == []


def test_protocol_scope_isolation(tmp_path):
    # two functions each call a different op and read "their" key;
    # a flat module-wide pass would cross-attribute the reads
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_a(self, msg):
                return {"akey": 1}

            def op_b(self, msg):
                return {"bkey": 2}

        class Client:
            def call(self, op, **kw):
                return {}

            def ga(self):
                resp = self.call("a")
                return resp["akey"]

            def gb(self):
                resp = self.call("b")
                return resp["bkey"]
"""})
    assert bad(check(root, "protocol")) == []


def test_protocol_nested_def_scope_isolation(tmp_path):
    # a nested def reusing the parent's response-variable name must
    # not cross-attribute its reads to the parent's op (or vice versa)
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_status(self, msg):
                return {"parked": 0}

            def op_lease(self, msg):
                return {"unit": 1}

        class Client:
            def call(self, op, **kw):
                return {}

            def outer(self):
                resp = self.call("status")
                n = resp["parked"]

                def inner():
                    resp = self.call("lease")
                    return resp["unit"]
                return n, inner
"""})
    assert bad(check(root, "protocol")) == []


# ---------------------------------------------------------------------------
# env-knobs: registry lint

ENV_REGISTRY = """\
    KNOBS = {}

    def _declare(name, default, type, doc):
        KNOBS[name] = (default, type, doc)

    _declare("DPRF_FIX_USED", 1, "int", "a knob somebody reads")
"""

ENV_READER = """\
    from dprf_tpu.utils import env

    def f():
        return env.get_int("DPRF_FIX_USED")
"""


def test_envknobs_raw_read_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY,
        "dprf_tpu/reader.py": ENV_READER,
        "dprf_tpu/rogue.py": """\
            import os

            A = os.environ.get("DPRF_FIX_USED")

            def g():
                return os.getenv("DPRF_FIX_USED")

            def h():
                return os.environ["DPRF_FIX_USED"]
"""})
    f = bad(check(root, "env-knobs"))
    assert len(f) == 3, [x.message for x in f]
    assert all("raw environment read" in x.message for x in f)
    assert all(x.path.endswith("rogue.py") for x in f)


def test_envknobs_unauditable_read_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY,
        "dprf_tpu/reader.py": ENV_READER,
        "dprf_tpu/sneaky.py": """\
            import os

            def h(name):
                return os.environ[name]
"""})
    f = bad(check(root, "env-knobs"))
    assert len(f) == 1 and "cannot resolve" in f[0].message


def test_envknobs_undeclared_getter_and_stale_knob_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY + """\
    _declare("DPRF_FIX_STALE", 0, "int", "nobody reads this")
""",
        "dprf_tpu/reader.py": ENV_READER + """\

    def g():
        return env.get_str("DPRF_FIX_MISSING")
"""})
    msgs = [x.message for x in bad(check(root, "env-knobs"))]
    assert len(msgs) == 2, msgs
    assert any("undeclared knob 'DPRF_FIX_MISSING'" in m for m in msgs)
    assert any("'DPRF_FIX_STALE' is declared but never read" in m
               for m in msgs)


def test_envknobs_module_constant_resolution(tmp_path):
    # the `ENABLE_ENV = "DPRF_X"` idiom: raw reads through a
    # module-level constant are still caught
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY,
        "dprf_tpu/reader.py": ENV_READER,
        "dprf_tpu/alias.py": """\
            import os

            KNOB = "DPRF_FIX_USED"

            def g():
                return os.environ.get(KNOB)
"""})
    f = bad(check(root, "env-knobs"))
    assert len(f) == 1 and "DPRF_FIX_USED" in f[0].message


def test_envknobs_aliased_os_import_caught(tmp_path):
    # `import os as _os` / `from os import environ, getenv` must not
    # make a raw read invisible (the hole that let an unmigrated
    # engines/device read survive the first migration pass)
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY,
        "dprf_tpu/reader.py": ENV_READER,
        "dprf_tpu/rogue.py": """\
            import os as _os
            from os import environ as _environ
            from os import getenv as _getenv

            def a():
                return _os.environ.get("DPRF_FIX_USED", "1")

            def b():
                return _os.getenv("DPRF_FIX_USED")

            def c():
                return _environ["DPRF_FIX_USED"]

            def d():
                return _getenv("DPRF_FIX_USED")
"""})
    f = bad(check(root, "env-knobs"))
    assert len(f) == 4, [x.message for x in f]
    assert all("raw environment read" in x.message for x in f)


def test_envknobs_clean_fixture_no_false_positives(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/utils/env.py": ENV_REGISTRY,
        "dprf_tpu/reader.py": ENV_READER + """\

    def non_knob():
        import os
        return os.environ.get("HOME")   # non-DPRF reads stay legal

    def writes_are_legal():
        import os
        os.environ["DPRF_FIX_USED"] = "2"
"""})
    assert bad(check(root, "env-knobs")) == []


# ---------------------------------------------------------------------------
# markers / metrics / worker-contract (absorbed conftest lints)

def test_markers_unmarked_device_test_caught(tmp_path):
    root = make_repo(tmp_path, {
        "tests/test_fixture_device.py": """\
            from dprf_tpu.ops import pallas_mask

            def test_x():
                assert pallas_mask is not None
""",
        "tests/test_fixture_marked.py": """\
            import pytest
            from dprf_tpu.ops import pallas_mask

            pytestmark = pytest.mark.compileheavy

            def test_y():
                assert pallas_mask is not None
""",
        "dprf_tpu/__init__.py": ""})
    f = bad(check(root, "markers"))
    assert len(f) == 1
    assert f[0].path.endswith("test_fixture_device.py")


def test_metrics_duplicate_declaration_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/a.py": """\
            def declare(reg):
                return reg.counter("dprf_fix_total", "doc")
""",
        "dprf_tpu/b.py": """\
            def declare_again(reg):
                return reg.counter("dprf_fix_total", "doc")
"""})
    f = bad(check(root, "metrics"))
    assert len(f) == 1 and "declared at 2 sites" in f[0].message


def test_metrics_undeclared_span_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/trace.py": """\
            SPAN_NAMES = ("lease", "sweep")
""",
        "dprf_tpu/user.py": """\
            def f(tracer, t0, t1):
                tracer.record("lease", t0, t1)
                tracer.record("bogus", t0, t1)
"""})
    f = bad(check(root, "metrics"))
    assert len(f) == 1
    assert "span 'bogus' not declared" in f[0].message


def test_metrics_profiler_call_outside_owner_caught(tmp_path):
    """Rule 4 (ISSUE 15): jax.profiler trace calls outside
    telemetry/profiler.py are findings -- jax allows ONE active
    trace, so every starter must share ProfileCapture's slot."""
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/profiler.py": """\
            def owner(directory):
                import jax
                jax.profiler.start_trace(directory)
                jax.profiler.stop_trace()
""",
        "dprf_tpu/rogue.py": """\
            def rogue(directory):
                import jax
                jax.profiler.start_trace(directory)
                with jax.profiler.trace(directory):
                    pass
                jax.profiler.stop_trace()
"""})
    f = bad(check(root, "metrics"))
    assert len(f) == 3
    assert all(x.path.endswith("rogue.py") for x in f)
    assert {x.line for x in f} == {3, 4, 6}


def test_metrics_profiler_unrelated_trace_calls_clean(tmp_path):
    """A clean twin: ``.trace(`` on anything NOT named profiler (span
    recorders, loggers) never matches rule 4."""
    root = make_repo(tmp_path, {
        "dprf_tpu/spans.py": """\
            def fine(recorder, directory):
                with recorder.trace(directory):
                    pass
"""})
    assert bad(check(root, "metrics")) == []


def test_worker_contract_violations_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/w.py": """\
            class Unmarked:
                def process(self, units):
                    return None

            class BadSubmit:
                def process(self, units):
                    return None
                process._submit_based = True

            class GoodSerial:
                def process(self, units):
                    return None
                process._serial_only = True

            class GoodSubmit:
                def submit(self, unit):
                    pass

                def process(self, units):
                    return None
                process._submit_based = True
"""})
    msgs = [x.message for x in bad(check(root, "worker-contract"))]
    assert len(msgs) == 2, msgs
    assert any("Unmarked" in m and "pipelining stance" in m
               for m in msgs)
    assert any("BadSubmit" in m and "no submit()" in m for m in msgs)


# ---------------------------------------------------------------------------
# suppressions

SUPPRESSIBLE = {
    "dprf_tpu/utils/env.py": ENV_REGISTRY,
    "dprf_tpu/reader.py": ENV_READER,
}


def test_suppression_with_reason_silences(tmp_path):
    root = make_repo(tmp_path, dict(SUPPRESSIBLE, **{
        "dprf_tpu/rogue.py": """\
            import os

            A = os.environ.get("DPRF_FIX_USED")  # dprf: disable=env-knobs -- fixture: documents the raw idiom
"""}))
    findings, _ = analysis.run(root, only=["env-knobs"])
    assert bad(findings) == []
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 1
    assert sup[0].reason == "fixture: documents the raw idiom"


def test_suppression_standalone_comment_covers_next_line(tmp_path):
    root = make_repo(tmp_path, dict(SUPPRESSIBLE, **{
        "dprf_tpu/rogue.py": """\
            import os

            # dprf: disable=env-knobs -- fixture: standalone form
            A = os.environ.get("DPRF_FIX_USED")
"""}))
    findings, _ = analysis.run(root, only=["env-knobs"])
    assert bad(findings) == []


def test_suppression_without_reason_is_a_finding(tmp_path):
    root = make_repo(tmp_path, dict(SUPPRESSIBLE, **{
        "dprf_tpu/rogue.py": """\
            import os

            A = os.environ.get("DPRF_FIX_USED")  # dprf: disable=env-knobs
"""}))
    findings, _ = analysis.run(root, only=["env-knobs"])
    msgs = [f.message for f in bad(findings)]
    # the raw read is NOT silenced, and the reasonless suppression is
    # itself flagged
    assert len(msgs) == 2, msgs
    assert any("without a reason" in m for m in msgs)
    assert any("raw environment read" in m for m in msgs)


def test_unused_suppression_is_a_finding(tmp_path):
    root = make_repo(tmp_path, dict(SUPPRESSIBLE, **{
        "dprf_tpu/fine.py": """\
            X = 1   # dprf: disable=env-knobs -- nothing here anymore
"""}))
    findings, _ = analysis.run(root, only=["env-knobs"])
    msgs = [f.message for f in bad(findings)]
    assert len(msgs) == 1 and "unused suppression" in msgs[0]


def test_unused_suppression_ignored_when_check_skipped(tmp_path):
    # a locks suppression is not "unused" on an env-knobs-only run:
    # the check it names never ran
    root = make_repo(tmp_path, dict(SUPPRESSIBLE, **{
        "dprf_tpu/fine.py": """\
            X = 1   # dprf: disable=locks -- for a run that skips locks
"""}))
    findings, _ = analysis.run(root, only=["env-knobs"])
    assert bad(findings) == []


# ---------------------------------------------------------------------------
# runner / CLI / real repo

def test_parse_failure_is_a_finding(tmp_path):
    # the broken file must contain an analyzer's needle: files the
    # source prefilters rule out are (intentionally) never parsed
    root = make_repo(tmp_path, {
        "dprf_tpu/broken.py": 'def f(:\n    os.getenv("DPRF_X")\n'})
    findings, _ = analysis.run(root)
    msgs = [f.message for f in bad(findings)]
    assert any("does not parse" in m for m in msgs)


def test_unknown_check_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown checks"):
        analysis.run(str(tmp_path), only=["nosuch"])


def test_run_only_and_skip(tmp_path):
    make_repo(tmp_path, {"dprf_tpu/x.py": "X = 1\n"})
    _, ran = analysis.run(str(tmp_path), only=["locks", "metrics"])
    assert ran == {"locks", "metrics"}
    _, ran = analysis.run(str(tmp_path), skip=["locks"])
    assert "locks" not in ran and "metrics" in ran


def test_cli_json_and_exit_codes(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/w.py": """\
            class Unmarked:
                def process(self, units):
                    return None
"""})
    proc = subprocess.run(
        [sys.executable, "-m", "dprf_tpu.analysis", "--root", root,
         "--only", "worker-contract,metrics", "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["total"] == 1
    assert sorted(payload["checks"]) == ["metrics", "worker-contract"]
    assert payload["findings"][0]["check"] == "worker-contract"
    assert payload["findings"][0]["line"] == 1
    # per-analyzer wall time for the CI artifact (ISSUE 8 satellite)
    assert sorted(payload["timings_s"]) == ["metrics",
                                            "worker-contract"]
    assert all(isinstance(v, float) and v >= 0
               for v in payload["timings_s"].values())

    proc = subprocess.run(
        [sys.executable, "-m", "dprf_tpu.analysis", "--root", root,
         "--only", "metrics"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0

    proc = subprocess.run(
        [sys.executable, "-m", "dprf_tpu.analysis", "--only", "nosuch"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# coverage-events: event-site manifest discipline (ISSUE 19)

COVERAGE_DECL = """\
    EVENT_NAMES = ("split", "complete", "redrive")

    COVERAGE_EVENT_SITES = (
        ("dprf_tpu/disp.py", "complete"),
        ("dprf_tpu/disp.py", "fail"),
    )
"""


def test_coverage_events_violations_caught(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/coverage.py": COVERAGE_DECL,
        "dprf_tpu/disp.py": """\
            from dprf_tpu.telemetry import coverage

            class D:
                def complete(self, s, e):
                    # undeclared event literal
                    self.coverage.event("explode", s, e)

                def fail(self, s, e):
                    # declared site that never calls the API
                    return (s, e)

                def reissue(self, s, e):
                    # caller missing from the manifest
                    self.coverage.event("split", s, e)

                def redrive(self, s, e, name):
                    # computed name: statically unauditable
                    coverage.note(name, s, e)
"""})
    # the computed-name call draws two findings: unauditable literal
    # AND an undeclared calling site
    msgs = [x.message for x in bad(check(root, "coverage-events"))]
    assert len(msgs) == 5, msgs
    assert any("'explode' not declared" in m for m in msgs)
    assert any("never calls" in m for m in msgs)
    assert any("'reissue'" in m and "not declared in" in m
               for m in msgs)
    assert any("'redrive'" in m and "not declared in" in m
               for m in msgs)
    assert any("string literal" in m for m in msgs)


def test_coverage_events_clean_twin(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/coverage.py": COVERAGE_DECL,
        "dprf_tpu/disp.py": """\
            class D:
                def complete(self, s, e):
                    self.coverage.event("complete", s, e)

                def fail(self, s, e):
                    self.coverage.event("split", s, e)
"""})
    assert bad(check(root, "coverage-events")) == []


def test_coverage_events_stale_manifest_entry(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/coverage.py": COVERAGE_DECL,
        "dprf_tpu/disp.py": """\
            class D:
                def complete(self, s, e):
                    self.coverage.event("complete", s, e)
"""})
    f = bad(check(root, "coverage-events"))
    assert len(f) == 1 and "no such function" in f[0].message


def test_run_for_conftest_formats_failures(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/w.py": """\
            class Unmarked:
                def process(self, units):
                    return None
"""})
    msg = analysis.run_for_conftest(root)
    assert msg is not None and "1 violation" in msg
    assert "w.py:1" in msg


def test_real_repo_is_clean_and_fast():
    """The acceptance criterion: all nine analyzers over the whole
    package, zero unsuppressed findings, comfortably inside the 5 s
    CLI budget on the 2-core box."""
    t0 = time.monotonic()
    findings, ran = analysis.run(REPO)
    elapsed = time.monotonic() - t0
    assert ran == {"markers", "metrics", "worker-contract", "locks",
                   "protocol", "env-knobs", "threads", "retrace",
                   "coverage-events"}
    assert bad(findings) == [], "\n".join(
        f.render() for f in bad(findings))
    # every suppression carries a reason (reasonless ones would be
    # unsuppressed findings above); budget check last
    assert elapsed < 5.0, f"analysis took {elapsed:.2f}s"


def test_readme_knob_table_roundtrip(tmp_path):
    from dprf_tpu.utils import env

    readme = tmp_path / "README.md"
    readme.write_text("# x\n\n%s\n%s\n\ntail\n"
                      % (env.README_BEGIN, env.README_END))
    assert env.readme_sync_error(str(readme)) is not None
    assert env.write_readme_table(str(readme)) is True
    assert env.readme_sync_error(str(readme)) is None
    # idempotent
    assert env.write_readme_table(str(readme)) is False
    # drift is detected
    readme.write_text(readme.read_text().replace(
        "DPRF_PIPELINE_DEPTH", "DPRF_GONE"))
    assert env.readme_sync_error(str(readme)) is not None


def test_registry_typed_getters(monkeypatch):
    from dprf_tpu.utils import env

    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "junk")
    assert env.get_int("DPRF_PIPELINE_DEPTH") == 2   # junk -> default
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "7")
    assert env.get_int("DPRF_PIPELINE_DEPTH") == 7
    monkeypatch.setenv("DPRF_TRACE", "0")
    assert env.get_bool("DPRF_TRACE") is False
    monkeypatch.setenv("DPRF_TRACE", "yes")
    assert env.get_bool("DPRF_TRACE") is True
    monkeypatch.delenv("DPRF_TRACE")
    assert env.get_bool("DPRF_TRACE") is True        # declared default
    with pytest.raises(KeyError, match="undeclared env knob"):
        # dprf: disable=env-knobs -- asserts the registry rejects undeclared names
        env.get_str("DPRF_NOT_A_KNOB")
