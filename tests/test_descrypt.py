"""descrypt (traditional DES crypt(3); hashcat 1500): scalar core vs
the system crypt(), bitslice vs scalar, encode/decode round-trip,
device workers end-to-end, CLI."""

import random
import subprocess
import warnings

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.engines import descrypt_decode, descrypt_encode
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.des import des_crypt25, descrypt_key8
from dprf_tpu.runtime.workunit import WorkUnit

with warnings.catch_warnings():
    warnings.simplefilter("ignore")             # removed in 3.13
    try:
        import crypt as _crypt
    except ImportError:                          # pragma: no cover
        _crypt = None

ITOA64 = "./0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"


def _syscrypt(pw: str, salt2: str) -> str:
    if _crypt is not None:
        return _crypt.crypt(pw, salt2)
    out = subprocess.run(
        ["perl", "-e", "print crypt($ARGV[0], $ARGV[1])", pw, salt2],
        capture_output=True, text=True).stdout
    if len(out) != 13:
        pytest.skip("no system crypt() available")
    return out


def test_scalar_matches_system_crypt():
    rnd = random.Random(1500)
    for _ in range(12):
        pw = "".join(chr(rnd.randrange(33, 127))
                     for _ in range(rnd.randrange(0, 12)))
        salt2 = ITOA64[rnd.randrange(64)] + ITOA64[rnd.randrange(64)]
        want = _syscrypt(pw, salt2)
        salt = ITOA64.index(salt2[0]) | (ITOA64.index(salt2[1]) << 6)
        got = salt2 + descrypt_encode(
            des_crypt25(descrypt_key8(pw.encode()), salt))
        assert got == want, (pw, salt2)


def test_encode_decode_roundtrip():
    rnd = random.Random(3)
    for _ in range(16):
        d = bytes(rnd.randrange(256) for _ in range(8))
        assert descrypt_decode(descrypt_encode(d)) == d


def test_bitslice_matches_scalar():
    from dprf_tpu.engines.device.lm import byte_planes
    from dprf_tpu.ops.des import descrypt_bitslice

    rnd = random.Random(46)
    B = 32
    cands = [bytes(rnd.randrange(32, 127)
                   for _ in range(rnd.randrange(0, 9)))
             for _ in range(B)]
    buf = np.zeros((B, 8), np.uint8)
    for i, c in enumerate(cands):
        buf[i] = np.frombuffer(descrypt_key8(c), np.uint8)
    salt = 0b011010110101
    planes = [np.asarray(p) for p in
              descrypt_bitslice(byte_planes(jnp.asarray(buf)), salt)]
    for i, c in enumerate(cands):
        want = des_crypt25(descrypt_key8(c), salt)
        bits = [(int(planes[b][i // 32]) >> (i % 32)) & 1
                for b in range(64)]
        got = bytes(sum(bits[8 * k + j] << (7 - j) for j in range(8))
                    for k in range(8))
        assert got == want, (i, c)


def test_parse_rejects_malformed():
    cpu = get_engine("descrypt")
    with pytest.raises(ValueError):
        cpu.parse_target("tooshort")
    with pytest.raises(ValueError):
        cpu.parse_target("ab" + "!" * 11)       # non-itoa64 chars
    t = cpu.parse_target(_syscrypt("x", "ab"))
    assert t.params["salt_text"] == "ab"


def test_mask_worker_finds_planted():
    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    t = cpu.parse_target(_syscrypt("dog", "K9"))
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"dog")]


def test_mask_worker_two_targets_distinct_salts():
    """Distinct salts become distinct circuits inside the one step;
    both planted passwords surface with their own indices."""
    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    ts = [cpu.parse_target(_syscrypt("07", "ab")),
          cpu.parse_target(_syscrypt("42", "zQ"))]
    gen = MaskGenerator("?d?d")
    w = dev.make_mask_worker(gen, ts, batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"07"), (1, b"42")}


def test_wordlist_worker_with_rules():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    words = [b"alpha", b"dog", b"cat"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=8)
    t = cpu.parse_target(_syscrypt("cat1", "zz"))
    w = dev.make_wordlist_worker(gen, [t], batch=96, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"cat1"]


def test_long_masks_rejected():
    dev = get_engine("descrypt", device="jax")
    cpu = get_engine("descrypt")
    t = cpu.parse_target(_syscrypt("x", "ab"))
    gen = MaskGenerator("?l" * 9)
    with pytest.raises(ValueError, match="cap at 8"):
        dev.make_mask_worker(gen, [t], batch=32, hit_capacity=8)


def test_cli_descrypt_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    hashes = tmp_path / "h.txt"
    hashes.write_text(_syscrypt("fox", "Qr") + "\n")
    pot = tmp_path / "pot.txt"
    rc = main(["crack", "--engine=descrypt", "--device=jax",
               "-a", "mask", "?l?l?l", str(hashes),
               "--potfile", str(pot), "--batch", "2048"])
    assert rc == 0
    assert pot.read_text().strip().endswith(":fox")


def test_mask_worker_same_salt_targets_fold():
    """Targets sharing a salt fold into ONE bitslice circuit (the
    salt-group step); all of them crack in one sweep with original
    indices."""
    from dprf_tpu.engines.device.descrypt import _salt_groups

    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    ts = [cpu.parse_target(_syscrypt("11", "ab")),
          cpu.parse_target(_syscrypt("99", "ab")),
          cpu.parse_target(_syscrypt("55", "cd"))]
    assert len(_salt_groups(ts)) == 2          # ab shared, cd alone
    gen = MaskGenerator("?d?d")
    w = dev.make_mask_worker(gen, ts, batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"11"), (1, b"99"), (2, b"55")}


def test_mask_worker_blocks_many_salts(monkeypatch):
    """More distinct salts than MAX_SALTS_PER_STEP compile into
    multiple blocked steps swept in sequence -- every target still
    cracks with its original index (ADVICE r3: unbounded per-salt
    unrolling)."""
    from dprf_tpu.engines.device import descrypt as dd

    monkeypatch.setattr(dd, "MAX_SALTS_PER_STEP", 2)
    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    salts = ["ab", "cd", "ef", "gh", "ij"]     # 5 salts -> 3 blocks
    ts = [cpu.parse_target(_syscrypt(f"{i}{i}", s))
          for i, s in enumerate(salts)]
    gen = MaskGenerator("?d?d")
    w = dev.make_mask_worker(gen, ts, batch=128, hit_capacity=8,
                             oracle=cpu)
    assert len(w._steps) == 3
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(i, f"{i}{i}".encode()) for i in range(5)}


def test_distinct_salt_cap_errors(monkeypatch):
    from dprf_tpu.engines.device import descrypt as dd

    monkeypatch.setattr(dd, "MAX_DISTINCT_SALTS", 3)
    cpu = get_engine("descrypt")
    dev = get_engine("descrypt", device="jax")
    salts = ["ab", "cd", "ef", "gh"]
    ts = [cpu.parse_target(_syscrypt("xx", s)) for s in salts]
    gen = MaskGenerator("?d?d")
    with pytest.raises(ValueError, match="distinct salts"):
        dev.make_mask_worker(gen, ts, batch=128, hit_capacity=8)
