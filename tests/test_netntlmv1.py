"""NetNTLMv1 (hashcat 5500): reference response construction, parse,
and the bitslice-DES device workers."""

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.engines import netntlmv1_response
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit

CHAL = bytes.fromhex("1122334455667788")


def _line(pw: bytes, chal: bytes = CHAL) -> str:
    return ("user::DOMAIN:" + "00" * 24 + ":"
            + netntlmv1_response(pw, chal).hex() + ":" + chal.hex())


def test_response_construction():
    """The response is three DES encryptions of the challenge under
    thirds of nt_hash||00*5 -- check against an independent spell-out."""
    from dprf_tpu.engines.cpu.md4 import md4
    from dprf_tpu.ops.des import des_encrypt, str_to_key

    pw = b"hashcat"
    nt = md4(pw.decode().encode("utf-16-le")) + bytes(5)
    want = b"".join(des_encrypt(str_to_key(nt[i:i + 7]), CHAL)
                    for i in (0, 7, 14))
    assert netntlmv1_response(pw, CHAL) == want


def test_parse_and_oracle():
    eng = get_engine("netntlmv1")
    t = eng.parse_target(_line(b"hashcat"))
    assert t.params["challenge"] == CHAL
    assert eng.hash_batch([b"hashcat"], params=t.params)[0] == t.digest
    assert not eng.verify(b"nope", t)
    with pytest.raises(ValueError):
        eng.parse_target("user:domain:notenough")
    with pytest.raises(ValueError):
        eng.parse_target("u::D:" + "00" * 24 + ":" + "00" * 24 + ":aabb")


def test_device_mask_worker_cracks():
    cpu = get_engine("netntlmv1")
    dev = get_engine("netntlmv1", device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(_line(b"fox"))
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


def test_device_two_targets_two_challenges():
    cpu = get_engine("netntlmv1")
    dev = get_engine("netntlmv1", device="jax")
    gen = MaskGenerator("?d?d")
    ta = cpu.parse_target(_line(b"42", bytes(range(8))))
    tb = cpu.parse_target(_line(b"77", bytes(range(8, 16))))
    w = dev.make_mask_worker(gen, [ta, tb], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"42"), (1, b"77")}


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("netntlmv1")
    dev = get_engine("netntlmv1", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")], max_len=16)
    t = cpu.parse_target(_line(b"banana"))
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}
    assert all(h.target_index == 0 for h in hits)


def test_ess_capture_effective_challenge():
    """NTLMv1-ESS: lmresp = client challenge + 16 zero bytes; the DES
    input is MD5(server||client)[:8], not the raw server challenge."""
    import hashlib

    schal = bytes.fromhex("aabbccddeeff0011")
    cchal = bytes.fromhex("0102030405060708")
    eff = hashlib.md5(schal + cchal).digest()[:8]
    resp = netntlmv1_response(b"hashcat", eff)
    line = ("u::D:" + (cchal + bytes(16)).hex() + ":" + resp.hex()
            + ":" + schal.hex())
    eng = get_engine("netntlmv1")
    t = eng.parse_target(line)
    assert t.params["challenge"] == eff
    assert eng.hash_batch([b"hashcat"], params=t.params)[0] == t.digest
