"""Pipelined remote worker loop (ISSUE 5): lease-ahead RPC form,
overlapped worker_loop, async completion, per-unit lease accounting
under crashes, the device-idle trace report, and the worker
pipelining-contract lint.

The loopback bench runs a simulated async device (a sleep-based
"stream" thread -- no compiles, hermetic timing) against a client that
injects a fixed latency into every RPC, and asserts the acceptance
criteria: pipelined >= 1.5x the serial loop's units/sec, within 10% of
the local Coordinator.run path, and inter-sweep device-idle gaps below
the injected round trip.
"""

import json
import queue
import subprocess
import sys
import threading
import time

import pytest

from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (MAX_LEASE_AHEAD, CoordinatorClient,
                                  CoordinatorServer, CoordinatorState,
                                  _CompletionSender, worker_loop)
from dprf_tpu.runtime.worker import CpuWorker, UnitPipeline, pipeline_depth
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import (TraceRecorder, lifecycle_report,
                                      load_trace, overlap_report)

#: injected per-RPC latency and the fake device's per-unit compute for
#: the loopback bench; compute is 2x the RTT so a serial loop pays
#: ~2 RTT of dead device time per unit while the pipelined loop hides
#: both round trips behind the stream
RTT = 0.08
COMPUTE = 0.16
N_UNITS = 16
UNIT = 100


def _recorder():
    return TraceRecorder(registry=MetricsRegistry())


def _serve(keyspace, unit_size, rec, reg, clock=None,
           lease_timeout=300.0):
    job = {"engine": "md5", "attack": "mask", "attack_arg": "?d",
           "customs": {}, "rules": None, "max_len": None,
           "targets": ["ff" * 16], "keyspace": keyspace,
           "unit_size": unit_size, "batch": 4096, "hit_cap": 8,
           "fingerprint": "test"}
    disp = Dispatcher(keyspace, unit_size, lease_timeout=lease_timeout,
                      clock=clock, registry=reg, recorder=rec)
    state = CoordinatorState(job, disp, 1, registry=reg, recorder=rec)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, disp


class StreamWorker:
    """Simulated async device: submit() enqueues COMPUTE seconds of
    work on a single 'stream' thread and returns immediately;
    resolve() blocks on that unit's completion.  The PendingUnit duck
    type without compiling anything -- hermetic, deterministic
    timing."""

    def __init__(self, compute_s=COMPUTE):
        self.compute_s = compute_s
        self._q: queue.Queue = queue.Queue()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            ev = self._q.get()
            if ev is None:
                return
            time.sleep(self.compute_s)
            ev.set()

    def submit(self, unit):
        ev = threading.Event()
        self._q.put(ev)

        class _Pending:
            def resolve(self_inner):
                ev.wait()
                return []

        return _Pending()

    def process(self, unit):
        return self.submit(unit).resolve()

    process._submit_based = True

    def close(self):
        self._q.put(None)


def _latent_client_cls(delay):
    class LatentClient(CoordinatorClient):
        DELAY = delay

        def call(self, op, **kw):
            time.sleep(self.DELAY)
            return super().call(op, **kw)

    return LatentClient


# ---------------------------------------------------------------------------
# lease-ahead RPC form

def test_lease_ahead_returns_units_with_per_unit_trace_context():
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(N_UNITS * UNIT, UNIT, rec, reg)
    try:
        client = CoordinatorClient(*server.address)
        resp = client.call("lease", worker_id="w0", ahead=3)
        units = resp["units"]
        assert len(units) == 3
        assert disp.outstanding_for("w0") == 3
        # per-unit trace context, and the legacy single-unit fields
        # still point at the first entry
        assert all(u["trace"]["trace"] and u["trace"]["span"]
                   for u in units)
        assert resp["unit"] == units[0]
        assert resp["trace"] == units[0]["trace"]
        assert len({u["trace"]["trace"] for u in units}) == 3
        # holdings are capped per worker, whatever the client asks for
        resp = client.call("lease", worker_id="w0", ahead=9999)
        assert disp.outstanding_for("w0") <= MAX_LEASE_AHEAD
        client.close()
    finally:
        server.shutdown()


def test_lease_ahead_reaps_expired_holdings_of_the_same_worker():
    """A restarted worker (same --id) whose crashed predecessor held
    MAX_LEASE_AHEAD leases must not clamp to zero forever: op_lease
    reaps expired leases BEFORE clamping against the worker's
    holdings, or a single-worker fleet livelocks."""

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(100 * MAX_LEASE_AHEAD * 2, 100, rec,
                                 reg, clock=clk, lease_timeout=10.0)
    try:
        client = CoordinatorClient(*server.address)
        resp = client.call("lease", worker_id="w", ahead=MAX_LEASE_AHEAD)
        assert len(resp["units"]) == MAX_LEASE_AHEAD
        clk.t += 60.0          # the worker "crashed"; leases expired
        resp = client.call("lease", worker_id="w", ahead=2)
        assert resp.get("units"), resp
        client.close()
    finally:
        server.shutdown()


def test_lease_ahead_clamps_greedy_worker():
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(100 * MAX_LEASE_AHEAD * 4, 100, rec,
                                 reg)
    try:
        client = CoordinatorClient(*server.address)
        for _ in range(4):
            client.call("lease", worker_id="greedy",
                        ahead=MAX_LEASE_AHEAD)
        assert disp.outstanding_for("greedy") == MAX_LEASE_AHEAD
        # another worker still gets units: the queue was not vacuumed
        assert client.call("lease", worker_id="other")["unit"]
        client.close()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# the acceptance bench: injected RPC latency, serial vs pipelined vs
# local, plus the span-level device-idle assertion

def _run_remote(depth, worker, wid, trace_file=None):
    rec, reg = _recorder(), MetricsRegistry()
    if trace_file:
        rec.attach_file(trace_file)
    state, server, disp = _serve(N_UNITS * UNIT, UNIT, rec, reg)
    cls = _latent_client_cls(RTT)
    try:
        client = cls(*server.address)
        t0 = time.monotonic()
        done = worker_loop(client, worker, wid, idle_sleep=0.05,
                           registry=reg, recorder=_recorder(),
                           depth=depth)
        elapsed = time.monotonic() - t0
        client.close()
        assert done == N_UNITS
        assert disp.done()
        return elapsed, reg
    finally:
        server.shutdown()
        if trace_file:
            rec.detach_file()


@pytest.mark.smoke
def test_pipelined_loop_outpaces_serial_and_matches_local(tmp_path):
    """ISSUE 5 acceptance: with ~100ms injected RPC latency the
    pipelined worker_loop reaches >= 1.5x the serial loop's units/sec
    and lands within 10% of the local Coordinator.run path on the same
    workload; the exported trace shows per-worker inter-sweep
    device-idle gaps below the injected RTT, with sweep N+1 starting
    before complete RPC N returned."""
    pipe_file = str(tmp_path / "pipe.session.trace.jsonl")
    serial_file = str(tmp_path / "serial.session.trace.jsonl")

    w = StreamWorker()
    serial_s, _ = _run_remote(1, w, "w-serial", trace_file=serial_file)
    w.close()
    # depth 3, not 2: on a loaded 2-core box a single scheduler hiccup
    # of ~1 RTT can momentarily drain a depth-2 queue; the extra queued
    # unit keeps the stream busy through it without changing what the
    # test proves (the overlap, not the minimum depth)
    w = StreamWorker()
    pipe_s, reg = _run_remote(3, w, "w-pipe", trace_file=pipe_file)
    w.close()

    # local Coordinator.run on the same workload (no RPC at all)
    w = StreamWorker()
    disp = Dispatcher(N_UNITS * UNIT, UNIT, registry=MetricsRegistry(),
                      recorder=_recorder())
    spec = JobSpec(engine="fake", device="jax", attack="mask",
                   attack_arg="?d", keyspace=N_UNITS * UNIT,
                   fingerprint="bench")
    coord = Coordinator(spec, [object()], disp, w,
                        registry=MetricsRegistry(),
                        recorder=_recorder())
    t0 = time.monotonic()
    result = coord.run()
    local_s = time.monotonic() - t0
    w.close()
    assert result.exhausted

    serial_rate = N_UNITS / serial_s
    pipe_rate = N_UNITS / pipe_s
    local_rate = N_UNITS / local_s
    assert pipe_rate >= 1.5 * serial_rate, (
        f"pipelined {pipe_rate:.2f}/s < 1.5x serial "
        f"{serial_rate:.2f}/s")
    assert pipe_rate >= 0.9 * local_rate, (
        f"pipelined {pipe_rate:.2f}/s not within 10% of local "
        f"{local_rate:.2f}/s")

    # span-level assertion: the pipelined worker never idled a full
    # round trip between sweeps (sweep N+1 was on the stream before
    # complete N landed); the serial loop pays ~2 RTT per unit
    rep = overlap_report(load_trace(pipe_file))
    wp = rep["workers"]["w-pipe"]
    assert wp["sweeps"] == N_UNITS
    assert wp["max_gap_s"] < RTT, wp
    assert wp["overlapped"] >= 1
    assert wp["complete_overlaps"] >= 1
    rep_serial = overlap_report(load_trace(serial_file))
    ws = rep_serial["workers"]["w-serial"]
    assert ws["max_gap_s"] > RTT, ws
    assert ws["complete_overlaps"] == 0

    # the worker-side telemetry told the same story
    assert reg.get("dprf_worker_pipeline_depth").value() == 3
    assert reg.get("dprf_worker_idle_seconds").value() < \
        N_UNITS * RTT

    # tools/trace_overlap.py: the operator-facing report agrees and
    # enforces the budget (exit 1 when a worker idles past it)
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "trace_overlap.py")
    proc = subprocess.run(
        [sys.executable, tool, pipe_file, "--max-gap", str(RTT),
         "--json"], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["workers"]["w-pipe"]["sweeps"] == N_UNITS
    proc = subprocess.run(
        [sys.executable, tool, serial_file, "--max-gap", str(RTT)],
        capture_output=True, text=True)
    assert proc.returncode == 1


# ---------------------------------------------------------------------------
# chaos: lease-ahead x fault paths (ISSUE 5 satellite)

def test_crashed_worker_with_two_leases_reissues_both_no_double_complete():
    """A worker holding 2 aheaded leases crashes: both units reissue to
    another worker with one trace each and zero orphans, coverage is
    exact, and the crashed worker's LATE complete arriving after the
    reissue is dropped (no double-complete, no double count)."""

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    rec, reg = _recorder(), MetricsRegistry()
    keyspace, unit = 1000, 500
    state, server, disp = _serve(keyspace, unit, rec, reg, clock=clk,
                                 lease_timeout=10.0)
    try:
        crashed = CoordinatorClient(*server.address)
        resp = crashed.call("lease", worker_id="wA", ahead=2)
        units = resp["units"]
        assert len(units) == 2
        assert disp.outstanding_for("wA") == 2
        # ... wA crashes holding both (one queued, one running):
        # expiry treats them identically, per-unit
        clk.t += 60.0
        survivor = CoordinatorClient(*server.address)
        r2 = survivor.call("lease", worker_id="wB")
        assert r2["unit"]["id"] == units[0]["id"]     # reissued
        # wA's late complete arrives while wB holds the lease: the
        # stale report must not complete the unit under wB
        late = crashed.call("complete", unit_id=units[0]["id"],
                            hits=[], worker_id="wA", elapsed=1.0)
        assert late["ok"]
        assert disp.outstanding_unit(units[0]["id"]) is not None
        assert disp.progress()[0] == 0
        assert reg.get("dprf_units_completed_total").value(job="j0") == 0
        crashed.close()
        # wB completes it for real, then sweeps the rest via the loop
        survivor.call("complete", unit_id=units[0]["id"], hits=[],
                      worker_id="wB", elapsed=1.0)
        assert disp.progress()[0] == unit
        from dprf_tpu.engines import get_engine
        from dprf_tpu.generators.mask import MaskGenerator
        eng = get_engine("md5")
        gen = MaskGenerator("?d?d?d")
        targets = [eng.parse_target("ff" * 16)]      # unmatchable
        worker_loop(survivor, CpuWorker(eng, gen, targets), "wB",
                    idle_sleep=0.01, registry=reg,
                    recorder=_recorder())
        survivor.close()
        # exact coverage, each unit completed exactly once
        assert disp.completed_intervals() == [(0, keyspace)]
        assert reg.get("dprf_units_completed_total").value(job="j0") == 2
        rep = lifecycle_report(rec.tail(1000))
        assert rep["traces"] == 2
        assert rep["orphans"] == 0
        assert rep["incomplete"] == []
        for detail in rep["details"].values():
            assert detail["names"].count("complete") == 1
            assert detail["leases"] == 2        # wA's, then wB's
            assert detail["reissues"] == 1      # one expiry each
    finally:
        server.shutdown()


def test_worker_crash_mid_pipeline_releases_every_lease():
    """A processing crash in the pipelined loop fails the aborted unit
    AND every other lease it held (submitted or still queued), so a
    healthy worker finishes the keyspace without waiting out expiry."""
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(400, 100, rec, reg)
    try:
        class Boom(Exception):
            pass

        class BadWorker:
            def process(self, unit):
                raise Boom()

        client = CoordinatorClient(*server.address)
        with pytest.raises(Boom):
            worker_loop(client, BadWorker(), "bad", idle_sleep=0.01,
                        registry=reg, recorder=_recorder(), depth=3)
        client.close()
        # every lease was released in-band (no 300s expiry wait)
        assert disp.outstanding_count() == 0
        from dprf_tpu.engines import get_engine
        from dprf_tpu.generators.mask import MaskGenerator
        eng = get_engine("md5")
        gen = MaskGenerator("?d?d?d")        # 1000 > 400 keyspace? no:
        # keyspace is the dispatcher's (400); the generator only needs
        # to cover it
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(
            eng, gen, [eng.parse_target("ff" * 16)]), "good",
            idle_sleep=0.01, registry=reg, recorder=_recorder())
        client.close()
        assert disp.done()
        assert disp.completed_intervals() == [(0, 400)]
    finally:
        server.shutdown()


def test_pipelined_elapsed_reports_throughput_not_queue_wait():
    """The elapsed a pipelined worker ships with complete feeds the
    adaptive unit sizer.  Submit->resolve time includes up to depth-1
    units of queue wait behind the device stream (~depth x the true
    cost), which would shrink every subsequent unit to ~1/depth of the
    target; the loop must report the inter-completion interval (the
    worker's real drain rate) instead."""
    observed = []

    class RecordingSizer:
        def next_size(self, wid):
            return UNIT

        def observe(self, wid, length, elapsed):
            observed.append(elapsed)

        def observe_failure(self, wid):
            pass

    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(8 * UNIT, UNIT, rec, reg)
    disp.sizer = RecordingSizer()
    try:
        w = StreamWorker(compute_s=0.05)
        client = CoordinatorClient(*server.address)
        done = worker_loop(client, w, "w-sizer", idle_sleep=0.01,
                           registry=reg, recorder=_recorder(), depth=3)
        client.close()
        w.close()
        assert done == 8
        # steady-state reports are ~compute_s apiece; queue-wait
        # reporting would sit at ~depth x compute_s
        steady = sorted(observed)[: len(observed) // 2]
        assert steady and max(steady) < 2 * 0.05, observed
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# depth knob + serial fallback + idle metric

def test_pipeline_depth_env_knob(monkeypatch):
    monkeypatch.delenv("DPRF_PIPELINE_DEPTH", raising=False)
    assert pipeline_depth() == 2
    assert pipeline_depth(4) == 4
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "1")
    assert pipeline_depth() == 1
    assert pipeline_depth(4) == 1          # env overrides the default
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "999")
    assert pipeline_depth() == 64          # clamped
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "junk")
    assert pipeline_depth() == 2           # unparsable -> default


def test_env_serial_fallback_runs_the_serial_loop(monkeypatch):
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "1")
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(300, 100, rec, reg)
    try:
        from dprf_tpu.engines import get_engine
        from dprf_tpu.generators.mask import MaskGenerator
        eng = get_engine("md5")
        gen = MaskGenerator("?d?d?d")
        client = CoordinatorClient(*server.address)
        done = worker_loop(client, CpuWorker(
            eng, gen, [eng.parse_target("ff" * 16)]), "w",
            idle_sleep=0.01, registry=reg, recorder=_recorder())
        client.close()
        assert done == 3 and disp.done()
        assert reg.get("dprf_worker_pipeline_depth").value() == 1
        # the serial loop idles between every unit (2 RTT + decode):
        # the idle counter exists and accumulated something >= 0
        assert reg.get("dprf_worker_idle_seconds").value() >= 0.0
    finally:
        server.shutdown()


def test_unit_pipeline_bounds_and_drain():
    class Sync:
        def process(self, unit):
            return ["hit", unit]

        process._serial_only = True

    pipe = UnitPipeline(Sync(), 2)
    assert len(pipe) == 0 and not pipe.full
    pipe.submit("u1")
    pipe.submit("u2")
    assert pipe.full
    unit, pending, t_submit, meta = pipe.pop()
    assert unit == "u1" and pending.resolve() == ["hit", "u1"]
    assert meta is None and t_submit <= time.monotonic()
    assert [e[0] for e in pipe.drain()] == ["u2"]
    assert len(pipe) == 0


# ---------------------------------------------------------------------------
# async completion sender semantics

def test_completion_sender_orders_latches_and_surfaces_stop():
    sent = []

    class FakeClient:
        def call(self, op, **kw):
            sent.append((op, kw.get("unit_id")))
            return {"ok": True, "stop": kw.get("unit_id") == 2}

        def close(self):
            pass

    s = _CompletionSender(FakeClient())
    s.send("complete", unit_id=1)
    s.send("complete", unit_id=2)
    s.drain()
    assert sent == [("complete", 1), ("complete", 2)]   # FIFO order
    assert s.stop_seen
    s.close()


def test_completion_sender_first_error_reraised_rest_dropped():
    attempts = []

    class DeadClient:
        def call(self, op, **kw):
            attempts.append(op)
            raise ConnectionError("coordinator gone")

        def close(self):
            pass

    s = _CompletionSender(DeadClient())
    s.send("complete", unit_id=1)
    s.send("complete", unit_id=2)
    s.send("fail", unit_id=3)
    with pytest.raises(ConnectionError, match="coordinator gone"):
        s.drain()
    # only the first report hit the wire; the rest were dropped (their
    # leases expire and reissue)
    assert attempts == ["complete"]
    s.close()


# ---------------------------------------------------------------------------
# incremental span streaming (dprf top --follow satellite)

def test_tail_after_incremental_and_resync():
    r = _recorder()
    ids = [r.record("sweep", unit=i)["span"] for i in range(5)]
    spans, resync = r.tail_after(ids[2])
    assert not resync
    assert [s["attrs"]["unit"] for s in spans] == [3, 4]
    spans, resync = r.tail_after(ids[4])
    assert spans == [] and not resync
    # unknown cursor (never seen, or wrapped off the ring): full tail
    # with the resync flag so the caller replaces its buffer
    spans, resync = r.tail_after("not-a-span-id")
    assert resync and len(spans) == 5
    small = TraceRecorder(capacity=16, registry=MetricsRegistry())
    first = small.record("sweep", unit=0)["span"]
    for i in range(1, 40):
        small.record("sweep", unit=i)
    spans, resync = small.tail_after(first)
    assert resync and len(spans) == 16
    # an increment LARGER than the window is a resync too: returning
    # the newest n with resync=False would silently hole the caller's
    # buffer
    spans, resync = r.tail_after(ids[0], n=2)
    assert resync and [s["attrs"]["unit"] for s in spans] == [3, 4]
    spans, resync = r.tail_after(ids[2], n=2)
    assert not resync and len(spans) == 2


def test_op_trace_tail_cursor_protocol():
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(200, 100, rec, reg)
    try:
        rec.record("sweep", unit=0)
        resp = state.op_trace_tail({"n": 50})
        assert resp["cursor"] and not resp["resync"]
        cur = resp["cursor"]
        # nothing new: empty payload, cursor unchanged
        resp = state.op_trace_tail({"n": 50, "since": cur})
        assert resp["spans"] == [] and resp["cursor"] == cur
        rec.record("sweep", unit=1)
        rec.record("sweep", unit=2)
        resp = state.op_trace_tail({"n": 50, "since": cur})
        assert [s["attrs"]["unit"] for s in resp["spans"]] == [1, 2]
        assert not resp["resync"]
        assert resp["cursor"] == resp["spans"][-1]["span"]
        # a cursor the ring no longer holds forces a resync
        resp = state.op_trace_tail({"n": 50, "since": "zz-gone"})
        assert resp["resync"] and len(resp["spans"]) == 3
    finally:
        server.shutdown()


def test_top_follow_cli(capsys):
    rec, reg = _recorder(), MetricsRegistry()
    state, server, disp = _serve(200, 100, rec, reg)
    try:
        from dprf_tpu.engines import get_engine
        from dprf_tpu.generators.mask import MaskGenerator
        eng = get_engine("md5")
        gen = MaskGenerator("?d?d?d")
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(
            eng, gen, [eng.parse_target("ff" * 16)]), "w-follow",
            idle_sleep=0.01, registry=reg, recorder=_recorder())
        client.close()
        from dprf_tpu.cli import main as cli_main
        host, port = server.address
        rc = cli_main(["top", "--connect", f"{host}:{port}",
                       "--follow", "--iterations", "2", "--interval",
                       "0.1", "--no-clear", "--quiet"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "w-follow" in out
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# worker pipelining-contract lint (tools/check_worker_contract.py)

def _run_contract_lint(*args):
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "check_worker_contract.py")
    return subprocess.run([sys.executable, tool, *args],
                          capture_output=True, text=True)


def test_worker_contract_passes_on_the_real_package():
    proc = _run_contract_lint()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_worker_contract_flags_unmarked_process_override(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "w.py").write_text(
        "class SneakyWorker:\n"
        "    def process(self, unit):\n"
        "        return []\n")
    proc = _run_contract_lint(str(pkg))
    assert proc.returncode == 1
    assert "SneakyWorker" in proc.stdout


def test_worker_contract_flags_marker_without_submit(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "w.py").write_text(
        "class InheritedSubmit:\n"
        "    def process(self, unit):\n"
        "        return []\n"
        "    process._submit_based = True\n")
    proc = _run_contract_lint(str(pkg))
    assert proc.returncode == 1
    assert "InheritedSubmit" in proc.stdout


def test_worker_contract_accepts_explicit_stances(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "w.py").write_text(
        "class SerialWorker:\n"
        "    def process(self, unit):\n"
        "        return []\n"
        "    process._serial_only = True\n"
        "\n"
        "class PipelinedWorker:\n"
        "    def submit(self, unit):\n"
        "        return unit\n"
        "    def process(self, unit):\n"
        "        return self.submit(unit).resolve()\n"
        "    process._submit_based = True\n")
    proc = _run_contract_lint(str(pkg))
    assert proc.returncode == 0, proc.stdout + proc.stderr
