"""PBKDF2-HMAC-SHA256 (Django / hashcat 10900): RFC-style vectors via
hashlib, runtime-salt device path, both line formats, workers, CLI."""

import base64
import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _django_line(pw, salt, iters):
    dk = hashlib.pbkdf2_hmac("sha256", pw, salt, iters, 32)
    return (f"pbkdf2_sha256${iters}${salt.decode('latin-1')}$"
            + base64.b64encode(dk).decode())


def _hashcat_line(pw, salt, iters):
    dk = hashlib.pbkdf2_hmac("sha256", pw, salt, iters, 32)
    return (f"sha256:{iters}:" + base64.b64encode(salt).decode()
            + ":" + base64.b64encode(dk).decode())


def test_parse_both_formats():
    cpu = get_engine("pbkdf2-sha256", "cpu")
    for line in (_django_line(b"pw", b"somesalt", 1000),
                 _hashcat_line(b"pw", b"\x01\x02binary", 1000)):
        t = cpu.parse_target(line)
        assert t.params["iterations"] == 1000
        assert cpu.verify(b"pw", t)
        assert not cpu.verify(b"no", t)


def test_device_matches_hashlib_runtime_salt():
    import random
    from dprf_tpu.engines.device.pbkdf2 import (
        SALT_MAX, pbkdf2_sha256_runtime_salt)
    from dprf_tpu.ops import pack as pack_ops

    rng = random.Random(10900)
    cands = [bytes(rng.randrange(1, 256) for _ in range(8))
             for _ in range(8)]
    salt = b"NaCl-salt"
    iters = 64
    buf = np.zeros((len(cands), 8), np.uint8)
    for i, c in enumerate(cands):
        buf[i] = np.frombuffer(c, np.uint8)
    key = pack_ops.pack_raw(jnp.asarray(buf), 8, big_endian=True)
    sbuf = np.zeros((SALT_MAX,), np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    dk = pbkdf2_sha256_runtime_salt(key, jnp.asarray(sbuf),
                                    jnp.int32(len(salt)),
                                    jnp.int32(iters))
    got = [np.asarray(dk)[i].astype(">u4").tobytes()
           for i in range(len(cands))]
    want = [hashlib.pbkdf2_hmac("sha256", c, salt, iters, 32)
            for c in cands]
    assert got == want


def test_mask_worker_end_to_end():
    dev = get_engine("pbkdf2-sha256", "jax")
    cpu = get_engine("pbkdf2-sha256", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"q7z"
    t = dev.parse_target(_django_line(secret, b"salty", 100))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_wordlist_worker_distinct_salts():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("pbkdf2-sha256", "jax")
    cpu = get_engine("pbkdf2-sha256", "cpu")
    words = [b"monday", b"friday"]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=12)
    t1 = dev.parse_target(_django_line(b"FRIDAY", b"saltA", 100))
    t2 = dev.parse_target(_hashcat_line(b"monday", b"saltBB", 150))
    w = dev.make_wordlist_worker(gen, [t1, t2], batch=8, hit_capacity=8,
                                 oracle=cpu)
    hits = sorted((h.target_index, h.plaintext)
                  for h in w.process(WorkUnit(0, 0, gen.keyspace)))
    assert hits == [(0, b"FRIDAY"), (1, b"monday")]


def test_cli_pbkdf2_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = _django_line(b"x9", b"grain", 100)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?d", str(hf), "--engine", "pbkdf2-sha256",
               "--device", "tpu", "--no-potfile", "--batch", "512",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:x9" in out


def test_sharded_pbkdf2_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("pbkdf2-sha256", "jax")
    cpu = get_engine("pbkdf2-sha256", "cpu")
    gen = MaskGenerator("?l?d")
    secret = b"p7"
    t = dev.parse_target(_django_line(secret, b"mesa", 100))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=16, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_pbkdf2_sha1_engine(tmp_path, capsys):
    """Generic PBKDF2-HMAC-SHA1 (12000): parse, oracle, device crack,
    truncated derived keys."""
    from dprf_tpu.cli import main

    def line(pw, salt, iters, dklen):
        dk = hashlib.pbkdf2_hmac("sha1", pw, salt, iters, dklen)
        return (f"sha1:{iters}:" + base64.b64encode(salt).decode()
                + ":" + base64.b64encode(dk).decode())

    cpu = get_engine("pbkdf2-sha1", "cpu")
    t = cpu.parse_target(line(b"pw", b"salty", 100, 16))
    assert t.params["dklen"] == 16 and cpu.verify(b"pw", t)

    dev = get_engine("pbkdf2-sha1", "jax")
    gen = MaskGenerator("?l?d")
    secret = b"z7"
    for dklen in (16, 20, 32):
        t = dev.parse_target(line(secret, b"mesa", 100, dklen))
        w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                                 oracle=cpu)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        assert [(h.target_index, h.plaintext)
                for h in hits] == [(0, secret)], dklen

    hf = tmp_path / "h.txt"
    hf.write_text(line(b"m3", b"grain", 100, 20) + "\n")
    rc = main(["crack", "?l?d", str(hf), "--engine", "pbkdf2-sha1",
               "--device", "tpu", "--no-potfile", "--batch", "512",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and ":m3" in out


def test_pbkdf2_sha1_wordlist_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    def line(pw, salt, iters, dklen):
        dk = hashlib.pbkdf2_hmac("sha1", pw, salt, iters, dklen)
        return (f"sha1:{iters}:" + base64.b64encode(salt).decode()
                + ":" + base64.b64encode(dk).decode())

    dev = get_engine("pbkdf2-sha1", "jax")
    cpu = get_engine("pbkdf2-sha1", "cpu")
    words = [b"monday", b"friday"]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=12)
    secret = b"FRIDAY"
    t = dev.parse_target(line(secret, b"saltX", 100, 20))
    w = dev.make_wordlist_worker(gen, [t], batch=8, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cisco8_published_vector_and_crack(tmp_path, capsys):
    """The published Cisco type 8 example (password 'hashcat')
    verifies, and a planted $8$ target cracks via the device path."""
    from dprf_tpu.cli import main
    from dprf_tpu.engines.cpu.engines import cisco8_encode

    cpu = get_engine("cisco8", "cpu")
    example = ("$8$TnGX/fE4KGHOVU$"
               "pEhnEvxrvaynpi8j4f.EMHr6M.FzU8xnZnBr/tJdFWk")
    t = cpu.parse_target(example)
    assert cpu.verify(b"hashcat", t)
    assert not cpu.verify(b"wrong", t)
    # encode round-trip
    assert cisco8_encode(t.digest) == example.split("$")[3]

    # planted crack (small iteration count is not possible in the $8$
    # format -- iterations are fixed 20000 -- so keep the keyspace tiny)
    dk = hashlib.pbkdf2_hmac("sha256", b"z7", b"saltsaltsalts", 20000, 32)
    line = "$8$saltsaltsalts$" + cisco8_encode(dk)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?d", str(hf), "--engine", "cisco8",
               "--device", "tpu", "--no-potfile", "--batch", "512",
               "--unit-size", "512", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and ":z7" in out
