"""Fleet health plane (ISSUE 10): the worker state machine +
straggler detection (telemetry/health.py), per-job SLOs
(jobs/scheduler.py), the alert engine lifecycle + rule loading
(telemetry/alerts.py), the op_heartbeat/op_health/op_alerts RPC
surface, owner-scoped tenant tokens, the unconditional job-tagged
journal, the `dprf check` alert-rule validation, and the acceptance
chaos test: kill a worker mid-job -> worker_missing fires -> rejoin
-> resolves, with zero keyspace coverage loss and exact accounting.
"""

import hashlib
import json
import textwrap
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.jobs.scheduler import STALL_WINDOWS, JobScheduler
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, RpcError,
                                  owner_token, token_owner,
                                  worker_loop)
from dprf_tpu.runtime.session import SessionJournal, job_fingerprint
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.telemetry import alerts as alerts_mod
from dprf_tpu.telemetry import health as health_mod
from dprf_tpu.telemetry.alerts import (AlertEngine, AlertRule,
                                       load_alerts, load_rules)
from dprf_tpu.telemetry.health import HealthRegistry
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import TraceRecorder

pytestmark = [pytest.mark.smoke, pytest.mark.health]

UNIT = 100
KEYSPACE = 1000


class Clock:
    """Settable fake clock (monotonic or wall)."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# HealthRegistry: state machine, rejoin, stragglers, payloads

def _health(reg=None, hb=1.0):
    reg = reg or MetricsRegistry()
    clk, wall = Clock(0.0), Clock(1_000.0)
    return HealthRegistry(registry=reg, clock=clk, wall=wall,
                          heartbeat_s=hb), clk, reg


def test_state_machine_decays_healthy_to_dead():
    h, clk, reg = _health()
    h.observe("w1")
    assert h.states() == {"w1": "healthy"}
    clk.t = 2.5            # > 2 beats
    trs = h.evaluate()
    assert [(t["from"], t["to"]) for t in trs] == \
        [("healthy", "degraded")]
    clk.t = 4.5            # > 4 beats
    assert h.evaluate()[0]["to"] == "missing"
    clk.t = 13.0           # > 12 beats
    assert h.evaluate()[0]["to"] == "dead"
    assert h.states() == {"w1": "dead"}
    g = reg.get("dprf_worker_health_state")
    assert g.value(worker="w1") == health_mod.DEAD


def test_any_contact_heals_and_queues_rejoin_transition():
    h, clk, reg = _health()
    h.observe("w1")
    clk.t = 5.0
    h.evaluate()                       # -> missing
    h.observe("w1")                    # rejoin: heals immediately
    assert h.states() == {"w1": "healthy"}
    assert reg.get("dprf_worker_health_state").value(worker="w1") == 0
    # the rejoin transition is DRAINED by the next evaluate (the
    # journaling contract: callbacks never run under observe's caller)
    trs = h.evaluate()
    assert ("missing", "healthy") in [(t["from"], t["to"])
                                      for t in trs]
    assert h.evaluate() == []          # drained exactly once


def test_transitions_carry_wall_ts_and_age():
    h, clk, _ = _health()
    h.observe("w1")
    clk.t = 2.5
    tr = h.evaluate()[0]
    assert tr["worker"] == "w1" and tr["ts"] == 1_000.0
    assert tr["age_s"] == pytest.approx(2.5)


def test_straggler_mad_zscore_flags_slow_worker():
    h, clk, reg = _health()
    for w, r in (("w1", 100.0), ("w2", 101.0), ("w3", 99.0),
                 ("w4", 100.0), ("w5", 10.0)):
        h.observe(w, rate_hs=r)
    h.evaluate()
    snap = h.snapshot()
    assert snap["w5"]["straggler"] is True
    assert all(not snap[w]["straggler"] for w in
               ("w1", "w2", "w3", "w4"))
    g = reg.get("dprf_worker_straggler")
    assert g.value(worker="w5") == 1 and g.value(worker="w1") == 0


def test_straggler_degenerate_mad_falls_back_to_median_floor():
    h, _, _ = _health()
    for w in ("w1", "w2", "w3", "w4"):
        h.observe(w, rate_hs=100.0)    # identical fleet: MAD = 0
    h.observe("w5", rate_hs=30.0)
    h.evaluate()
    assert h.snapshot()["w5"]["straggler"] is True


def test_straggler_needs_a_minimum_fleet():
    h, _, _ = _health()
    h.observe("w1", rate_hs=100.0)
    h.observe("w2", rate_hs=1.0)
    h.evaluate()
    assert not any(r["straggler"] for r in h.snapshot().values())


def test_heartbeat_payload_sanitized():
    h, _, _ = _health()
    h.observe("w1", payload={"engine": "md5", "queue": 2,
                             "error": "x" * 500, "junk": "nope"})
    pl = h.snapshot()["w1"]["payload"]
    assert pl["engine"] == "md5" and pl["queue"] == 2
    assert len(pl["error"]) == health_mod.MAX_PAYLOAD_STR
    assert "junk" not in pl


def test_worker_id_cardinality_capped(monkeypatch):
    monkeypatch.setattr(health_mod, "MAX_WORKERS", 4)
    h, _, _ = _health()
    for i in range(8):
        h.observe(f"w{i}")
    snap = h.snapshot()
    assert len(snap) == 5 and "_overflow" in snap


def test_rate_ewma_smooths():
    h, _, _ = _health()
    h.observe("w1", rate_hs=100.0)
    h.observe("w1", rate_hs=200.0)
    r = h.snapshot()["w1"]["rate_hs"]
    assert 100.0 < r < 200.0


# ---------------------------------------------------------------------------
# per-job SLOs in the scheduler

def _slo_sched():
    reg = MetricsRegistry()
    clk = Clock(0.0)
    s = JobScheduler(registry=reg, clock=clk)
    jid = s.reserve_id()
    d = Dispatcher(KEYSPACE, UNIT, registry=reg, job_id=jid,
                   recorder=TraceRecorder(registry=reg))
    job = s.add({"engine": "md5"}, d, 1, job_id=jid)
    return s, job, reg, clk


def test_eta_from_coverage_rate_ewma():
    s, job, reg, clk = _slo_sched()
    s.update_slos()                    # initializes the window
    for _ in range(2):
        (j, u), = s.lease_many("w0", 1)
        j.dispatcher.complete(u.unit_id)
    clk.t = 10.0                       # 200 indices in 10s = 20 ips
    s.update_slos()
    assert reg.get("dprf_job_eta_seconds").value(job=job.job_id) == \
        pytest.approx((KEYSPACE - 200) / 20.0)
    row, = s.slo_summaries()
    assert row["rate_ips"] == pytest.approx(20.0)
    assert row["eta_s"] == pytest.approx(40.0)


def test_job_stalled_after_flat_windows_and_recovers():
    s, job, reg, clk = _slo_sched()
    (j, u), = s.lease_many("w0", 1)    # RUNNING
    j.dispatcher.complete(u.unit_id)
    s.update_slos()
    g = reg.get("dprf_job_stalled")
    for i in range(STALL_WINDOWS):
        clk.t += 5.0
        s.update_slos()
    assert g.value(job=job.job_id) == 1
    assert s.slo_summaries()[0]["stalled"] is True
    # progress clears the stall
    (j, u), = s.lease_many("w0", 1)
    j.dispatcher.complete(u.unit_id)
    clk.t += 5.0
    s.update_slos()
    assert g.value(job=job.job_id) == 0


def test_paused_job_is_not_stalled():
    s, job, reg, clk = _slo_sched()
    (j, u), = s.lease_many("w0", 1)
    j.dispatcher.complete(u.unit_id)
    s.update_slos()
    s.pause(job.job_id)
    for _ in range(STALL_WINDOWS + 1):
        clk.t += 5.0
        s.update_slos()
    assert reg.get("dprf_job_stalled").value(job=job.job_id) == 0


def test_time_to_first_hit_published_once():
    s, job, reg, clk = _slo_sched()
    clk.t = 7.5
    s.record_hit(job, 0, 42, b"x")
    s.record_hit(job, 0, 43, b"y")     # dup target: not a new hit
    clk.t = 20.0
    s.update_slos()
    s.update_slos()
    assert reg.get("dprf_job_ttfh_seconds").value(job=job.job_id) \
        == pytest.approx(7.5)
    assert s.slo_summaries()[0]["ttfh_s"] == pytest.approx(7.5)


def test_lease_wait_histogram_observes_grant_intervals():
    s, job, reg, clk = _slo_sched()
    clk.t = 5.0
    s.lease_many("w0", 1)              # wait: 5s from creation
    clk.t = 7.0
    s.lease_many("w0", 1)              # wait: 2s since last grant
    h = reg.get("dprf_job_lease_wait_seconds")
    assert h.count(job=job.job_id) == 2
    assert h.sum(job=job.job_id) == pytest.approx(7.0)


# ---------------------------------------------------------------------------
# alert engine: lifecycle, flap suppression, rate rules, streams

def _engine(rules, reg=None):
    reg = reg or MetricsRegistry()
    clk, wall = Clock(100.0), Clock(5_000.0)
    return AlertEngine(rules=rules, registry=reg, clock=clk,
                       wall=wall), reg, clk


def _wm_rule(**kw):
    d = dict(name="wm", metric="dprf_worker_health_state", op=">=",
             threshold=2, for_s=10.0, clear_s=5.0)
    d.update(kw)
    return AlertRule(**d)


def test_alert_pending_firing_resolved_lifecycle():
    eng, reg, clk = _engine([_wm_rule()])
    g = reg.gauge("dprf_worker_health_state", "h",
                  labelnames=("worker",))
    g.set(3, worker="w1")
    ev = eng.evaluate()
    assert [e["state"] for e in ev] == ["pending"]
    assert eng.active()[0]["state"] == "pending"
    clk.t += 10.0
    ev = eng.evaluate()
    assert [e["state"] for e in ev] == ["firing"]
    assert eng.firing_names() == ["wm(w1)"]
    assert reg.get("dprf_alerts_firing").value(rule="wm") == 1
    assert reg.get("dprf_alerts_fired_total").value(rule="wm") == 1
    g.set(0, worker="w1")
    clk.t += 1.0
    assert eng.evaluate() == []        # clear hold running
    clk.t += 5.0
    ev = eng.evaluate()
    assert [e["state"] for e in ev] == ["resolved"]
    assert eng.active() == []
    assert reg.get("dprf_alerts_firing").value(rule="wm") == 0


def test_flapping_dip_neither_resolves_nor_refires():
    eng, reg, clk = _engine([_wm_rule()])
    g = reg.gauge("dprf_worker_health_state", "h",
                  labelnames=("worker",))
    g.set(3, worker="w1")
    eng.evaluate()
    clk.t += 10.0
    eng.evaluate()                     # firing
    for _ in range(4):                 # flap under the 5s clear hold
        g.set(0, worker="w1")
        clk.t += 2.0
        assert eng.evaluate() == []
        g.set(3, worker="w1")
        clk.t += 2.0
        assert eng.evaluate() == []    # no re-fire either
    assert eng.active()[0]["state"] == "firing"
    assert reg.get("dprf_alerts_fired_total").value(rule="wm") == 1


def test_pending_that_clears_vanishes_silently():
    eng, reg, clk = _engine([_wm_rule()])
    g = reg.gauge("dprf_worker_health_state", "h",
                  labelnames=("worker",))
    g.set(3, worker="w1")
    eng.evaluate()
    g.set(0, worker="w1")
    clk.t += 1.0
    assert eng.evaluate() == []
    assert eng.active() == []
    assert [e["state"] for e in eng.history()] == ["pending"]


def test_per_label_child_alerts_are_independent():
    eng, reg, clk = _engine([_wm_rule(for_s=0.0)])
    g = reg.gauge("dprf_worker_health_state", "h",
                  labelnames=("worker",))
    g.set(3, worker="w1")
    g.set(3, worker="w2")
    g.set(0, worker="w3")
    eng.evaluate()
    assert sorted(eng.firing_names()) == ["wm(w1)", "wm(w2)"]
    assert reg.get("dprf_alerts_firing").value(rule="wm") == 2


def test_rate_rule_needs_two_sightings_then_fires_on_delta():
    rule = AlertRule(name="storm",
                     metric="dprf_trace_spans_dropped_total",
                     rate=True, op=">", threshold=0.5, for_s=0.0)
    eng, reg, clk = _engine([rule])
    c = reg.counter("dprf_trace_spans_dropped_total", "d")
    c.inc(100)
    assert eng.evaluate() == []        # first sighting: no baseline
    c.inc(100)
    clk.t += 10.0                      # 10/s > 0.5
    ev = eng.evaluate()
    assert [e["state"] for e in ev] == ["pending", "firing"]
    clk.t += 10.0                      # rate drops to 0; clear_s=0
    assert "resolved" in [e["state"] for e in eng.evaluate()]


def test_rule_label_filter_selects_one_child():
    rule = AlertRule(name="fails", metric="dprf_units_reissued_total",
                     labels={"reason": "failed"}, rate=True, op=">",
                     threshold=0.5, for_s=0.0)
    eng, reg, clk = _engine([rule])
    c = reg.counter("dprf_units_reissued_total", "r",
                    labelnames=("reason", "job"))
    c.inc(100, reason="lease_expired", job="j0")
    c.inc(1, reason="failed", job="j0")
    eng.evaluate()
    clk.t += 10.0
    c.inc(1000, reason="lease_expired", job="j0")  # filtered out
    assert eng.evaluate() == []


def test_alert_stream_rotates_under_byte_cap(tmp_path):
    path = str(tmp_path / "s.alerts.jsonl")
    eng, reg, clk = _engine([_wm_rule(for_s=0.0, clear_s=0.0)])
    eng.attach_file(path, max_bytes=600)
    g = reg.gauge("dprf_worker_health_state", "h",
                  labelnames=("worker",))
    import os
    for i in range(30):                # fire/resolve churn
        g.set(3, worker="w1")
        clk.t += 1.0
        eng.evaluate()
        g.set(0, worker="w1")
        clk.t += 1.0
        eng.evaluate()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 600
    events = load_alerts(path)
    assert events and all(e["rule"] == "wm" for e in events)
    assert {e["state"] for e in events} >= {"firing", "resolved"}


def test_load_rules_default_pack_and_override(tmp_path):
    rules = {r.name for r in load_rules(path="")}
    assert {"worker_missing", "straggler", "job_stalled",
            "compile_miss_storm", "reissue_storm",
            "unit_failure_rate", "trace_drops"} <= rules
    # the shipped fixture file parses and OVERRIDES by name
    loaded = load_rules(path="tests/fixtures/alert_rules_custom.json")
    by_name = {r.name: r for r in loaded}
    assert by_name["worker_missing"].for_s == 2.0
    assert "reject_storm" in by_name
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(ValueError, match="DPRF_ALERT_RULES"):
        load_rules(path=str(bad))
    junk = tmp_path / "junk.json"
    junk.write_text('[{"name": "x", "metric": "m", "bogus": 1}]')
    with pytest.raises(ValueError, match="unknown keys"):
        load_rules(path=str(junk))


def test_env_rules_file_knob(monkeypatch):
    monkeypatch.setenv("DPRF_ALERT_RULES",
                       "tests/fixtures/alert_rules_custom.json")
    assert "reject_storm" in {r.name for r in load_rules()}


# ---------------------------------------------------------------------------
# RPC surface: op_heartbeat / op_health / op_alerts + dprf top

def _mask_job(mask="?d?d?d", plants=(b"999",), unit_size=UNIT):
    eng = get_engine("md5")
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest())
               for p in plants]
    fp = job_fingerprint("md5", f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "md5", "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets],
           "keyspace": gen.keyspace, "unit_size": unit_size,
           "batch": 4096, "hit_cap": 8, "fingerprint": fp}
    return eng, gen, targets, job


def _serve(job, gen, targets, lease_timeout=300.0, token=None):
    reg = MetricsRegistry()
    rec = TraceRecorder(registry=reg)
    eng = get_engine(job["engine"])
    disp = Dispatcher(gen.keyspace, job["unit_size"], registry=reg,
                      recorder=rec, job_id="j0",
                      lease_timeout=lease_timeout)
    state = CoordinatorState(
        job, disp, len(targets), registry=reg, recorder=rec,
        token=token,
        verifier=lambda ti, p: eng.verify(p, targets[ti]))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, reg


def test_op_heartbeat_feeds_health_and_last_seen_gauge():
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        # a worker that holds NO lease is now visible (the old gauge
        # only tracked lease holders)
        c.call("heartbeat", worker_id="idle-w",
               payload={"engine": "md5", "queue": 0})
        assert reg.get("dprf_worker_last_seen_timestamp").value(
            worker="idle-w") > 0
        assert state.health.states() == {"idle-w": "healthy"}
        assert state.health.snapshot()["idle-w"]["payload"][
            "engine"] == "md5"
        resp = c.call("health")
        assert "idle-w" in resp["workers"]
        assert resp["jobs"][0]["job"] == "j0"
        resp = c.call("alerts", n=10)
        assert resp["alerts"] == [] and resp["history"] == []
        c.close()
    finally:
        server.shutdown()


def test_lease_and_complete_count_as_health_contact():
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        resp = c.call("lease", worker_id="w0")
        u = resp["unit"]
        assert state.health.states() == {"w0": "healthy"}
        c.call("complete", unit_id=u["id"], hits=[], worker_id="w0",
               elapsed=0.5, job=u["job"])
        # completes feed the straggler detector's rate EWMA
        assert state.health.snapshot()["w0"]["rate_hs"] == \
            pytest.approx(u["length"] / 0.5)
        c.close()
    finally:
        server.shutdown()


def test_trace_tail_status_and_render_top_show_health():
    from dprf_tpu.telemetry.trace import render_top
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        c.call("heartbeat", worker_id="hb-w", payload={})
        # force a firing alert through the engine directly
        state.health.heartbeat_s = 0.01
        time.sleep(0.1)
        state.alerts.rules = [AlertRule(
            name="worker_missing",
            metric="dprf_worker_health_state", op=">=", threshold=2,
            for_s=0.0)]
        state.health_tick()
        resp = c.call("trace_tail", n=10)
        assert resp["status"]["health"]["hb-w"] in ("missing", "dead")
        assert resp["status"]["alerts"] == ["worker_missing(hb-w)"]
        text = render_top(resp)
        assert "FIRING ALERTS: worker_missing(hb-w)" in text
        assert "HEALTH" in text and "hb-w" in text
        c.close()
    finally:
        server.shutdown()


def test_health_and_alerts_cli_json(capsys):
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        addr = f"{server.address[0]}:{server.address[1]}"
        c = CoordinatorClient(*server.address)
        c.call("heartbeat", worker_id="cli-w", payload={"queue": 1})
        c.close()
        assert cli_main(["health", "--connect", addr, "--json",
                         "-q"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert "cli-w" in doc["workers"]
        assert doc["jobs"][0]["job"] == "j0"
        assert cli_main(["alerts", "--connect", addr, "--json",
                         "-q"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["alerts"] == []
        # the human renderings run too
        assert cli_main(["health", "--connect", addr, "-q"]) == 0
        assert cli_main(["alerts", "--connect", addr, "-q"]) == 0
    finally:
        server.shutdown()


class _SlowWorker:
    """CpuWorker whose sweeps outlast the heartbeat cadence -- the
    case where the main connection goes quiet mid-unit."""

    def __init__(self, eng, gen, targets, delay):
        self._inner = CpuWorker(eng, gen, targets)
        self.engine = eng
        self._delay = delay

    def process(self, unit):
        time.sleep(self._delay)
        return self._inner.process(unit)
    process._serial_only = True


def test_worker_loop_heartbeats_when_sweeps_outlast_cadence(
        monkeypatch):
    """Lease traffic counts as contact, so a busy fast loop never
    beats; a loop whose SWEEPS outlast DPRF_HEARTBEAT_S sends
    op_heartbeat between units, payload included."""
    monkeypatch.setenv("DPRF_HEARTBEAT_S", "0.05")
    eng, gen, targets, job = _mask_job(unit_size=500)  # 2 units
    state, server, reg = _serve(job, gen, targets)
    try:
        wclient = CoordinatorClient(*server.address)
        done = worker_loop(
            wclient, _SlowWorker(eng, gen, targets, delay=0.12),
            "hb-worker", idle_sleep=0.02, depth=1,
            registry=MetricsRegistry(),
            recorder=TraceRecorder(registry=MetricsRegistry()))
        wclient.close()
        assert done == 2
        snap = state.health.snapshot()
        assert "hb-worker" in snap
        pl = snap["hb-worker"]["payload"]
        # a real beat arrived (payload only ships on op_heartbeat;
        # plain lease contacts carry none)
        assert pl.get("engine") == "md5"
        assert "rate_hs" in pl and "queue" in pl
        # and the liveness gauge covers it
        assert reg.get("dprf_worker_last_seen_timestamp").value(
            worker="hb-worker") > 0
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# the acceptance chaos test

@pytest.mark.jobs
def test_chaos_worker_death_alert_fires_rejoin_resolves(tmp_path):
    """ISSUE 10 acceptance: worker dies mid-job holding a lease ->
    worker_missing fires after the sustained window -> the worker
    rejoins -> the alert resolves -- zero keyspace coverage loss,
    exact accounting, every health transition journaled, alert
    lifecycle visible via op_alerts."""
    eng, gen, targets, job = _mask_job()           # plant at 999
    state, server, reg = _serve(job, gen, targets,
                                lease_timeout=1.0)
    path = str(tmp_path / "chaos.session")
    session = SessionJournal(path, snapshot_every=1)
    session.open(job, default_job="j0")
    state.on_worker_health = lambda tr: session.record_worker_health(
        tr["worker"], tr["from"], tr["to"], ts=tr.get("ts"),
        age_s=tr.get("age_s"))
    # fast state machine + fast rules so the test runs in seconds
    state.health.heartbeat_s = 0.2
    state.alerts = AlertEngine(
        rules=[AlertRule(name="worker_missing",
                         metric="dprf_worker_health_state",
                         op=">=", threshold=2, for_s=0.3,
                         clear_s=0.2, severity="critical")],
        registry=reg)
    state.alerts.attach_file(str(tmp_path / "chaos.alerts.jsonl"))
    try:
        # -- phase 1: w1 works, then dies holding a lease ------------
        w1 = CoordinatorClient(*server.address)
        resp = w1.call("lease", worker_id="w1", ahead=2)
        u_done, u_held = resp["units"]
        w1.call("complete", unit_id=u_done["id"], hits=[],
                worker_id="w1", elapsed=0.2, job=u_done["job"])
        w1.close()                                 # the "crash"

        def tick_until(pred, timeout=8.0, what=""):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                state.health_tick()
                if pred():
                    return
                time.sleep(0.05)
            raise AssertionError(f"timed out waiting for {what}")

        tick_until(lambda: "worker_missing(w1)"
                   in state.alerts.firing_names(),
                   what="worker_missing to fire")
        assert state.health.states()["w1"] in ("missing", "dead")

        # -- phase 2: w1 rejoins (same id) and finishes the job ------
        w1b = CoordinatorClient(*server.address)
        done = worker_loop(
            w1b, CpuWorker(eng, gen, targets), "w1",
            idle_sleep=0.01, depth=1, registry=MetricsRegistry(),
            recorder=TraceRecorder(registry=MetricsRegistry()))
        w1b.close()
        tick_until(lambda: state.alerts.firing_names() == [],
                   what="the alert to resolve")
        assert state.health.states()["w1"] == "healthy"

        # -- zero coverage loss, exact accounting --------------------
        with state.lock:
            j = state.scheduler.get("j0")
            assert j.dispatcher.completed_intervals() == \
                [(0, KEYSPACE)]
            assert j.found == {0: b"999"}
            assert j.dispatcher.parked_count() == 0
        # the held unit expired and was REISSUED, never lost
        assert reg.get("dprf_units_reissued_total").value(
            reason="lease_expired", job="j0") >= 1
        # every index swept exactly once across both lives: the dead
        # worker's unit counted 0 times, the reissue once
        assert reg.get("dprf_candidates_hashed_total").value(
            engine="md5", device="remote") == KEYSPACE
        assert done == KEYSPACE // UNIT - 1   # w1's first complete

        # -- lifecycle visible via op_alerts + the journal -----------
        c = CoordinatorClient(*server.address)
        hist = c.call("alerts", n=50)["history"]
        c.close()
        states = [e["state"] for e in hist
                  if e["rule"] == "worker_missing"]
        assert states == ["pending", "firing", "resolved"]
        session.close()
        prior = SessionJournal.load(path)
        trans = [(h["from"], h["to"]) for h in prior.health_events]
        assert ("healthy", "degraded") in trans
        assert ("degraded", "missing") in trans
        assert trans[-1][1] == "healthy"           # the rejoin
        # the alert stream on disk matches the op_alerts history
        events = load_alerts(str(tmp_path / "chaos.alerts.jsonl"))
        assert [e["state"] for e in events] == \
            ["pending", "firing", "resolved"]
    finally:
        server.shutdown()


def test_health_tick_overhead_under_two_percent():
    """PR 4-style overhead bound: one evaluation pass costs well
    under 2% of its DPRF_ALERT_EVAL_S cadence, even with a populated
    fleet and the full default rule pack."""
    eng, gen, targets, job = _mask_job()
    reg = MetricsRegistry()
    rec = TraceRecorder(registry=reg)
    disp = Dispatcher(gen.keyspace, UNIT, registry=reg, recorder=rec,
                      job_id="j0")
    state = CoordinatorState(job, disp, len(targets), registry=reg,
                             recorder=rec)
    for i in range(16):
        state.health.observe(f"w{i}", rate_hs=100.0 + i,
                             payload={"engine": "md5", "queue": i})
    with state.lock:
        for _ in range(4):
            state.scheduler.lease_many("w0", 1)
    state.health_tick()                 # warm (rate baselines etc.)
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        state.health_tick()
    per_tick = (time.perf_counter() - t0) / n
    budget = 0.02 * alerts_mod.eval_interval()
    assert per_tick <= budget, \
        f"health_tick {per_tick * 1e3:.2f}ms > 2% of the eval cadence"


# ---------------------------------------------------------------------------
# owner-scoped tenant tokens

def test_owner_token_mint_and_parse():
    t = owner_token("s3cret", "alice")
    assert t.startswith("ot1.alice.")
    assert token_owner(t) == "alice"
    assert token_owner("s3cret") is None
    assert token_owner(None) is None
    with pytest.raises(ValueError, match="owner must be"):
        owner_token("s3cret", "bad owner!")


def test_token_cli_mints(capsys):
    assert cli_main(["token", "--owner", "alice", "--token",
                     "s3cret", "-q"]) == 0
    out = capsys.readouterr().out.strip()
    assert out == owner_token("s3cret", "alice")


def _submit_spec(mask, plants, **extra):
    spec = {"engine": "md5", "attack": "mask", "attack_arg": mask,
            "targets": [hashlib.md5(p).hexdigest() for p in plants],
            "unit_size": UNIT, "unit_seconds": 0}
    spec.update(extra)
    return spec


def test_owner_scoped_ops_enforced():
    secret = "adm1n"
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets, token=secret)
    try:
        alice = CoordinatorClient(*server.address,
                                  token=owner_token(secret, "alice"))
        assert alice.hello()["owner"] == "alice"   # mutual auth too
        # a tenant's submission is FORCED to its authenticated owner
        resp = alice.call("job_submit",
                          spec=_submit_spec("?d?d?d", [b"zzz"]),
                          owner="mallory")
        jid = resp["job_id"]
        assert resp["job"]["owner"] == "alice"

        bob = CoordinatorClient(*server.address,
                                token=owner_token(secret, "bob"))
        bob.hello()
        with pytest.raises(RpcError, match="scoped to 'bob'"):
            bob.call("job_cancel", job=jid)
        with pytest.raises(RpcError, match="scoped to 'bob'"):
            bob.call("job_pause", job=jid)
        with pytest.raises(RpcError, match="scoped to 'bob'"):
            bob.call("hits_pull", job=jid)
        # read-only list stays open and SHOWS the owner
        assert any(j["owner"] == "alice"
                   for j in bob.call("job_list")["jobs"])

        # the owner itself may pause/pull/cancel
        assert alice.call("job_pause", job=jid)["job"]["state"] == \
            "paused"
        assert alice.call("hits_pull", job=jid)["hits"] == []
        # the ADMIN token is exempt
        admin = CoordinatorClient(*server.address, token=secret)
        admin.hello()
        assert admin.call("job_cancel", job=jid)["job"]["state"] == \
            "cancelled"
        for c in (alice, bob, admin):
            c.close()
    finally:
        server.shutdown()


def test_open_protocol_hello_never_confirms_a_claimed_owner():
    """Without a coordinator token there is no tenant scoping: a
    client claiming an owner in hello must NOT get it echoed back as
    if the connection were an authenticated, scoped tenant."""
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)   # token-less
    try:
        c = CoordinatorClient(*server.address)
        resp = c.call("hello", owner="alice")
        assert resp["owner"] is None
        c.close()
    finally:
        server.shutdown()


def test_forged_owner_token_rejected():
    secret = "adm1n"
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets, token=secret)
    try:
        forged = CoordinatorClient(*server.address,
                                   token="ot1.alice.deadbeef")
        with pytest.raises(RpcError, match="authentication failed"):
            forged.hello()
        forged.close()
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# journal tagging (ISSUE 10 satellite): tag everything, read anything

def test_new_journals_tag_every_line_and_restore_folds(tmp_path):
    hashfile = tmp_path / "h.txt"
    hashfile.write_text(hashlib.md5(b"99").hexdigest() + "\n")
    path = str(tmp_path / "t.session")
    rc = cli_main(["crack", "--engine", "md5", "--device", "cpu",
                   "-a", "mask", "?d?d", str(hashfile),
                   "--session", path, "--unit-size", "40",
                   "--no-potfile", "--quiet"])
    assert rc == 0
    lines = [json.loads(ln) for ln in open(path)]
    header = lines[0]
    assert header["type"] == "header"
    assert header["default_job"] == "j0"
    tagged = [ln for ln in lines if ln["type"] in ("units", "hit")]
    assert tagged and all(ln.get("job") == "j0" for ln in tagged)
    # load() folds the default job's tagged lines into the FLAT
    # resume fields; no phantom tenant job appears
    prior = SessionJournal.load(path)
    assert prior.completed == [(0, 100)]
    assert [h["plaintext"] for h in prior.hits] == [b"99".hex()]
    assert prior.jobs == {}


def test_untagged_legacy_journal_still_reads(tmp_path):
    path = tmp_path / "old.session"
    path.write_text("\n".join([
        json.dumps({"type": "header", "spec": {"engine": "md5"}}),
        json.dumps({"type": "units", "intervals": [[0, 64]]}),
        json.dumps({"type": "hit", "target": 0, "index": 3,
                    "plaintext": b"x".hex()}),
        json.dumps({"type": "units", "intervals": [[0, 32]],
                    "job": "j1"}),
    ]) + "\n")
    prior = SessionJournal.load(str(path))
    assert prior.completed == [(0, 64)]
    assert len(prior.hits) == 1
    assert prior.jobs["j1"]["completed"] == [(0, 32)]


def test_worker_health_records_survive_load(tmp_path):
    path = str(tmp_path / "h.session")
    s = SessionJournal(path)
    s.open({"engine": "md5"}, default_job="j0")
    s.record_worker_health("w1", "healthy", "degraded", ts=1.0,
                           age_s=2.0)
    s.close()
    prior = SessionJournal.load(path)
    assert prior.health_events == [
        {"type": "worker_health", "worker": "w1", "from": "healthy",
         "to": "degraded", "ts": 1.0, "age_s": 2.0}]


# ---------------------------------------------------------------------------
# dprf report health section

def test_report_health_section(tmp_path):
    from dprf_tpu.perfreport import build_report, render_report
    path = str(tmp_path / "r.session")
    s = SessionJournal(path)
    s.open({"engine": "md5"}, default_job="j0")
    s.record_worker_health("w1", "healthy", "missing")
    s.close()
    with open(str(tmp_path / "r.session.alerts.jsonl"), "w") as fh:
        for st in ("pending", "firing"):
            fh.write(json.dumps({"ts": 1.0, "rule": "worker_missing",
                                 "state": st,
                                 "labels": {"worker": "w1"}}) + "\n")
    doc = build_report(path)
    h = doc["health"]
    assert h["fired"] == {"worker_missing": 1}
    assert h["unresolved"] == ["worker_missing(w1)"]
    assert h["workers"] == {"w1": "missing"}
    text = render_report(doc)
    assert "fleet health & alerts" in text
    assert "UNRESOLVED" in text


# ---------------------------------------------------------------------------
# `dprf check` validates alert rules (metrics analyzer)

def make_repo(tmp_path, files):
    """Same fixture-tree shape test_analysis.py uses."""
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return str(tmp_path)


def check(root, only):
    from dprf_tpu import analysis
    findings, _ = analysis.run(root, only=[only])
    return findings


def test_default_pack_undeclared_metric_is_a_finding(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/m.py": """\
            REG.counter("dprf_real_total", "h")
        """,
        "dprf_tpu/telemetry/alerts.py": """\
            DEFAULT_RULES = [
                {"name": "ok", "metric": "dprf_real_total",
                 "op": ">", "threshold": 0},
                {"name": "stale", "metric": "dprf_gone_total",
                 "op": ">", "threshold": 0},
            ]
        """})
    findings = check(root, "metrics")
    msgs = [f.message for f in findings]
    assert any("'stale'" in m and "dprf_gone_total" in m
               for m in msgs), msgs
    assert not any("'ok'" in m for m in msgs)


def test_rules_fixture_file_validated(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/m.py": """\
            REG.counter("dprf_real_total", "h")
        """,
        "dprf_tpu/telemetry/alerts.py": """\
            DEFAULT_RULES = []
        """,
        "tests/fixtures/alert_rules_extra.json": """\
            [{"name": "good", "metric": "dprf_real_total"},
             {"name": "bad", "metric": "dprf_renamed_total"}]
        """})
    findings = check(root, "metrics")
    msgs = [f.message for f in findings]
    assert any("'bad'" in m and "dprf_renamed_total" in m
               for m in msgs), msgs
    assert not any("'good'" in m for m in msgs)


def test_nonliteral_default_pack_is_a_finding(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/telemetry/alerts.py": """\
            DEFAULT_RULES = build_rules()
        """})
    findings = check(root, "metrics")
    assert any("pure dict literals" in f.message for f in findings)


def test_real_default_pack_references_declared_metrics_only():
    """The shipped pack + shipped fixtures are clean (the real-repo
    acceptance test in test_analysis covers the full suite; this one
    pins the alert-rule half specifically)."""
    import os

    from dprf_tpu import analysis
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings, _ = analysis.run(repo, only=["metrics"])
    bad = [f for f in findings if not f.suppressed
           and "alert rule" in f.message]
    assert bad == [], "\n".join(f.render() for f in bad)
