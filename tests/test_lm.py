"""LM hash via bitslice DES: FIPS vectors, scalar-vs-bitslice
equivalence, and the device workers."""

import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops.des import (LM_MAGIC, des_encrypt, lm_half,
                              str_to_key)
from dprf_tpu.runtime.workunit import WorkUnit


def test_des_fips_vector():
    c = des_encrypt(bytes.fromhex("133457799BBCDFF1"),
                    bytes.fromhex("0123456789ABCDEF"))
    assert c.hex().upper() == "85E813540F0AB405"


def test_lm_known_values():
    full = lm_half(b"PASSWOR") + lm_half(b"D")
    assert full.hex().upper() == "E52CAC67419A9A224A3B108F3FA6CB6D"
    # empty-half constant every pentester recognizes
    assert lm_half(b"").hex().upper() == "AAD3B435B51404EE"


def test_bitslice_equals_scalar():
    import jax.numpy as jnp

    from dprf_tpu.engines.device.lm import byte_planes
    from dprf_tpu.ops.des import (const_planes, des_encrypt_bitslice,
                                  key_planes_from_bytes7)

    rng = np.random.RandomState(7)
    cands = rng.randint(32, 127, (64, 7)).astype(np.uint8)
    cipher = des_encrypt_bitslice(
        key_planes_from_bytes7(byte_planes(jnp.asarray(cands))),
        const_planes(LM_MAGIC))
    cipher = [p if isinstance(p, int) else np.asarray(p)
              for p in cipher]
    for j in range(64):
        bits = []
        for p in cipher:
            if isinstance(p, int):
                bits.append(1 if p else 0)
            else:
                v = int(np.uint32(p[j // 32]))
                bits.append((v >> (j % 32)) & 1)
        got = bytearray(8)
        for i, b in enumerate(bits):
            got[i // 8] |= b << (7 - i % 8)
        want = des_encrypt(str_to_key(bytes(cands[j])), LM_MAGIC)
        assert bytes(got) == want, j


def test_parse_rejects_full_hash_and_junk():
    eng = get_engine("lm")
    with pytest.raises(ValueError, match="two 8-byte halves"):
        eng.parse_target("aa" * 16)
    with pytest.raises(ValueError):
        eng.parse_target("zz")


def test_device_mask_worker_cracks_two_targets():
    cpu = get_engine("lm")
    dev = get_engine("lm", device="jax")
    gen = MaskGenerator("?l?l?l")
    t1 = cpu.parse_target(lm_half(b"FOX").hex())
    t2 = cpu.parse_target(lm_half(b"DOG").hex())
    w = dev.make_mask_worker(gen, [t1, t2], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"fox"), (1, b"dog")}


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("lm")
    dev = get_engine("lm", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"zebra", b"Banana"],
        rules=[parse_rule(":"), parse_rule("u")], max_len=7)
    t = cpu.parse_target(lm_half(b"ZEBRA").hex())
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    # both ':' and 'u' rules produce the SAME LM digest (uppercasing
    # is idempotent), so expect one hit per matching rule expansion
    assert {h.plaintext for h in hits} <= {b"zebra", b"ZEBRA"}
    assert len(hits) == 2
