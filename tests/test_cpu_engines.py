"""CPU oracle engines against published RFC/FIPS/OpenBSD test vectors."""

import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu import get_engine
from dprf_tpu.engines.cpu.md4 import md4
from dprf_tpu.engines.cpu import bcrypt as bc

# RFC 1320 appendix A.5
MD4_VECTORS = [
    (b"", "31d6cfe0d16ae931b73c59d7e0c089c0"),
    (b"a", "bde52cb31de33e46245e05fbdbd6fb24"),
    (b"abc", "a448017aaf21d8525fc10ae87aa6729d"),
    (b"message digest", "d9130a8164549fe818874806e1c7014b"),
    (b"abcdefghijklmnopqrstuvwxyz", "d79e1c308aa5bbcdeea8ed63df412da9"),
    (b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789",
     "043f8582f241db351ce627e153e7f0e4"),
    (b"1234567890123456789012345678901234567890123456789012345678901234"
     b"5678901234567890", "e33b4ddc9c38f2199c3e7b164fcc0536"),
]

# RFC 1321 appendix A.5
MD5_VECTORS = [
    (b"", "d41d8cd98f00b204e9800998ecf8427e"),
    (b"abc", "900150983cd24fb0d6963f7d28e17f72"),
    (b"message digest", "f96b697d7cb7938d525a2f31aaf161d0"),
]

SHA1_VECTORS = [
    (b"", "da39a3ee5e6b4b0d3255bfef95601890afd80709"),
    (b"abc", "a9993e364706816aba3e25717850c26c9cd0d89d"),
]

SHA256_VECTORS = [
    (b"abc",
     "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"),
    (b"",
     "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"),
]

# Widely-published NTLM digests
NTLM_VECTORS = [
    (b"password", "8846f7eaee8fb117ad06bdd830b7586c"),
    (b"", "31d6cfe0d16ae931b73c59d7e0c089c0"),
]

# Classic OpenBSD/John-the-Ripper bcrypt vectors
BCRYPT_VECTORS = [
    (b"U*U", "$2a$05$CCCCCCCCCCCCCCCCCCCCC.E5YPO9kmyuRGyh0XouQYb4YMJKvyOeW"),
    (b"U*U*", "$2a$05$CCCCCCCCCCCCCCCCCCCCC.VGOzA784oUp/Z0DY336zx7pLYAy0lwK"),
    (b"U*U*U", "$2a$05$XXXXXXXXXXXXXXXXXXXXXOAcXxm9kjPGEMsLznoKqmqw7tc8WCx4a"),
]


@pytest.mark.parametrize("msg,hexdigest", MD4_VECTORS)
def test_md4_rfc1320(msg, hexdigest):
    assert md4(msg).hex() == hexdigest


@pytest.mark.parametrize("engine,vectors", [
    ("md5", MD5_VECTORS), ("sha1", SHA1_VECTORS), ("sha256", SHA256_VECTORS),
    ("ntlm", NTLM_VECTORS),
])
def test_fast_hash_vectors(engine, vectors):
    eng = get_engine(engine)
    msgs = [m for m, _ in vectors]
    digests = eng.hash_batch(msgs)
    for (msg, expect), got in zip(vectors, digests):
        assert got.hex() == expect, f"{engine}({msg!r})"
        assert len(got) == eng.digest_size


def test_parse_target_roundtrip():
    eng = get_engine("md5")
    t = eng.parse_target("900150983cd24fb0d6963f7d28e17f72")
    assert eng.verify(b"abc", t)
    assert not eng.verify(b"abd", t)


@pytest.mark.parametrize("password,expected", BCRYPT_VECTORS)
def test_bcrypt_vectors(password, expected):
    variant, cost, salt, digest = bc.parse_hash(expected)
    assert bc.bcrypt_hash(password, salt, cost, variant) == expected


def test_bcrypt_engine_verify():
    eng = get_engine("bcrypt")
    t = eng.parse_target(BCRYPT_VECTORS[0][1])
    assert t.params["cost"] == 5
    assert eng.verify(b"U*U", t)
    assert not eng.verify(b"U*V", t)


def test_bcrypt_b64_roundtrip():
    raw = bytes(range(16))
    assert bc.b64_decode(bc.b64_encode(raw)[:22], 16) == raw


def test_pmkid_engine():
    import hashlib, hmac
    essid, mac_ap, mac_sta = b"TestNet", bytes(6), bytes(range(6))
    pw = b"hunter2hunter2"
    pmk = hashlib.pbkdf2_hmac("sha1", pw, essid, 4096, 32)
    pmkid = hmac.new(pmk, b"PMK Name" + mac_ap + mac_sta,
                     hashlib.sha1).digest()[:16]
    line = f"{pmkid.hex()}*{mac_ap.hex()}*{mac_sta.hex()}*{essid.hex()}"
    eng = get_engine("wpa2-pmkid")
    t = eng.parse_target(line)
    assert eng.verify(pw, t)
    assert not eng.verify(b"wrong-pass", t)


def test_registry():
    from dprf_tpu import engine_names
    names = engine_names("cpu")
    for n in ["md5", "sha1", "sha256", "ntlm", "bcrypt", "wpa2-pmkid"]:
        assert n in names


def test_engine_alias_sets_device_symmetric():
    """Every name resolvable on one device resolves on the other
    (VERDICT r3 weak #6: a job written with a jax-side alias must not
    fail under --device=cpu, and vice versa)."""
    from dprf_tpu.engines import engine_names

    cpu = set(engine_names("cpu"))
    jax = set(engine_names("jax"))
    assert cpu == jax, (sorted(cpu - jax), sorted(jax - cpu))
