"""Multi-target compare: benchmark config 2's shape (1k-hash NTLM list,
batched compare) plus adversarial sort-key collisions."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.pipeline import make_mask_crack_step


def test_target_table_window_counts_duplicate_runs():
    # three digests sharing word0, two sharing another word0
    mk = lambda w0, tail: w0.to_bytes(4, "little") + tail.to_bytes(12, "little")
    digests = [mk(5, 1), mk(5, 2), mk(5, 3), mk(9, 1), mk(9, 2), mk(2, 7)]
    table = cmp_ops.make_target_table(digests)
    assert table.window == 3
    assert table.num_targets == 6


def test_compare_multi_with_colliding_sort_keys():
    mk = lambda w0, tail: w0.to_bytes(4, "little") + tail.to_bytes(12, "little")
    digests = [mk(5, 1), mk(5, 2), mk(5, 3), mk(2, 7), mk(9, 1)]
    table = cmp_ops.make_target_table(digests)
    # probe batch: each target digest + near-misses sharing word0
    probes = digests + [mk(5, 99), mk(9, 99), mk(1, 1), mk(10, 1)]
    rows = np.stack([np.frombuffer(d, dtype="<u4") for d in probes])
    found, tpos = cmp_ops.compare_multi(jnp.asarray(rows.astype(np.uint32)),
                                        table)
    found = np.asarray(found)
    assert found.tolist() == [True] * 5 + [False] * 4
    # each found probe maps back to its own digest
    tpos = np.asarray(tpos)
    for i in range(5):
        orig = int(table.order[tpos[i]])
        assert digests[orig] == probes[i]


def test_thousand_hash_ntlm_crack_cli(tmp_path, capsys):
    """Config 2 in miniature: 1000-target NTLM list, mask attack,
    on-device multi-target compare, all planted targets found."""
    from dprf_tpu.cli import main

    rng = random.Random(42)
    gen = MaskGenerator("?l?l?l")
    oracle = get_engine("ntlm", "cpu")
    planted_idx = sorted(rng.sample(range(gen.keyspace), 60))
    planted = [gen.candidate(i) for i in planted_idx]
    digests = [d.hex() for d in oracle.hash_batch(planted)]
    # pad the list to 1000 with digests of passwords outside the keyspace
    fillers = [f"xx{i:06d}".encode() for i in range(940)]
    digests += [d.hex() for d in oracle.hash_batch(fillers)]
    rng.shuffle(digests)
    hashfile = tmp_path / "ntlm1k.txt"
    hashfile.write_text("\n".join(digests) + "\n")

    rc = main(["crack", "?l?l?l", str(hashfile), "--engine", "ntlm",
               "--device", "tpu", "--no-potfile",
               "--unit-size", "8192", "--batch", "2048", "-q"])
    out = capsys.readouterr().out
    assert rc == 0
    lines = dict(l.split(":", 1) for l in out.strip().splitlines())
    assert len(lines) == 60
    for p in planted:
        d = oracle.hash_batch([p])[0].hex()
        assert lines[d] == p.decode()


def test_multi_target_hits_across_batches(tmp_path):
    """Hits for different targets in the same batch resolve to the right
    (target, plaintext) pairs through the sorted-table order mapping."""
    from dprf_tpu.engines.base import Target
    from dprf_tpu.runtime.worker import DeviceMaskWorker
    from dprf_tpu.runtime.workunit import WorkUnit

    gen = MaskGenerator("?d?d?d")
    dev = get_engine("md5", "jax")
    oracle = get_engine("md5", "cpu")
    secrets = [b"007", b"008", b"123", b"999"]
    targets = [Target(raw=f"t{i}", digest=oracle.hash_batch([s])[0])
               for i, s in enumerate(secrets)]
    w = DeviceMaskWorker(dev, gen, targets, batch=256, oracle=oracle)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    got = {h.target_index: h.plaintext for h in hits}
    assert got == {0: b"007", 1: b"008", 2: b"123", 3: b"999"}
