"""Native (C++) wordlist loader vs the pure-Python reference.

The .so is compiled on first use by dprf_tpu/native; these tests skip
only if no system compiler exists (the build image has g++).
"""

import numpy as np
import pytest

from dprf_tpu import native
from dprf_tpu.generators.wordlist import (WordlistRulesGenerator,
                                          load_words)


CASES = {
    "plain": b"alpha\nbravo\ncharlie\n",
    "crlf": b"alpha\r\nbravo\r\n",
    "no_trailing_newline": b"alpha\nbravo",
    "empty_lines": b"\n\nalpha\n\n\nbravo\n\n",
    "spaces_kept": b"  padded word \nx\n",
    "long_skipped": b"ok\n" + b"x" * 200 + b"\nalso-ok\n",
    "high_bytes": bytes(range(1, 10)) + b"\n" + b"caf\xe9\n",
}


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("no system compiler for the native loader")
    return lib


@pytest.mark.parametrize("name,data", list(CASES.items()))
def test_native_matches_python(tmp_path, lib, name, data):
    p = tmp_path / f"{name}.txt"
    p.write_bytes(data)
    got = native.load_words_packed(str(p), 55)
    assert got is not None
    buf, lens, skipped = got
    want, want_skipped = load_words(str(p), 55)
    assert skipped == want_skipped
    assert len(lens) == len(want)
    for i, w in enumerate(want):
        assert lens[i] == len(w)
        assert buf[i, :lens[i]].tobytes() == w
        assert not buf[i, lens[i]:].any()          # zero padding


def test_generator_from_files_uses_packed(tmp_path):
    p = tmp_path / "w.txt"
    p.write_bytes(CASES["long_skipped"])
    gen = WordlistRulesGenerator.from_files(str(p))
    assert gen.n_words == 2
    assert gen.word(0) == b"ok"
    assert gen.candidate(1) == b"also-ok"
    buf, lens = gen.packed_words(pad_to=8)
    assert buf.shape[0] % 8 == 0
    assert lens[0] == 2 and lens[1] == 7


def test_generator_packed_vs_list_equivalent(tmp_path):
    p = tmp_path / "w.txt"
    p.write_bytes(CASES["plain"])
    g1 = WordlistRulesGenerator.from_files(str(p))
    words, _ = load_words(str(p), 55)
    g2 = WordlistRulesGenerator(words)
    assert g1.keyspace == g2.keyspace
    for i in range(g1.keyspace):
        assert g1.candidate(i) == g2.candidate(i)
    b1, l1 = g1.packed_words(pad_to=4)
    b2, l2 = g2.packed_words(pad_to=4)
    assert (b1 == b2).all() and (l1 == l2).all()


def test_scan_missing_file():
    assert native.load_words_packed("/nonexistent/x.txt", 55) is None
