"""md5crypt ($1$): reference vs system crypt (when present), device
digests vs reference, worker end-to-end (mask/wordlist/sharded), CLI."""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.md5crypt import (md5crypt_hash, md5crypt_raw,
                                           parse_md5crypt)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def test_against_system_crypt_if_available():
    try:
        import crypt
    except ImportError:
        pytest.skip("no crypt module")
    for pw, salt in ((b"password", b"abcd1234"), (b"x", b"s"),
                     (b"", b"zz"), (b"abcdefghijklmno", b"12345678")):
        want = crypt.crypt(pw.decode(), "$1$" + salt.decode() + "$")
        if want is None:
            pytest.skip("system crypt lacks md5crypt")
        assert md5crypt_hash(pw, salt) == want


def test_parse_roundtrip():
    line = md5crypt_hash(b"hunter2", b"saltfour")
    salt, digest = parse_md5crypt(line)
    assert salt == b"saltfour"
    assert md5crypt_raw(b"hunter2", salt) == digest
    with pytest.raises(ValueError):
        parse_md5crypt("$2$bad$x")


def test_device_digest_matches_reference():
    import random
    from dprf_tpu.engines.device.md5crypt import md5crypt_digest_batch

    rng = random.Random(501)
    cands = [bytes(rng.randrange(1, 256)
                   for _ in range(rng.randrange(0, 16)))
             for _ in range(10)]
    salt = b"Q7b"
    maxlen = max((len(c) for c in cands), default=1) or 1
    buf = np.zeros((len(cands), maxlen), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    sbuf = np.zeros((8,), np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    dw = md5crypt_digest_batch(jnp.asarray(buf), jnp.asarray(lens),
                               jnp.asarray(sbuf), jnp.int32(len(salt)))
    got = [np.asarray(dw)[i].astype("<u4").tobytes()
           for i in range(len(cands))]
    assert got == [md5crypt_raw(c, salt) for c in cands]


def test_mask_worker_end_to_end():
    dev = get_engine("md5crypt", "jax")
    cpu = get_engine("md5crypt", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"p4q"
    t = dev.parse_target(md5crypt_hash(secret, b"NaCl"))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_wordlist_worker_with_rules():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("md5crypt", "jax")
    cpu = get_engine("md5crypt", "cpu")
    words = [b"monday", b"friday", b"sunday"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$9")]
    gen = WordlistRulesGenerator(words, rules, max_len=15)
    secret = b"friday9"
    t = dev.parse_target(md5crypt_hash(secret, b"pep"))
    w = dev.make_wordlist_worker(gen, [t], batch=32, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_sharded_md5crypt_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("md5crypt", "jax")
    cpu = get_engine("md5crypt", "cpu")
    gen = MaskGenerator("?d?d?l")
    secret = b"19z"
    t = dev.parse_target(md5crypt_hash(secret, b"mesa8"))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=64, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_md5crypt_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = md5crypt_hash(b"xy7", b"grain")
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "md5crypt",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:xy7" in out


def test_length_guard_rejects_over_budget_masks():
    """Masks beyond the single-block budget must fail loudly at worker
    construction, never silently compute garbage digests."""
    from dprf_tpu.engines.cpu.md5crypt import md5crypt_hash

    dev = get_engine("md5crypt", "jax")
    t = dev.parse_target(md5crypt_hash(b"x" * 16, b"salt"))
    gen = MaskGenerator("?l" * 16)
    with pytest.raises(ValueError, match="single-block budget"):
        dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8)


def test_cpu_reference_handles_long_passwords():
    """Passwords > 16 bytes cycle the alt digest (glibc semantics) --
    regression test for the alt-slicing bug."""
    import hashlib
    from dprf_tpu.engines.cpu.md5crypt import md5crypt_raw

    # independent reimplementation of the glibc ctx construction
    pw, salt = b"a" * 23, b"saltsalt"
    alt = hashlib.md5(pw + salt + pw).digest()
    ctx = pw + b"$1$" + salt
    for i in range(len(pw)):
        ctx += alt[i % 16:i % 16 + 1]
    i = len(pw)
    while i > 0:
        ctx += b"\0" if i & 1 else pw[:1]
        i >>= 1
    inter = hashlib.md5(ctx).digest()
    for i in range(1000):
        msg = pw if i & 1 else inter
        if i % 3:
            msg += salt
        if i % 7:
            msg += pw
        msg += inter if i & 1 else pw
        inter = hashlib.md5(msg).digest()
    assert md5crypt_raw(pw, salt) == inter


# ---------------- apr1 (Apache $apr1$; hashcat 1600) ----------------

APR1_VECTORS = [
    # openssl passwd -apr1 -salt <salt> <pw>
    ("$apr1$myQ9PyAF$L5YLQ39NLlrY7ONcZW.XQ/", b"hello"),
    ("$apr1$saltsalt$GPKuzxa7vsYnZ2yysFVga.", b"secret12"),
]


@pytest.mark.parametrize("line,pw", APR1_VECTORS)
def test_apr1_cpu_vectors(line, pw):
    eng = get_engine("apr1")
    t = eng.parse_target(line)
    assert eng.hash_batch([pw], t.params)[0] == t.digest
    # magic matters: the same inputs under $1$ give a different digest
    assert md5crypt_raw(pw, t.params["salt"]) != t.digest


def test_apr1_device_matches_cpu():
    import random

    from dprf_tpu.engines.device.md5crypt import md5crypt_digest_batch

    rnd = random.Random(1600)
    salt = b"apr1salt"
    cands = [bytes(rnd.randrange(1, 256) for _ in range(rnd.randrange(1, 15)))
             for _ in range(6)]
    L = max(len(c) for c in cands)
    buf = np.zeros((len(cands), L), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    sbuf = np.zeros((8,), np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    words = np.asarray(md5crypt_digest_batch(
        jnp.asarray(buf), jnp.asarray(lens), jnp.asarray(sbuf),
        jnp.int32(len(salt)), b"$apr1$"))
    for i, c in enumerate(cands):
        want = md5crypt_raw(c, salt, b"$apr1$")
        got = words[i].astype("<u4").tobytes()
        assert got == want, c


def test_apr1_mask_worker_finds_planted():
    from dprf_tpu.engines.cpu.md5crypt import encode_digest

    gen = MaskGenerator("?d?d?d")
    raw = md5crypt_raw(b"407", b"saltsalt", b"$apr1$")
    dev = get_engine("apr1", device="jax")
    t = dev.parse_target("$apr1$saltsalt$" + encode_digest(raw))
    w = dev.make_mask_worker(gen, [t], batch=256, hit_capacity=8)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"407")]
