"""HMAC engine family (hashcat 50/60/150/160/1450/1460) and JWT HS256
(16500): CPU oracles vs stdlib hmac, device workers vs oracles, and the
runtime-salt block builders vs hashlib constructions."""

import base64
import hashlib
import hmac as hmod
import json

import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit

ALGOS = ["md5", "sha1", "sha256"]


def _mk_jwt(secret: bytes, payload: dict) -> str:
    b64 = lambda b: base64.urlsafe_b64encode(b).rstrip(b"=").decode()
    h = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    p = b64(json.dumps(payload).encode())
    sig = b64(hmod.new(secret, (h + "." + p).encode(),
                       hashlib.sha256).digest())
    return h + "." + p + "." + sig


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("key_is_pass", [True, False])
def test_cpu_oracle_matches_stdlib(algo, key_is_pass):
    name = f"hmac-{algo}" + ("" if key_is_pass else "-salt")
    eng = get_engine(name)
    rng = np.random.RandomState(7)
    cands = [bytes(rng.randint(1, 255, rng.randint(1, 40),
                               dtype=np.uint8).tolist())
             for _ in range(16)]
    salt = b"pepper-01"
    got = eng.hash_batch(cands, params={"salt": salt})
    for c, d in zip(cands, got):
        want = (hmod.new(c, salt, algo) if key_is_pass
                else hmod.new(salt, c, algo)).digest()
        assert d == want


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("key_is_pass", [True, False])
def test_device_mask_worker_cracks(algo, key_is_pass):
    name = f"hmac-{algo}" + ("" if key_is_pass else "-salt")
    cpu = get_engine(name)
    dev = get_engine(name, device="jax")
    gen = MaskGenerator("?l?l?l")
    digest = cpu.hash_batch([b"fox"], params={"salt": b"mysalt99"})[0]
    t = cpu.parse_target(digest.hex() + ":mysalt99")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


def test_device_mask_hex_salt_and_two_targets():
    cpu = get_engine("hmac-sha256")
    dev = get_engine("hmac-sha256", device="jax")
    gen = MaskGenerator("?d?d?d")
    salt_a, salt_b = b"\x00\x01\xff", b"plain"
    da = cpu.hash_batch([b"042"], params={"salt": salt_a})[0]
    db = cpu.hash_batch([b"777"], params={"salt": salt_b})[0]
    ta = cpu.parse_target(da.hex() + ":$HEX[0001ff]")
    tb = cpu.parse_target(db.hex() + ":plain")
    assert ta.params["salt"] == salt_a
    w = dev.make_mask_worker(gen, [ta, tb], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"042"), (1, b"777")}


@pytest.mark.parametrize("key_is_pass", [True, False])
def test_device_wordlist_rules_worker(key_is_pass):
    name = "hmac-sha1" + ("" if key_is_pass else "-salt")
    cpu = get_engine(name)
    dev = get_engine(name, device="jax")
    from dprf_tpu.rules.parser import parse_rule

    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l"), parse_rule("u")])
    # candidate 'banana' only exists via the lowercase rule on 'Banana'
    digest = cpu.hash_batch([b"banana"], params={"salt": b"s4lt"})[0]
    t = cpu.parse_target(digest.hex() + ":s4lt")
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}


def test_sharded_mask_worker():
    from dprf_tpu.parallel import make_mesh

    cpu = get_engine("hmac-md5")
    dev = get_engine("hmac-md5", device="jax")
    gen = MaskGenerator("?l?l?l")
    digest = cpu.hash_batch([b"dog"], params={"salt": b"m"})[0]
    t = cpu.parse_target(digest.hex() + ":m")
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=512,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"dog"]


def test_jwt_parse_and_oracle():
    eng = get_engine("jwt-hs256")
    tok = _mk_jwt(b"hunter2", {"sub": "alice", "iat": 1516239022})
    t = eng.parse_target(tok)
    assert len(t.digest) == 32
    assert eng.hash_batch([b"hunter2"], params=t.params)[0] == t.digest
    assert eng.hash_batch([b"hunter3"], params=t.params)[0] != t.digest
    with pytest.raises(ValueError):
        eng.parse_target("only.twoparts")


def test_jwt_device_mask_cracks():
    cpu = get_engine("jwt-hs256")
    dev = get_engine("jwt", device="jax")
    # long payload -> multi-block constant signing input
    tok = _mk_jwt(b"abc", {"sub": "1234567890", "name": "John Doe",
                           "admin": True, "iat": 1516239022,
                           "scope": "read write delete admin audit"})
    t = cpu.parse_target(tok)
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"abc"]


def test_jwt_device_wordlist_cracks():
    cpu = get_engine("jwt-hs256")
    dev = get_engine("jwt-hs256", device="jax")
    tok = _mk_jwt(b"correcthorse", {"sub": "x"})
    t = cpu.parse_target(tok)
    gen = WordlistRulesGenerator(
        words=[b"password", b"correcthorse", b"letmein"])
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"correcthorse"]


def test_msg_block_after_prefix_matches_reference():
    """The runtime-built message block must equal hashlib's result:
    HMAC with a one-block message computed via the ops chain equals
    stdlib hmac for every salt length 0..32."""
    import jax.numpy as jnp

    from dprf_tpu.ops.hmac import (hmac_one_block_msg, key_states,
                                   msg_block_after_prefix)
    from dprf_tpu.ops.pack import pack_raw

    key = b"k3y"
    kw = pack_raw(jnp.asarray(np.frombuffer(key, np.uint8)[None, :]),
                  len(key), big_endian=True)
    ist, ost = key_states("sha256", kw)
    for n in (0, 1, 31, 32):
        salt = bytes(range(n))
        buf = np.zeros(32, np.uint8)
        buf[:n] = np.frombuffer(salt, np.uint8)
        blk = msg_block_after_prefix(
            jnp.asarray(np.pad(buf, (0, 32))[None, :32]),
            jnp.asarray([n], np.int32), True)
        got = np.asarray(hmac_one_block_msg("sha256", ist, ost, blk[0]))
        want = np.frombuffer(hmod.new(key, salt, "sha256").digest(),
                             ">u4")
        assert (got[0] == want).all(), n


def test_md_pad_blocks_matches_reference():
    """Constant-message padding vs hashlib over 1..3 block messages."""
    import jax.numpy as jnp

    from dprf_tpu.ops.hmac import (hmac_const_msg, key_states,
                                   md_pad_blocks)
    from dprf_tpu.ops.pack import pack_raw

    key = b"jwtsecret"
    kw = pack_raw(jnp.asarray(np.frombuffer(key, np.uint8)[None, :]),
                  len(key), big_endian=True)
    ist, ost = key_states("sha256", kw)
    for n in (0, 55, 56, 64, 119, 130):
        msg = bytes(i & 0xFF for i in range(n))
        blocks = md_pad_blocks(msg, big_endian=True)
        got = np.asarray(hmac_const_msg("sha256", ist, ost, blocks))
        want = np.frombuffer(hmod.new(key, msg, "sha256").digest(),
                             ">u4")
        assert (got[0] == want).all(), n
