"""Per-position Markov mask ordering: training, stats round-trip,
charset permutation (bijection preserved), CLI train + ordered crack,
and the job-identity fingerprint."""

import hashlib

import numpy as np
import pytest

from dprf_tpu.generators.markov import (load_stats, reorder_charsets,
                                        save_stats, stats_digest,
                                        train_stats)
from dprf_tpu.generators.mask import MaskGenerator


CORPUS = [b"password", b"pass123", b"panda", b"qwerty"]


def test_train_counts():
    c = train_stats(CORPUS)
    assert c[0, ord("p")] == 3 and c[0, ord("q")] == 1
    assert c[1, ord("a")] == 3 and c[1, ord("w")] == 1
    assert c[7, ord("d")] == 1      # only 'password' is 8 long


def test_stats_roundtrip(tmp_path):
    c = train_stats(CORPUS)
    path = tmp_path / "s.dprfstat"
    save_stats(str(path), c)
    back = load_stats(str(path))
    assert (back == c).all()
    assert stats_digest(back) == stats_digest(c)
    with pytest.raises(ValueError):
        load_stats(__file__)        # not a stats file


def test_reorder_is_permutation_and_frequency_ordered():
    c = train_stats(CORPUS)
    base = MaskGenerator("?l?l")
    ordered = reorder_charsets(base.charsets, c)
    for orig, new in zip(base.charsets, ordered):
        assert sorted(orig) == sorted(new)      # same charset, permuted
    assert ordered[0][0] == ord("p")
    assert ordered[1][0] == ord("a")


def test_generator_bijection_preserved():
    c = train_stats(CORPUS)
    plain = MaskGenerator("?l?d")
    ordered = MaskGenerator("?l?d", markov_counts=c)
    assert ordered.keyspace == plain.keyspace
    all_plain = {plain.candidate(i) for i in range(plain.keyspace)}
    all_ordered = [ordered.candidate(i) for i in range(ordered.keyspace)]
    assert set(all_ordered) == all_plain
    assert len(set(all_ordered)) == len(all_ordered)
    assert all_ordered[0][0] == ord("p")


def test_positions_past_training_reuse_last_row():
    c = train_stats(CORPUS, max_len=2)
    gen = MaskGenerator("?l?l?l?l", markov_counts=c)
    assert gen.charsets[2] == gen.charsets[1] == gen.charsets[3]


def test_cli_train_and_markov_crack(tmp_path, capsys):
    from tests.test_cli_e2e import run_cli

    corpus = tmp_path / "corpus.txt"
    corpus.write_bytes(b"\n".join(CORPUS) + b"\n")
    stats = tmp_path / "s.dprfstat"
    rc, _ = run_cli(["markov", str(corpus), "-o", str(stats)], capsys)
    assert rc == 0

    hashes = tmp_path / "h.txt"
    hashes.write_text(hashlib.md5(b"pat").hexdigest() + "\n")
    pot = tmp_path / "pot"
    rc, _ = run_cli(["crack", "?l?l?l", str(hashes), "--engine", "md5",
                     "--device", "cpu", "--markov", str(stats),
                     "--potfile", str(pot), "-q"], capsys)
    assert rc == 0
    assert pot.read_text().strip().endswith(":pat")

    # ordered stdout leads with the trained most-likely prefix
    rc, out = run_cli(["stdout", "?l?l", "--limit", "1",
                       "--markov", str(stats)], capsys)
    assert rc == 0 and out.split() == ["pa"]


def test_markov_changes_job_fingerprint(tmp_path, capsys):
    """Divergent stats reorder the keyspace, so they MUST change the
    job identity (a worker with other stats would mark wrong ranges
    done)."""
    from dprf_tpu.cli import _build_gen
    from dprf_tpu.utils.logging import Log

    log = Log(quiet=True)
    stats_a = tmp_path / "a.dprfstat"
    stats_b = tmp_path / "b.dprfstat"
    save_stats(str(stats_a), train_stats(CORPUS))
    save_stats(str(stats_b), train_stats([b"zzz"]))
    _, desc_none, _ = _build_gen("mask", "?l?l", {}, None, None, None,
                                 "cpu", log)
    _, desc_a, _ = _build_gen("mask", "?l?l", {}, None, None, None,
                              "cpu", log, markov=str(stats_a))
    _, desc_b, _ = _build_gen("mask", "?l?l", {}, None, None, None,
                              "cpu", log, markov=str(stats_b))
    assert len({desc_none, desc_a, desc_b}) == 3


def test_markov_rejected_for_wordlist_attack(tmp_path):
    from dprf_tpu.cli import _build_gen
    from dprf_tpu.engines import get_engine
    from dprf_tpu.utils.logging import Log

    stats = tmp_path / "s.dprfstat"
    save_stats(str(stats), train_stats(CORPUS))
    wl = tmp_path / "w.txt"
    wl.write_text("a\n")
    with pytest.raises(ValueError, match="mask attacks only"):
        _build_gen("wordlist", str(wl), {}, None, 16,
                   get_engine("md5"), "cpu", Log(quiet=True),
                   markov=str(stats))


def test_zero_position_stats_rejected(tmp_path):
    import struct

    from dprf_tpu.generators.markov import MAGIC

    with pytest.raises(ValueError):
        train_stats(CORPUS, max_len=0)
    bad = tmp_path / "zero.dprfstat"
    bad.write_bytes(MAGIC + struct.pack("<H", 0))
    with pytest.raises(ValueError, match="no positions"):
        load_stats(str(bad))


def test_stdout_rejects_markov_for_wordlist(tmp_path, capsys):
    from tests.test_cli_e2e import run_cli

    stats = tmp_path / "s.dprfstat"
    save_stats(str(stats), train_stats(CORPUS))
    wl = tmp_path / "w.txt"
    wl.write_text("a\n")
    rc, _ = run_cli(["stdout", str(wl), "-a", "wordlist",
                     "--markov", str(stats)], capsys)
    assert rc == 2      # ValueError -> CLI error exit
