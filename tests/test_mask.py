"""Mask generator: parsing, keyspace, bijection, device decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.smoke
try:
    from hypothesis import given, settings, strategies as st
except ImportError:                       # container without hypothesis:
    class _St:                            # run the property test on a
        @staticmethod                     # fixed sample instead of
        def integers(min_value, max_value):   # aborting collection
            step = max(1, (max_value - min_value) // 49)
            return list(range(min_value, max_value + 1, step))

    st = _St()

    def given(values):
        def deco(fn):
            return pytest.mark.parametrize("i", values)(fn)
        return deco

    def settings(**kw):
        return lambda fn: fn

from dprf_tpu.generators.mask import MaskGenerator, parse_mask, BUILTIN_CHARSETS


def test_builtin_sizes():
    sizes = {k: len(v) for k, v in BUILTIN_CHARSETS.items()}
    assert sizes == {"l": 26, "u": 26, "d": 10, "s": 33, "a": 95, "b": 256}
    # ?a must be exactly the 95 printable ASCII chars 0x20..0x7e
    assert sorted(BUILTIN_CHARSETS["a"]) == list(range(0x20, 0x7F))


def test_keyspace():
    assert MaskGenerator("?l?l?l?l?l?l").keyspace == 26 ** 6
    assert MaskGenerator("?a?a?a?a?a?a?a").keyspace == 95 ** 7
    assert MaskGenerator("?d?d").keyspace == 100
    assert MaskGenerator("pass?d").keyspace == 10  # literals are radix-1


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_mask("?l?")
    with pytest.raises(ValueError):
        parse_mask("?z")
    with pytest.raises(ValueError):
        parse_mask("")
    with pytest.raises(ValueError):
        parse_mask("?1")  # no custom charset given


def test_custom_and_literal():
    g = MaskGenerator("ab?1?d", custom={1: b"xyz"})
    assert g.keyspace == 30
    assert g.candidate(0) == b"abx0"
    assert g.candidate(29) == b"abz9"
    assert MaskGenerator("??" "?l").candidate(0) == b"?a"


def test_odometer_order():
    g = MaskGenerator("?d?d")
    assert g.candidate(0) == b"00"
    assert g.candidate(1) == b"01"   # rightmost varies fastest
    assert g.candidate(10) == b"10"
    assert g.candidate(99) == b"99"


def test_full_coverage_distinct():
    g = MaskGenerator("?d?l", custom=None)
    seen = {g.candidate(i) for i in range(g.keyspace)}
    assert len(seen) == g.keyspace == 260


@given(st.integers(min_value=0, max_value=26 ** 6 - 1))
@settings(max_examples=50, deadline=None)
def test_index_roundtrip(i):
    g = MaskGenerator("?l?l?l?l?l?l")
    assert g.index_of(g.candidate(i)) == i


@pytest.mark.parametrize("mask,start", [
    ("?l?l?l?l?l?l", 0),
    ("?l?l?l?l?l?l", 26 ** 6 - 17),        # tail of keyspace
    ("?a?a?a?a?a?a?a", 95 ** 7 - 1000),    # keyspace > 2^32
    ("?b?b?d", 12345),
    ("pre?d?u", 3),
])
def test_device_decode_matches_host(mask, start):
    g = MaskGenerator(mask)
    batch = 16
    base = jnp.asarray(g.digits(start), dtype=jnp.int32)
    out = jax.jit(g.decode_batch, static_argnums=2)(
        base, g.flat_charsets, batch)
    n_valid = min(batch, g.keyspace - start)
    host = [g.candidate(start + i) for i in range(n_valid)]
    got = np.asarray(out)
    assert got.shape == (batch, g.length)
    for i, h in enumerate(host):
        assert bytes(got[i].tobytes()) == h, f"lane {i}"


def test_device_decode_segment_mux_and_gather_fallback():
    """Builtin charsets decode via the segment mux (few contiguous
    byte runs); a scrambled custom charset exceeds MUX_MAX_SEGMENTS
    and falls back to the flat-table gather.  Both must match host."""
    g = MaskGenerator("?s?l?d")
    assert all(s is not None for s in g._segments)
    out = np.asarray(g.decode_batch(
        jnp.asarray(g.digits(1000), jnp.int32), g.flat_charsets, 64))
    for i in range(64):
        assert out[i].tobytes() == g.candidate(1000 + i)

    scrambled = bytes((i * 37) % 251 for i in range(100))
    g2 = MaskGenerator("?1?l", custom={1: scrambled})
    assert g2._segments[0] is None      # gather path retained
    assert g2._segments[1] is not None  # mux for ?l
    out = np.asarray(g2.decode_batch(
        jnp.asarray(g2.digits(5), jnp.int32), g2.flat_charsets, 64))
    for i in range(64):
        assert out[i].tobytes() == g2.candidate(5 + i)


def test_device_decode_large_batch_contiguous():
    g = MaskGenerator("?l?l?l")
    base = jnp.asarray(g.digits(700), dtype=jnp.int32)
    out = np.asarray(g.decode_batch(base, g.flat_charsets, 256))
    for i in range(256):
        assert out[i].tobytes() == g.candidate(700 + i)
