"""PDF RC4 user-password engines (hashcat 10400/10500): forward
construction, parsing, device-vs-oracle filters, workers."""

import hashlib
import random
import struct

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.krb5 import rc4
from dprf_tpu.engines.cpu.pdf import (PAD, parse_pdf, pdf_key,
                                      pdf_user_check)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(password: bytes, rev: int, p: int = -1,
          enc_metadata: bool = True, seed: int = 5,
          bits: int = None) -> str:
    """A self-consistent $pdf$ line: run the spec algorithm forward
    from a random O/ID and the true password, store the resulting U."""
    rng = random.Random(seed)
    o = bytes(rng.randrange(256) for _ in range(32))
    doc_id = bytes(rng.randrange(256) for _ in range(16))
    bits = bits or (40 if rev == 2 else 128)
    key_len = bits // 8
    u = pdf_user_check(password, o, p, doc_id, rev, key_len,
                       enc_metadata)
    ver = 1 if rev == 2 else 2
    if rev >= 3:
        u = u + bytes(16)          # files store 32 bytes, last 16 noise
    return (f"$pdf${ver}*{rev}*{bits}*{p}*{int(enc_metadata)}*16*"
            f"{doc_id.hex()}*32*{u.hex()}*32*{o.hex()}")


def test_forward_construction_is_spec_algorithm():
    """pdf_key literally implements Algorithm 2 (hashlib cross-build)."""
    pw, o = b"tiger", bytes(range(32))
    doc_id, p = bytes(range(16)), -44
    msg = (pw + PAD)[:32] + o + struct.pack("<i", p) + doc_id
    assert pdf_key(pw, o, p, doc_id, 2, 5) == \
        hashlib.md5(msg).digest()[:5]
    d = hashlib.md5(msg).digest()
    for _ in range(50):
        d = hashlib.md5(d[:16]).digest()
    assert pdf_key(pw, o, p, doc_id, 3, 16) == d[:16]
    # R2 U is RC4 of the PAD with that key
    assert pdf_user_check(pw, o, p, doc_id, 2, 5) == \
        rc4(hashlib.md5(msg).digest()[:5], PAD)


@pytest.mark.parametrize("rev", [2, 3])
def test_oracle_roundtrip_and_parse(rev):
    pw = b"Sec9"
    cpu = get_engine("pdf", "cpu")
    t = cpu.parse_target(_line(pw, rev))
    assert t.params["rev"] == rev
    assert cpu.verify(pw, t) and not cpu.verify(b"nope", t)


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_pdf("$pdf$2*5*256*-1*1*16*00*32*00*32*00")   # R5/R6
    with pytest.raises(ValueError):
        parse_pdf("not-a-pdf-line")
    with pytest.raises(ValueError):
        parse_pdf("$pdf$1*2*40*-1*1")                      # too few


@pytest.mark.smoke
@pytest.mark.parametrize("rev", [
    2,
    # rev 3's 50-round MD5 rehash loop traces a far bigger program:
    # minutes of XLA compile, so it rides the full suite only
    pytest.param(3, marks=pytest.mark.compileheavy)])
def test_mask_worker_end_to_end(rev):
    dev = get_engine("pdf", "jax")
    cpu = get_engine("pdf", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = gen.candidate(4242)
    t = dev.parse_target(_line(secret, rev))
    w = dev.make_mask_worker(gen, [t], batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 4242, secret)]


def test_mask_worker_mixed_revisions_and_rev4_metadata():
    dev = get_engine("pdf", "jax")
    cpu = get_engine("pdf", "cpu")
    gen = MaskGenerator("?d?d?d")
    s1, s2, s3 = (gen.candidate(i) for i in (12, 340, 876))
    targets = [dev.parse_target(_line(s1, 2, seed=1)),
               dev.parse_target(_line(s2, 3, seed=2)),
               dev.parse_target(_line(s3, 4, enc_metadata=False,
                                      seed=3))]
    # plus an R3 40-bit document (legal per spec: R3 allows 40-128)
    s4 = gen.candidate(555)
    targets.append(dev.parse_target(_line(s4, 3, seed=4, bits=40)))
    w = dev.make_mask_worker(gen, targets, batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted((h.target_index, h.plaintext) for h in hits) == \
        [(0, s1), (1, s2), (2, s3), (3, s4)]


def test_wordlist_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("pdf", "jax")
    cpu = get_engine("pdf", "cpu")
    words = [b"draft", b"final"]
    rules = [parse_rule(":"), parse_rule("c $2")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    secret = b"Final2"
    t = dev.parse_target(_line(secret, 3))
    w = dev.make_wordlist_worker(gen, [t], batch=16, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_sharded_worker():
    import jax

    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("pdf", "jax")
    cpu = get_engine("pdf", "cpu")
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(133)
    t = dev.parse_target(_line(secret, 2))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=32, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


@pytest.mark.parametrize("rev,bits", [(2, 40), (3, 128), (3, 40)])
def test_pallas_kernel_matches_oracle(rev, bits):
    """Interpret-mode pallas_pdf kernel over one small batch: planted
    hit at its exact tile-local index, every other candidate rejected
    (the CPU oracle is the ground truth the plant was built from)."""
    import jax.numpy as jnp
    import numpy as np

    from dprf_tpu.ops import pallas_pdf

    gen = MaskGenerator("?l?d")
    plant = 97
    cpu = get_engine("pdf", "cpu")
    t = cpu.parse_target(_line(gen.candidate(plant), rev, bits=bits))
    sub, chunks = 8, 2
    tile = sub * chunks
    batch = tile * 8                 # plant 97 sits in grid cell 6
    fn = pallas_pdf.make_pdf_pallas_fn(
        gen, batch, 2 if rev == 2 else 3, bits // 8, sub=sub,
        chunks=chunks, interpret=True)
    base = jnp.asarray(gen.digits(0), jnp.int32)
    counts, lanes = fn(base, jnp.asarray([batch], jnp.int32),
                       *pallas_pdf.target_scalars(t))
    counts = np.asarray(counts)[:, 0]
    lanes = np.asarray(lanes)[:, 0]
    hits = [ti * tile + lanes[ti] for ti in np.nonzero(counts)[0]]
    assert hits == [plant] and counts.sum() == 1


def test_pallas_worker_planted_mixed_revisions(monkeypatch):
    """DPRF_PALLAS=1 routes PdfMaskWorker's eligible kinds onto the
    kernel steps (interpret mode off-TPU); planted cracks for an R2
    and an R3 document through the production sweep."""
    from dprf_tpu.ops import pallas_krb5, pallas_pdf

    monkeypatch.setenv("DPRF_PALLAS", "1")
    monkeypatch.setattr(pallas_krb5, "SUBC", 8)
    monkeypatch.setattr(pallas_pdf, "CHUNKS", 2)
    dev = get_engine("pdf", "jax")
    cpu = get_engine("pdf", "cpu")
    gen = MaskGenerator("?d?d?l")
    s2, s3 = gen.candidate(303), gen.candidate(1799)
    targets = [dev.parse_target(_line(s2, 2, seed=11)),
               dev.parse_target(_line(s3, 3, seed=12))]
    w = dev.make_mask_worker(gen, targets, batch=64, hit_capacity=8,
                             oracle=cpu)
    assert w.kernel_kinds == {(2, 5), (3, 16)}
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted((h.target_index, h.cand_index, h.plaintext)
                  for h in hits) == [(0, 303, s2), (1, 1799, s3)]
