"""Pallas MD5 mask kernel vs the oracle (interpret mode on the CPU
backend; the same kernel compiles natively on TPU).

Covers: charset segment decomposition, planted-password extraction,
n_valid masking, the tile-collision -> rescan overflow convention, and
worker-level equivalence with the XLA pipeline path.
"""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import BUILTIN_CHARSETS, MaskGenerator
from dprf_tpu.ops.pallas_md5 import (MAX_SEGMENTS, TILE, charset_segments,
                                     make_pallas_mask_crack_step,
                                     mask_supported)
from dprf_tpu.runtime.worker import PallasMd5MaskWorker
from dprf_tpu.runtime.workunit import WorkUnit


def _target(plain: bytes) -> np.ndarray:
    return np.frombuffer(hashlib.md5(plain).digest(),
                         dtype="<u4").astype(np.uint32)


def test_charset_segments_reconstruct():
    for name, cs in BUILTIN_CHARSETS.items():
        segs = charset_segments(cs)
        assert len(segs) <= MAX_SEGMENTS, name
        # reconstruct every byte from the piecewise map
        got = []
        for d in range(len(cs)):
            delta = [dl for s, dl in segs if s <= d][-1]
            got.append(d + delta)
        assert bytes(got) == cs, name
    assert mask_supported(list(BUILTIN_CHARSETS.values()))


@pytest.mark.parametrize("mask,plant", [
    ("?l?l?l?l", b"crab"),
    ("?d?d?d?d?d", b"90210"),
    ("?a?a?a", b"X& "),
    ("pre?l?d", b"prez7"),      # literals + mixed charsets
])
def test_kernel_finds_planted(mask, plant):
    gen = MaskGenerator(mask)
    pidx = gen.index_of(plant)
    step = make_pallas_mask_crack_step(gen, _target(plant), batch=TILE,
                                       interpret=True)
    base = TILE * (pidx // TILE)
    n_valid = min(TILE, gen.keyspace - base)
    bd = jnp.asarray(gen.digits(base), dtype=jnp.int32)
    count, lanes, _ = step(bd, jnp.int32(n_valid))
    assert int(count) == 1
    assert int(np.asarray(lanes)[0]) == pidx - base
    # plant masked out by n_valid -> no hit
    count2, _, _ = step(bd, jnp.int32(pidx - base))
    assert int(count2) == 0


def test_tile_collision_forces_rescan_convention():
    """Two hits in one tile can't both be extracted; the step must
    report count > hit_capacity so the worker rescans exactly."""
    gen = MaskGenerator("?l?l?l")
    # same digest can't come from two plaintexts; instead fabricate a
    # collision by hashing a candidate and planting it -- single hit --
    # then check the convention arithmetic with capacity=0.
    plant = b"abc"
    step = make_pallas_mask_crack_step(gen, _target(plant), batch=TILE,
                                       hit_capacity=0, interpret=True)
    bd = jnp.asarray(gen.digits(0), dtype=jnp.int32)
    count, _, _ = step(bd, jnp.int32(min(TILE, gen.keyspace)))
    assert int(count) == 1 > 0   # count still exact with tiny capacity


def test_pallas_worker_matches_xla_worker():
    gen = MaskGenerator("?l?l?l?l")
    plant = b"wasp"
    eng = get_engine("md5", device="jax")
    targets = [eng.parse_target(hashlib.md5(plant).hexdigest())]
    oracle = get_engine("md5")
    pworker = PallasMd5MaskWorker(eng, gen, targets, batch=TILE,
                                  hit_capacity=8, oracle=oracle,
                                  interpret=True)
    unit = WorkUnit(0, 0, gen.keyspace)
    phits = pworker.process(unit)
    xworker = eng.make_mask_worker(gen, targets, batch=1 << 14,
                                   hit_capacity=8, oracle=oracle)
    xhits = xworker.process(unit)
    assert [(h.target_index, h.cand_index, h.plaintext) for h in phits] == \
        [(h.target_index, h.cand_index, h.plaintext) for h in xhits]
    assert phits[0].plaintext == plant
