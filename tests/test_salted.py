"""Salted fast-hash engines (md5/sha1/sha256 x $pass.$salt /
$salt.$pass): oracle equivalence, worker end-to-end for both orders
and both attacks, sharded mask worker, CLI surface."""

import hashlib

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(algo, plain, salt, order):
    data = plain + salt if order == "ps" else salt + plain
    return (hashlib.new(algo, data).hexdigest()
            + ":" + salt.decode("latin-1"))


@pytest.mark.parametrize("algo", ["md5", "sha1", "sha256"])
@pytest.mark.parametrize("order", ["ps", "sp"])
def test_device_matches_oracle(algo, order):
    import random
    dev = get_engine(f"{algo}-{order}", "jax")
    cpu = get_engine(f"{algo}-{order}", "cpu")
    rng = random.Random(42)
    cands = [bytes(rng.randrange(1, 256) for _ in range(rng.randrange(0, 20)))
             for _ in range(24)]
    salt = b"pepper!"
    got_dev = dev.hash_batch(cands, params={"salt": salt})
    got_cpu = cpu.hash_batch(cands, params={"salt": salt})
    want = [hashlib.new(algo, c + salt if order == "ps" else salt + c)
            .digest() for c in cands]
    assert got_cpu == want
    # the device engine's hash_batch has no salt plumbing (salting
    # happens in the fused step), so only the oracle is checked here;
    # the fused step is covered by the worker tests below.
    assert len(got_dev) == len(cands)


@pytest.mark.parametrize("order,secret", [("ps", b"fox"), ("sp", b"hen")])
def test_salted_mask_worker_end_to_end(order, secret):
    name = f"md5-{order}"
    dev = get_engine(name, "jax")
    cpu = get_engine(name, "cpu")
    salt = b"s4lt"
    gen = MaskGenerator("?l?l?l")
    t = dev.parse_target(_line("md5", secret, salt, order))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_salted_wordlist_worker_with_rules():
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("sha1-sp", "jax")
    cpu = get_engine("sha1-sp", "cpu")
    salt = b"NaCl"
    words = [b"winter", b"summer", b"autumn"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=20)
    secret = b"SUMMER"     # summer + 'u'
    t = dev.parse_target(_line("sha1", secret, salt, "sp"))
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
    assert gen.candidate(hits[0].cand_index) == secret


def test_salted_multi_target_distinct_salts():
    """Two targets with different salts, same plaintext keyspace: each
    sweep honors its own salt."""
    dev = get_engine("md5-ps", "jax")
    cpu = get_engine("md5-ps", "cpu")
    gen = MaskGenerator("?d?d")
    t1 = dev.parse_target(_line("md5", b"42", b"A", "ps"))
    t2 = dev.parse_target(_line("md5", b"77", b"BB", "ps"))
    w = dev.make_mask_worker(gen, [t1, t2], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = sorted((h.target_index, h.plaintext)
                  for h in w.process(WorkUnit(0, 0, gen.keyspace)))
    assert hits == [(0, b"42"), (1, b"77")]


def test_sharded_salted_mask_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("sha256-ps", "jax")
    cpu = get_engine("sha256-ps", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret, salt = b"q7x", b"mesa"
    t = dev.parse_target(_line("sha256", secret, salt, "ps"))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=128,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_salted_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = _line("md5", b"ab1", b"grain", "ps")
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "md5-ps",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "--unit-size", "8192", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:ab1" in out


def test_length_guard_rejects_overflow():
    dev = get_engine("md5-ps", "jax")
    gen = MaskGenerator("?l" * 40)          # 40 + 32-byte salt > 55
    t = dev.parse_target(_line("md5", b"x" * 40, b"s" * 20, "ps"))
    with pytest.raises(ValueError, match="single-block"):
        dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8)


def test_sha512_salted_crack():
    """sha512-ps/sp (hashcat 1710/1720): 128-byte block, wider salt
    headroom (111 - SALT_MAX)."""
    dev = get_engine("sha512-sp", "jax")
    cpu = get_engine("sha512-sp", "cpu")
    assert dev.max_candidate_len == 111 - 32
    salt = b"m1neral"
    gen = MaskGenerator("?d?l?d")
    t = dev.parse_target(_line("sha512", b"4x2", salt, "sp"))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"4x2")]


def test_postgres_engine():
    """PostgreSQL MD5 auth (hashcat 12): md5(pass||user), 'md5hex:user'
    lines, riding the salted-md5 device machinery."""
    import hashlib

    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.workunit import WorkUnit

    cpu = get_engine("postgres")
    dev = get_engine("postgres", device="jax")
    line = "md5" + hashlib.md5(b"fox" + b"alice").hexdigest() + ":alice"
    t = cpu.parse_target(line)
    assert cpu.hash_batch([b"fox"], params=t.params)[0] == t.digest
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]
