"""Salted fast-hash engines (md5/sha1/sha256 x $pass.$salt /
$salt.$pass): oracle equivalence, worker end-to-end for both orders
and both attacks, sharded mask worker, CLI surface."""

import hashlib

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _line(algo, plain, salt, order):
    data = plain + salt if order == "ps" else salt + plain
    return (hashlib.new(algo, data).hexdigest()
            + ":" + salt.decode("latin-1"))


@pytest.mark.parametrize("algo", ["md5", "sha1", "sha256"])
@pytest.mark.parametrize("order", ["ps", "sp"])
def test_device_matches_oracle(algo, order):
    import random
    dev = get_engine(f"{algo}-{order}", "jax")
    cpu = get_engine(f"{algo}-{order}", "cpu")
    rng = random.Random(42)
    cands = [bytes(rng.randrange(1, 256) for _ in range(rng.randrange(0, 20)))
             for _ in range(24)]
    salt = b"pepper!"
    got_dev = dev.hash_batch(cands, params={"salt": salt})
    got_cpu = cpu.hash_batch(cands, params={"salt": salt})
    want = [hashlib.new(algo, c + salt if order == "ps" else salt + c)
            .digest() for c in cands]
    assert got_cpu == want
    # the device engine's hash_batch has no salt plumbing (salting
    # happens in the fused step), so only the oracle is checked here;
    # the fused step is covered by the worker tests below.
    assert len(got_dev) == len(cands)


@pytest.mark.parametrize("order,secret", [("ps", b"fox"), ("sp", b"hen")])
def test_salted_mask_worker_end_to_end(order, secret):
    name = f"md5-{order}"
    dev = get_engine(name, "jax")
    cpu = get_engine(name, "cpu")
    salt = b"s4lt"
    gen = MaskGenerator("?l?l?l")
    t = dev.parse_target(_line("md5", secret, salt, order))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_salted_wordlist_worker_with_rules():
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("sha1-sp", "jax")
    cpu = get_engine("sha1-sp", "cpu")
    salt = b"NaCl"
    words = [b"winter", b"summer", b"autumn"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=20)
    secret = b"SUMMER"     # summer + 'u'
    t = dev.parse_target(_line("sha1", secret, salt, "sp"))
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
    assert gen.candidate(hits[0].cand_index) == secret


def test_salted_multi_target_distinct_salts():
    """Two targets with different salts, same plaintext keyspace: each
    sweep honors its own salt."""
    dev = get_engine("md5-ps", "jax")
    cpu = get_engine("md5-ps", "cpu")
    gen = MaskGenerator("?d?d")
    t1 = dev.parse_target(_line("md5", b"42", b"A", "ps"))
    t2 = dev.parse_target(_line("md5", b"77", b"BB", "ps"))
    w = dev.make_mask_worker(gen, [t1, t2], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = sorted((h.target_index, h.plaintext)
                  for h in w.process(WorkUnit(0, 0, gen.keyspace)))
    assert hits == [(0, b"42"), (1, b"77")]


def test_sharded_salted_mask_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("sha256-ps", "jax")
    cpu = get_engine("sha256-ps", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret, salt = b"q7x", b"mesa"
    t = dev.parse_target(_line("sha256", secret, salt, "ps"))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=128,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_salted_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = _line("md5", b"ab1", b"grain", "ps")
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "md5-ps",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "--unit-size", "8192", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:ab1" in out


def test_length_guard_rejects_overflow():
    dev = get_engine("md5-ps", "jax")
    gen = MaskGenerator("?l" * 40)          # 40 + 32-byte salt > 55
    t = dev.parse_target(_line("md5", b"x" * 40, b"s" * 20, "ps"))
    with pytest.raises(ValueError, match="single-block"):
        dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8)


def test_sha512_salted_crack():
    """sha512-ps/sp (hashcat 1710/1720): 128-byte block, wider salt
    headroom (111 - SALT_MAX)."""
    dev = get_engine("sha512-sp", "jax")
    cpu = get_engine("sha512-sp", "cpu")
    assert dev.max_candidate_len == 111 - 32
    salt = b"m1neral"
    gen = MaskGenerator("?d?l?d")
    t = dev.parse_target(_line("sha512", b"4x2", salt, "sp"))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"4x2")]


def test_postgres_engine():
    """PostgreSQL MD5 auth (hashcat 12): md5(pass||user), 'md5hex:user'
    lines, riding the salted-md5 device machinery."""
    import hashlib

    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.workunit import WorkUnit

    cpu = get_engine("postgres")
    dev = get_engine("postgres", device="jax")
    line = "md5" + hashlib.md5(b"fox" + b"alice").hexdigest() + ":alice"
    t = cpu.parse_target(line)
    assert cpu.hash_batch([b"fox"], params=t.params)[0] == t.digest
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


# ---------------- LDAP schemes ({SSHA}/{SHA}/... ) ----------------

def _ldap_line(scheme, algo, plain, salt=b""):
    import base64
    return ("{%s}" % scheme) + base64.b64encode(
        hashlib.new(algo, plain + salt).digest() + salt).decode()


@pytest.mark.parametrize("name,scheme,algo,salted", [
    ("ldap-ssha", "SSHA", "sha1", True),       # hashcat 111
    ("ldap-ssha512", "SSHA512", "sha512", True),  # hashcat 1711
    ("ldap-smd5", "SMD5", "md5", True),
    ("ldap-sha", "SHA", "sha1", False),        # hashcat 101
    ("ldap-md5", "MD5", "md5", False),
])
def test_ldap_parse_and_oracle(name, scheme, algo, salted):
    salt = b"NaCl" if salted else b""
    line = _ldap_line(scheme, algo, b"hunter2", salt)
    cpu = get_engine(name)
    t = cpu.parse_target(line)
    assert cpu.hash_batch([b"hunter2"], t.params)[0] == t.digest
    if salted:
        assert t.params["salt"] == salt
    dev = get_engine(name, device="jax")
    assert dev.parse_target(line).digest == t.digest


def test_ldap_rejects_malformed():
    cpu = get_engine("ldap-ssha")
    with pytest.raises(ValueError):
        cpu.parse_target("{SSHA}!!!notbase64!!!")
    with pytest.raises(ValueError):
        cpu.parse_target("{SSHA}" + "QUJD")       # shorter than digest
    with pytest.raises(ValueError):
        get_engine("ldap-sha").parse_target(
            _ldap_line("SHA", "sha1", b"x", b"saltbytes"))  # salt on unsalted


def test_ldap_ssha_mask_worker_end_to_end():
    dev = get_engine("ldap-ssha", "jax")
    cpu = get_engine("ldap-ssha", "cpu")
    gen = MaskGenerator("?l?l?l")
    t = dev.parse_target(_ldap_line("SSHA", "sha1", b"fox", b"abcd1234"))
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"fox")]


def test_ldap_sha_multi_target_fast_path():
    """{SHA} rides the unsalted fast path: a 3-target list resolves in
    one sweep with per-target indices."""
    dev = get_engine("ldap-sha", "jax")
    cpu = get_engine("ldap-sha", "cpu")
    gen = MaskGenerator("?d?d?d")
    secrets = [b"042", b"700", b"999"]
    targets = [dev.parse_target(_ldap_line("SHA", "sha1", s))
               for s in secrets]
    w = dev.make_mask_worker(gen, targets, batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(i, s) for i, s in enumerate(secrets)}


# ---------------- MSSQL family (hashcat 131/132/1731) ----------------

def _wide(b):
    return bytes(x for ch in b for x in (ch, 0))


MSSQL_SALT = bytes.fromhex("1a2b3c4d")


def _mssql_line(version, pw):
    if version == 2000:
        cs = hashlib.sha1(_wide(pw) + MSSQL_SALT).hexdigest()
        up = hashlib.sha1(_wide(pw.upper()) + MSSQL_SALT).hexdigest()
        return "0x0100" + MSSQL_SALT.hex() + cs + up
    if version == 2005:
        return "0x0100" + MSSQL_SALT.hex() + \
            hashlib.sha1(_wide(pw) + MSSQL_SALT).hexdigest()
    return "0x0200" + MSSQL_SALT.hex() + \
        hashlib.sha512(_wide(pw) + MSSQL_SALT).hexdigest()


@pytest.mark.parametrize("name,version,planted,cracks_as", [
    ("mssql2005", 2005, b"fox", b"fox"),
    ("mssql2012", 2012, b"hen", b"hen"),
    # 2000 is case-insensitive: the stored digest is over UPPER(pass),
    # so a lowercase sweep finds the mixed-case original
    ("mssql2000", 2000, b"Fox", b"fox"),
])
def test_mssql_mask_worker_end_to_end(name, version, planted, cracks_as):
    cpu = get_engine(name)
    dev = get_engine(name, "jax")
    t = cpu.parse_target(_mssql_line(version, planted))
    assert cpu.hash_batch([cracks_as], t.params)[0] == t.digest
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == \
        [(0, cracks_as)]


def test_mssql_wordlist_worker_with_rules():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("mssql2005")
    dev = get_engine("mssql2005", "jax")
    words = [b"alpha", b"fox", b"delta"]
    rules = [parse_rule(":"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=8)
    t = cpu.parse_target(_mssql_line(2005, b"fox1"))
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox1"]


def test_mssql_parse_rejects_malformed():
    cpu = get_engine("mssql2005")
    with pytest.raises(ValueError):
        cpu.parse_target("0x0200" + "00" * 24)          # wrong version tag
    with pytest.raises(ValueError):
        cpu.parse_target("0x0100" + "zz" * 24)          # bad hex
    with pytest.raises(ValueError):
        cpu.parse_target("0x0100" + "aabbccdd" + "ab")  # short digest


def test_mssql_long_candidates_fit_single_block():
    """12+-char candidates must trace: the widened bytes + 4-byte salt
    (2L+4 <= 55) fit the block because MSSQL's salt buffer is 4 bytes,
    not the generic 32-byte reservation."""
    pw = b"abcdefghijkl"                       # 12 chars -> 28 bytes
    line = _mssql_line(2005, pw)
    cpu = get_engine("mssql2005")
    dev = get_engine("mssql2005", "jax")
    t = cpu.parse_target(line)
    gen = MaskGenerator("?l" * 12)
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    w.process(WorkUnit(0, 0, 64))              # traces at length 12


def test_mssql_cross_version_lines_rejected():
    """A 2000-format line (two digests) fed to the 2005 engine must
    error, not silently crack against the upper-cased digest (and vice
    versa)."""
    with pytest.raises(ValueError, match="wrong MSSQL version"):
        get_engine("mssql2005").parse_target(_mssql_line(2000, b"x"))
    with pytest.raises(ValueError, match="wrong MSSQL version"):
        get_engine("mssql2000").parse_target(_mssql_line(2005, b"x"))
