"""Test configuration.

Force JAX onto the CPU backend with 8 virtual devices so multi-chip
sharding paths (shard_map over a Mesh) are exercised without TPU
hardware, per SURVEY.md section 4.  Must run before jax is imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
