"""Test configuration: hermetic CPU-mesh execution.

The session environment registers an axon TPU-tunnel PJRT plugin in
every python process (sitecustomize on PYTHONPATH) and forces
``jax_platforms`` to "axon,cpu" via jax.config.update -- so env vars
alone cannot keep tests off the TPU tunnel (which serves one client at
a time and wedges if a test run is killed).  Override the config back
to plain CPU here, before any backend initializes, and give the CPU
platform 8 virtual devices so multi-chip sharding paths run without
hardware (SURVEY.md section 4's fake-mesh strategy).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# For any subprocess a test might spawn:
os.environ["JAX_PLATFORMS"] = "cpu"
# The production default tile (SUB=128, tuned on real TPU -- see
# BASELINE.md) makes interpret-mode kernel tests 4x slower without
# changing semantics; keep the hermetic suite on the small tile.
os.environ.setdefault("DPRF_PALLAS_SUB", "32")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
