"""Test configuration: hermetic CPU-mesh execution.

The session environment registers an axon TPU-tunnel PJRT plugin in
every python process (sitecustomize on PYTHONPATH) and forces
``jax_platforms`` to "axon,cpu" via jax.config.update -- so env vars
alone cannot keep tests off the TPU tunnel (which serves one client at
a time and wedges if a test run is killed).  Override the config back
to plain CPU here, before any backend initializes, and give the CPU
platform 8 virtual devices so multi-chip sharding paths run without
hardware (SURVEY.md section 4's fake-mesh strategy).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# For any subprocess a test might spawn:
os.environ["JAX_PLATFORMS"] = "cpu"
# The production default tile (SUB=128, tuned on real TPU -- see
# BASELINE.md) makes interpret-mode kernel tests 4x slower without
# changing semantics; keep the hermetic suite on the small tile.
os.environ.setdefault("DPRF_PALLAS_SUB", "32")

# Hermetic tuning cache: `--batch auto` is the CLI default now, so any
# e2e test would otherwise read/write the USER's ~/.cache/dprf tuning
# cache -- cross-contaminating real tuning state with test runs.
if "DPRF_TUNE_DIR" not in os.environ:
    import tempfile as _tempfile
    os.environ["DPRF_TUNE_DIR"] = _tempfile.mkdtemp(prefix="dprf-tune-test-")

# Hermetic persistent compile cache (ISSUE 3): CLI/bench paths call
# compilecache.enable(), which would otherwise point jax's
# compilation cache at the USER's ~/.cache/dprf/xla -- test-compiled
# executables must never leak into (or warm-start from) real fleet
# state.
if "DPRF_COMPILE_CACHE_DIR" not in os.environ:
    import tempfile as _tempfile
    os.environ["DPRF_COMPILE_CACHE_DIR"] = _tempfile.mkdtemp(
        prefix="dprf-xla-cache-test-")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# smoke-tier time guard: pytest.ini promises the smoke tier under 5
# minutes; a silently-slowed tier is exactly the kind of unverifiable
# claim VERDICT r5 flagged for bench numbers, so the promise is
# machine-checked here.  Applies only to smoke-tier selections (`-m
# smoke...` without negation); DPRF_TIER_BUDGET_S overrides the
# budget, 0 disables.

import re as _re      # noqa: E402
import time as _time  # noqa: E402

_TIER_BUDGET_DEFAULT_S = 300.0


def _smoke_budget(config):
    # word-boundary match: a future marker merely CONTAINING "smoke"
    # (or an expression deselecting it) must not inherit the budget
    expr = (config.getoption("-m") or "").strip()
    if (not _re.search(r"\bsmoke\b", expr)
            or _re.search(r"\bnot\s+smoke\b", expr)):
        return None
    from dprf_tpu.utils import env as envreg
    budget = envreg.get_float("DPRF_TIER_BUDGET_S",
                              _TIER_BUDGET_DEFAULT_S)
    return budget if budget > 0 else None


def pytest_configure(config):
    config._dprf_tier_t0 = _time.monotonic()
    _run_static_checks()


def _run_static_checks():
    """One in-process `dprf check` pass (all six analyzers: markers,
    metrics, worker-contract, locks, protocol, env-knobs -- see
    dprf_tpu/analysis/) at the top of every tier run, so a
    lock-discipline race, a one-sided RPC key, or a rogue env read
    fails the run before the first test executes.  Budget: <2 s
    (the analyzers share one parse and prefilter on source text)."""
    import pytest

    from dprf_tpu import analysis

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    failure = analysis.run_for_conftest(repo)
    if failure is not None:
        raise pytest.UsageError(failure)


def _has_compileheavy(session) -> bool:
    # the <5-min promise is for the tier WITHOUT compileheavy cases; a
    # selection that includes them gets the wall-time line but not the
    # hard failure.  Read session.items (the post-deselection list) --
    # a collection_modifyitems hook would see compileheavy tests that
    # `-m "... and not compileheavy"` is about to drop.
    items = getattr(session, "items", None) or []
    return any(i.get_closest_marker("compileheavy") is not None
               for i in items)


def pytest_sessionfinish(session, exitstatus):
    budget = _smoke_budget(session.config)
    if budget is None or _has_compileheavy(session):
        return
    elapsed = _time.monotonic() - session.config._dprf_tier_t0
    if elapsed > budget and exitstatus == 0:
        print(f"\nFAIL: smoke tier took {elapsed:.0f}s, over its "
              f"{budget:.0f}s budget (pytest.ini promise).  Mark the "
              "offender compileheavy or shrink its traced shapes; "
              "DPRF_TIER_BUDGET_S=0 disables this guard.")
        session.exitstatus = 1


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    budget = _smoke_budget(config)
    if budget is None:
        return
    elapsed = _time.monotonic() - config._dprf_tier_t0
    verdict = "within" if elapsed <= budget else "OVER"
    terminalreporter.write_line(
        f"smoke tier wall time: {elapsed:.0f}s ({verdict} the "
        f"{budget:.0f}s budget)")
