"""Dispatcher, session journal, potfile unit tests."""

import json

import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu.runtime.dispatcher import Dispatcher, IntervalSet
from dprf_tpu.runtime.potfile import Potfile, encode_plain, decode_plain
from dprf_tpu.runtime.session import SessionJournal, job_fingerprint


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_interval_set_merge():
    s = IntervalSet()
    s.add(10, 20)
    s.add(0, 5)
    s.add(5, 10)          # bridges
    assert s.intervals() == [(0, 20)]
    s.add(30, 40)
    assert s.gaps(50) == [(20, 30), (40, 50)]
    assert s.covered() == 30
    assert s.contains_range(3, 18)
    assert not s.contains_range(18, 25)


def test_dispatcher_full_sweep():
    d = Dispatcher(keyspace=1000, unit_size=128)
    seen = []
    while True:
        u = d.lease("w0")
        if u is None:
            break
        seen.append((u.start, u.end))
        d.complete(u.unit_id)
    assert seen[0] == (0, 128)
    assert seen[-1] == (896, 1000)       # tail unit is short
    assert d.done()
    assert d.progress() == (1000, 1000)


def test_dispatcher_lease_expiry_reissues():
    clk = FakeClock()
    d = Dispatcher(keyspace=256, unit_size=128, lease_timeout=10.0, clock=clk)
    u1 = d.lease("w0")
    u2 = d.lease("w1")
    assert d.lease("w2") is None          # everything outstanding
    clk.t = 11.0                          # w0 and w1 die
    u3 = d.lease("w2")                    # reissued unit
    assert (u3.start, u3.end) in {(u1.start, u1.end), (u2.start, u2.end)}
    # late completion by the dead worker is idempotent
    d.complete(u1.unit_id)
    d.complete(u3.unit_id)
    u4 = d.lease("w2")
    d.complete(u4.unit_id)
    assert d.done()


def test_dispatcher_resume_from_completed():
    # covered: [0,100) and [200,300); frontier 300 -> gap [100,200) pending
    d = Dispatcher.from_completed(keyspace=1000, unit_size=64,
                                  completed=[(0, 100), (200, 300)])
    first = d.lease()
    second = d.lease()
    assert (first.start, first.end) == (100, 164)
    assert (second.start, second.end) == (164, 200)
    third = d.lease()
    assert third.start == 300             # continues at frontier
    done, total = d.progress()
    assert (done, total) == (200, 1000)


def test_session_journal_roundtrip(tmp_path):
    p = str(tmp_path / "job.session")
    j = SessionJournal(p, snapshot_every=1)
    j.open({"engine": "md5", "fingerprint": "abc"})
    j.record_units([(0, 100)])
    j.record_hit(0, 42, b"pass")
    j.record_units([(0, 250)])
    j.close()
    st = SessionJournal.load(p)
    assert st.spec["fingerprint"] == "abc"
    assert st.completed == [(0, 250)]     # last snapshot wins
    assert st.hits[0]["index"] == 42
    assert bytes.fromhex(st.hits[0]["plaintext"]) == b"pass"


def test_session_journal_torn_tail(tmp_path):
    p = str(tmp_path / "job.session")
    j = SessionJournal(p, snapshot_every=1)
    j.open({"engine": "md5"})
    j.record_units([(0, 64)])
    j.close()
    with open(p, "a") as fh:
        fh.write('{"type": "units", "intervals": [[0, 9')   # torn write
    st = SessionJournal.load(p)
    assert st.completed == [(0, 64)]


def test_fingerprint_sensitivity():
    a = job_fingerprint("md5", "mask:?l?l", 676, [b"x" * 16])
    assert a == job_fingerprint("md5", "mask:?l?l", 676, [b"x" * 16])
    assert a != job_fingerprint("md5", "mask:?l?d", 676, [b"x" * 16])
    assert a != job_fingerprint("md5", "mask:?l?l", 676, [b"y" * 16])


def test_potfile_roundtrip(tmp_path):
    p = str(tmp_path / "t.pot")
    pot = Potfile(p)
    pot.add("deadbeef", b"hello")
    pot.add("cafebabe", b"\x01\xffbin:")
    # reload from disk
    pot2 = Potfile(p)
    assert pot2.get("deadbeef") == b"hello"
    assert pot2.get("cafebabe") == b"\x01\xffbin:"
    assert "deadbeef" in pot2 and len(pot2) == 2


@pytest.mark.parametrize("plain", [b"simple", b"", b"with:colon",
                                   b"\x00\x01", "pässword".encode(),
                                   b"$HEX[41]"])
def test_plain_encoding_roundtrip(plain):
    assert decode_plain(encode_plain(plain)) == plain


def test_dispatcher_chaos_full_coverage():
    """Elastic-recovery stress (SURVEY.md section 5): workers randomly
    crash (fail), stall (lease expiry), or double-report completions;
    the ledger must still converge to exactly-full coverage."""
    import random
    rng = random.Random(7)
    clk = FakeClock()
    # retry cap disabled: this chaos model fails units at random (not
    # because the unit itself is poisoned), so parking would be wrong
    # -- full convergence is the invariant under test
    d = Dispatcher(keyspace=10_000, unit_size=37, lease_timeout=50.0,
                   clock=clk, max_unit_retries=None)
    held = []                      # units currently "running"
    completed_ids = []
    for _ in range(200_000):
        if d.done():
            break
        clk.t += rng.uniform(0, 5)
        action = rng.random()
        if action < 0.45 or not held:
            u = d.lease(f"w{rng.randrange(8)}")
            if u is not None:
                held.append(u)
        elif action < 0.75:
            u = held.pop(rng.randrange(len(held)))
            d.complete(u.unit_id)
            completed_ids.append(u.unit_id)
        elif action < 0.85:
            u = held.pop(rng.randrange(len(held)))
            d.fail(u.unit_id)
        elif action < 0.95:
            # stalled worker: just sit on the unit past its lease;
            # dispatcher reaps it and someone else finishes it
            clk.t += 60.0
            if held and rng.random() < 0.5:
                held.pop(rng.randrange(len(held)))   # worker died silently
        else:
            # late/duplicate completion of an already-finished unit
            if completed_ids:
                d.complete(rng.choice(completed_ids))
    assert d.done()
    assert d.completed_intervals() == [(0, 10_000)]


def test_dispatcher_poison_guard_parks_after_retry_cap():
    """A unit that fails every worker that touches it must be PARKED
    after the retry cap, not reissued forever: before the guard,
    Dispatcher.fail()/reap_expired() livelocked the whole job on one
    poisoned unit."""
    from dprf_tpu.telemetry import MetricsRegistry

    m = MetricsRegistry()
    d = Dispatcher(keyspace=256, unit_size=128, registry=m,
                   max_unit_retries=5)
    poisoned = d.lease("w0")
    for i in range(5):
        assert d.parked_count() == 0
        d.fail(poisoned.unit_id)
        if i < 4:                       # reissued, not yet parked
            again = d.lease("w0")
            assert (again.start, again.end) == (poisoned.start,
                                                poisoned.end)
    # 5th failure parks it: the range becomes unreachable this run
    assert d.parked_count() == 1
    assert d.parked_indices() == poisoned.length
    assert m.counter("dprf_units_poisoned_total",
                     labelnames=("job",)).value(job="j0") == 1
    # the rest of the keyspace still sweeps, and the job terminates
    u = d.lease("w1")
    assert (u.start, u.end) == (128, 256)
    d.complete(u.unit_id)
    assert d.lease("w1") is None
    assert d.done()                     # reachable keyspace covered
    assert not d.exhausted()            # ...but honestly NOT exhausted
    assert d.progress() == (128, 256)


def test_dispatcher_poison_guard_counts_lease_expiry():
    """Lease expiry (dead worker) burns the same retry budget as an
    explicit fail -- a unit that kills every worker that leases it
    never reports fail() at all."""
    clk = FakeClock()
    d = Dispatcher(keyspace=128, unit_size=128, lease_timeout=10.0,
                   clock=clk, max_unit_retries=3)
    for _ in range(3):
        u = d.lease("w0")
        assert u is not None
        clk.t += 11.0                   # worker dies holding the lease
        d.reap_expired()
    assert d.parked_count() == 1
    assert d.done() and not d.exhausted()


def test_dispatcher_retry_parked_requeues_with_fresh_budget():
    """Satellite (ISSUE 3): the retry-parked admin op un-parks
    poisoned units WITHOUT restarting the job -- attempt counts reset
    (a requeued unit gets the full retry budget again), the parked
    gauge drops to 0, and `done()` stops treating the ranges as
    unreachable."""
    from dprf_tpu.telemetry import MetricsRegistry

    m = MetricsRegistry()
    d = Dispatcher(keyspace=256, unit_size=128, registry=m,
                   max_unit_retries=2)
    poisoned = d.lease("w0")
    d.fail(poisoned.unit_id)
    d.fail(d.lease("w0").unit_id)       # 2nd failure parks it
    u = d.lease("w1")                   # rest of the keyspace done
    d.complete(u.unit_id)
    assert d.parked_count() == 1 and d.done() and not d.exhausted()
    assert m.gauge("dprf_units_parked",
                   labelnames=("job",)).value(job="j0") == 1

    assert d.retry_parked() == 1
    assert d.parked_count() == 0 and d.parked_indices() == 0
    assert m.gauge("dprf_units_parked",
                   labelnames=("job",)).value(job="j0") == 0
    assert not d.done()                 # the range is reachable again
    # fresh budget: the requeued unit survives max_unit_retries - 1
    # NEW failures before parking again (attempt count was reset)
    again = d.lease("w2")
    assert (again.start, again.end) == (poisoned.start, poisoned.end)
    d.fail(again.unit_id)
    assert d.parked_count() == 0        # 1 of 2: reissued, not parked
    d.complete(d.lease("w2").unit_id)
    assert d.exhausted()                # full honest coverage now
    assert d.retry_parked() == 0        # idempotent when nothing parked
    # the parking EVENT counter keeps history; reissue reason is logged
    assert m.counter("dprf_units_poisoned_total",
                     labelnames=("job",)).value(job="j0") == 1
    assert m.counter("dprf_units_reissued_total",
                     labelnames=("reason", "job")).value(
        reason="retry_parked", job="j0") == 1


def test_rpc_retry_parked_admin_op():
    """The op reaches the dispatcher through CoordinatorState (what
    `dprf retry-parked --connect` invokes server-side)."""
    from dprf_tpu.runtime.rpc import CoordinatorState
    from dprf_tpu.telemetry import MetricsRegistry

    m = MetricsRegistry()
    d = Dispatcher(keyspace=128, unit_size=128, registry=m,
                   max_unit_retries=1)
    state = CoordinatorState({"engine": "md5"}, d, n_targets=1,
                             registry=m)
    resp = state.op_lease({"worker_id": "w0"})
    state.op_fail({"unit_id": resp["unit"]["id"]})   # parks (cap 1)
    assert state.op_status({})["parked"] == 1
    assert state.op_retry_parked({}) == {"ok": True, "retried": 1}
    assert state.op_status({})["parked"] == 0
    assert state.op_lease({"worker_id": "w1"})["unit"] is not None


def test_dispatcher_retry_count_resets_nothing_on_success():
    """Retries are per-unit: one unit's failures must not park a
    DIFFERENT unit, and a unit that eventually completes clears its
    tally."""
    d = Dispatcher(keyspace=512, unit_size=128, max_unit_retries=5)
    u1 = d.lease("w0")
    for _ in range(4):
        d.fail(u1.unit_id)
        u1 = d.lease("w0")
        assert u1 is not None
    d.complete(u1.unit_id)              # 4 failures then success
    assert d.parked_count() == 0
    while True:
        u = d.lease("w0")
        if u is None:
            break
        d.complete(u.unit_id)
    assert d.exhausted()


def test_resume_resplit_with_different_unit_size_exact_coverage():
    """Satellite regression (ISSUE 2): a session journaled under one
    unit size resumes under ANOTHER (adaptive sizing makes that the
    normal case) -- gap re-splitting with the new size must yield
    exact coverage: every uncovered index issued exactly once, no
    overlap with the journaled intervals."""
    keyspace = 10_000
    # intervals a previous run with odd adaptive sizes might journal
    completed = [(0, 37), (1000, 1771), (4096, 9001)]
    for new_size in (64, 300, 8192):
        d = Dispatcher.from_completed(keyspace, new_size, completed)
        issued = []
        while True:
            u = d.lease("w")
            if u is None:
                break
            issued.append((u.start, u.end))
            d.complete(u.unit_id)
        # disjoint among themselves and with the journaled coverage
        spans = sorted(issued + list(completed))
        for (s1, e1), (s2, e2) in zip(spans, spans[1:]):
            assert e1 <= s2, f"overlap: {(s1, e1)} vs {(s2, e2)}"
        assert sum(e - s for s, e in issued) == keyspace - sum(
            e - s for s, e in completed)
        assert d.exhausted()
        assert d.completed_intervals() == [(0, keyspace)]


def test_coordinator_rejects_unverifiable_hit_and_rescans(tmp_path):
    """A buggy device worker reporting a wrong plaintext must not poison
    the potfile: the local Coordinator re-hashes hits with the CPU
    oracle, rejects the fake, and exactly rescans the unit -- finding
    the true crack the buggy worker missed (VERDICT r2 weak #3)."""
    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.worker import Hit
    from dprf_tpu.runtime.workunit import WorkUnit

    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator("?l?l?l")
    secret = b"fox"
    target = oracle.parse_target(
        __import__("hashlib").md5(secret).hexdigest())

    class BuggyWorker:
        """Claims a wrong plaintext for the target, never the real one."""
        def __init__(self):
            self.gen = gen
            self.targets = [target]

        def process(self, unit: WorkUnit):
            if unit.start <= gen.index_of(secret) < unit.end:
                return [Hit(0, unit.start, b"zzz")]   # fake plaintext
            return []

    pot = Potfile(str(tmp_path / "pot"))
    spec = JobSpec(engine="md5", device="jax", attack="mask",
                   attack_arg="?l?l?l", keyspace=gen.keyspace,
                   fingerprint="t")
    disp = Dispatcher(gen.keyspace, 26 * 26)
    coord = Coordinator(spec, [target], disp, BuggyWorker(),
                        potfile=pot, oracle=oracle)
    result = coord.run()
    assert coord.rejected >= 1
    assert result.found == {0: secret}          # rescan found the truth
    assert pot.get(target.raw) == secret        # potfile never poisoned


def test_coordinator_cpu_path_trusts_worker(tmp_path):
    """oracle=None (the CPU path) records hits directly -- no double
    hashing of every CpuWorker hit."""
    from dprf_tpu.engines import get_engine
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.worker import CpuWorker

    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator("?l?l")
    secret = b"ok"
    target = oracle.parse_target(
        __import__("hashlib").md5(secret).hexdigest())
    spec = JobSpec(engine="md5", device="cpu", attack="mask",
                   attack_arg="?l?l", keyspace=gen.keyspace,
                   fingerprint="t")
    disp = Dispatcher(gen.keyspace, 64)
    coord = Coordinator(spec, [target], disp,
                        CpuWorker(oracle, gen, [target]))
    result = coord.run()
    assert result.found == {0: secret} and coord.rejected == 0
