"""Coverage audit plane (ISSUE 19): IntervalSet semantics, the
order-independent coverage digest, the CoverageLedger's
gap/overlap/partition invariants, the worker-side note() API, the
dispatcher digest round-trip (resume refuses a torn journal), and the
offline auditor's sensitivity -- a planted gap, a planted
double-complete, and a tampered digest must each be flagged.
"""

import itertools

import pytest

from dprf_tpu.perfreport.audit import build_audit, render_audit
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.telemetry import coverage
from dprf_tpu.telemetry.coverage import (CoverageLedger, IntervalSet,
                                         coverage_digest)
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import TraceRecorder

pytestmark = [pytest.mark.smoke, pytest.mark.audit]


@pytest.fixture(autouse=True)
def _clean_notes():
    coverage.reset_notes()
    yield
    coverage.install_collector(None)
    coverage.reset_notes()


def _ledger(keyspace, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return CoverageLedger(keyspace, **kw)


# -- IntervalSet ------------------------------------------------------------

def test_intervalset_add_returns_newly_covered():
    iv = IntervalSet()
    assert iv.add(0, 100) == 100
    assert iv.add(50, 150) == 50          # half was already covered
    assert iv.add(20, 80) == 0            # fully inside
    assert iv.add(150, 200) == 50         # touching: merges
    assert iv.intervals() == [(0, 200)]
    assert iv.covered() == 200


def test_intervalset_gaps_and_contains():
    iv = IntervalSet([(10, 20), (40, 50)])
    assert iv.gaps(60) == [(0, 10), (20, 40), (50, 60)]
    assert iv.gaps(15) == [(0, 10)]
    assert iv.contains_range(12, 18)
    assert not iv.contains_range(15, 45)


# -- the digest -------------------------------------------------------------

def test_digest_order_independent():
    parts = [(0, 100), (300, 400), (100, 200)]
    digests = {coverage_digest(1000, p)
               for p in itertools.permutations(parts)}
    assert len(digests) == 1
    # pre-merged journal form digests identically
    assert coverage_digest(1000, [(0, 200), (300, 400)]) in digests
    # different covered set, or different keyspace: different digest
    assert coverage_digest(1000, [(0, 200)]) not in digests
    assert coverage_digest(999, parts) not in digests


# -- the ledger -------------------------------------------------------------

def test_ledger_clean_lifecycle():
    led = _ledger(300)
    for uid, (s, e) in enumerate([(0, 100), (100, 200), (200, 300)]):
        led.event("split", s, e, unit=uid)
        led.event("lease", s, e, unit=uid)
        led.event("complete", s, e, unit=uid)
    assert led.fraction() == 1.0
    assert led.gaps() == [] and led.gap_total() == 0
    assert led.overlap_total == 0
    assert led.counts["complete"] == 3
    assert led.summary()["digest"] == coverage_digest(300, [(0, 300)])


def test_ledger_flags_planted_gap():
    """A unit completed over HALF its range loses the other half from
    every population -- the exact loss the gap gauge and the
    coverage_gap alert exist to surface."""
    led = _ledger(100)
    led.event("split", 0, 100, unit=0)
    led.event("complete", 0, 50, unit=0)   # planted: half went missing
    assert led.gaps() == [(50, 100)]
    assert led.gap_total() == 50


def test_ledger_flags_planted_double_cover():
    led = _ledger(200)
    led.event("split", 0, 100, unit=0)
    led.event("split", 100, 200, unit=1)
    led.event("complete", 0, 100, unit=0)
    # planted double-lease aftermath: unit 1 reports unit 0's range
    led.event("complete", 0, 100, unit=1)
    assert led.overlap_total == 100
    assert led.gaps() == [(100, 200)]      # unit 1's real range: lost


def test_ledger_abandon_freezes_gap_reporting():
    led = _ledger(100)
    led.event("split", 0, 50, unit=0)
    led.event("abandon")
    assert led.abandoned and led.gaps() == []


def test_disabled_ledger_still_digests(monkeypatch):
    monkeypatch.setenv("DPRF_COVERAGE", "0")
    led = _ledger(100)
    led.event("split", 0, 100, unit=0)
    led.event("complete", 0, 100, unit=0)
    assert led.counts["complete"] == 0     # accounting is off...
    # ...but digests stay live: resume correctness must not depend on
    # a telemetry knob (this digest is of the EMPTY covered set)
    assert led.digest() == coverage_digest(100, [])


def test_event_rejects_undeclared_name():
    led = _ledger(10)
    with pytest.raises(ValueError):
        led.event("bogus", 0, 10)
    with pytest.raises(ValueError):
        coverage.note("bogus", 0, 10)


# -- worker-side notes ------------------------------------------------------

def test_note_counters_and_collector():
    got = []
    coverage.install_collector(
        lambda name, s, e, attrs: got.append((name, s, e, attrs)))
    coverage.note("window", 0, 512, unit=7, kind="sshard")
    coverage.note("redrive", 128, 256, unit=7)
    n = coverage.notes()
    assert n["window"] == 1 and n["redrive"] == 1
    assert got == [("window", 0, 512, {"unit": 7, "kind": "sshard"}),
                   ("redrive", 128, 256, {"unit": 7})]


def test_note_disabled_is_silent(monkeypatch):
    monkeypatch.setenv("DPRF_COVERAGE", "0")
    got = []
    coverage.install_collector(lambda *a: got.append(a))
    coverage.note("window", 0, 512, unit=1)
    assert coverage.notes()["window"] == 0 and got == []


# -- dispatcher round-trip --------------------------------------------------

def _drain(disp, worker="w"):
    while True:
        u = disp.lease(worker)
        if u is None:
            break
        disp.complete(u.unit_id, worker_id=worker)


def test_dispatcher_digest_roundtrip_and_refusal():
    reg = MetricsRegistry()
    d = Dispatcher(1000, 100, registry=reg)
    _drain(d)
    dg = d.coverage_digest()
    assert dg == coverage_digest(1000, d.completed_intervals())
    # an honest resume reproduces the digest
    d2 = Dispatcher.from_completed(1000, 100, d.completed_intervals(),
                                   expect_digest=dg,
                                   registry=MetricsRegistry())
    assert d2.coverage_digest() == dg
    # a torn journal (intervals edited, digest stale) is refused
    with pytest.raises(ValueError, match="refusing to resume"):
        Dispatcher.from_completed(1000, 100, [(0, 500)],
                                  expect_digest=dg,
                                  registry=MetricsRegistry())


def test_resume_resplit_redrive_same_unit():
    """The nastiest interval path: a unit is completed, the journal
    misses it (crash), resume RESPLITS its range into a fresh unit,
    the fresh unit overflows and REDRIVES a window -- coverage must
    come out exact with the overlap visible nowhere (the ledger was
    rebuilt without the lost completion) and the redrive note clipped
    inside the resplit unit."""
    reg = MetricsRegistry()
    d = Dispatcher(1000, 100, registry=reg)
    units = [d.lease("w") for _ in range(4)]
    for u in units[:3]:
        d.complete(u.unit_id, worker_id="w")
    # crash: the journal only ever saw the first two completions
    journaled = [(0, 200)]
    d2 = Dispatcher.from_completed(
        1000, 100, journaled,
        expect_digest=coverage_digest(1000, journaled),
        registry=MetricsRegistry())
    # the un-journaled third unit's range is pending again
    got = []
    coverage.install_collector(
        lambda name, s, e, attrs: got.append((name, s, e)))
    seen = IntervalSet(journaled)
    while True:
        u = d2.lease("w")
        if u is None:
            break
        if u.start <= 250 < u.end:
            # the resplit unit re-running [200, 300): its worker
            # overflows and redrives a sub-window
            coverage.note("redrive", u.start + 10, u.end - 10,
                          unit=u.unit_id)
        seen.add(u.start, u.end)
        d2.complete(u.unit_id, worker_id="w")
    assert d2.coverage.fraction() == 1.0
    assert d2.coverage.gap_total() == 0
    assert d2.coverage.overlap_total == 0
    assert seen.intervals() == [(0, 1000)]
    assert ("redrive", 210, 290) in got
    assert d2.coverage_digest() == coverage_digest(1000, [(0, 1000)])


# -- offline auditor sensitivity --------------------------------------------

def _session(tmp_path, name="s.session", keyspace=1000):
    j = SessionJournal(str(tmp_path / name), snapshot_every=2)
    j.open({"engine": "md5", "attack": "mask", "keyspace": keyspace})
    return j


def test_auditor_flags_planted_gap(tmp_path):
    j = _session(tmp_path)
    iv = [(0, 400), (500, 1000)]          # planted: [400, 500) lost
    j.snapshot(iv, digest=coverage_digest(1000, iv))
    j.close()
    doc = build_audit(j.path)
    assert doc["verdict"] == "incomplete"
    row = doc["jobs"][0]
    assert row["gap_total"] == 100
    assert row["gaps"] == [(400, 500)]
    assert row["digest_match"] is True
    assert "GAPS" in render_audit(doc)


def test_auditor_flags_planted_double_complete(tmp_path):
    """A double-lease that lands twice shows up in the trace replay
    as double-covered candidates -- dirty, even though the journal's
    interval set looks complete."""
    j = _session(tmp_path)
    rec = TraceRecorder(enabled=True, proc="coordinator",
                        registry=MetricsRegistry())
    rec.attach_file(j.trace_path)
    rec.record("complete", start=0, length=500, job="j0")
    rec.record("complete", start=500, length=500, job="j0")
    rec.record("complete", start=200, length=300, job="j0")  # planted
    rec.detach_file()
    j.snapshot([(0, 1000)], digest=coverage_digest(1000, [(0, 1000)]))
    j.close()
    doc = build_audit(j.path)
    assert doc["verdict"] == "dirty"
    assert doc["jobs"][0]["trace_overlap"] == 300
    assert any("double-covered" in p for p in doc["problems"])


def test_auditor_flags_tampered_digest(tmp_path):
    j = _session(tmp_path)
    j.snapshot([(0, 1000)], digest=coverage_digest(1000, [(0, 900)]))
    j.close()
    doc = build_audit(j.path)
    assert doc["verdict"] == "dirty"
    assert doc["jobs"][0]["digest_match"] is False
    assert any("does not match" in p for p in doc["problems"])


def test_auditor_flags_duplicate_hits(tmp_path):
    j = _session(tmp_path)
    j.record_hit(0, 123, b"pw")
    j.record_hit(0, 123, b"pw")            # planted: found twice
    j.snapshot([(0, 1000)], digest=coverage_digest(1000, [(0, 1000)]))
    j.close()
    doc = build_audit(j.path)
    assert doc["verdict"] == "dirty"
    assert doc["jobs"][0]["hit_dupes"] == 1
    assert any("exactly once" in p for p in doc["problems"])


def test_auditor_restart_generation_not_flagged(tmp_path):
    """A crash-restart legitimately re-sweeps ranges completed after
    the last journal snapshot; the restore-span generation boundary
    keeps the replay from misreading that as double coverage --
    while a double WITHIN the new generation still flags."""
    j = _session(tmp_path)
    rec = TraceRecorder(enabled=True, proc="coordinator",
                        registry=MetricsRegistry())
    rec.attach_file(j.trace_path)
    rec.record("complete", start=0, length=500, job="j0")
    rec.record("complete", start=500, length=300, job="j0")  # unsnapshotted
    # restart: the journal only snapshotted [0, 500)
    rec.record("restore", start=0, length=500, job="j0")
    rec.record("complete", start=500, length=300, job="j0")  # legit resweep
    rec.record("complete", start=800, length=200, job="j0")
    rec.detach_file()
    j.snapshot([(0, 1000)], digest=coverage_digest(1000, [(0, 1000)]))
    j.close()
    doc = build_audit(j.path)
    assert doc["jobs"][0]["trace_overlap"] == 0
    assert doc["verdict"] == "clean"
    # but re-covering a range the restore itself seeded IS dirty
    rec.attach_file(j.trace_path)
    rec.record("complete", start=100, length=50, job="j0")
    rec.detach_file()
    doc = build_audit(j.path)
    assert doc["jobs"][0]["trace_overlap"] == 50
    assert doc["verdict"] == "dirty"


def test_ledger_event_overhead_budget():
    """The ledger must stay far under the <=2% H/s budget: a sweep's
    worth of events (split+lease+complete per unit) has to be cheap.
    Loose wall-clock bound -- this is a tripwire for an accidental
    O(n^2) (e.g. re-scanning the interval list per insert), not a
    benchmark."""
    import time
    led = _ledger(10_000_000)
    t0 = time.perf_counter()
    for uid in range(10_000):
        s = uid * 1000
        led.event("split", s, s + 1000, unit=uid)
        led.event("lease", s, s + 1000, unit=uid)
        led.event("complete", s, s + 1000, unit=uid)
    dt = time.perf_counter() - t0
    assert led.fraction() == 1.0
    assert dt < 2.0, f"30k ledger events took {dt:.2f}s"
