"""Coordinator RPC + remote worker loop (runtime/rpc.py, CLI serve/
worker).  Everything runs in-process over localhost sockets: real
framing, real threads, fake clock only where lease expiry is tested.
"""

import hashlib
import threading
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, worker_loop)
from dprf_tpu.runtime.worker import CpuWorker


def _mask_job(mask: str, plants, engine="md5", unit_size=2000):
    from dprf_tpu.runtime.session import job_fingerprint

    eng = get_engine(engine)
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest()) for p in plants]
    # identical composition to cli._build_gen/_setup_job
    fp = job_fingerprint(engine, f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": engine, "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": unit_size, "batch": 4096, "hit_cap": 8,
           "fingerprint": fp}
    return eng, gen, targets, job


def _serve(job, gen, targets, lease_timeout=300.0, clock=None):
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"],
                            lease_timeout=lease_timeout, clock=clock)
    state = CoordinatorState(job, dispatcher, len(targets))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, dispatcher


def test_two_workers_crack_everything():
    eng, gen, targets, job = _mask_job("?l?l?l", [b"cat", b"zzz"])
    state, server, _ = _serve(job, gen, targets)
    try:
        results = []

        def run_worker(wid):
            client = CoordinatorClient(*server.address)
            w = CpuWorker(eng, gen, targets)
            results.append(worker_loop(client, w, wid, idle_sleep=0.01))
            client.close()

        ts = [threading.Thread(target=run_worker, args=(f"w{i}",))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert state.finished()
        assert state.found == {0: b"cat", 1: b"zzz"}
        # every unit was processed exactly once across the worker pool
        # ("zzz" is the last candidate, so no early stop): 26^3 / 2000
        assert len(results) == 2
        assert sum(results) == -(-gen.keyspace // 2000)
    finally:
        server.shutdown()


def test_dead_worker_lease_reissued():
    """A worker that leases a unit and dies must not stall the job: the
    lease expires and another worker finishes the keyspace."""
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    eng, gen, targets, job = _mask_job("?d?d?d", [b"042"], unit_size=300)
    state, server, dispatcher = _serve(job, gen, targets,
                                       lease_timeout=10.0, clock=clk)
    try:
        dead = CoordinatorClient(*server.address)
        resp = dead.call("lease", worker_id="dead")
        assert resp["unit"] is not None     # leased, never completed
        dead.close()

        clk.t += 60.0                       # lease expires

        client = CoordinatorClient(*server.address)
        w = CpuWorker(eng, gen, targets)
        worker_loop(client, w, "alive", idle_sleep=0.01)
        client.close()
        assert state.found == {0: b"042"}
        # the dead worker's unit [0, 300) was reissued and completed by
        # the survivor (the job stops early once every target cracks)
        assert dispatcher.completed_intervals()[0][0] == 0
        assert dispatcher.completed_intervals()[0][1] >= 300
    finally:
        server.shutdown()


def test_worker_exception_releases_lease():
    eng, gen, targets, job = _mask_job("?d?d", [b"77"], unit_size=100)
    state, server, dispatcher = _serve(job, gen, targets)
    try:
        class Boom(Exception):
            pass

        class BadWorker:
            def process(self, unit):
                raise Boom()

        client = CoordinatorClient(*server.address)
        with pytest.raises(Boom):
            worker_loop(client, BadWorker(), "bad")
        client.close()
        # the failed unit went back on the queue, not into the void
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(eng, gen, targets), "good",
                    idle_sleep=0.01)
        client.close()
        assert state.found == {0: b"77"}
    finally:
        server.shutdown()


def test_cli_worker_end_to_end(capsys):
    """`dprf worker` against a live coordinator: job rebuild from the
    wire description, device-path worker selection, hit reporting."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"dog"])
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 0
        assert state.found == {0: b"dog"}
    finally:
        server.shutdown()


def test_cli_worker_fingerprint_mismatch_aborts(tmp_path):
    """A worker whose local job content fingerprints differently (e.g.
    divergent wordlist bytes on that host) must refuse to run -- a
    same-size divergence would otherwise punch silent coverage holes."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"dog"])
    job["fingerprint"] = "0" * 16           # content divergence
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "cpu", "--quiet"])
        assert rc == 2
        assert not state.found
    finally:
        server.shutdown()


def test_status_op():
    eng, gen, targets, job = _mask_job("?d?d", [b"11"])
    state, server, _ = _serve(job, gen, targets)
    try:
        client = CoordinatorClient(*server.address)
        st = client.call("status")
        assert st["total"] == gen.keyspace and st["done"] == 0
        worker_loop(client, CpuWorker(eng, gen, targets), "w",
                    idle_sleep=0.01)
        st = client.call("status")
        assert st["done"] == gen.keyspace and st["found"] == 1
        client.close()
    finally:
        server.shutdown()
