"""Coordinator RPC + remote worker loop (runtime/rpc.py, CLI serve/
worker).  Everything runs in-process over localhost sockets: real
framing, real threads, fake clock only where lease expiry is tested.
"""

import hashlib
import threading
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, worker_loop)
from dprf_tpu.runtime.worker import CpuWorker


def _mask_job(mask: str, plants, engine="md5", unit_size=2000):
    from dprf_tpu.runtime.session import job_fingerprint

    eng = get_engine(engine)
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest()) for p in plants]
    # identical composition to cli._build_gen/_setup_job
    fp = job_fingerprint(engine, f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": engine, "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": unit_size, "batch": 4096, "hit_cap": 8,
           "fingerprint": fp}
    return eng, gen, targets, job


def _serve(job, gen, targets, lease_timeout=300.0, clock=None):
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"],
                            lease_timeout=lease_timeout, clock=clock)
    state = CoordinatorState(job, dispatcher, len(targets))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, dispatcher


@pytest.mark.smoke
def test_two_workers_crack_everything():
    eng, gen, targets, job = _mask_job("?l?l?l", [b"cat", b"zzz"])
    state, server, _ = _serve(job, gen, targets)
    try:
        results = []

        def run_worker(wid):
            client = CoordinatorClient(*server.address)
            w = CpuWorker(eng, gen, targets)
            results.append(worker_loop(client, w, wid, idle_sleep=0.01))
            client.close()

        ts = [threading.Thread(target=run_worker, args=(f"w{i}",))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert state.finished()
        assert state.found == {0: b"cat", 1: b"zzz"}
        # every unit was processed exactly once across the worker pool
        # ("zzz" is the last candidate, so no early stop): 26^3 / 2000
        assert len(results) == 2
        assert sum(results) == -(-gen.keyspace // 2000)
    finally:
        server.shutdown()


def test_dead_worker_lease_reissued():
    """A worker that leases a unit and dies must not stall the job: the
    lease expires and another worker finishes the keyspace."""
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    eng, gen, targets, job = _mask_job("?d?d?d", [b"042"], unit_size=300)
    state, server, dispatcher = _serve(job, gen, targets,
                                       lease_timeout=10.0, clock=clk)
    try:
        dead = CoordinatorClient(*server.address)
        resp = dead.call("lease", worker_id="dead")
        assert resp["unit"] is not None     # leased, never completed
        dead.close()

        clk.t += 60.0                       # lease expires

        client = CoordinatorClient(*server.address)
        w = CpuWorker(eng, gen, targets)
        worker_loop(client, w, "alive", idle_sleep=0.01)
        client.close()
        assert state.found == {0: b"042"}
        # the dead worker's unit [0, 300) was reissued and completed by
        # the survivor (the job stops early once every target cracks)
        assert dispatcher.completed_intervals()[0][0] == 0
        assert dispatcher.completed_intervals()[0][1] >= 300
    finally:
        server.shutdown()


def test_worker_exception_releases_lease():
    eng, gen, targets, job = _mask_job("?d?d", [b"77"], unit_size=100)
    state, server, dispatcher = _serve(job, gen, targets)
    try:
        class Boom(Exception):
            pass

        class BadWorker:
            def process(self, unit):
                raise Boom()

        client = CoordinatorClient(*server.address)
        with pytest.raises(Boom):
            worker_loop(client, BadWorker(), "bad")
        client.close()
        # the failed unit went back on the queue, not into the void
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(eng, gen, targets), "good",
                    idle_sleep=0.01)
        client.close()
        assert state.found == {0: b"77"}
    finally:
        server.shutdown()


def test_cli_worker_end_to_end(capsys):
    """`dprf worker` against a live coordinator: job rebuild from the
    wire description, device-path worker selection, hit reporting."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"dog"])
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 0
        assert state.found == {0: b"dog"}
    finally:
        server.shutdown()


def test_cli_worker_fingerprint_mismatch_aborts(tmp_path):
    """A worker whose local job content fingerprints differently (e.g.
    divergent wordlist bytes on that host) must refuse to run -- a
    same-size divergence would otherwise punch silent coverage holes."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"dog"])
    job["fingerprint"] = "0" * 16           # content divergence
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "cpu", "--quiet"])
        assert rc == 2
        assert not state.found
    finally:
        server.shutdown()


def test_bogus_hit_rejected_by_verifier():
    """A worker reporting a plaintext that does not hash to the target
    must not poison the found set (ADVICE r1: verify hits with the
    coordinator's CPU oracle before accepting)."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"cat"])
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])

    def verifier(ti, plain):
        return eng.verify(plain, targets[ti])

    state = CoordinatorState(job, dispatcher, len(targets),
                             verifier=verifier)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        client = CoordinatorClient(*server.address)
        unit = client.call("lease", worker_id="liar")["unit"]
        resp = client.call("complete", unit_id=unit["id"],
                           hits=[{"target": 0, "cand": 0,
                                  "plaintext": b"WRONG".hex()}])
        assert not resp["ok"] and not resp["stop"]
        assert state.found == {} and state.rejected == 1
        # the unit was requeued, not marked done: the range may hold the
        # real crack the lying worker missed
        assert dispatcher.progress()[0] == 0
        reissued = client.call("lease", worker_id="honest")["unit"]
        assert reissued["start"] == unit["start"]
        client.call("complete", unit_id=reissued["id"],
                    hits=[{"target": 0, "cand": 1,
                           "plaintext": b"cat".hex()}])
        assert state.found == {0: b"cat"}
        assert dispatcher.progress()[0] == unit["length"]
        client.close()
    finally:
        server.shutdown()


def test_status_op():
    eng, gen, targets, job = _mask_job("?d?d", [b"11"])
    state, server, _ = _serve(job, gen, targets)
    try:
        client = CoordinatorClient(*server.address)
        st = client.call("status")
        assert st["total"] == gen.keyspace and st["done"] == 0
        worker_loop(client, CpuWorker(eng, gen, targets), "w",
                    idle_sleep=0.01)
        st = client.call("status")
        assert st["done"] == gen.keyspace and st["found"] == 1
        client.close()
    finally:
        server.shutdown()


@pytest.mark.smoke
def test_auth_bad_token_rejected_good_token_accepted():
    """Challenge-response on hello: a client without the shared secret
    gets no job and no ops; the right token unlocks the connection."""
    eng, gen, targets, job = _mask_job("?l?l?l", [b"cat"])
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(job, dispatcher, len(targets), token="s3cret")
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        # no token: hello yields a challenge, other ops are refused
        anon = CoordinatorClient(*server.address)
        resp = anon.call("hello")
        assert resp.get("challenge") and "job" not in resp
        with pytest.raises(RuntimeError, match="unauthenticated"):
            anon.call("lease", worker_id="anon")
        with pytest.raises(RuntimeError, match="requires authentication"):
            anon.hello()
        anon.close()

        # wrong token: the proof fails, the challenge repeats
        bad = CoordinatorClient(*server.address, token="wrong")
        with pytest.raises(RuntimeError, match="authentication failed"):
            bad.hello()
        bad.close()

        # right token: full worker loop runs
        good = CoordinatorClient(*server.address, token="s3cret")
        assert good.hello()["job"]["engine"] == "md5"
        worker_loop(good, CpuWorker(eng, gen, targets), "w",
                    idle_sleep=0.01)
        good.close()
        assert state.found == {0: b"cat"}
    finally:
        server.shutdown()


def test_cli_worker_with_token(capsys):
    eng, gen, targets, job = _mask_job("?l?l?l", [b"fox"])
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(job, dispatcher, len(targets), token="tk")
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "cpu", "--quiet", "--token", "bad"])
        assert rc == 2 and not state.found
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "cpu", "--quiet", "--token", "tk"])
        assert rc == 0 and state.found == {0: b"fox"}
    finally:
        server.shutdown()


@pytest.mark.parametrize("msg", [
    {"op": "complete"},                                  # missing unit_id
    {"op": "complete", "unit_id": "zap", "hits": []},    # non-int id
    {"op": "complete", "unit_id": 0,
     "hits": [{"target": 0, "cand": 0, "plaintext": "zz"}]},  # bad hex
    {"op": "complete", "unit_id": 0, "hits": [{}]},      # empty hit
    {"op": "complete", "unit_id": 0,
     "hits": [{"target": "x", "cand": 0, "plaintext": ""}]},
    {"op": "fail"},
    {"op": "fail", "unit_id": None},
    {"op": "lease", "worker_id": {"nested": "junk"}},
    {"op": "__init__"},
    {"op": None},
    {"no_op_at_all": 1},
])
def test_malformed_requests_never_kill_server(msg):
    """Every malformed request yields an error response (or a clean
    drop), never a dead coordinator: the job must finish afterwards."""
    from dprf_tpu.runtime.rpc import send_msg, recv_msg
    import socket as _socket

    eng, gen, targets, job = _mask_job("?d?d", [b"42"])
    # short lease: the {"op": "lease"} case grabs the only unit and never
    # completes it; the cleanup worker must not wait out a 300 s lease
    state, server, _ = _serve(job, gen, targets, lease_timeout=0.5)
    try:
        raw = _socket.create_connection(server.address, timeout=10)
        fh = raw.makefile("rb")
        send_msg(raw, msg)
        resp = recv_msg(fh)
        assert resp is not None           # server answered, didn't die
        raw.close()

        # a raw non-JSON line drops the connection but not the server
        raw2 = _socket.create_connection(server.address, timeout=10)
        raw2.sendall(b"\x00garbage, not json\n")
        raw2.close()

        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(eng, gen, targets), "w",
                    idle_sleep=0.01)
        client.close()
        assert state.found == {0: b"42"}
    finally:
        server.shutdown()


# ----------------------------------------------------- r3 robustness

def test_repeated_rejections_quarantine_worker_and_complete_unit():
    """A worker whose hits always fail verification must not livelock
    the job: after MAX_WORKER_REJECTS it is quarantined (refused
    leases), and a unit rejected MAX_UNIT_REJECTS times is completed
    with a logged warning so the job can terminate."""
    from dprf_tpu.runtime.rpc import RpcError

    eng, gen, targets, job = _mask_job("?l?l", [b"ok"], unit_size=1000)
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(
        job, dispatcher, len(targets),
        verifier=lambda ti, plain: eng.verify(plain, targets[ti]))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        class LiarWorker:
            def process(self, unit):
                from dprf_tpu.runtime.worker import Hit
                return [Hit(0, unit.start, b"zz")]   # always wrong

        client = CoordinatorClient(*server.address)
        with pytest.raises(RpcError, match="quarantined"):
            worker_loop(client, LiarWorker(), "liar", idle_sleep=0.01)
        client.close()
        assert "liar" in state.quarantined
        assert state.rejected >= CoordinatorState.MAX_WORKER_REJECTS
        # a second divergent worker is likewise benched; the unit is
        # still requeued (only 2 distinct rejecters < 3)
        client = CoordinatorClient(*server.address)
        with pytest.raises(RpcError, match="quarantined"):
            worker_loop(client, LiarWorker(), "liar2", idle_sleep=0.01)
        client.close()
        assert not state.finished()
        # an honest worker now takes the requeued unit and cracks it
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(eng, gen, targets), "honest",
                    idle_sleep=0.01)
        client.close()
        assert state.finished()
        assert state.found == {0: b"ok"}
    finally:
        server.shutdown()


def test_unit_force_completes_after_distinct_worker_rejections():
    """When MAX_UNIT_REJECT_WORKERS distinct workers all produce
    unverifiable hits for one unit, it completes with a logged hole so
    the job can terminate (no honest worker exists to save it)."""
    from dprf_tpu.runtime.rpc import RpcError

    eng, gen, targets, job = _mask_job("?l?l", [b"ok"], unit_size=1000)
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(job, dispatcher, len(targets),
                             verifier=lambda ti, plain: False)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        class LiarWorker:
            def process(self, unit):
                from dprf_tpu.runtime.worker import Hit
                return [Hit(0, unit.start, b"zz")]

        for i in range(CoordinatorState.MAX_UNIT_REJECT_WORKERS):
            client = CoordinatorClient(*server.address)
            try:
                worker_loop(client, LiarWorker(), f"liar{i}",
                            idle_sleep=0.01)
            except RpcError:
                pass      # quarantined after its rejections
            client.close()
        # keyspace exhausted via the force-complete: job terminates
        # with the target uncracked (the logged coverage hole)
        assert state.finished()
        assert state.found == {}
    finally:
        server.shutdown()


def test_connection_drop_without_stop_raises():
    """A coordinator crash mid-job must NOT look like a clean drain:
    a connection closed at the lease boundary with no stop signal seen
    raises instead of returning success."""
    import json as _json
    import socket as _socket

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def fake_coordinator():
        conn, _ = srv.accept()
        fh = conn.makefile("rb")
        fh.readline()                       # first lease poll
        conn.sendall(_json.dumps(
            {"unit": None, "stop": False}).encode() + b"\n")
        fh.readline()                       # second lease poll
        conn.close()                        # "crash": bare drop, no stop

    t = threading.Thread(target=fake_coordinator, daemon=True)
    t.start()
    client = CoordinatorClient(*srv.getsockname())

    class NeverCalled:
        def process(self, unit):
            raise AssertionError("no unit should ever be leased")

    with pytest.raises(ConnectionError, match="before any stop"):
        worker_loop(client, NeverCalled(), "w", idle_sleep=0.01)
    client.close()
    srv.close()


def test_auth_nonce_rotates_and_connection_drops():
    """Each failed hello gets a FRESH challenge, and the connection is
    dropped after MAX_AUTH_FAILURES failed guesses."""
    import json as _json
    import socket as _socket

    eng, gen, targets, job = _mask_job("?l?l", [b"aa"])
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(job, dispatcher, len(targets), token="tk")
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        sock = _socket.create_connection(server.address, timeout=10)
        fh = sock.makefile("rb")
        challenges = []
        for _ in range(3):
            sock.sendall(b'{"op": "hello", "hmac": "00"}\n')
            line = fh.readline()
            if not line:
                break
            resp = _json.loads(line)
            assert resp.get("ok") is False
            challenges.append(resp["challenge"])
        # every challenge distinct: no fixed nonce to grind against
        assert len(challenges) == len(set(challenges)) == 3
        # 4th attempt: server has dropped the connection
        try:
            sock.sendall(b'{"op": "hello", "hmac": "00"}\n')
            assert fh.readline() == b""
        except (BrokenPipeError, ConnectionResetError):
            pass
        sock.close()
    finally:
        server.shutdown()


def test_mutual_auth_worker_rejects_tokenless_coordinator():
    """A worker holding --token must refuse a coordinator that cannot
    prove knowledge of the same token (spoofed-coordinator defense)."""
    from dprf_tpu.runtime.rpc import RpcError

    eng, gen, targets, job = _mask_job("?l?l", [b"aa"])
    # coordinator WITHOUT a token (stands in for a spoofed one)
    state, server, _ = _serve(job, gen, targets)
    try:
        client = CoordinatorClient(*server.address, token="tk")
        with pytest.raises(RpcError, match="mutual"):
            client.hello()
        client.close()
    finally:
        server.shutdown()


def test_mutual_auth_good_token_passes():
    eng, gen, targets, job = _mask_job("?l?l", [b"aa"])
    dispatcher = Dispatcher(gen.keyspace, job["unit_size"])
    state = CoordinatorState(job, dispatcher, len(targets), token="tk")
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        client = CoordinatorClient(*server.address, token="tk")
        resp = client.hello()
        assert resp["ok"] and "job" in resp
        client.close()
    finally:
        server.shutdown()


def test_hashlist_dedupes_same_digest_different_case():
    from dprf_tpu.utils.hashlist import parse_lines

    eng = get_engine("md5")
    d = hashlib.md5(b"pw").hexdigest()
    res = parse_lines(eng, [d, d.upper(), d])
    assert len(res.targets) == 1
    assert res.duplicates == 2


def test_cli_worker_combinator_job(tmp_path, capsys):
    """A distributed combinator job: the worker rebuilds the left/right
    tables from the wire description (files must exist on its host)
    and cracks the planted concatenation."""
    from dprf_tpu.generators.combinator import CombinatorGenerator
    from dprf_tpu.runtime.session import job_fingerprint

    lp = tmp_path / "l.txt"
    lp.write_text("red\nblue\n")
    rp = tmp_path / "r.txt"
    rp.write_text("fish\nbird\n")
    eng = get_engine("md5")
    gen = CombinatorGenerator([b"red", b"blue"], [b"fish", b"bird"],
                              max_len=55)
    targets = [eng.parse_target(hashlib.md5(b"bluebird").hexdigest())]
    attack_arg = f"{lp},{rp}"
    fp = job_fingerprint("md5", f"combinator:{gen.content_id()}",
                         gen.keyspace, [t.digest for t in targets])
    job = {"engine": "md5", "attack": "combinator",
           "attack_arg": attack_arg, "customs": {}, "rules": None,
           "max_len": 55, "targets": [t.raw for t in targets],
           "keyspace": gen.keyspace, "unit_size": 4, "batch": 64,
           "hit_cap": 8, "fingerprint": fp}
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 0
        assert state.found == {0: b"bluebird"}
    finally:
        server.shutdown()


def test_cli_worker_phpass_job(capsys):
    """A distributed slow-hash job (phpass): the worker rebuilds the
    salted engine from the wire description and cracks the target."""
    from dprf_tpu.engines.cpu.phpass import phpass_hash
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.session import job_fingerprint

    eng = get_engine("phpass")
    gen = MaskGenerator("?l?d")
    secret = b"k7"
    line = phpass_hash(secret, b"abcdefgh", 7)
    targets = [eng.parse_target(line)]
    fp = job_fingerprint("phpass", "mask:?l?d", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "phpass", "attack": "mask", "attack_arg": "?l?d",
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": 128, "batch": 256, "hit_cap": 8,
           "fingerprint": fp}
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 0
        assert state.found == {0: secret}
    finally:
        server.shutdown()


def test_cli_worker_markov_job(tmp_path, capsys):
    """A distributed Markov-ordered mask job: the worker rebuilds the
    reordered keyspace from the shipped stats path, and divergent
    stats content fails the fingerprint check instead of leaving
    coverage holes."""
    from dprf_tpu.generators.markov import save_stats, stats_digest, \
        train_stats
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.runtime.session import job_fingerprint

    counts = train_stats([b"pat", b"pig", b"cat"])
    stats = tmp_path / "s.dprfstat"
    save_stats(str(stats), counts)
    eng = get_engine("md5")
    gen = MaskGenerator("?l?l?l", markov_counts=counts)
    targets = [eng.parse_target(hashlib.md5(b"pig").hexdigest())]
    desc = f"mask:?l?l?l:markov={stats_digest(counts)}"
    fp = job_fingerprint("md5", desc, gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "md5", "attack": "mask", "attack_arg": "?l?l?l",
           "customs": {}, "rules": None, "markov": str(stats),
           "max_len": None, "targets": [t.raw for t in targets],
           "keyspace": gen.keyspace, "unit_size": 1 << 12,
           "batch": 1 << 12, "hit_cap": 8, "fingerprint": fp}
    state, server, _ = _serve(job, gen, targets)
    try:
        host, port = server.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 0
        assert state.found == {0: b"pig"}
    finally:
        server.shutdown()

    # divergent stats on the worker host: fingerprint mismatch, rc 2
    save_stats(str(stats), train_stats([b"zzz"]))
    state2, server2, _ = _serve(job, gen, targets)
    try:
        host, port = server2.address
        rc = cli_main(["worker", "--connect", f"{host}:{port}",
                       "--device", "tpu", "--quiet"])
        assert rc == 2
    finally:
        server2.shutdown()
