"""The driver's entry points must always compile and run."""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    jax.jit(fn).lower(*args).compile()
    count, lanes, tpos = fn(*args)
    # batch 8192 covers indices [0, 8192) of ?l^6: 'aaaaaa' is index 0.
    assert int(count) >= 1
    import numpy as np
    assert 0 in np.asarray(lanes)


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
