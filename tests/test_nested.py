"""Nested double-hash engines: oracle equivalence, planted-password
cracks through the standard workers (mask, multi-target, wordlist),
and CLI."""

import hashlib

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit

COMBOS = ["md5(md5)", "sha1(sha1)", "md5(sha1)", "sha1(md5)",
          "sha256(md5)", "sha256(sha1)"]


def _nested(outer, inner, plain):
    return hashlib.new(
        outer, hashlib.new(inner, plain).hexdigest().encode()).digest()


@pytest.mark.parametrize("name", COMBOS)
def test_device_matches_oracle(name):
    import random
    outer, inner = name[:-1].split("(")
    dev = get_engine(name, "jax")
    cpu = get_engine(name, "cpu")
    rng = random.Random(7)
    cands = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 30)))
             for _ in range(40)]
    want = [_nested(outer, inner, c) for c in cands]
    assert cpu.hash_batch(cands) == want
    assert dev.hash_batch(cands) == want


def test_mask_worker_cracks_nested():
    dev = get_engine("md5(md5)", "jax")
    cpu = get_engine("md5(md5)", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"j4k"
    t = dev.parse_target(_nested("md5", "md5", secret).hex())
    w = dev.make_mask_worker(gen, [t], batch=1024, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_multi_target_nested():
    dev = get_engine("sha1(md5)", "jax")
    cpu = get_engine("sha1(md5)", "cpu")
    gen = MaskGenerator("?d?d?d")
    secrets = [b"042", b"777", b"999"]
    targets = [dev.parse_target(_nested("sha1", "md5", s).hex())
               for s in secrets]
    w = dev.make_mask_worker(gen, targets, batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = sorted((h.target_index, h.plaintext)
                  for h in w.process(WorkUnit(0, 0, gen.keyspace)))
    assert hits == [(0, b"042"), (1, b"777"), (2, b"999")]


def test_sharded_nested_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("sha256(sha1)", "jax")
    gen = MaskGenerator("?l?l")
    secret = b"qx"
    t = dev.parse_target(_nested("sha256", "sha1", secret).hex())
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=32, hit_capacity=8)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_nested_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    digest = _nested("md5", "md5", b"za9").hex()
    hf = tmp_path / "h.txt"
    hf.write_text(digest + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "md5(md5)",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{digest}:za9" in out


def test_mysql41_matches_oracle_and_cracks(tmp_path, capsys):
    """MySQL 4.1+ (*HEX double-SHA1 over RAW bytes): oracle match,
    '*'-prefixed parsing, CLI crack."""
    import random
    from dprf_tpu.cli import main

    dev = get_engine("mysql41", "jax")
    cpu = get_engine("mysql41", "cpu")
    rng = random.Random(301)
    cands = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 30)))
             for _ in range(32)]
    want = [hashlib.sha1(hashlib.sha1(c).digest()).digest()
            for c in cands]
    assert cpu.hash_batch(cands) == want
    assert dev.hash_batch(cands) == want

    # the classic published example: PASSWORD('password')
    line = "*2470C0C06DEE42FD1618BB99005ADCA2EC9D1E19"
    t = cpu.parse_target(line)
    assert cpu.verify(b"password", t)

    secret = b"pw7"
    digest = hashlib.sha1(hashlib.sha1(secret).digest()).hexdigest()
    hf = tmp_path / "h.txt"
    hf.write_text("*" + digest.upper() + "\n")
    rc = main(["crack", "?l?l?d", str(hf), "--engine", "mysql41",
               "--device", "tpu", "--no-potfile", "--batch", "1024",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and ":pw7" in out
