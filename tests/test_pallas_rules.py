"""Rules-kernel (ops/pallas_rules.py) vs the CPU rule engine.

Strategy: the interpreter's SEMANTICS are tested by running
_interp_step eagerly (plain jnp on CPU, no pallas machinery) over a
lane-packed word batch and comparing bytes/lengths/validity against
rules/cpu.py for EVERY word x EVERY supported opcode -- stronger than
digest-level checks and fast.  The pallas plumbing (grid, SMEM
bytecode, varlen pack, digest, lane mapping, bucketing) is covered by
one small interpret-mode end-to-end test plus the worker tests; the
full best64 job is proven on real hardware (TPU_RESULTS_r04
rules_kernel stage).
"""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops import pallas_rules as pr
from dprf_tpu.rules.cpu import apply_rule as apply_rule_cpu
from dprf_tpu.rules.parser import parse_rule
from dprf_tpu.runtime.workunit import WorkUnit

WORDS = ([b"alpha", b"bravo", b"s3cret", b"Delta", b"echo99",
          b"FOXtrot", b"g0lf!", b"hotellll", b"in", b"j", b"",
          b"aAzZ09!~", b"xxxxxxxxxxxxxxxx"]
         + [b"w%02d" % i for i in range(19)])     # 32 words = 1 row

#: one rule per supported opcode family (p1/p2 chosen so some of
#: WORDS survive and some fail the guards), plus multi-op chains
RULES = [":", "l", "u", "c", "C", "t", "T2", "r", "d", "p2", "f",
         "{", "}", "[", "]", "D2", "x12", "O12", "i2X", "o2Y", "'3",
         "se3", "z2", "Z2", "q", "k", "K", "*03", "L2", "R2", "+2",
         "-2", ".2", ",2", "y2", "Y2", "$!", "^#", "<5", ">3", "_6",
         "!x", "/e", "(a", ")o", "=1e", "%2e", "c $1 $2 $3", "u r ]"]

L = 16


def _lane_pack(words):
    """words -> (w tuple of L int32[(8,128)], lens, valid) with word i
    at sublane i//128, lane i%128 (only the first len(words) lanes are
    meaningful)."""
    shape = (8, 128)
    wb = np.zeros((8 * 128, L), np.int32)
    lens = np.zeros((8 * 128,), np.int32)
    for i, wd in enumerate(words):
        wb[i, :len(wd)] = np.frombuffer(wd, np.uint8)
        lens[i] = len(wd)
    w = tuple(jnp.asarray(wb[:, q].reshape(shape)) for q in range(L))
    return w, jnp.asarray(lens.reshape(shape)), \
        jnp.ones(shape, jnp.int32)


@pytest.mark.parametrize("rule", RULES)
def test_interp_step_matches_cpu(rule):
    """Every opcode family: _interp_step (eager) == rules/cpu.py on
    every word, byte for byte, including lengths and rejections."""
    ops = parse_rule(rule)
    w, lens, valid = _lane_pack(WORDS)
    for op in ops:
        w, lens, valid = pr._interp_step(
            w, lens, valid, jnp.int32(int(op.opcode)),
            jnp.int32(op.p1), jnp.int32(op.p2), L, (8, 128))
    wb = np.stack([np.asarray(x).reshape(-1) for x in w], axis=1)
    lens = np.asarray(lens).reshape(-1)
    valid = np.asarray(valid).reshape(-1)
    for i, word in enumerate(WORDS):
        want = apply_rule_cpu(word, ops, L)
        if want is None:
            assert valid[i] == 0, (rule, word)
        else:
            assert valid[i] == 1, (rule, word)
            got = bytes(wb[i, :lens[i]].astype(np.uint8))
            assert got == want, (rule, word, got, want)
            # zero-tail invariant
            assert not wb[i, lens[i]:].any(), (rule, word)


def test_small_end_to_end_interpret():
    """One small interpret-mode job through the full pallas chain:
    bucketed kernels, SMEM bytecode, varlen pack, digest, runtime
    target, flat-lane mapping."""
    words = [b"alpha", b"bravo", b"s3cret"] + [b"w%03d" % i
                                              for i in range(300)]
    rules = [parse_rule(":"), parse_rule("d"), parse_rule("c $!")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    step = pr.make_rules_crack_step(
        "md5", gen, np.full((4,), 0xFFFFFFFF, np.uint32),
        word_batch=1024, interpret=True)
    B = step.word_batch
    for (wi, ri) in ((2, 1), (1, 2)):
        plain = apply_rule_cpu(words[wi], rules[ri], 16)
        tgt = jnp.asarray(np.frombuffer(hashlib.md5(plain).digest(),
                                        "<u4").astype(np.uint32)
                          .view(np.int32))
        c, lanes, _ = step(jnp.int32(0), jnp.int32(gen.n_words),
                           target=tgt)
        got = np.asarray(lanes)
        assert int(c) == 1 and list(got[got >= 0]) == [ri * B + wi]


def test_all_best64_opcodes_supported():
    from dprf_tpu.rules.parser import load_rules
    assert pr.rules_supported(load_rules("best64"))


def test_rules_supported_rejects_purge_title():
    assert not pr.rules_supported([parse_rule("@x")])
    assert not pr.rules_supported([parse_rule("E")])
    assert not pr.rules_supported([parse_rule(":" * (pr.MAX_STEPS + 1))])


def test_step_buckets():
    rules = [parse_rule(r) for r in (":", "u r", "c $1 $2 $3", "$a")]
    assert pr.step_buckets(rules) == {1: [0, 3], 2: [1], 4: [2]}
    assert pr.ceil_pow2(1) == 1 and pr.ceil_pow2(3) == 4 \
        and pr.ceil_pow2(8) == 8


def test_worker_selected_and_cracks(monkeypatch):
    """DPRF_PALLAS=1 routes an eligible single-target wordlist job to
    the kernel worker; hits carry correct keyspace indices."""
    from dprf_tpu.runtime.worker import PallasWordlistWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")
    words = [b"alpha", b"bravo", b"s3cret"] + [b"w%03d" % i
                                              for i in range(300)]
    rules = [parse_rule(":"), parse_rule("d")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    cpu = get_engine("md5", device="cpu")
    dev = get_engine("md5", device="jax")
    plain = apply_rule_cpu(words[2], rules[1], 16)
    t = cpu.parse_target(hashlib.md5(plain).hexdigest())
    w = dev.make_wordlist_worker(gen, [t], batch=1 << 16,
                                 hit_capacity=8, oracle=cpu)
    assert isinstance(w, PallasWordlistWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.cand_index) for h in hits} == \
        {(0, gen.index_of(2, 1))}
    for h in hits:
        assert cpu.hash_batch([h.plaintext])[0] == t.digest


def test_worker_falls_back_multi_target(monkeypatch):
    from dprf_tpu.runtime.worker import (DeviceWordlistWorker,
                                         PallasWordlistWorker)

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = WordlistRulesGenerator(WORDS, [parse_rule(":")], max_len=16)
    cpu = get_engine("md5", device="cpu")
    dev = get_engine("md5", device="jax")
    ts = [cpu.parse_target(hashlib.md5(b"x%d" % i).hexdigest())
          for i in range(3)]
    w = dev.make_wordlist_worker(gen, ts, batch=1 << 16,
                                 hit_capacity=8, oracle=cpu)
    assert isinstance(w, DeviceWordlistWorker)
    assert not isinstance(w, PallasWordlistWorker)


def test_worker_falls_back_unsupported_rule(monkeypatch):
    from dprf_tpu.runtime.worker import (DeviceWordlistWorker,
                                         PallasWordlistWorker)

    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = WordlistRulesGenerator(WORDS, [parse_rule(":"),
                                         parse_rule("@x")], max_len=16)
    cpu = get_engine("md5", device="cpu")
    dev = get_engine("md5", device="jax")
    t = cpu.parse_target(hashlib.md5(b"nothing").hexdigest())
    w = dev.make_wordlist_worker(gen, [t], batch=1 << 16,
                                 hit_capacity=8, oracle=cpu)
    assert isinstance(w, DeviceWordlistWorker)
    assert not isinstance(w, PallasWordlistWorker)


def test_worker_non_aligned_units(monkeypatch):
    """WorkUnits whose word start is NOT TILE_W-aligned must decode
    hits at the correct keyspace indices (regression: the first kernel
    floored w0 to the tile boundary, hashing the wrong words)."""
    from dprf_tpu.runtime.worker import PallasWordlistWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")
    words = [b"w%04d" % i for i in range(2000)]
    plant_word = 1500
    words[plant_word] = b"s3cret"
    rules = [parse_rule(":"), parse_rule("d"), parse_rule("$!")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    cpu = get_engine("md5", device="cpu")
    dev = get_engine("md5", device="jax")
    plain = apply_rule_cpu(b"s3cret", rules[1], 16)
    t = cpu.parse_target(hashlib.md5(plain).hexdigest())
    w = dev.make_wordlist_worker(gen, [t], batch=1 << 12,
                                 hit_capacity=8, oracle=cpu)
    assert isinstance(w, PallasWordlistWorker)
    # a unit starting mid-tile: word start = 300 (not a multiple of
    # TILE_W=1024), covering the planted word
    unit = WorkUnit(0, 300 * gen.n_rules, (1990 - 300) * gen.n_rules)
    hits = w.process(unit)
    assert {(h.target_index, h.cand_index) for h in hits} == \
        {(0, gen.index_of(plant_word, 1))}
    for h in hits:
        assert cpu.hash_batch([h.plaintext])[0] == t.digest
