"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

Validates that the shard_map crack step produces exactly the hits the
single-device fused step (and the CPU oracle) produce, that the psum'd
total matches per-shard counts, and that the sharded worker cracks an
end-to-end planted-password job.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.base import Target
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.pipeline import make_mask_crack_step, target_words
from dprf_tpu.parallel import (ShardedMaskWorker, make_mesh,
                               make_sharded_mask_step)
from dprf_tpu.runtime.workunit import WorkUnit


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should fake 8 CPU devices"
    return make_mesh(8)


def _ntlm(pw: bytes) -> bytes:
    from dprf_tpu.engines.cpu.md4 import md4
    return md4(bytes(b for ch in pw for b in (ch, 0)))


def test_mesh_shape(mesh):
    assert mesh.devices.shape == (8,)
    assert mesh.axis_names == ("candidates",)


def test_sharded_md5_finds_planted_password(mesh):
    gen = MaskGenerator("?l?l?l?l")
    pw = b"crab"
    idx = gen.index_of(pw)
    tgt = target_words(hashlib.md5(pw).digest(), little_endian=True)
    engine = get_engine("md5", device="jax")
    step = make_sharded_mask_step(engine, gen, tgt, mesh,
                                        batch_per_device=1024)
    super_batch = 8 * 1024
    bstart = (idx // super_batch) * super_batch
    base = jnp.asarray(gen.digits(bstart), dtype=jnp.int32)
    total, counts, lanes, tpos = step(base, jnp.int32(super_batch))
    assert int(total) == 1
    assert int(counts.sum()) == 1
    lanes_np = np.asarray(lanes)
    hit_lanes = lanes_np[lanes_np >= 0]
    assert list(hit_lanes) == [idx - bstart]


def test_sharded_matches_single_device_step(mesh):
    """Same super-batch through the 8-shard step and the 1-device step."""
    gen = MaskGenerator("?l?l?l?l")
    engine = get_engine("md5", device="jax")
    # plant several targets inside one super-batch
    super_batch = 8 * 512
    bstart = 3 * super_batch
    plant_idx = [bstart + 7, bstart + 600, bstart + 2048, bstart + 4095]
    digests = [hashlib.md5(gen.candidate(i)).digest() for i in plant_idx]
    table = cmp_ops.make_target_table(digests, little_endian=True)

    sh_step = make_sharded_mask_step(engine, gen, table, mesh,
                                           batch_per_device=512)
    single = make_mask_crack_step(engine, gen, table, batch=super_batch)

    base = jnp.asarray(gen.digits(bstart), dtype=jnp.int32)
    total, counts, lanes, tpos = sh_step(base, jnp.int32(super_batch))
    s_count, s_lanes, s_tpos = single(base, jnp.int32(super_batch))

    assert int(total) == int(s_count) == len(plant_idx)
    sh_pairs = sorted((int(l), int(t))
                      for l, t in zip(np.asarray(lanes).ravel(),
                                      np.asarray(tpos).ravel()) if l >= 0)
    s_pairs = sorted((int(l), int(t))
                     for l, t in zip(np.asarray(s_lanes),
                                     np.asarray(s_tpos)) if l >= 0)
    assert sh_pairs == s_pairs
    assert [p[0] + bstart for p in sh_pairs] == plant_idx


def test_sharded_respects_n_valid(mesh):
    """Lanes past n_valid must not report hits even if they match."""
    gen = MaskGenerator("?d?d?d")
    engine = get_engine("md5", device="jax")
    idx = gen.index_of(b"777")
    tgt = target_words(hashlib.md5(b"777").digest(), little_endian=True)
    step = make_sharded_mask_step(engine, gen, tgt, mesh,
                                        batch_per_device=128)
    base = jnp.asarray(gen.digits(0), dtype=jnp.int32)
    total, *_ = step(base, jnp.int32(idx))       # 777 is lane idx: excluded
    assert int(total) == 0
    total, *_ = step(base, jnp.int32(idx + 1))   # included
    assert int(total) == 1


def test_sharded_ntlm_multi_target_worker(mesh):
    """End-to-end: sharded NTLM worker over a unit spanning super-batches."""
    gen = MaskGenerator("?l?l?l")
    pws = [b"abc", b"xyz", b"qqq"]
    targets = [Target(p.decode(), _ntlm(p)) for p in pws]
    engine = get_engine("ntlm", device="jax")
    w = ShardedMaskWorker(engine, gen, targets, mesh, batch_per_device=256)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert len(hits) == 3
    got = {h.plaintext: h.target_index for h in hits}
    assert got == {b"abc": 0, b"xyz": 1, b"qqq": 2}
    for h in hits:
        assert gen.candidate(h.cand_index) == h.plaintext


def test_sharded_overflow_rescan_no_duplicates(mesh):
    """An overflowing shard triggers a full super-batch rescan; hits from
    non-overflowed shards must not be double-reported."""
    gen = MaskGenerator("?d?d?d")
    # hit_capacity=2: make shard 1 overflow (3 hits in its lane range)
    # while shard 0 has a normal hit.
    batch = 32
    pws = [b"005",                        # shard 0 (lanes 0..31)
           b"033", b"040", b"050",        # shard 1 (lanes 32..63): overflow
           ]
    targets = [Target(p.decode(), hashlib.md5(p).digest()) for p in pws]
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=batch, hit_capacity=2,
                          oracle=get_engine("md5", device="cpu"))
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted(h.plaintext for h in hits) == sorted(pws)
    assert len(hits) == len(set(h.cand_index for h in hits)) == 4


def test_sharded_worker_matches_cpu_worker(mesh):
    from dprf_tpu.runtime.worker import CpuWorker
    gen = MaskGenerator("?d?d?d?d")
    pws = [b"0042", b"9999", b"1234"]
    targets = [Target(p.decode(), hashlib.sha256(p).digest()) for p in pws]
    dev = ShardedMaskWorker(get_engine("sha256", device="jax"), gen, targets,
                            mesh, batch_per_device=128)
    cpu = CpuWorker(get_engine("sha256", device="cpu"), gen, targets)
    unit = WorkUnit(0, 0, gen.keyspace)
    dev_hits = sorted((h.target_index, h.cand_index, h.plaintext)
                      for h in dev.process(unit))
    cpu_hits = sorted((h.target_index, h.cand_index, h.plaintext)
                      for h in cpu.process(unit))
    assert dev_hits == cpu_hits == [
        (0, gen.index_of(b"0042"), b"0042"),
        (1, gen.index_of(b"9999"), b"9999"),
        (2, gen.index_of(b"1234"), b"1234"),
    ]


# ------------------------------------------------- salted engines (r3)

def test_sharded_bcrypt_mask_worker(mesh):
    """Config 4's engine on the 8-chip mesh: planted password found,
    hits identical to the single-chip worker."""
    from dprf_tpu.engines.cpu.bcrypt import bcrypt_hash
    from dprf_tpu.engines.device.bcrypt import (BcryptMaskWorker,
                                                ShardedBcryptMaskWorker)

    eng = get_engine("bcrypt", device="jax")
    cpu = get_engine("bcrypt", device="cpu")
    gen = MaskGenerator("?d?d?l")
    pw = b"42x"
    line = bcrypt_hash(pw, bytes(range(16)), cost=4)
    targets = [cpu.parse_target(line)]
    sharded = ShardedBcryptMaskWorker(eng, gen, targets, mesh,
                                      batch_per_device=32)
    hits = sharded.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, pw)]
    single = BcryptMaskWorker(eng, gen, targets, batch=256)
    assert ([(h.target_index, h.cand_index, h.plaintext)
             for h in single.process(WorkUnit(0, 0, gen.keyspace))]
            == [(h.target_index, h.cand_index, h.plaintext) for h in hits])


def test_sharded_bcrypt_wordlist_worker(mesh):
    from dprf_tpu.engines.cpu.bcrypt import bcrypt_hash
    from dprf_tpu.engines.device.bcrypt import ShardedBcryptWordlistWorker
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    eng = get_engine("bcrypt", device="jax")
    cpu = get_engine("bcrypt", device="cpu")
    words = [b"alpha", b"beta", b"gamma", b"delta", b"omega"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules)
    pw = b"GAMMA"        # gamma + 'u' rule
    line = bcrypt_hash(pw, bytes(range(16)), cost=4)
    targets = [cpu.parse_target(line)]
    w = ShardedBcryptWordlistWorker(eng, gen, targets, mesh,
                                    word_batch_per_device=2)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, pw)]
    assert gen.candidate(hits[0].cand_index) == pw


def test_sharded_pmkid_worker(mesh):
    """Config 5's pod-scale path on the fake mesh, including the
    multi-match lane (same passphrase cracking two captures)."""
    import hashlib as _hl
    import hmac as _hmac
    from dprf_tpu.engines.device.pmkid import ShardedPmkidWorker

    eng = get_engine("wpa2-pmkid", device="jax")
    cpu = get_engine("wpa2-pmkid", device="cpu")
    eng.iterations = cpu.iterations = 64
    try:
        gen = MaskGenerator("pw?d?d")
        ap = bytes.fromhex("aabbccddeeff")
        sta = bytes.fromhex("112233445566")

        def line(pw, essid):
            pmk = _hl.pbkdf2_hmac("sha1", pw, essid, 64, 32)
            pmkid = _hmac.new(pmk, b"PMK Name" + ap + sta,
                              _hl.sha1).digest()[:16]
            return f"{pmkid.hex()}*{ap.hex()}*{sta.hex()}*{essid.hex()}"

        targets = [cpu.parse_target(line(b"pw37", b"NetA")),
                   cpu.parse_target(line(b"pw55", b"NetB")),
                   cpu.parse_target(line(b"pw55", b"NetA"))]
        w = ShardedPmkidWorker(eng, gen, targets, mesh,
                               batch_per_device=8, oracle=cpu)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        got = sorted((h.target_index, h.plaintext) for h in hits)
        assert got == [(0, b"pw37"), (1, b"pw55"), (2, b"pw55")]
    finally:
        del eng.iterations, cpu.iterations     # restore class attrs


def test_multihost_init_and_crack_subprocess():
    """init_multihost (jax.distributed) with an explicit 1-process
    coordinator, then a sharded crack over the virtual mesh -- run in a
    subprocess so the distributed global state can't leak into other
    tests.  Exercises the same code path a real pod slice uses."""
    import os
    import subprocess
    import sys

    code = r"""
import hashlib
import jax
jax.config.update("jax_platforms", "cpu")
from dprf_tpu.parallel.mesh import init_multihost
assert init_multihost("localhost:12757", 1, 0) is True
assert init_multihost() is False          # idempotent second call
assert jax.process_index() == 0 and jax.process_count() == 1
import jax.numpy as jnp
import numpy as np
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.pipeline import target_words
from dprf_tpu.parallel import make_mesh, make_sharded_mask_step
gen = MaskGenerator("?l?l?l")
pw = b"fox"
idx = gen.index_of(pw)
tgt = target_words(hashlib.md5(pw).digest(), little_endian=True)
step = make_sharded_mask_step(get_engine("md5", device="jax"),
                                    gen, tgt, make_mesh(8), 64)
base = jnp.asarray(gen.digits(0), dtype=jnp.int32)
for bstart in range(0, gen.keyspace, 512):
    base = jnp.asarray(gen.digits(bstart), dtype=jnp.int32)
    total, counts, lanes, tpos = step(base, jnp.int32(
        min(512, gen.keyspace - bstart)))
    if int(total):
        lanes_np = np.asarray(lanes)
        assert bstart + int(lanes_np[lanes_np >= 0][0]) == idx
        print("MULTIHOST_OK")
        break
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTIHOST_OK" in proc.stdout


@pytest.mark.xfail(
    reason="multi-process CPU collectives (jax.distributed over Gloo "
    "between two host processes) are unimplemented in jax 0.4.37: "
    "the cross-process mesh never forms on the CPU backend, so both "
    "ranks abort at init; single-process multi-device coverage "
    "(test_multihost_init_and_crack_subprocess above) keeps the SPMD "
    "crack path tested",
    run=False)
def test_multihost_two_process_crack(tmp_path):
    """The REAL multi-process DCN path (VERDICT r4 missing #4): two
    separate OS processes, each with 4 local virtual CPU devices, form
    one 8-device mesh via `jax.distributed` (Gloo collectives) and run
    the SAME `dprf crack --multihost` command SPMD.  Process 0 owns the
    potfile; both observe the planted hit through the replicated
    buffers and exit 0.  This is the only in-environment proof that the
    cross-host mesh actually forms and the sharded step's collectives
    run over a process boundary."""
    import os
    import socket
    import subprocess
    import sys

    pw = b"fox"
    digest = hashlib.md5(pw).hexdigest()
    hashfile = tmp_path / "hashes.txt"
    hashfile.write_text(digest + "\n")
    pot = tmp_path / "mh.pot"

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))

    def free_port() -> int:
        with socket.socket() as s:      # free TCP port for the
            s.bind(("127.0.0.1", 0))    # jax.distributed coordinator
            return s.getsockname()[1]

    def spawn(rank: int, port: int):
        argv = [sys.executable, "-m", "dprf_tpu", "crack",
                "?l?l?l", str(hashfile), "--engine", "md5",
                "--device", "tpu", "--devices", "8", "--multihost",
                "--coordinator-address", f"127.0.0.1:{port}",
                "--num-processes", "2", "--process-id", str(rank),
                "--potfile", str(pot), "--unit-size", "4096",
                "--batch", "512", "-q"]
        return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    def attempt():
        port = free_port()
        procs = [spawn(0, port), spawn(1, port)]
        results = []
        try:
            for p in procs:
                results.append(p.communicate(timeout=600) +
                               (p.returncode,))
        finally:
            for q in procs:   # on any failure, don't orphan the peer
                if q.poll() is None:
                    q.kill()
                    q.communicate()
        return results

    results = attempt()
    if any(rc != 0 and "bind" in err.lower() for _, err, rc in results):
        results = attempt()   # free_port TOCTOU: retry on a new port
    for rank, (_, err, rc) in enumerate(results):
        assert rc == 0, f"rank {rank}: {err[-2000:]}"
    # process 0 owns the potfile and prints the crack
    assert f"{digest}:fox" in results[0][0]
    from dprf_tpu.runtime.potfile import Potfile
    assert Potfile(str(pot)).get(digest) == pw


def test_sharded_keccak_worker(mesh):
    """Round 4b: the sha3/keccak family rides the generic sharded
    worker via the digest_candidates hook (previously --devices N on
    this family had no path)."""
    gen = MaskGenerator("?l?l?l?l")
    pw = b"toad"
    idx = gen.index_of(pw)
    dev = get_engine("sha3-256", device="jax")
    t = dev.parse_target(hashlib.sha3_256(pw).hexdigest())
    w = dev.make_sharded_mask_worker(gen, [t], mesh,
                                     batch_per_device=1024,
                                     hit_capacity=8,
                                     oracle=get_engine("sha3-256"))
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, idx, pw)]


def test_sharded_keccak_wordlist_worker(mesh):
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    words = [b"alpha", b"bravo", b"charlie"] + \
        [b"w%03d" % i for i in range(200)]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=12)
    dev = get_engine("keccak-256", device="jax")
    cpu = get_engine("keccak-256")
    plant = b"BRAVO"                     # rule 'u' on word 1
    t = dev.parse_target(cpu.hash_batch([plant])[0].hex())
    w = dev.make_sharded_wordlist_worker(gen, [t], mesh,
                                         word_batch_per_device=16,
                                         hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 1 * gen.n_rules + 1, plant)]
