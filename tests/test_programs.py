"""Device introspection plane (ISSUE 13): compiled-program registry,
XLA-derived rooflines, HBM accounting, the op_programs surface, the
peak-memory regression gate, and the report memory section.

Runs entirely on the CPU backend: ``compiled.cost_analysis()`` /
``memory_analysis()`` work there, while ``device.memory_stats()``
returns None -- exactly the graceful-degrade half the tests pin.
"""

import hashlib
import json

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the
# <5-min smoke tier (tools/check_markers.py enforces a tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.telemetry import DEFAULT as METRICS
from dprf_tpu.telemetry import devstats
from dprf_tpu.telemetry import perf as perf_mod
from dprf_tpu.telemetry import programs as programs_mod
from dprf_tpu.telemetry.programs import ProgramRegistry


def _warm_worker(engine: str, mask: str = "?l?l?l?l",
                 batch: int = 1 << 12):
    dev = get_engine(engine, device="jax")
    oracle = get_engine(engine, device="cpu")
    gen = MaskGenerator(mask)
    w = dev.make_mask_worker(
        gen, [oracle.parse_target("ff" * oracle.digest_size)],
        batch=batch, hit_capacity=16, oracle=oracle)
    if not getattr(w, "_warmed", False):
        w.warmup()
    return w


# ---------------------------------------------------------------------------
# registry round-trip

def test_registry_roundtrip_keyed_by_fingerprint():
    w = _warm_worker("md5")
    # warmup registered the site; analysis is deferred until asked
    n = programs_mod.analyze_pending()
    recs = [r for r in programs_mod.get_programs().snapshot()
            if r["engine"] == "md5" and r["attack"] == "mask"
            and r["batch"] == w.stride]
    assert recs, "warmup did not land a program record"
    rec = recs[-1]
    for key in ("key", "fingerprint", "engine", "attack", "batch",
                "flops", "flops_per_candidate", "peak_bytes",
                "argument_bytes", "output_bytes", "total_peak_bytes"):
        assert key in rec
    assert rec["flops"] and rec["flops"] > 0
    assert rec["total_peak_bytes"] and rec["total_peak_bytes"] > 0
    # re-registering the SAME step re-analyzes to the SAME fingerprint:
    # the registry stays deduped (round-trip keyed by the fingerprint)
    before = len(programs_mod.get_programs().snapshot())
    programs_mod.register_program("md5", "mask", w.stride,
                                  step=w.step, args=w.warmup_args())
    programs_mod.analyze_pending()
    assert len(programs_mod.get_programs().snapshot()) == before
    assert n >= 0


def test_wire_roundtrip_ingest_sanitizes():
    reg = ProgramRegistry()
    rec = {"fingerprint": "abc123", "engine": "md5", "attack": "mask",
           "batch": 4096, "flops": 4096 * 900.0,
           "peak_bytes": 1 << 20, "junk": "dropped",
           "key": "x" * 500}
    assert reg.ingest([rec], proc="w0") == 1
    got = reg.snapshot()[0]
    assert "junk" not in got
    assert len(got["key"]) <= 128
    assert got["proc"] == "w0"
    assert reg.analyzed_ops_per_candidate("md5") == pytest.approx(900.0)
    # duplicate fingerprints and junk entries drop silently
    assert reg.ingest([rec, "nope", {"engine": "md5"}], proc="w1") == 0


# ---------------------------------------------------------------------------
# analyzed roofline + hand-model cross-check

def test_md5_analyzed_within_2x_of_hand_model():
    _warm_worker("md5")
    programs_mod.analyze_pending()
    analyzed = programs_mod.analyzed_ops_per_candidate("md5")
    hand = perf_mod.OPS_PER_CANDIDATE["md5"]
    assert analyzed is not None
    ratio = max(analyzed, hand) / min(analyzed, hand)
    assert ratio < perf_mod.MODEL_DIVERGENCE_MAX, (
        f"analyzed {analyzed:.0f} vs hand {hand} ops/candidate "
        f"diverge {ratio:.2f}x")
    # the cross-check gauge carries the ratio
    assert perf_mod.ops_per_candidate("md5") == analyzed
    g = METRICS.get("dprf_roofline_model_divergence")
    assert g is not None
    assert 1.0 <= g.value(engine="md5") < perf_mod.MODEL_DIVERGENCE_MAX


#: one engine per family shape, including engines the hand table never
#: covered (sha512, lm, mysql41's nested sha1(sha1)): the silent
#: no-roofline path is gone -- compiling a step is enough to publish
ROOFLINE_ENGINES = ["md5", "ntlm", "sha512", "lm", "mysql41"]


@pytest.mark.parametrize("engine", ROOFLINE_ENGINES)
def test_every_engine_family_publishes_roofline(engine):
    _warm_worker(engine, mask="?l?l?l", batch=1 << 10)
    programs_mod.analyze_pending()
    assert programs_mod.analyzed_ops_per_candidate(engine) is not None
    frac = perf_mod.publish_roofline(engine, 1.0e9)
    assert frac is not None and frac > 0
    g = METRICS.get("dprf_roofline_frac")
    assert g.value(engine=engine) > 0


def test_no_silent_skip_for_any_registered_engine_with_a_record():
    """Every registered device engine's roofline publishes once a
    program record exists -- the registry itself has no per-engine
    skip list (synthetic records on a FRESH registry, so the real
    DEFAULT registry's analyzed values stay untouched)."""
    from dprf_tpu import engine_names
    reg = ProgramRegistry(registry=None)
    names = sorted(engine_names("jax"))
    reg.ingest([{"fingerprint": f"fp-{n}", "engine": n,
                 "attack": "mask", "batch": 1024,
                 "flops": 1024 * 500.0} for n in names],
               limit=len(names))
    for n in names:
        ops = reg.analyzed_ops_per_candidate(n)
        assert ops is not None, f"engine {n} lost its analyzed model"
        lo, hi = perf_mod.CHIP_INT_OPS_BAND
        assert hi / ops > 0


# ---------------------------------------------------------------------------
# HBM accounting: graceful None on the CPU backend

def test_memory_stats_none_degrade_on_cpu():
    assert devstats.device_memory_stats() == {}
    assert devstats.poll() == {}
    assert devstats.summary() is None
    assert devstats.bytes_free() is None
    assert devstats.headroom_frac() is None
    poller = devstats.DevstatsPoller(interval=0.05).start()
    poller.stop()       # no crash, no gauges
    assert METRICS.get("dprf_hbm_bytes_in_use") is None or \
        not METRICS.get("dprf_hbm_bytes_in_use").snapshot_values()


def test_peak_hbm_falls_back_to_program_analysis():
    _warm_worker("md5")
    programs_mod.analyze_pending()
    peak, source = devstats.peak_hbm_bytes()
    assert source == "program_analysis"
    assert peak and peak > 0


def test_unit_sizer_halves_under_low_headroom():
    from dprf_tpu.telemetry.registry import MetricsRegistry
    from dprf_tpu.tune.unit_sizer import AdaptiveUnitSizer
    full = AdaptiveUnitSizer(1 << 20, registry=MetricsRegistry(),
                             headroom_fn=lambda: 0.5)
    low = AdaptiveUnitSizer(1 << 20, registry=MetricsRegistry(),
                            headroom_fn=lambda: 0.05)
    none = AdaptiveUnitSizer(1 << 20, registry=MetricsRegistry(),
                             headroom_fn=lambda: None)
    assert low.next_size("w") == full.next_size("w") // 2
    assert none.next_size("w") == full.next_size("w")
    # serve plane: per-WORKER headroom from heartbeats, no local fn
    served = AdaptiveUnitSizer(1 << 20, registry=MetricsRegistry())
    served.observe_headroom("w1", 0.05)
    assert served.next_size("w1") == full.next_size("w") // 2
    assert served.next_size("w2") == full.next_size("w")
    served.observe_headroom("w1", None)       # report stopped: clear
    assert served.next_size("w1") == full.next_size("w")


# ---------------------------------------------------------------------------
# serve-plane surface: op_programs / heartbeat shipping / top fields

def _loopback_state():
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.rpc import CoordinatorState
    from dprf_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    disp = Dispatcher(1000, 100, registry=reg)
    return CoordinatorState({"engine": "md5"}, disp, 1, registry=reg)


def test_op_programs_serves_heartbeat_shipped_records():
    state = _loopback_state()
    rec = {"fingerprint": "deadbeef", "engine": "md5",
           "attack": "mask", "batch": 4096,
           "flops": 4096 * 1000.0, "peak_bytes": 5 << 20,
           "argument_bytes": 128, "output_bytes": 64}
    resp = state.op_heartbeat({
        "worker_id": "w0",
        "payload": {"engine": "md5", "hbm_in_use": 1 << 30,
                    "hbm_limit": 16 << 30, "hbm_peak": 2 << 30},
        "programs": [rec]})
    assert resp["ok"]
    out = state.op_programs({})
    assert out["ok"]
    got = [r for r in out["programs"]
           if r["fingerprint"] == "deadbeef"]
    assert got and got[0]["proc"] == "w0"
    assert got[0]["flops_per_candidate"] == pytest.approx(1000.0)
    # fleet memory view from the heartbeat payload
    assert state.health.mem_by_worker() == {"w0": 1 << 30}
    totals = state.health.hbm_totals()
    assert totals == {"in_use": 1 << 30, "limit": 16 << 30,
                      "workers": 1}
    # ... and the dprf top status carries both
    tail = state.op_trace_tail({"n": 10})
    assert tail["status"]["mem"] == {"w0": 1 << 30}
    assert tail["status"]["hbm"]["limit"] == 16 << 30


def test_programs_cli_json_schema(capsys):
    from dprf_tpu.cli import main as cli_main
    from dprf_tpu.runtime.rpc import CoordinatorServer
    state = _loopback_state()
    state.programs.ingest([{"fingerprint": "f1", "engine": "sha512",
                            "attack": "mask", "batch": 2048,
                            "flops": 2048 * 3000.0,
                            "peak_bytes": 1 << 20}], proc="w1")
    server = CoordinatorServer(state, "127.0.0.1", 0)
    t = server.start_background()
    try:
        host, port = server.address
        rc = cli_main(["programs", "--connect", f"{host}:{port}",
                       "--json", "--quiet"])
        assert rc == 0
        out = capsys.readouterr().out
        records = json.loads(out)
        assert isinstance(records, list)
        mine = [r for r in records if r.get("fingerprint") == "f1"]
        assert mine
        for key in ("engine", "attack", "batch",
                    "flops_per_candidate", "total_peak_bytes"):
            assert key in mine[0]
        # the human rendering works on the same records
        table = programs_mod.render_table(records)
        assert "sha512" in table
    finally:
        server.shutdown()
        t.join(timeout=5)


def test_render_top_shows_mem_column_and_hbm_header():
    from dprf_tpu.telemetry.trace import render_top
    text = render_top({
        "status": {"done": 10, "total": 100, "found": 0,
                   "targets": 1, "parked": 0, "elapsed": 1.0,
                   "mem": {"w0": 3 << 30},
                   "hbm": {"in_use": 3 << 30, "limit": 16 << 30,
                           "workers": 1},
                   "health": {"w0": "healthy"}},
        "spans": [], "leases": []})
    assert "MEM" in text
    assert "hbm 3.0G/16.0G (1w)" in text
    assert "3.0G" in text


# ---------------------------------------------------------------------------
# peak-memory regression gate

def _bench_rec(round_no, value=1.0e9, peak=None):
    rec = {"value": value, "device": "cpu", "engine": "md5",
           "round": round_no}
    if peak is not None:
        rec["peak_hbm_bytes"] = peak
    return rec


def test_memory_gate_fails_planted_peak_regression():
    from dprf_tpu.perfreport import compare
    base = [_bench_rec(i, peak=100 << 20) for i in range(5)]
    # throughput flat, peak +30%: memory regression drives the verdict
    cur = _bench_rec(6, peak=130 << 20)
    out = compare.gate(cur, base)
    assert out["memory"]["verdict"] == "regression"
    assert out["verdict"] == "regression"
    # +5% stays inside the noise floor
    ok = compare.gate(_bench_rec(6, peak=105 << 20), base)
    assert ok["memory"]["verdict"] == "pass"
    assert ok["verdict"] == "pass"


def test_memory_gate_no_baseline_on_legacy_records():
    from dprf_tpu.perfreport import compare
    legacy = [_bench_rec(i) for i in range(5)]          # no memory
    out = compare.gate(_bench_rec(6, peak=100 << 20), legacy)
    assert out["memory"]["verdict"] == "no-baseline"
    assert out["verdict"] == "pass"
    # and a record that itself lacks the field gates clean too
    out2 = compare.gate(_bench_rec(6), legacy)
    assert out2["memory"]["verdict"] == "no-baseline"


def test_gate_dry_passes_committed_history():
    """The committed BENCH_r*.json records predate the memory fields:
    the dry gate must treat them as no-baseline, not crash."""
    from dprf_tpu.perfreport import compare
    out = compare.gate_dry(compare.repo_root())
    assert out["verdict"] in ("pass", "no-baseline")
    assert out["memory"]["verdict"] == "no-baseline"


# ---------------------------------------------------------------------------
# dprf report memory section, from session artifacts alone

def test_report_memory_section_e2e(tmp_path, monkeypatch, capsys):
    from dprf_tpu.cli import main as cli_main
    from dprf_tpu.perfreport import build_report
    monkeypatch.setenv("DPRF_TELEMETRY_INTERVAL", "600")
    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path / "tune"))
    hashfile = tmp_path / "h.txt"
    hashfile.write_text(hashlib.md5(b"zz7").hexdigest() + "\n")
    session = str(tmp_path / "s.session")
    rc = cli_main(["crack", "--engine", "md5", "--device", "tpu",
                   "-a", "mask", "?l?l?d", str(hashfile),
                   "--session", session, "--batch", "4096",
                   "--unit-size", "4096", "--no-potfile", "--quiet"])
    capsys.readouterr()
    assert rc == 0
    doc = build_report(session)
    assert doc is not None
    memory = doc.get("memory")
    assert memory, "report lost the device-memory section"
    progs = memory["programs"]
    assert any(p["engine"] == "md5" and p["peak_bytes"] > 0
               for p in progs)
    # CPU backend: no HBM gauges, the section degrades to programs
    assert memory["devices"] == {}
    from dprf_tpu.perfreport import render_report
    text = render_report(doc)
    assert "device memory & program costs" in text
