"""oracle11 (hashcat 112), mysql323 (200), atlassian {PKCS5S2}
(12001): parse formats, oracle equivalence, device workers e2e."""

import base64
import hashlib
import random

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


# ---------------- mysql323 ----------------

MYSQL323_VECTORS = [
    # OLD_PASSWORD() canonical vectors
    ("test", "378b243e220ca493"),
    ("password", "5d2e19393cc5ef67"),
]


@pytest.mark.parametrize("pw,want", MYSQL323_VECTORS)
def test_mysql323_vectors(pw, want):
    cpu = get_engine("mysql323")
    assert cpu.hash_batch([pw.encode()])[0].hex() == want


def test_mysql323_device_matches_oracle():
    cpu = get_engine("mysql323")
    dev = get_engine("mysql323", device="jax")
    rnd = random.Random(200)
    cands = [bytes(rnd.randrange(1, 127)
                   for _ in range(rnd.randrange(0, 20)))
             for _ in range(24)]
    # the server skips space and tab mid-password
    cands += [b"has space", b"tab\there", b"", b" \t "]
    assert dev.hash_batch(cands) == cpu.hash_batch(cands)


def test_mysql323_multi_target_mask():
    cpu = get_engine("mysql323")
    dev = get_engine("mysql323", device="jax")
    gen = MaskGenerator("?l?l?l")
    ts = [cpu.parse_target(cpu.hash_batch([b"fox"])[0].hex()),
          cpu.parse_target(cpu.hash_batch([b"hen"])[0].hex())]
    w = dev.make_mask_worker(gen, ts, batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"fox"), (1, b"hen")}


def test_mysql323_wordlist_with_rules():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("mysql323")
    dev = get_engine("mysql323", device="jax")
    words = [b"alpha", b"fox", b"delta"]
    rules = [parse_rule(":"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=8)
    t = cpu.parse_target(cpu.hash_batch([b"fox1"])[0].hex())
    w = dev.make_wordlist_worker(gen, [t], batch=64, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox1"]


def test_mysql323_parse_rejects_malformed():
    cpu = get_engine("mysql323")
    with pytest.raises(ValueError):
        cpu.parse_target("xyz")
    with pytest.raises(ValueError):
        cpu.parse_target("ab" * 10)


# ---------------- oracle11 ----------------

def _oracle11_line(pw: bytes, salt: bytes) -> str:
    return ("S:" + hashlib.sha1(pw + salt).hexdigest().upper()
            + salt.hex().upper())


def test_oracle11_parse_and_crack():
    cpu = get_engine("oracle11")
    dev = get_engine("oracle11", device="jax")
    salt = bytes(range(10))
    t = cpu.parse_target(_oracle11_line(b"dog", salt))
    assert t.params["salt"] == salt
    assert cpu.hash_batch([b"dog"], t.params)[0] == t.digest
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"dog")]


def test_oracle11_hashcat_style_line():
    """A literal (non-hex) 10-byte salt after the colon is accepted;
    anything that isn't 10 raw bytes is rejected (the 11g salt is
    fixed-width)."""
    cpu = get_engine("oracle11")
    salt = b"saltysalty"                        # 10 literal bytes
    t = cpu.parse_target(hashlib.sha1(b"x" + salt).hexdigest()
                         + ":" + salt.decode())
    assert t.params["salt"] == salt
    assert cpu.hash_batch([b"x"], t.params)[0] == t.digest


# ---------------- atlassian {PKCS5S2} ----------------

def _atlassian_line(pw: bytes, salt: bytes) -> str:
    dk = hashlib.pbkdf2_hmac("sha1", pw, salt, 10000, 32)
    return "{PKCS5S2}" + base64.b64encode(salt + dk).decode()


def test_atlassian_parse_and_crack():
    cpu = get_engine("atlassian")
    dev = get_engine("atlassian", device="jax")
    salt = bytes(range(16))
    t = cpu.parse_target(_atlassian_line(b"ca", salt))
    assert t.params == {"salt": salt, "iterations": 10000, "dklen": 32}
    assert cpu.hash_batch([b"ca"], t.params)[0] == t.digest
    gen = MaskGenerator("?l?l")
    w = dev.make_mask_worker(gen, [t], batch=256, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"ca")]


def test_atlassian_parse_rejects_malformed():
    cpu = get_engine("atlassian")
    with pytest.raises(ValueError):
        cpu.parse_target("{PKCS5S2}!!!")
    with pytest.raises(ValueError):
        cpu.parse_target("{PKCS5S2}" + base64.b64encode(b"x" * 20).decode())
    with pytest.raises(ValueError):
        cpu.parse_target("sha1:100:AAAA:BBBB{PKCS5S2}")


def test_oracle11_hashcat_hex_salt_line():
    """hashcat -m 112 lines carry the 10-byte salt hex-encoded; the
    parser must decode it, not hash the ASCII hex."""
    cpu = get_engine("oracle11")
    salt = bytes(range(10))
    line = hashlib.sha1(b"pw" + salt).hexdigest() + ":" + salt.hex()
    t = cpu.parse_target(line)
    assert t.params["salt"] == salt
    assert cpu.hash_batch([b"pw"], t.params)[0] == t.digest
    with pytest.raises(ValueError, match="10 bytes"):
        cpu.parse_target(hashlib.sha1(b"x").hexdigest() + ":abc")


def test_oracle11_long_candidates_fit():
    """The fixed 10-byte salt leaves 45 bytes for candidates; a
    30-char job must trace (the generic 23-byte cap must not apply)."""
    cpu = get_engine("oracle11")
    dev = get_engine("oracle11", device="jax")
    assert dev.max_candidate_len == 45
    salt = bytes(range(10))
    t = cpu.parse_target(_oracle11_line(b"x" * 30, salt))
    gen = MaskGenerator("?l" * 30)
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    w.process(WorkUnit(0, 0, 64))              # traces at length 30
