"""Device bcrypt (EksBlowfish) vs the CPU oracle and OpenBSD vectors.

Covers: raw digest equivalence over random candidates, the device
hash_batch against classic $2a$05 vectors, and both fused workers
(wordlist+rules and mask) end-to-end with planted passwords.  Costs are
kept at 4-5 (16-32 rounds) so the serial chains stay test-sized; the
chain structure is identical at cost 12.
"""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.bcrypt import bcrypt_hash, bcrypt_raw
from dprf_tpu.ops import blowfish as bf_ops
from dprf_tpu.runtime.workunit import WorkUnit


def _pack(cands):
    L = max(len(c) for c in cands)
    buf = np.zeros((len(cands), L), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    return jnp.asarray(buf), jnp.asarray(lens)


def test_bcrypt_batch_matches_oracle():
    rng = random.Random(0xbc)
    cands = [bytes(rng.randrange(1, 256) for _ in range(rng.randrange(0, 24)))
             for _ in range(12)]
    salt = bytes(rng.randrange(256) for _ in range(16))
    cost = 4
    cand, lens = _pack(cands)
    dw = jax.jit(bf_ops.bcrypt_batch)(
        cand, lens, jnp.asarray(bf_ops.salt_to_words(salt)),
        jnp.int32(1 << cost))
    got = bf_ops.words_to_digests(np.asarray(dw))
    for g, c in zip(got, cands):
        assert g == bcrypt_raw(c, salt, cost), c


def test_cost_is_runtime_arg():
    """One compiled program must serve different costs (the trip count
    is a traced argument, not a constant baked into the executable)."""
    fn = jax.jit(bf_ops.bcrypt_batch)
    cand, lens = _pack([b"hunter2"])
    salt = bytes(range(16))
    sw = jnp.asarray(bf_ops.salt_to_words(salt))
    for cost in (4, 5):
        dw = fn(cand, lens, sw, jnp.int32(1 << cost))
        assert bf_ops.words_to_digests(np.asarray(dw))[0] == \
            bcrypt_raw(b"hunter2", salt, cost)


@pytest.mark.parametrize("password,line", [
    (b"U*U", "$2a$05$CCCCCCCCCCCCCCCCCCCCC.E5YPO9kmyuRGyh0XouQYb4YMJKvyOeW"),
    (b"U*U*U", "$2a$05$XXXXXXXXXXXXXXXXXXXXXOAcXxm9kjPGEMsLznoKqmqw7tc8WCx4a"),
])
@pytest.mark.smoke
def test_device_hash_batch_openbsd_vectors(password, line):
    eng = get_engine("bcrypt", device="jax")
    t = eng.parse_target(line)
    [digest] = eng.hash_batch([password], params=t.params)
    assert digest == t.digest


def test_device_hash_batch_vs_oracle_batch():
    eng = get_engine("bcrypt", device="jax")
    salt = b"0123456789abcdef"
    params = {"salt": salt, "cost": 4}
    cands = [b"", b"a", b"password", b"x" * 23]
    got = eng.hash_batch(cands, params=params)
    want = get_engine("bcrypt").hash_batch(cands, params=params)
    assert got == want


def test_device_rejects_cost_31():
    """Cost 31 is legal bcrypt but 2**31 overflows the int32 loop
    bound; the device engine must refuse loudly, not wrap to a
    zero-iteration loop (silent false negatives)."""
    from dprf_tpu.engines.device.bcrypt import _n_rounds
    with pytest.raises(ValueError, match="4..30"):
        _n_rounds(31)
    with pytest.raises(ValueError):
        get_engine("bcrypt", device="jax").hash_batch(
            [b"x"], params={"salt": b"0123456789abcdef", "cost": 31})


def test_parse_rejects_out_of_range_cost():
    with pytest.raises(ValueError):
        get_engine("bcrypt").parse_target(
            "$2b$03$KBCwKxOzLha2MUDgW0PjXeFaAPh7cxmjSZ5c00P8D0A2tzxy8Lhdy")


def test_bcrypt_wordlist_worker_finds_planted():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    words = [b"alpha", b"bravo", b"s3cret", b"delta", b"echo"]
    rules = [parse_rule(":"), parse_rule("u"), parse_rule("$1")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    cost = 4
    salt = b"fedcba9876543210"
    eng = get_engine("bcrypt", device="jax")
    # plant "S3CRET" (rule u on word 2) and "echo1" (rule $1 on word 4)
    targets = [eng.parse_target(bcrypt_hash(b"S3CRET", salt, cost)),
               eng.parse_target(bcrypt_hash(b"echo1", salt, cost))]
    worker = eng.make_wordlist_worker(gen, targets, batch=8,
                                      hit_capacity=8,
                                      oracle=get_engine("bcrypt"))
    hits = worker.process(WorkUnit(0, 0, gen.keyspace))
    got = {(h.target_index, h.plaintext) for h in hits}
    assert got == {(0, b"S3CRET"), (1, b"echo1")}
    assert {h.cand_index for h in hits} == \
        {gen.index_of(2, 1), gen.index_of(4, 2)}


def test_bcrypt_mask_worker_finds_planted():
    from dprf_tpu.generators.mask import MaskGenerator

    gen = MaskGenerator("?d?d")
    cost = 4
    salt = b"0123456789abcdef"
    eng = get_engine("bcrypt", device="jax")
    targets = [eng.parse_target(bcrypt_hash(b"42", salt, cost))]
    worker = eng.make_mask_worker(gen, targets, batch=32, hit_capacity=8,
                                  oracle=None)
    hits = worker.process(WorkUnit(0, 0, gen.keyspace))
    assert len(hits) == 1
    assert hits[0].plaintext == b"42"
    assert hits[0].target_index == 0


@pytest.mark.smoke
@pytest.mark.compileheavy    # two full EKS program compiles (~1 min)
def test_chunked_eks_matches_fused():
    """Splitting the cost loop across arbitrary dispatch boundaries must
    reproduce the one-shot eks_setup state exactly (the chunked path is
    how cost >= 10 runs in production: one dispatch per time budget, not
    one per batch -- see ChunkedEks)."""
    rng = np.random.default_rng(7)
    kw = jnp.asarray(rng.integers(0, 2**32, (4, 18), dtype=np.uint32))
    sw = jnp.asarray(rng.integers(0, 2**32, (4,), dtype=np.uint32))
    n = 32                                    # cost 5
    P1, S1 = bf_ops.eks_setup(kw, sw, jnp.int32(n))
    want = np.asarray(bf_ops.bcrypt_digest_words(P1, S1))

    salt18 = bf_ops.salt18_words(sw)
    P, S = bf_ops.eks_setup_begin(kw, sw)
    for chunk in (1, 16, 5, 10):              # uneven split of 32
        P, S = bf_ops.eks_rounds(P, S, kw, salt18, jnp.int32(chunk))
    got = np.asarray(bf_ops.bcrypt_digest_words(P, S))
    np.testing.assert_array_equal(got, want)


def test_chunked_worker_many_dispatches_finds_planted():
    """A dispatch budget far below one chunk's calibration time forces
    the worker down to 1-round dispatches; the sweep must still find the
    planted password (state carries across dispatch boundaries)."""
    from dprf_tpu.generators.mask import MaskGenerator

    gen = MaskGenerator("?d?d")
    salt = b"0123456789abcdef"
    eng = get_engine("bcrypt", device="jax")
    targets = [eng.parse_target(bcrypt_hash(b"73", salt, 4))]
    worker = eng.make_mask_worker(gen, targets, batch=128, hit_capacity=8,
                                  oracle=None)
    worker.chunker.dispatch_s = 1e-9          # force minimum chunks
    hits = worker.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"73")]
    # calibration chunk (16) + 1-round tail dispatches
    assert worker.chunker._per_round is not None


def test_chunked_growth_cap():
    """One optimistic per-round estimate must not jump the chunk size
    straight past the deadline: growth is capped at 8x per dispatch."""
    from dprf_tpu.engines.device.bcrypt import ChunkedEks

    c = ChunkedEks(dispatch_s=100.0)
    assert c._next_chunk(1 << 20, 16) == 16   # calibration first
    c._per_round = 1e-6                       # looks 1e8-rounds-cheap
    assert c._next_chunk(1 << 30, 16) == 128  # 16 * 8, not 1e8
    assert c._next_chunk(100, 1 << 20) == 100  # remaining clamps


def test_pallas_eks_advance_matches_xla():
    """The Pallas EksBlowfish advance kernel (ops/pallas_bcrypt.py) is
    bit-exact vs the XLA form over the ChunkedEks advance contract
    (interpret mode; the same kernel was proven on TPU v5 lite --
    TPU_RESULTS_r04 / tpu_cases pallaseks)."""
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.ops import blowfish as bf
    from dprf_tpu.ops.pallas_bcrypt import make_pallas_eks_advance

    B = 8
    rng = np.random.RandomState(0)
    cand = rng.randint(97, 123, (B, 6), dtype=np.uint8)
    kw = bf.key_words_from_candidates(jnp.asarray(cand),
                                      jnp.full((B,), 6, jnp.int32))
    sw = jnp.asarray(np.frombuffer(bytes(range(16)), ">u4")
                     .astype(np.uint32))
    P, S = bf.eks_setup_begin(kw, sw)
    s18 = bf.salt18_words(sw)
    n = jnp.int32(2)
    P_ref, S_ref = bf.eks_rounds(P, S, kw, s18, n)
    adv = make_pallas_eks_advance(B, interpret=True, subc=8)
    P_k, S_k = adv(P, S, kw, s18, n)
    assert np.array_equal(np.asarray(P_ref), np.asarray(P_k))
    assert np.array_equal(np.asarray(S_ref), np.asarray(S_k))


def test_bcrypt_route_forced_cpu_cracks(monkeypatch):
    """DPRF_BCRYPT_ROUTE=cpu returns the routed CPU worker from the
    device factory and it still cracks a planted target."""
    from dprf_tpu.engines.device.bcrypt import RoutedCpuBcryptWorker
    from dprf_tpu.generators.mask import MaskGenerator

    monkeypatch.setenv("DPRF_BCRYPT_ROUTE", "cpu")
    gen = MaskGenerator("?d?d")
    cpu = get_engine("bcrypt", device="cpu")
    dev = get_engine("bcrypt", device="jax")
    salt = bytes(range(16))
    t = cpu.parse_target(bcrypt_hash(b"42", salt, 4))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert isinstance(w, RoutedCpuBcryptWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"42")]


def test_bcrypt_route_forced_device(monkeypatch):
    from dprf_tpu.engines.device.bcrypt import BcryptMaskWorker
    from dprf_tpu.generators.mask import MaskGenerator

    monkeypatch.setenv("DPRF_BCRYPT_ROUTE", "device")
    gen = MaskGenerator("?d?d")
    cpu = get_engine("bcrypt", device="cpu")
    dev = get_engine("bcrypt", device="jax")
    t = cpu.parse_target(bcrypt_hash(b"xx", bytes(range(16)), 4))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert isinstance(w, BcryptMaskWorker)


def test_measure_eks_rates_runs():
    """The routing micro-bench returns positive head-to-head rates."""
    from dprf_tpu.engines.device.bcrypt import measure_eks_rates

    cpu = get_engine("bcrypt", device="cpu")
    rates = measure_eks_rates(cpu, batch=8, rounds=2)
    assert rates["device_cand_rounds_s"] > 0
    assert rates["cpu_cand_rounds_s"] > 0
