"""MS Cache v1/v2 (DCC/DCC2, hashcat 1100/2100): oracles vs the
reference construction, device workers, and parsing."""

import hashlib

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.engines import _dcc1, _utf16_lower_user
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def _v1_line(pw: bytes, user: str) -> str:
    return _dcc1(pw, _utf16_lower_user(user)).hex() + ":" + user


def _v2_line(pw: bytes, user: str, iters: int = 100) -> str:
    salt = _utf16_lower_user(user)
    dk = hashlib.pbkdf2_hmac("sha1", _dcc1(pw, salt), salt, iters, 16)
    return f"$DCC2${iters}#{user}#{dk.hex()}"


def test_v1_oracle_and_parse():
    eng = get_engine("mscache")
    t = eng.parse_target(_v1_line(b"hashcat", "tom"))
    assert eng.hash_batch([b"hashcat"], params=t.params)[0] == t.digest
    assert not eng.verify(b"nope", t)
    with pytest.raises(ValueError):
        eng.parse_target("deadbeef")            # no username
    with pytest.raises(ValueError):
        eng.parse_target("aa" * 16 + ":" + "u" * 20)   # user too long


def test_v2_oracle_and_parse():
    eng = get_engine("mscache2")
    t = eng.parse_target(_v2_line(b"hashcat", "Tom", 10240))
    assert t.params["iterations"] == 10240
    assert t.params["salt"] == _utf16_lower_user("tom")
    assert eng.hash_batch([b"hashcat"], params=t.params)[0] == t.digest
    with pytest.raises(ValueError):
        eng.parse_target("$DCC2$bad")


@pytest.mark.parametrize("name,line", [
    ("mscache", _v1_line(b"fox", "Alice")),
    ("mscache2", _v2_line(b"fox", "Alice")),
])
def test_device_mask_worker_cracks(name, line):
    cpu = get_engine(name)
    dev = get_engine(name, device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(line)
    w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"fox"]


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("mscache2")
    dev = get_engine("mscache2", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")], max_len=16)
    t = cpu.parse_target(_v2_line(b"banana", "svc_backup"))
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}


def test_sharded_mask_worker_cracks():
    from dprf_tpu.parallel import make_mesh

    cpu = get_engine("mscache")
    dev = get_engine("mscache", device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(_v1_line(b"dog", "bob"))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=512,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [b"dog"]


def test_two_targets_different_iterations():
    """Per-target iteration counts are runtime args: one step serves
    targets with different DCC2 iteration settings."""
    cpu = get_engine("mscache2")
    dev = get_engine("mscache2", device="jax")
    gen = MaskGenerator("?d?d")
    ta = cpu.parse_target(_v2_line(b"42", "ann", 50))
    tb = cpu.parse_target(_v2_line(b"77", "ben", 200))
    w = dev.make_mask_worker(gen, [ta, tb], batch=128, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"42"), (1, b"77")}
