"""Keccak-256 (uint32-pair lanes) and the Ethereum keystore engines
(hashcat 15600/15700)."""

import hashlib

import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.keccak import keccak256, keccak256_words
from dprf_tpu.runtime.workunit import WorkUnit

SALT = bytes(range(16))
CT = bytes(range(32))


def test_keccak_scalar_vs_hashlib_sha3():
    """Same permutation as SHA3-256; only the padding byte differs."""
    for n in (0, 1, 57, 135, 136, 300):
        data = bytes(i & 0xFF for i in range(n))
        assert keccak256(data, pad_byte=0x06) == \
            hashlib.sha3_256(data).digest(), n


def test_keccak_ethereum_empty_vector():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0"
        "e500b653ca82273b7bfad8045d85a470")


def test_device_keccak_matches_scalar():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    for n in (0, 48, 135):
        batch = rng.randint(0, 256, (8, max(1, n)), dtype=np.uint8)
        w = np.asarray(keccak256_words(
            jnp.asarray(batch[:, :max(1, n)]),
            jnp.full((8,), n, jnp.int32)))
        for j in range(8):
            want = np.frombuffer(keccak256(bytes(batch[j, :n])), ">u4")
            assert (w[j] == want).all(), (n, j)


def _pbkdf2_line(pw: bytes, iters: int = 64) -> str:
    dk = hashlib.pbkdf2_hmac("sha256", pw, SALT, iters, 32)
    return "$ethereum$p*%d*%s*%s*%s" % (
        iters, SALT.hex(), CT.hex(), keccak256(dk[16:32] + CT).hex())


def _scrypt_line(pw: bytes, n: int = 16, r: int = 1, p: int = 1) -> str:
    dk = hashlib.scrypt(pw, salt=SALT, n=n, r=r, p=p, dklen=32,
                        maxmem=1 << 26)
    return "$ethereum$s*%d*%d*%d*%s*%s*%s" % (
        n, r, p, SALT.hex(), CT.hex(), keccak256(dk[16:32] + CT).hex())


@pytest.mark.parametrize("name,line", [
    ("ethereum-pbkdf2", _pbkdf2_line(b"password")),
    ("ethereum-scrypt", _scrypt_line(b"password")),
])
def test_parse_and_oracle(name, line):
    eng = get_engine(name)
    t = eng.parse_target(line)
    assert eng.hash_batch([b"password"], params=t.params)[0] == t.digest
    assert not eng.verify(b"nope", t)
    with pytest.raises(ValueError):
        eng.parse_target("$ethereum$x*garbage")


@pytest.mark.parametrize("name,line,plant", [
    ("ethereum-pbkdf2", _pbkdf2_line(b"fox"), b"fox"),
    ("ethereum-scrypt", _scrypt_line(b"cab"), b"cab"),
])
def test_device_mask_worker_cracks(name, line, plant):
    cpu = get_engine(name)
    dev = get_engine(name, device="jax")
    gen = MaskGenerator("?l?l?l")
    t = cpu.parse_target(line)
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits] == [plant]


def test_sha3_and_keccak_raw_engines():
    cpu = get_engine("sha3-256")
    dev = get_engine("sha3-256", device="jax")
    gen = MaskGenerator("?l?l?l")
    t1 = cpu.parse_target(hashlib.sha3_256(b"fox").hexdigest())
    t2 = cpu.parse_target(hashlib.sha3_256(b"dog").hexdigest())
    w = dev.make_mask_worker(gen, [t1, t2], batch=4096, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"fox"), (1, b"dog")}

    k = get_engine("keccak-256")
    kd = get_engine("keccak-256", device="jax")
    tk = k.parse_target(keccak256(b"cab").hex())
    w2 = kd.make_mask_worker(gen, [tk], batch=4096, hit_capacity=8,
                             oracle=k)
    hits2 = w2.process(WorkUnit(0, 0, gen.keyspace))
    assert [h.plaintext for h in hits2] == [b"cab"]


def test_keccak_wordlist_rules_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("keccak-256")
    dev = get_engine("keccak-256", device="jax")
    gen = WordlistRulesGenerator(
        words=[b"apple", b"Banana", b"zebra"],
        rules=[parse_rule(":"), parse_rule("l")])
    t = cpu.parse_target(keccak256(b"banana").hex())
    w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert b"banana" in {h.plaintext for h in hits}
