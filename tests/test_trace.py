"""Distributed tracing & flight recorder (telemetry/trace.py, ISSUE 4):
recorder semantics, trace-context propagation across the RPC boundary,
Chrome-trace export schema, the dprf top live view, crash-history unit
sizing, JSONL rotation, and the declaration lint.
"""

import hashlib
import json
import subprocess
import sys
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, worker_loop)
from dprf_tpu.runtime.session import job_fingerprint
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry import trace as trace_mod
from dprf_tpu.telemetry.trace import (TraceRecorder, export_chrome_trace,
                                      lifecycle_report, load_trace,
                                      render_top)

pytestmark = pytest.mark.smoke


def _recorder(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return TraceRecorder(**kw)


# ---------------------------------------------------------------------------
# recorder semantics

def test_ring_is_bounded_and_tail_ordered():
    r = _recorder(capacity=16)
    for i in range(100):
        r.record("sweep", unit=i)
    spans = r.tail(1000)
    assert len(spans) == 16
    assert [s["attrs"]["unit"] for s in spans] == list(range(84, 100))
    assert all(s["name"] == "sweep" for s in spans)
    # span ids unique; tail(n) truncates from the old end
    assert len({s["span"] for s in spans}) == 16
    assert [s["attrs"]["unit"] for s in r.tail(4)] == [96, 97, 98, 99]


def test_disabled_recorder_records_nothing(monkeypatch):
    monkeypatch.setenv("DPRF_TRACE", "0")
    r = _recorder()          # enabled resolved from env at construction
    assert r.record("sweep") is None
    assert r.ingest([{"name": "sweep", "ts": 1.0}]) == 0
    assert r.tail() == []


def test_record_backdates_ts_by_duration():
    r = _recorder(clock=lambda: 100.0)
    s = r.record("sweep", dur=2.5)
    assert s["ts"] == pytest.approx(97.5)
    assert s["dur"] == pytest.approx(2.5)


def test_ingest_sanitizes_client_controlled_spans():
    r = _recorder()
    junk = [
        "not a dict",
        {"name": "not_a_declared_span", "ts": 1.0},
        {"name": "sweep", "ts": "NaN-ish junk"},
        {"name": "sweep", "ts": 1.0, "dur": 0.5, "trace": "t" * 500,
         "proc": "liar", "attrs": {"k": object()}},
        {"name": "rpc", "ts": 2.0, "attrs": {str(i): i
                                             for i in range(50)}},
    ]
    n = r.ingest(junk, proc="w1")
    assert n == 2
    spans = r.tail()
    # proc is forced to the server-known worker id, never trusted
    assert all(s["proc"] == "w1" for s in spans)
    over_long_trace = spans[0]
    assert over_long_trace["trace"] is None        # over MAX_ID_LEN
    assert len(spans[1]["attrs"]) <= trace_mod.MAX_ATTRS


def test_ingest_rebases_skewed_worker_clocks():
    """A worker 30s behind the coordinator must not render its sweep
    before its lease: span timestamps rebase by (coordinator now -
    sender's clock at send time)."""
    r = _recorder(clock=lambda: 1000.0)
    r.ingest([{"name": "sweep", "ts": 965.0, "dur": 2.0}],
             proc="w", sent_at=970.0)       # worker clock 30s behind
    (s,) = r.tail()
    assert s["ts"] == pytest.approx(995.0)  # 965 + (1000 - 970)
    assert s["dur"] == pytest.approx(2.0)   # durations are never scaled
    # no sent_at (old worker / local test harness): ts kept verbatim
    r.ingest([{"name": "rpc", "ts": 965.0}], proc="w")
    assert r.tail()[-1]["ts"] == pytest.approx(965.0)


def test_rotation_target_unusable_still_caps_the_file(tmp_path):
    """An unwritable rotation target must not defeat the size cap: the
    stream truncates in place instead of growing unbounded."""
    import os
    path = str(tmp_path / "s.trace.jsonl")
    os.mkdir(path + ".1")                   # os.replace onto a dir fails
    r = _recorder()
    r.attach_file(path, max_bytes=2000)
    for i in range(500):
        r.record("sweep", unit=i)
    r.detach_file()
    assert os.path.getsize(path) <= 2300    # cap + one span of slack


def test_file_stream_rotates_at_cap(tmp_path):
    path = str(tmp_path / "s.trace.jsonl")
    r = _recorder()
    r.attach_file(path, max_bytes=2000)
    for i in range(200):
        r.record("sweep", unit=i)
    r.detach_file()
    import os
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 2300     # cap + one span of slack
    assert os.path.getsize(path + ".1") <= 2300
    # load_trace stitches the rotated part back, oldest first
    spans = load_trace(path)
    units = [s["attrs"]["unit"] for s in spans]
    assert units == sorted(units)
    assert units[-1] == 199


def test_snapshotter_rotates_at_cap(tmp_path, monkeypatch):
    from dprf_tpu.telemetry import TelemetrySnapshotter
    monkeypatch.setenv("DPRF_TELEMETRY_MAX_BYTES", "400")
    reg = MetricsRegistry()
    reg.counter("dprf_hits_total", "x").inc()
    path = str(tmp_path / "t.telemetry.jsonl")
    snap = TelemetrySnapshotter(path, reg, interval=60.0)
    for _ in range(20):
        snap.write_once()
    import os
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 600
    # the snapshot stream still loads (torn-tail tolerant)
    from dprf_tpu.telemetry import load_snapshots
    assert load_snapshots(path)


# ---------------------------------------------------------------------------
# dispatcher lifecycle spans

def test_dispatcher_spans_cover_the_unit_lifecycle():
    rec = _recorder()
    d = Dispatcher(100, 100, registry=MetricsRegistry(), recorder=rec,
                   max_unit_retries=2)
    u = d.lease("w1")
    tid, lease_sid = d.trace_context(u.unit_id)
    assert tid and lease_sid
    d.fail(u.unit_id)
    assert d.trace_context(u.unit_id) is None
    u2 = d.lease("w2")
    assert u2.unit_id == u.unit_id          # reissued, same trace id
    assert d.trace_context(u.unit_id)[0] == tid
    d.complete(u.unit_id, elapsed=1.5)
    names = [s["name"] for s in rec.tail() if s["trace"] == tid]
    assert names == ["lease", "fail", "reissue", "lease", "complete"]
    rep = lifecycle_report(rec.tail())
    assert rep["orphans"] == 0
    assert rep["details"][tid]["terminal"]
    # second attempt's lease carries the attempt number
    leases = [s for s in rec.tail() if s["name"] == "lease"]
    assert leases[1]["attrs"]["attempt"] == 2


def test_dispatcher_park_span_after_retry_budget():
    rec = _recorder()
    d = Dispatcher(50, 50, registry=MetricsRegistry(), recorder=rec,
                   max_unit_retries=1)
    u = d.lease("w1")
    tid = d.trace_context(u.unit_id)[0]
    d.fail(u.unit_id)
    names = [s["name"] for s in rec.tail() if s["trace"] == tid]
    assert names == ["lease", "fail", "park"]
    assert lifecycle_report(rec.tail())["details"][tid]["terminal"]
    # retry-parked requeues with a reissue span on the same trace
    assert d.retry_parked() == 1
    names = [s["name"] for s in rec.tail() if s["trace"] == tid]
    assert names[-1] == "reissue"


# ---------------------------------------------------------------------------
# trace-context propagation across the RPC boundary (ISSUE 4 satellite:
# a unit that fails on one worker and completes on another yields ONE
# trace holding both workers' spans, no orphans, correct parent links)

def _loopback_job(mask, plants, unit_size, rec, reg, **dispatcher_kw):
    eng = get_engine("md5")
    gen = MaskGenerator(mask)
    targets = [eng.parse_target(hashlib.md5(p).hexdigest())
               for p in plants]
    fp = job_fingerprint("md5", f"mask:{mask}", gen.keyspace,
                         [t.digest for t in targets])
    job = {"engine": "md5", "attack": "mask", "attack_arg": mask,
           "customs": {}, "rules": None, "max_len": None,
           "targets": [t.raw for t in targets],
           "keyspace": gen.keyspace, "unit_size": unit_size,
           "batch": 4096, "hit_cap": 8, "fingerprint": fp}
    disp = Dispatcher(gen.keyspace, unit_size, registry=reg,
                      recorder=rec, **dispatcher_kw)
    state = CoordinatorState(
        job, disp, len(targets), registry=reg, recorder=rec,
        verifier=lambda ti, plain: eng.verify(plain, targets[ti]))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return eng, gen, targets, state, server, disp


class _FailOnce:
    """Worker whose first unit raises; the crash-and-reissue chaos."""

    def __init__(self, inner):
        self.inner = inner
        self.crashed = False

    def process(self, unit):
        if not self.crashed:
            self.crashed = True
            raise RuntimeError("injected chaos crash")
        return self.inner.process(unit)


def test_distributed_reissue_stitches_both_workers_onto_one_trace(tmp_path):
    reg = MetricsRegistry()
    rec = _recorder()
    path = str(tmp_path / "chaos.session.trace.jsonl")
    rec.attach_file(path)
    eng, gen, targets, state, server, disp = _loopback_job(
        "?l?l", [b"zz"], unit_size=26 * 26, rec=rec, reg=reg)
    try:
        c1 = CoordinatorClient(*server.address)
        with pytest.raises(RuntimeError, match="chaos"):
            worker_loop(c1, _FailOnce(CpuWorker(eng, gen, targets)),
                        "wA", idle_sleep=0.01)
        c1.close()
        c2 = CoordinatorClient(*server.address)
        worker_loop(c2, CpuWorker(eng, gen, targets), "wB",
                    idle_sleep=0.01)
        c2.close()
        assert state.found == {0: b"zz"}
    finally:
        server.shutdown()
        rec.detach_file()

    spans = load_trace(path)
    rep = lifecycle_report(spans)
    # ONE trace for the bounced unit, zero orphan spans anywhere
    assert rep["orphans"] == 0
    assert rep["incomplete"] == []
    (tid, detail), = rep["details"].items()
    assert detail["leases"] == 2 and detail["reissues"] == 1
    assert detail["terminal"]
    assert {"coordinator", "wA", "wB"} <= set(detail["procs"])
    # correct parent links: every worker span parents onto a lease
    # span of ITS attempt, and the failed attempt's spans carry wA
    by_id = {s["span"]: s for s in spans if s.get("span")}
    leases = [s for s in spans if s["name"] == "lease"]
    assert len(leases) == 2
    first_lease, second_lease = leases
    for s in spans:
        if s["name"] == "phase":
            # sampled-probe phase children parent onto their unit's
            # SWEEP span (same proc), not the lease span directly
            assert by_id[s["parent"]]["name"] == "sweep"
            assert by_id[s["parent"]]["proc"] == s["proc"]
            continue
        if s["proc"] == "wA":
            assert s["parent"] == first_lease["span"]
        if s["proc"] == "wB":
            assert s["parent"] == second_lease["span"]
        if s.get("parent"):
            assert s["parent"] in by_id
    crashed = [s for s in spans
               if s["name"] == "sweep" and s["proc"] == "wA"]
    assert crashed and crashed[0]["attrs"]["error"] == "RuntimeError"
    # hit_verify ran on the coordinator, parented to the live attempt
    hv = [s for s in spans if s["name"] == "hit_verify"]
    assert hv and hv[0]["parent"] == second_lease["span"]


def test_trace_export_cli_on_chaos_session(tmp_path):
    """Acceptance: export on a chaos-test distributed session
    reconstructs every lifecycle with zero orphans, and the emitted
    file is schema-valid Chrome-trace JSON."""
    reg = MetricsRegistry()
    rec = _recorder()
    session = str(tmp_path / "chaos.session")
    rec.attach_file(session + ".trace.jsonl")
    eng, gen, targets, state, server, disp = _loopback_job(
        "?l?l", [b"qq", b"zz"], unit_size=200, rec=rec, reg=reg)
    try:
        c1 = CoordinatorClient(*server.address)
        with pytest.raises(RuntimeError, match="chaos"):
            worker_loop(c1, _FailOnce(CpuWorker(eng, gen, targets)),
                        "wA", idle_sleep=0.01)
        c1.close()
        c2 = CoordinatorClient(*server.address)
        worker_loop(c2, CpuWorker(eng, gen, targets), "wB",
                    idle_sleep=0.01)
        c2.close()
    finally:
        server.shutdown()
        rec.detach_file()

    out = str(tmp_path / "chaos.perfetto.json")
    rc = cli_main(["trace", "export", session, "--out", out, "--quiet"])
    assert rc == 0

    spans = load_trace(session + ".trace.jsonl")
    rep = lifecycle_report(spans)
    assert rep["orphans"] == 0 and rep["incomplete"] == []
    # every unit's lifecycle reconstructs lease -> ... -> complete
    # (a worker's rpc span may SORT before its lease: its round trip
    # started before the coordinator recorded the lease, which is the
    # honest timeline)
    for detail in rep["details"].values():
        assert detail["leases"] >= 1
        assert detail["terminal"]
    assert any(d["reissues"] for d in rep["details"].values())

    with open(out, encoding="utf-8") as fh:
        doc = json.load(fh)
    _assert_chrome_trace_schema(doc)


def _assert_chrome_trace_schema(doc):
    assert isinstance(doc, dict)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    seen_x = False
    for e in events:
        assert isinstance(e["name"], str)
        assert e["ph"] in ("X", "M")
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] == "X":
            seen_x = True
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] > 0
            assert e["name"] in trace_mod.SPAN_NAMES
        else:
            assert e["name"] in ("process_name", "thread_name")
            assert isinstance(e["args"]["name"], str)
    assert seen_x
    # every X event's pid/tid has a metadata name
    named_pids = {e["pid"] for e in events
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert {e["pid"] for e in events if e["ph"] == "X"} <= named_pids


def test_chrome_export_is_deterministic_for_empty_attrs():
    r = _recorder(clock=lambda: 50.0)
    r.record("lease", trace="t1", proc="coordinator")
    doc = export_chrome_trace(r.tail())
    _assert_chrome_trace_schema(doc)


# ---------------------------------------------------------------------------
# op_trace_tail + dprf top

def test_trace_tail_rpc_and_top_cli(capsys):
    reg = MetricsRegistry()
    rec = _recorder()
    eng, gen, targets, state, server, disp = _loopback_job(
        "?d?d", [b"42"], unit_size=25, rec=rec, reg=reg)
    try:
        client = CoordinatorClient(*server.address)
        worker_loop(client, CpuWorker(eng, gen, targets), "w-tail",
                    idle_sleep=0.01)
        resp = client.call("trace_tail", n=50)
        client.close()
        assert resp["ok"]
        assert resp["status"]["found"] == 1
        assert resp["status"]["stop"] is True
        assert resp["status"]["targets"] == 1
        assert resp["leases"] == []
        procs = {s["proc"] for s in resp["spans"]}
        assert {"coordinator", "w-tail"} <= procs
        # render + the CLI view both carry the worker
        text = render_top(resp)
        assert "w-tail" in text and "FINISHED" in text
        host, port = server.address
        rc = cli_main(["top", "--connect", f"{host}:{port}",
                       "--iterations", "1", "--no-clear", "--quiet"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "w-tail" in out and "WORKER" in out
    finally:
        server.shutdown()


def test_trace_tail_shows_live_lease_countdown():
    reg = MetricsRegistry()
    rec = _recorder()
    eng, gen, targets, state, server, disp = _loopback_job(
        "?d?d?d", [b"999"], unit_size=100, rec=rec, reg=reg)
    try:
        client = CoordinatorClient(*server.address)
        leased = client.call("lease", worker_id="holder")["unit"]
        resp = client.call("trace_tail", n=10)
        client.close()
        (lease,), = (resp["leases"],)
        assert lease["worker"] == "holder"
        assert lease["unit"] == leased["id"]
        assert 0 < lease["deadline_s"] <= 300.0
        assert lease["trace"]
        text = render_top(resp)
        assert "holder" in text
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# local coordinator path: cli crack --session writes the trace stream

def test_local_crack_session_writes_trace_stream(tmp_path, capsys):
    hashes = tmp_path / "h.txt"
    hashes.write_text(hashlib.md5(b"zz9").hexdigest() + "\n")
    session = str(tmp_path / "job.session")
    rc = cli_main(["crack", "--engine", "md5", "--device", "cpu",
                   "-a", "mask", "?l?l?d", str(hashes),
                   "--session", session, "--unit-size", "2000",
                   "--no-potfile", "--quiet"])
    assert rc == 0
    spans = load_trace(session + ".trace.jsonl")
    rep = lifecycle_report(spans)
    assert rep["traces"] >= 1 and rep["orphans"] == 0
    names = {s["name"] for s in spans}
    assert {"lease", "sweep", "hit_verify", "complete"} <= names
    # export round-trips through the cli
    rc = cli_main(["trace", "export", session, "--quiet"])
    assert rc == 0
    with open(session + ".perfetto.json", encoding="utf-8") as fh:
        _assert_chrome_trace_schema(json.load(fh))


# ---------------------------------------------------------------------------
# overhead: tracing on the local sweep hot path <= 2% (bench mode)

def _timed_sweep(trace_on: bool) -> tuple:
    """One local sweep through the real Coordinator/Dispatcher path;
    returns (wall seconds, spans recorded)."""
    reg = MetricsRegistry()
    rec = TraceRecorder(enabled=trace_on, registry=reg)
    eng = get_engine("md5")
    gen = MaskGenerator("?l?l?l?l")          # 456,976 candidates
    targets = [eng.parse_target("ff" * 16)]  # unmatchable: pure sweep
    disp = Dispatcher(gen.keyspace, 1 << 14, registry=reg, recorder=rec)
    worker = CpuWorker(eng, gen, targets, chunk=8192)
    spec = JobSpec(engine="md5", device="cpu", attack="mask",
                   attack_arg="?l?l?l?l", keyspace=gen.keyspace,
                   fingerprint="bench")
    coord = Coordinator(spec, targets, disp, worker, registry=reg,
                        recorder=rec)
    t0 = time.perf_counter()
    result = coord.run()
    elapsed = time.perf_counter() - t0
    assert result.exhausted
    return elapsed, len(rec.tail(100000))


def test_tracing_overhead_on_sweep_hot_path_within_2_percent():
    # interleaved min-of-N wall clocks, recorder on vs off
    offs, ons = [], []
    for _ in range(2):
        offs.append(_timed_sweep(False)[0])
        ons.append(_timed_sweep(True)[0])
    t_off, t_on = min(offs), min(ons)
    # primary, noise-free bound: the spans the traced run actually
    # recorded, costed at a measured per-record price, must be <= 2%
    # of the sweep
    _, n_spans = _timed_sweep(True)
    assert n_spans > 0
    r = _recorder()
    reps = 5000
    t0 = time.perf_counter()
    for i in range(reps):
        r.record("sweep", unit=i, length=1 << 14, hits=0)
    per_span = (time.perf_counter() - t0) / reps
    overhead = per_span * n_spans
    assert overhead <= 0.02 * t_on, (
        f"{n_spans} spans x {per_span * 1e6:.1f}us = {overhead:.4f}s "
        f"> 2% of the {t_on:.3f}s sweep")
    # sanity wall-clock guard (generous: catches a gross regression
    # like an fsync per span without flaking on a loaded 2-core box)
    assert t_on <= t_off * 1.25 + 0.1, (t_on, t_off)


# ---------------------------------------------------------------------------
# crash history -> unit sizing (ROADMAP item satellite)

def test_sizer_shrinks_units_for_crashy_workers_and_recovers():
    from dprf_tpu.tune import AdaptiveUnitSizer
    s = AdaptiveUnitSizer(1 << 20, target_seconds=10.0,
                          min_unit=1 << 8, registry=MetricsRegistry())
    s.observe("w", 1 << 20, 10.0)            # rate -> exactly target
    base = s.next_size("w")
    assert base == 1 << 20
    s.observe_failure("w")
    assert s.next_size("w") == base // 2
    s.observe_failure("w")
    s.observe_failure("w")
    assert s.next_size("w") == base // 8
    # penalty is capped
    for _ in range(20):
        s.observe_failure("w")
    assert s.next_size("w") == base // (1 << s.MAX_PENALTY_BITS)
    assert s.failures("w") == s.MAX_FAILURES
    # clean completions at the same rate earn the size back
    for _ in range(s.MAX_FAILURES):
        s.observe("w", 1 << 18, 2.5)         # same rate, no poisoning
    assert s.failures("w") == 0
    assert s.next_size("w") == base
    # other workers are unaffected throughout
    assert s.next_size("other") == 1 << 20


def test_dispatcher_reports_failures_and_expiries_to_sizer():
    from dprf_tpu.tune import AdaptiveUnitSizer

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    sizer = AdaptiveUnitSizer(100, target_seconds=10.0, min_unit=1,
                              registry=MetricsRegistry())
    d = Dispatcher(10000, 100, lease_timeout=10.0, clock=clk,
                   registry=MetricsRegistry(), sizer=sizer,
                   recorder=_recorder())
    u = d.lease("crashy")
    d.fail(u.unit_id)
    assert sizer.failures("crashy") == 1
    d.lease("crashy")
    clk.t += 60.0                            # lease expires
    d.reap_expired()
    assert sizer.failures("crashy") == 2
    # the reissued unit keeps its geometry (resizing it would tear the
    # ledger); completing it decays one failure and seeds the rate
    u3 = d.lease("crashy")
    assert u3.unit_id == u.unit_id and u3.length == 100
    d.complete(u3.unit_id, elapsed=10.0)     # rate 10/s -> 100 target
    assert sizer.failures("crashy") == 1
    # the next LAZILY-GENERATED unit carries the crash penalty: halved
    assert d.lease("crashy").length == 50


# ---------------------------------------------------------------------------
# declaration lint (tools/check_metrics.py)

def _run_lint(*args):
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "check_metrics.py")
    return subprocess.run([sys.executable, tool, *args],
                          capture_output=True, text=True)


def test_check_metrics_passes_on_the_real_package():
    proc = _run_lint()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_check_metrics_flags_duplicate_declaration(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "telemetry").mkdir(parents=True)
    (pkg / "telemetry" / "trace.py").write_text(
        'SPAN_NAMES = ("lease", "sweep")\n')
    (pkg / "a.py").write_text(
        'def f(m):\n    m.counter("dprf_dup_total", "x")\n')
    (pkg / "b.py").write_text(
        'def g(m):\n    m.counter("dprf_dup_total", "x")\n')
    proc = _run_lint(str(pkg))
    assert proc.returncode == 1
    assert "dprf_dup_total" in proc.stdout


def test_check_metrics_flags_undeclared_span_name(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "telemetry").mkdir(parents=True)
    (pkg / "telemetry" / "trace.py").write_text(
        'SPAN_NAMES = ("lease",)\n')
    (pkg / "a.py").write_text(
        'def f(tracer):\n    tracer.record("made_up_span")\n')
    proc = _run_lint(str(pkg))
    assert proc.returncode == 1
    assert "made_up_span" in proc.stdout
