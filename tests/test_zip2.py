"""WinZip AES ($zip2$, hashcat 13600): parse, oracle, and device
workers with the 2-byte prefilter + oracle auth confirmation."""

import hashlib
import hmac

import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.runtime.workunit import WorkUnit

_KEYLEN = {1: 16, 2: 24, 3: 32}


def _line(pw: bytes, mode: int = 3, iterations: int = 1000,
          data: bytes = b"sekrit-payload" * 5) -> str:
    kl = _KEYLEN[mode]
    salt = bytes(range(4 + 4 * mode))
    dk = hashlib.pbkdf2_hmac("sha1", pw, salt, iterations, 2 * kl + 2)
    verify = dk[2 * kl:]
    auth = hmac.new(dk[kl:2 * kl], data, hashlib.sha1).digest()[:10]
    return "$zip2$*0*%d*0*%s*%s*%x*%s*%s*$/zip2$" % (
        mode, salt.hex(), verify.hex(), len(data), data.hex(), auth.hex())


@pytest.mark.parametrize("mode", [1, 2, 3])
def test_parse_and_oracle(mode):
    eng = get_engine("zip2")
    t = eng.parse_target(_line(b"password", mode=mode))
    assert t.params["mode"] == mode
    assert len(t.params["salt"]) == 4 + 4 * mode
    assert eng.hash_batch([b"password"], params=t.params)[0] == t.digest
    assert not eng.verify(b"nope", t)


def test_parse_rejects_malformed():
    eng = get_engine("zip2")
    for bad in ("$zip2$*0*9*0*aa*aaaa*1*aa*" + "00" * 10 + "*$/zip2$",
                "$zip2$*0*3*0*aabb*aaaa*1*aa*" + "00" * 10 + "*$/zip2$",
                "not a zip line"):
        with pytest.raises(ValueError):
            eng.parse_target(bad)


@pytest.mark.parametrize("mode", [1, 2, 3])
def test_device_mask_worker_cracks(mode):
    cpu = get_engine("zip2")
    dev = get_engine("zip2", device="jax")
    cpu.iterations = dev.iterations = 20    # keep the CPU-mesh suite fast
    try:
        gen = MaskGenerator("?l?l?l")
        t = cpu.parse_target(_line(b"fox", mode=mode, iterations=20))
        w = dev.make_mask_worker(gen, [t], batch=4096, hit_capacity=8,
                                 oracle=cpu)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        assert [h.plaintext for h in hits] == [b"fox"]
    finally:
        cpu.iterations = dev.iterations = 1000


def test_device_wordlist_worker_cracks():
    from dprf_tpu.rules.parser import parse_rule

    cpu = get_engine("zip2")
    dev = get_engine("zip2", device="jax")
    cpu.iterations = dev.iterations = 20
    try:
        gen = WordlistRulesGenerator(
            words=[b"apple", b"Banana", b"zebra"],
            rules=[parse_rule(":"), parse_rule("l")])
        t = cpu.parse_target(_line(b"banana", iterations=20))
        w = dev.make_wordlist_worker(gen, [t], batch=256, hit_capacity=8,
                                     oracle=cpu)
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        assert b"banana" in {h.plaintext for h in hits}
    finally:
        cpu.iterations = dev.iterations = 1000


def test_prefilter_false_maybe_rejected():
    """A target whose verify value collides with some candidate but
    whose auth code matches nothing must produce zero hits (the
    _accept oracle confirmation drops the maybe)."""
    cpu = get_engine("zip2")
    dev = get_engine("zip2", device="jax")
    cpu.iterations = dev.iterations = 20
    try:
        gen = MaskGenerator("?d?d")
        line = _line(b"42", iterations=20)
        # corrupt the auth code: prefilter still fires for '42'
        head, auth_hex, tail = line.rsplit("*", 2)
        line = head + "*" + ("00" * 10) + "*" + tail
        t = cpu.parse_target(line)
        w = dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8,
                                 oracle=cpu)
        assert w.process(WorkUnit(0, 0, gen.keyspace)) == []
    finally:
        cpu.iterations = dev.iterations = 1000
