"""Interprocedural `dprf check` tests (ISSUE 7): the call-graph core,
the locks/protocol analyzers following facts through helpers, and the
two new analyzers (threads, retrace) -- each against planted-violation
fixtures caught at the planted line, with clean twins pinning the
no-false-positive behavior.

Same fixture idiom as test_analysis.py: trees under tmp_path with the
shape the AnalysisContext walks; nothing in a fixture is imported.
"""

import os
import textwrap

import pytest

from dprf_tpu import analysis
from dprf_tpu.analysis import callgraph as cg

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return str(tmp_path)


def check(root, only):
    findings, _ = analysis.run(root, only=[only])
    return findings


def bad(findings):
    return analysis.unsuppressed(findings)


def graph_for(root):
    ctx = analysis.AnalysisContext(root)
    return cg.get(ctx), ctx


# ---------------------------------------------------------------------------
# call-graph core

def test_callgraph_resolves_cross_module_function(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/a.py": """\
            from dprf_tpu.b import helper

            def entry():
                return helper(1)
        """,
        "dprf_tpu/b.py": """\
            def helper(x):
                return x
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "a.py"))
    s = g.summary(mod.functions["entry"])
    callees = [fi.qualname for fi, _ in s.callees.values()]
    assert callees == ["helper"]


def test_callgraph_resolves_method_via_annotation(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/w.py": """\
            class Worker:
                def go(self):
                    return 1

            def drive(w: Worker):
                return w.go()
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "w.py"))
    s = g.summary(mod.functions["drive"])
    assert [fi.qualname for fi, _ in s.callees.values()] == ["Worker.go"]


def test_callgraph_factory_return_annotation_types_result(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/w.py": """\
            class Worker:
                def go(self):
                    return 1

            def make() -> Worker:
                return Worker()

            def drive():
                w = make()
                return w.go()
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "w.py"))
    s = g.summary(mod.functions["drive"])
    names = {fi.qualname for fi, _ in s.callees.values()}
    assert "Worker.go" in names


def test_callgraph_closure_blocking_through_chain(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/c.py": """\
            import time

            def a():
                b()

            def b():
                c()

            def c():
                time.sleep(1)
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "c.py"))
    cl = g.closure(mod.functions["a"])
    assert any(reason == "time.sleep" for reason, _via, _ln in cl.blocking)
    # the via-qualname names the function holding the blocking call
    assert any(via == "c" for _r, via, _ln in cl.blocking)


def test_callgraph_closure_cycle_terminates(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/c.py": """\
            import time

            def ping(n):
                time.sleep(1)
                pong(n)

            def pong(n):
                ping(n)
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "c.py"))
    cl = g.closure(mod.functions["pong"])
    assert any(r == "time.sleep" for r, _v, _ln in cl.blocking)


def test_callgraph_param_key_reads_summarized(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/h.py": """\
            def handle(msg):
                a = msg["worker_id"]
                b = msg.get("ahead")
                if "trace" in msg:
                    pass
                msg["seen"] = 1
                return a, b
        """,
    })
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "h.py"))
    s = g.summary(mod.functions["handle"])
    assert set(s.param_reads["msg"]) == {"worker_id", "ahead", "trace"}
    assert set(s.param_writes["msg"]) == {"seen"}


# ---------------------------------------------------------------------------
# locks: interprocedural upgrades

LOCKED_STATE = """\
    import threading
    import time

    GUARDED_BY = {
        "State": {"lock": ("count",)},
    }

    class State:
        def __init__(self):
            self.lock = threading.Lock()
            self.count = 0
"""


def test_locks_blocking_through_helper_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKED_STATE + """\

        def bump(self):
            with self.lock:
                self.count += 1
                self._log()

        def _log(self):
            time.sleep(0.1)
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1
    assert "reached via State._log()" in f[0].message


def test_locks_blocking_through_module_function_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKED_STATE + """\

        def bump(self):
            with self.lock:
                self.count += 1
                pause()

    def pause():
        time.sleep(0.1)
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1 and "reached via pause()" in f[0].message, \
        [x.message for x in f]


def test_locks_helper_chain_clean_when_not_blocking(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKED_STATE + """\

        def bump(self):
            with self.lock:
                self.count += 1
                self._note()

        def _note(self):
            return self.count

        _note._holds_lock = "lock"
"""})
    assert bad(check(root, "locks")) == []


def test_locks_module_global_unlocked_read_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/cachestate.py": """\
        import threading

        GUARDED_BY = {"<module>": {"_lock": ("_state",)}}

        _lock = threading.Lock()
        _state = {"dir": None}

        def bad_read():
            return _state["dir"]

        def good_read():
            with _lock:
                return _state["dir"]
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1
    assert "module global '_state'" in f[0].message
    assert f[0].line == 9


def test_locks_rlock_reentrant_not_a_deadlock(tmp_path):
    base = """\
        import threading

        GUARDED_BY = {"R": {"lock": ("v",)}}

        class R:
            def __init__(self):
                self.lock = threading.{KIND}()
                self.v = 0

            def outer(self):
                with self.lock:
                    self.v += 1
                    self.inner()

            def inner(self):
                with self.lock:
                    self.v += 2
    """
    root = make_repo(tmp_path, {
        "dprf_tpu/r.py": base.replace("{KIND}", "RLock")})
    assert bad(check(root, "locks")) == []
    root2 = make_repo(tmp_path / "plain", {
        "dprf_tpu/r.py": base.replace("{KIND}", "Lock")})
    f = bad(check(root2, "locks"))
    assert len(f) == 1 and "re-acquiring" in f[0].message, \
        [x.message for x in f]
    assert "via R.inner()" in f[0].message


# ---------------------------------------------------------------------------
# protocol: keys followed through helper functions

def test_protocol_helper_laundered_request_key_caught(tmp_path):
    # the handler hands msg to a helper; the helper reads a key no
    # client ever sends -- the PR 6 blind spot
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                return handle(msg)

        def handle(msg):
            wid = msg["worker_id"]
            n = msg.get("ahead")
            return {"unit": wid, "n": n}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                return resp["unit"]
"""})
    msgs = [x.message for x in bad(check(root, "protocol"))]
    assert len(msgs) == 1, msgs
    assert "reads request key 'ahead'" in msgs[0]


def test_protocol_helper_built_response_keys_clean(tmp_path):
    # response keys built by a helper the handler returns are visible
    # to the client-side read check
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                return build(msg["worker_id"])

        def build(wid):
            return {"unit": wid, "trace": None}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                return resp["unit"], resp.get("trace")
"""})
    assert bad(check(root, "protocol")) == []


def test_protocol_client_helper_response_read_caught(tmp_path):
    # the client hands the response to a helper that reads a key the
    # handler never returns
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                wid = msg["worker_id"]
                return {"unit": wid}

        def pick(resp):
            return resp["unit"], resp["missing"]

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                return pick(resp)
"""})
    msgs = [x.message for x in bad(check(root, "protocol"))]
    assert len(msgs) == 1, msgs
    assert "'missing'" in msgs[0]


# ---------------------------------------------------------------------------
# *args/**kwargs forwarding (ISSUE 8 satellite: the PR 7 gap --
# positional names only -- closed by callgraph slots)

def test_callgraph_forwarded_slots_map_star_and_keyword(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/f.py": """\
        def wrapper(*args, **kwargs):
            return inner(*args, **kwargs)

        def inner(msg, extra=None):
            return msg
"""})
    g, ctx = graph_for(root)
    mod = g.load_file(os.path.join(root, "dprf_tpu", "f.py"))
    wrapper = mod.functions["wrapper"]
    inner = mod.functions["inner"]
    # a positional arg past wrapper's (empty) param list lands in *args
    assert cg.slot_at(wrapper, 0) == ("*", "args", 0)
    # a keyword with no matching param lands in **kwargs
    assert cg.slot_for_keyword(wrapper, "msg") == ("**", "kwargs",
                                                   "msg")
    s = g.summary(wrapper)
    (callee, argspec, kwspec, _line), = s.calls
    assert callee is inner
    # *args element 0 forwarded through wrapper reaches inner's "msg"
    assert cg.forwarded_slots(callee, argspec, kwspec,
                              ("*", "args", 0)) == ["msg"]
    # **kwargs entry "extra" reaches inner's keyword param
    assert cg.forwarded_slots(callee, argspec, kwspec,
                              ("**", "kwargs", "extra")) == ["extra"]
    # an unknown kwargs entry resolves to nothing, not a guess
    assert cg.forwarded_slots(callee, argspec, kwspec,
                              ("**", "kwargs", "nope")) == []


def test_protocol_star_forwarding_wrapper_key_caught(tmp_path):
    # the handler launders msg through a *args/**kwargs wrapper; the
    # eventual reader's undeclared key must still surface
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                return fwd(msg)

        def fwd(*args, **kwargs):
            return handle(*args, **kwargs)

        def handle(msg):
            return {"unit": msg["worker_id"], "n": msg.get("ahead")}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                return resp["unit"]
"""})
    msgs = [x.message for x in bad(check(root, "protocol"))]
    assert len(msgs) == 1, msgs
    assert "reads request key 'ahead'" in msgs[0]


def test_protocol_keyword_passed_dict_followed(tmp_path):
    # msg handed on BY KEYWORD (helper(req=msg)) -- dropped entirely
    # by the positional-names-only dataflow
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                return handle(req=msg)

        def handle(req=None):
            return {"unit": req["worker_id"], "n": req["ahead"]}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3)
                return resp["unit"]
"""})
    msgs = [x.message for x in bad(check(root, "protocol"))]
    assert len(msgs) == 1, msgs
    assert "reads request key 'ahead'" in msgs[0]


def test_protocol_star_forwarding_clean_when_keys_sent(tmp_path):
    # clean twin: every key the forwarded reader touches is sent
    root = make_repo(tmp_path, {"dprf_tpu/rpc.py": """\
        class Server:
            def op_lease(self, msg):
                return fwd(msg)

        def fwd(*args, **kwargs):
            return handle(*args, **kwargs)

        def handle(msg):
            return {"unit": msg["worker_id"], "n": msg.get("ahead")}

        class Client:
            def call(self, op, **kw):
                return {}

            def go(self):
                resp = self.call("lease", worker_id=3, ahead=2)
                return resp["unit"]
"""})
    assert bad(check(root, "protocol")) == []


def test_locks_blocking_through_star_forwarding_wrapper_caught(
        tmp_path):
    # blocking facts survive a *args/**kwargs forwarding wrapper
    root = make_repo(tmp_path, {"dprf_tpu/state.py": LOCKED_STATE + """\

        def bump(self):
            with self.lock:
                self.count += 1
                self._fwd(1, 2)

        def _fwd(self, *args, **kwargs):
            return self._slow(*args, **kwargs)

        def _slow(self, a, b):
            time.sleep(a + b)
"""})
    f = bad(check(root, "locks"))
    assert len(f) == 1, [x.message for x in f]
    assert "blocking" in f[0].message


# ---------------------------------------------------------------------------
# threads: lifecycle discipline

def test_threads_unjoined_local_thread_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/t.py": """\
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "never joined in this function" in f[0].message
    assert f[0].line == 4


def test_threads_joined_or_daemon_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/t.py": """\
        import threading

        def run_sync(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        def run_background(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()

        def run_late_daemon(fn):
            t = threading.Thread(target=fn)
            t.daemon = True
            t.start()

        def handoff(fn):
            t = threading.Thread(target=fn)
            return t
"""})
    assert bad(check(root, "threads")) == []


def test_threads_unbound_thread_start_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/t.py": """\
        import threading

        def fire(fn):
            threading.Thread(target=fn).start()
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "unbound non-daemon Thread" in f[0].message


def test_threads_attr_thread_unjoined_caught_and_join_clean(tmp_path):
    planted = """\
        import threading

        class S:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
    """
    root = make_repo(tmp_path, {"dprf_tpu/s.py": planted})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "never joined by any method" in f[0].message
    clean = planted + """\

            def stop(self):
                self._t.join()
    """
    root2 = make_repo(tmp_path / "clean", {"dprf_tpu/s.py": clean})
    assert bad(check(root2, "threads")) == []


def test_threads_resource_closed_on_one_path_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/r.py": """\
        import socket

        def fetch(host, want):
            s = socket.create_connection((host, 1))
            data = s.recv(1)
            if want:
                s.close()
            return data
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "only some paths" in f[0].message


def test_threads_resource_finally_close_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/r.py": """\
        import socket

        def fetch(host):
            s = socket.create_connection((host, 1))
            try:
                return s.recv(1)
            finally:
                s.close()

        def read(path):
            with open(path) as fh:
                return fh.read()

        def chain(path):
            open(path).close()
"""})
    assert bad(check(root, "threads")) == []


def test_threads_resource_never_released_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/r.py": """\
        def leak(path):
            fh = open(path)
            return fh.read()
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "never released here" in f[0].message


def test_threads_resource_passed_straight_on_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/r.py": """\
        import json

        def load(path):
            return json.load(open(path))
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "passed straight on" in f[0].message


def test_threads_self_resource_requires_releases_entry(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/c.py": """\
        class Journal:
            def __init__(self, path):
                self._fh = open(path, "a")
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1
    assert "not declared in a module-level RELEASES" in f[0].message


def test_threads_releases_declared_and_released_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/c.py": """\
        RELEASES = {"Journal": {"_fh": "close"}}

        class Journal:
            def __init__(self, path):
                self._fh = open(path, "a")

            def close(self):
                self._fh.close()
"""})
    assert bad(check(root, "threads")) == []


def test_threads_stale_releases_declarations_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/c.py": """\
        RELEASES = {
            "Ghost": {"_fh": "close"},
            "NoMeth": {"_fh": "shutdown"},
            "NoClose": {"_fh": "close"},
        }

        class NoMeth:
            def __init__(self, path):
                self._fh = open(path)

        class NoClose:
            def __init__(self, path):
                self._fh = open(path)

            def close(self):
                pass
"""})
    msgs = [x.message for x in bad(check(root, "threads"))]
    assert len(msgs) == 3, msgs
    assert any("unknown class 'Ghost'" in m for m in msgs)
    assert any("no such method" in m for m in msgs)
    assert any("never closes it" in m for m in msgs)


def test_threads_condition_wait_without_while_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/q.py": """\
        import threading

        class Q:
            def __init__(self):
                self.cv = threading.Condition()
                self.items = []

            def get(self):
                with self.cv:
                    if not self.items:
                        self.cv.wait()
                    return self.items.pop()
"""})
    f = bad(check(root, "threads"))
    assert len(f) == 1 and "outside a `while`" in f[0].message


def test_threads_condition_unheld_wait_and_notify_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/q.py": """\
        import threading

        class Q:
            def __init__(self):
                self.cv = threading.Condition()
                self.items = []

            def get(self):
                while not self.items:
                    self.cv.wait()

            def put(self, x):
                self.items.append(x)
                self.cv.notify()
"""})
    msgs = [x.message for x in bad(check(root, "threads"))]
    assert len(msgs) == 2, msgs
    assert all("without holding it" in m for m in msgs)


def test_threads_condition_disciplined_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/q.py": """\
        import threading

        class Q:
            def __init__(self):
                self.cv = threading.Condition()
                self.items = []

            def get(self):
                with self.cv:
                    while not self.items:
                        self.cv.wait()
                    return self.items.pop()

            def get_pred(self):
                with self.cv:
                    self.cv.wait_for(lambda: self.items)
                    return self.items.pop()

            def put(self, x):
                with self.cv:
                    self.items.append(x)
                    self.cv.notify()

            def _drain(self):
                while not self.items:
                    self.cv.wait()

            _drain._holds_lock = "cv"
"""})
    assert bad(check(root, "threads")) == []


def test_threads_lambda_body_is_not_this_functions_code(tmp_path):
    # a lambda CONSTRUCTING a thread hands it to its caller -- the
    # enclosing function must not be charged with the leak (ast.walk
    # without subtree pruning used to flag this)
    root = make_repo(tmp_path, {"dprf_tpu/t.py": """\
        import threading

        def factory():
            make = lambda: threading.Thread(target=print)
            return make
"""})
    assert bad(check(root, "threads")) == []


def test_threads_event_wait_is_not_condition_wait(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/e.py": """\
        import threading

        class W:
            def __init__(self):
                self.done = threading.Event()

            def block(self):
                self.done.wait()
"""})
    assert bad(check(root, "threads")) == []


# ---------------------------------------------------------------------------
# retrace: host syncs + silent recompiles in declared hot paths

RETRACE_HEAD = """\
    import jax
    import numpy as np

    @jax.jit
    def step(xs):
        return xs
"""


def test_retrace_item_in_hot_loop_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(units):
        out = 0
        for u in units:
            r = step(u)
            out += r.item()
        return out
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and ".item() inside the hot loop" in f[0].message


def test_retrace_sync_after_loop_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(units):
        flag = None
        for u in units:
            r = step(u)
            flag = r if flag is None else flag + r
        return flag.item()
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_varying_shape_into_jit_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(xs):
        n = 1
        r = None
        for _ in range(8):
            n = n + 1
            r = step(xs[:n])
        return r
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "loop-varying shape" in f[0].message


def test_retrace_fixed_shape_jit_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(xs, stride):
        r = None
        for i in range(8):
            r = step(xs[:stride])
        return r
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_loop_varying_static_argnum_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        import jax

        def body(xs, n):
            return xs

        HOT_PATHS = ("sweep",)

        def sweep(xs):
            f = jax.jit(body, static_argnums=(1,))
            for n in range(8):
                r = f(xs, n)
            return r
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "static_argnums position 1" in f[0].message


def test_retrace_implicit_bool_on_device_value_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(units):
        for u in units:
            r = step(u)
            if r:
                break
        return r
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "implicit bool()" in f[0].message


def test_retrace_np_asarray_on_device_value_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(units):
        out = []
        for u in units:
            r = step(u)
            out.append(np.asarray(r))
        return out
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "np.asarray()" in f[0].message


def test_retrace_np_asarray_on_host_value_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def sweep(units, gen):
        r = None
        for u in units:
            base = np.asarray(gen.digits(u))
            r = step(base)
        return r
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_helper_laundered_sync_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def fetch(x):
        return np.asarray(x)

    def sweep(units):
        out = []
        for u in units:
            r = step(u)
            out.append(fetch(r))
        return out
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1
    assert "fetch() syncs the device value" in f[0].message


def test_retrace_factory_assigned_step_resolved(tmp_path):
    # the make_*_step idiom: a factory returning an inner @jax.jit
    # closure, stored on self in __init__, dispatched in the hot loop
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        import jax

        def make_step():
            @jax.jit
            def step(xs):
                return xs
            return step

        HOT_PATHS = ("W.submit",)

        class W:
            def __init__(self):
                self.step = make_step()

            def submit(self, xs):
                n = 0
                r = None
                for _ in range(4):
                    n = n + 1
                    r = self.step(xs[:n])
                return r
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "loop-varying shape" in f[0].message


def test_retrace_stale_hot_path_declaration_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        HOT_PATHS = ("nope",)
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "stale declaration" in f[0].message


def test_retrace_lambda_deferring_sync_clean(tmp_path):
    # a lambda built in the loop but invoked after it is deferred
    # work, not an in-loop sync; same for a helper whose only "sync"
    # sits in a nested def it never runs
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def fetch_later(x):
        def inner():
            return np.asarray(x)
        return inner

    def sweep(units):
        out = []
        for u in units:
            r = step(u)
            out.append(lambda v=r: v.item())
            out.append(fetch_later(r))
        return [f() for f in out]
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_undeclared_module_not_scanned(tmp_path):
    # no HOT_PATHS -> the module's loops are out of scope by design
    root = make_repo(tmp_path, {"dprf_tpu/cold.py": RETRACE_HEAD + """\

    def warmup(units):
        for u in units:
            step(u).item()
"""})
    assert bad(check(root, "retrace")) == []


# ---------------------------------------------------------------------------
# framework: --explain

def test_explain_renders_rules_and_tables(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/c.py": """\
        RELEASES = {"Journal": {"_fh": "close"}}

        class Journal:
            def __init__(self, path):
                self._fh = open(path, "a")

            def close(self):
                self._fh.close()
"""})
    text = analysis.explain(root, "threads")
    assert "RELEASES" in text
    assert "dprf_tpu/c.py:1" in text
    assert '"Journal": {"_fh": "close"}' in text
    with pytest.raises(ValueError):
        analysis.explain(root, "nope")


def test_explain_real_repo_declares_all_tables():
    # the runtime's live declarations render for each table-backed
    # check -- the reference future suppression-writers read
    for name, needle in (("locks", "GUARDED_BY"),
                         ("threads", "RELEASES"),
                         ("retrace", "HOT_PATHS")):
        text = analysis.explain(REPO, name)
        assert "Declarations in this repo:" in text
        assert needle in text


# ---------------------------------------------------------------------------
# retrace: attribute-target taint (ISSUE 9 satellite) -- the device
# value must not launder out of the taint set through `self.attr = ...`

def test_retrace_attribute_target_taint_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        import jax

        def ident(x):
            return x

        HOT_PATHS = ("W.sweep",)

        class W:
            def __init__(self):
                self.step = jax.jit(ident)

            def sweep(self, units):
                out = 0
                for u in units:
                    self._flag = self.step(u)
                    out += int(self._flag)
                return out
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "int() on a device value" in f[0].message


def test_retrace_attribute_flag_read_after_loop_clean(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        import jax

        def ident(x):
            return x

        HOT_PATHS = ("W.sweep",)

        class W:
            def __init__(self):
                self.step = jax.jit(ident)
                self._flag = None

            def sweep(self, units):
                for u in units:
                    f = self.step(u)
                    self._flag = f if self._flag is None \
                        else self._flag + f
                return int(self._flag)
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_attribute_truth_test_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        import jax

        def ident(x):
            return x

        HOT_PATHS = ("W.sweep",)

        class W:
            def __init__(self):
                self.step = jax.jit(ident)

            def sweep(self, units):
                hits = []
                for u in units:
                    self._flag = self.step(u)
                    if self._flag:
                        hits.append(u)
                return hits
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "implicit bool()" in f[0].message


# ---------------------------------------------------------------------------
# retrace: PERF_PROBE declared sampled-probe exemption (ISSUE 9)

def test_retrace_undeclared_probe_helper_caught(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)

    def grab(r):
        return r.item()

    def sweep(units):
        out = 0
        for u in units:
            r = step(u)
            out += grab(r)
        return out
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "syncs the device value" in f[0].message


def test_retrace_declared_perf_probe_exempt(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)
    PERF_PROBE = ("grab",)

    def grab(r):
        return r.item()

    def sweep(units):
        out = 0
        for u in units:
            r = step(u)
            out += grab(r)
        return out
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_dotted_perf_probe_resolves_cross_module(tmp_path):
    root = make_repo(tmp_path, {
        "dprf_tpu/probe_mod.py": """\
            def grab(r):
                return r.item()
        """,
        "dprf_tpu/hot.py": RETRACE_HEAD + """\

    from dprf_tpu.probe_mod import grab

    HOT_PATHS = ("sweep",)
    PERF_PROBE = ("dprf_tpu.probe_mod.grab",)

    def sweep(units):
        out = 0
        for u in units:
            r = step(u)
            out += grab(r)
        return out
"""})
    assert bad(check(root, "retrace")) == []


def test_retrace_stale_perf_probe_entry_is_finding(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": RETRACE_HEAD + """\

    HOT_PATHS = ("sweep",)
    PERF_PROBE = ("nope",)

    def sweep(units):
        r = None
        for u in units:
            r = step(u)
        return r
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "stale declaration" in f[0].message
    assert "nope" in f[0].message


def test_retrace_probe_table_without_hot_paths_is_finding(tmp_path):
    root = make_repo(tmp_path, {"dprf_tpu/hot.py": """\
        HOT_PATHS = ()
        PERF_PROBE = ("grab",)

        def grab(r):
            return r.item()
"""})
    f = bad(check(root, "retrace"))
    assert len(f) == 1 and "exemption applies to nothing" \
        in f[0].message
