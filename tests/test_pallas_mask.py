"""Pallas MD5 mask kernel vs the oracle (interpret mode on the CPU
backend; the same kernel compiles natively on TPU).

Covers: charset segment decomposition, planted-password extraction,
n_valid masking, the tile-collision -> rescan overflow convention, and
worker-level equivalence with the XLA pipeline path.
"""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import BUILTIN_CHARSETS, MaskGenerator
from dprf_tpu.ops.pallas_mask import (MAX_SEGMENTS, TILE, charset_segments,
                                     make_pallas_mask_crack_step,
                                     mask_supported)
from dprf_tpu.runtime.worker import PallasMaskWorker
from dprf_tpu.runtime.workunit import WorkUnit


def _target(plain: bytes) -> np.ndarray:
    return np.frombuffer(hashlib.md5(plain).digest(),
                         dtype="<u4").astype(np.uint32)


@pytest.mark.smoke
def test_charset_segments_reconstruct():
    for name, cs in BUILTIN_CHARSETS.items():
        segs = charset_segments(cs)
        assert len(segs) <= MAX_SEGMENTS, name
        # reconstruct every byte from the piecewise map
        got = []
        for d in range(len(cs)):
            delta = [dl for s, dl in segs if s <= d][-1]
            got.append(d + delta)
        assert bytes(got) == cs, name
    assert mask_supported(list(BUILTIN_CHARSETS.values()))


def _engine_target(engine_name: str, plain: bytes) -> np.ndarray:
    """Target digest words in the engine's layout, via hashlib oracles."""
    if engine_name == "md5":
        d, dt = hashlib.md5(plain).digest(), "<u4"
    elif engine_name == "sha1":
        d, dt = hashlib.sha1(plain).digest(), ">u4"
    elif engine_name == "sha256":
        d, dt = hashlib.sha256(plain).digest(), ">u4"
    elif engine_name == "sha512":
        d, dt = hashlib.sha512(plain).digest(), ">u4"
    elif engine_name == "sha384":
        d, dt = hashlib.sha384(plain).digest(), ">u4"
    else:   # ntlm: MD4 over UTF-16LE
        from dprf_tpu.engines.cpu.md4 import md4
        d, dt = md4(plain.decode("latin-1").encode("utf-16-le")), "<u4"
    return np.frombuffer(d, dtype=dt).astype(np.uint32)


@pytest.mark.parametrize("engine", ["md5", "sha1", "ntlm"])
@pytest.mark.parametrize("mask,plant", [
    ("?l?l?l?l", b"crab"),
    ("?d?d?d?d?d", b"90210"),
    ("?a?a?a", b"X& "),
    ("pre?l?d", b"prez7"),      # literals + mixed charsets
])
def test_kernel_finds_planted(engine, mask, plant):
    gen = MaskGenerator(mask)
    pidx = gen.index_of(plant)
    step = make_pallas_mask_crack_step(engine, gen,
                                       _engine_target(engine, plant),
                                       batch=TILE, interpret=True)
    base = TILE * (pidx // TILE)
    n_valid = min(TILE, gen.keyspace - base)
    bd = jnp.asarray(gen.digits(base), dtype=jnp.int32)
    count, lanes, _ = step(bd, jnp.int32(n_valid))
    assert int(count) == 1
    assert int(np.asarray(lanes)[0]) == pidx - base
    # plant masked out by n_valid -> no hit
    count2, _, _ = step(bd, jnp.int32(pidx - base))
    assert int(count2) == 0


@pytest.mark.smoke
def test_tile_collision_forces_rescan_convention():
    """Two hits in one tile can only report one lane, so the reducer
    must return count > hit_capacity (the worker then rescans exactly).
    Driven directly through reduce_tile_hits: an MD5 collision can't be
    fabricated, but the kernel's counts output can."""
    from dprf_tpu.ops.pallas_mask import reduce_tile_hits

    cap = 8
    # tile 3 holds two hits; only lane 7 was extractable
    counts = jnp.asarray([[0], [1], [0], [2]], jnp.int32)
    lanes = jnp.asarray([[-1], [5], [-1], [7]], jnp.int32)
    count, glanes, _ = reduce_tile_hits(counts, lanes, cap, tile=100)
    assert int(count) == cap + 1          # forces worker rescan
    # single-hit tiles still decode to global lanes
    counts1 = jnp.asarray([[0], [1], [0], [1]], jnp.int32)
    count1, glanes1, _ = reduce_tile_hits(counts1, lanes, cap, tile=100)
    assert int(count1) == 2
    got = sorted(int(x) for x in np.asarray(glanes1) if x >= 0)
    assert got == [105, 307]
    # capacity still exact when more hit-tiles than capacity slots
    count0, _, _ = reduce_tile_hits(counts1, lanes, 0, tile=100)
    assert int(count0) == 2


def test_worker_rescan_on_fabricated_collision():
    """End-to-end: a step reporting a tile collision must make the
    worker fall back to the oracle rescan and recover every hit."""
    gen = MaskGenerator("?l?l?l?l")
    plant = b"wasp"
    eng = get_engine("md5", device="jax")
    targets = [eng.parse_target(hashlib.md5(plant).hexdigest())]
    worker = PallasMaskWorker(eng, gen, targets, batch=TILE,
                                 hit_capacity=8,
                                 oracle=get_engine("md5"), interpret=True)
    real_step = worker.step

    def lying_step(base, n_valid):
        count, lanes, tpos = real_step(base, n_valid)
        # pretend a tile had 2 hits: overflow convention
        return jnp.int32(9), lanes, tpos

    worker.step = lying_step
    hits = worker.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.cand_index, h.plaintext) for h in hits] == \
        [(gen.index_of(plant), plant)]


@pytest.mark.parametrize("engine", ["md5", "sha1", "ntlm"])
def test_pallas_worker_matches_xla_worker(engine):
    gen = MaskGenerator("?l?l?l?l")
    plant = b"wasp"
    eng = get_engine(engine, device="jax")
    targets = [eng.parse_target(_engine_target(engine, plant).astype(
        "<u4" if eng.little_endian else ">u4").tobytes().hex())]
    oracle = get_engine(engine)
    pworker = PallasMaskWorker(eng, gen, targets, batch=TILE,
                               hit_capacity=8, oracle=oracle,
                               interpret=True)
    unit = WorkUnit(0, 0, gen.keyspace)
    phits = pworker.process(unit)
    xworker = eng.make_mask_worker(gen, targets, batch=1 << 14,
                                   hit_capacity=8, oracle=oracle)
    xhits = xworker.process(unit)
    assert [(h.target_index, h.cand_index, h.plaintext) for h in phits] == \
        [(h.target_index, h.cand_index, h.plaintext) for h in xhits]
    assert phits[0].plaintext == plant


@pytest.mark.parametrize("engine", ["md5", "sha1", "sha256", "ntlm",
                                    "sha512", "sha384"])
def test_kernel_body_emulated_finds_planted(engine):
    """Eager (no-jit) drive of the shared kernel body: the only CPU
    vehicle for the SHA-256 kernel math, whose statically-unrolled
    graph XLA:CPU cannot compile in reasonable time; also cross-checks
    the other engines against the same body the pallas_call wraps."""
    from dprf_tpu.ops.pallas_mask import emulate_mask_kernel

    gen = MaskGenerator("?l?l?l?l")
    plant = b"crab"
    pidx = gen.index_of(plant)
    tw = _engine_target(engine, plant)
    base = TILE * (pidx // TILE)
    bd = gen.digits(base)
    counts, lanes = emulate_mask_kernel(engine, gen, tw, batch=TILE,
                                        base_digits=bd,
                                        n_valid=min(TILE, gen.keyspace - base))
    assert counts.sum() == 1               # batch == TILE: a single tile
    assert base + int(lanes[0, 0]) == pidx
    # n_valid masking: plant excluded -> no hit anywhere
    counts2, _ = emulate_mask_kernel(engine, gen, tw, batch=TILE,
                                     base_digits=bd, n_valid=pidx - base)
    assert counts2.sum() == 0


def test_emulator_matches_pallas_interpret():
    """The emulator and the pallas_call path must agree tile-for-tile
    (they share the kernel body; this pins the plumbing equivalence
    that lets emulator-only SHA-256 coverage stand in for interpret
    runs)."""
    from dprf_tpu.ops.pallas_mask import emulate_mask_kernel, make_mask_pallas_fn

    gen = MaskGenerator("?l?l?l?l")
    plant = b"wasp"
    tw = _engine_target("md5", plant)
    batch = 2 * TILE
    bd = gen.digits(0)
    fn = make_mask_pallas_fn("md5", gen, tw, batch, interpret=True)
    pc, pl_ = fn(jnp.asarray(bd, jnp.int32), jnp.asarray([batch], jnp.int32))
    ec, el = emulate_mask_kernel("md5", gen, tw, batch, bd, batch)
    assert (np.asarray(pc) == ec).all()
    assert (np.asarray(pl_) == el).all()


def test_make_mask_worker_routes_to_kernel(monkeypatch):
    """With DPRF_PALLAS=1: single-target sha1 routes to the kernel;
    multi-target routes to the kernel ONLY when an oracle is available
    to verify Bloom maybes; SHA-256 stays on the XLA pipeline off-TPU
    (its unrolled kernel graph is Mosaic-only, see kernel_eligible)."""
    monkeypatch.setenv("DPRF_PALLAS", "1")
    gen = MaskGenerator("?l?l?l")
    eng = get_engine("sha1", device="jax")
    t1 = eng.parse_target(hashlib.sha1(b"abc").hexdigest())
    t2 = eng.parse_target(hashlib.sha1(b"xyz").hexdigest())
    w1 = eng.make_mask_worker(gen, [t1], batch=TILE, hit_capacity=8)
    assert isinstance(w1, PallasMaskWorker)
    w2 = eng.make_mask_worker(gen, [t1, t2], batch=TILE, hit_capacity=8)
    assert not isinstance(w2, PallasMaskWorker)      # no oracle
    w2o = eng.make_mask_worker(gen, [t1, t2], batch=TILE, hit_capacity=8,
                               oracle=get_engine("sha1"))
    assert isinstance(w2o, PallasMaskWorker) and w2o.multi
    e256 = get_engine("sha256", device="jax")
    t3 = e256.parse_target(hashlib.sha256(b"abc").hexdigest())
    w3 = e256.make_mask_worker(gen, [t3], batch=TILE, hit_capacity=8)
    assert not isinstance(w3, PallasMaskWorker)      # cpu backend


def test_bloom_tables_never_false_negative():
    """Every target's own digest bits must be set in its set's bitmap
    for all probes -- a real hit can never be filtered out."""
    from dprf_tpu.ops.pallas_mask import K_PROBES, SET_SIZE, bloom_tables

    rng = np.random.default_rng(7)
    tw = rng.integers(0, 1 << 32, size=(2500, 4), dtype=np.uint64).astype(
        np.uint32)
    T = bloom_tables(tw)
    assert T.shape == (3 * K_PROBES, 128)
    for i, words in enumerate(tw):
        s = i // SET_SIZE
        for p in range(K_PROBES):
            o = 12 * p
            j, sh = divmod(o, 32)
            bits = int(words[j]) >> sh
            if sh > 20:
                bits |= int(words[j + 1]) << (32 - sh)
            bits &= 0xFFF
            word = T[s * K_PROBES + p, bits >> 5]
            assert (word >> (bits & 31)) & 1, (i, p)


def _multi_targets(engine_name, eng, plants, n_fill=1000, seed=3):
    """Parse targets for planted passwords + n_fill random off-keyspace
    digests (Bloom fillers that can never hit)."""
    rng = np.random.default_rng(seed)
    raws = [
        _engine_target(engine_name, p).astype(
            "<u4" if eng.little_endian else ">u4").tobytes().hex()
        for p in plants]
    W = len(_engine_target(engine_name, b"x"))
    for _ in range(n_fill):
        raws.append(rng.bytes(4 * W).hex())
    return [eng.parse_target(r) for r in raws]


@pytest.mark.parametrize("engine", ["md5", "ntlm"])
def test_pallas_multi_target_matches_xla(engine):
    """The Bloom multi-target kernel path must match the XLA
    multi-target path hit-for-hit on a 1k-target list, including a
    deliberate two-hits-in-one-tile collision (VERDICT r1 item 5)."""
    from dprf_tpu.runtime.worker import DeviceMaskWorker

    gen = MaskGenerator("?l?l?l?l")
    # tiles: 0 holds two planted hits (collision -> tile rescan),
    # 2 and 5 hold one isolated hit each (single-maybe -> oracle verify)
    plant_idx = [7, 2000, 2 * TILE + 11, 5 * TILE + 4095]
    plants = [gen.candidate(i) for i in plant_idx]
    eng = get_engine(engine, device="jax")
    oracle = get_engine(engine)
    targets = _multi_targets(engine, eng, plants)

    pworker = PallasMaskWorker(eng, gen, targets, batch=2 * TILE,
                               hit_capacity=8, oracle=oracle,
                               interpret=True)
    assert pworker.multi
    unit = WorkUnit(0, 0, 6 * TILE)
    phits = sorted((h.target_index, h.cand_index, h.plaintext)
                   for h in pworker.process(unit))
    xworker = DeviceMaskWorker(eng, gen, targets, batch=2 * TILE,
                               hit_capacity=8, oracle=oracle)
    xhits = sorted((h.target_index, h.cand_index, h.plaintext)
                   for h in xworker.process(unit))
    assert phits == xhits
    assert [c for _, c, _ in phits] == plant_idx
    assert [p for _, _, p in phits] == plants


def test_make_mask_worker_falls_back_on_kernel_failure(monkeypatch, capsys):
    """A kernel that fails to build/compile (Mosaic regression) must
    degrade to the XLA DeviceMaskWorker with a warning, not abort."""
    import dprf_tpu.runtime.worker as worker_mod
    from dprf_tpu.runtime.worker import DeviceMaskWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")

    class Boom(worker_mod.PallasMaskWorker):
        def __init__(self, *a, **kw):
            raise RuntimeError("injected Mosaic lowering failure")

    monkeypatch.setattr(worker_mod, "PallasMaskWorker", Boom)
    gen = MaskGenerator("?l?l?l")
    eng = get_engine("sha1", device="jax")
    t1 = eng.parse_target(hashlib.sha1(b"abc").hexdigest())
    w = eng.make_mask_worker(gen, [t1], batch=TILE, hit_capacity=8)
    assert isinstance(w, DeviceMaskWorker)
    err = capsys.readouterr().err
    assert "falling back to the XLA pipeline" in err
    # and the fallback worker actually cracks
    planted = gen.index_of(b"dog")
    tdog = eng.parse_target(hashlib.sha1(b"dog").hexdigest())
    w = eng.make_mask_worker(gen, [tdog], batch=TILE, hit_capacity=8)
    hits = w.process(WorkUnit(-1, 0, gen.keyspace))
    assert [h.cand_index for h in hits] == [planted]


def test_make_mask_worker_warmup_failure_falls_back(monkeypatch, capsys):
    """A compile failure at first call (not construction) is also
    caught: warmup() forces the compile inside the factory's guard."""
    import dprf_tpu.runtime.worker as worker_mod
    from dprf_tpu.runtime.worker import DeviceMaskWorker

    monkeypatch.setenv("DPRF_PALLAS", "1")

    class LateBoom(worker_mod.PallasMaskWorker):
        def warmup(self):
            raise RuntimeError("injected compile failure")

    monkeypatch.setattr(worker_mod, "PallasMaskWorker", LateBoom)
    gen = MaskGenerator("?l?l?l")
    eng = get_engine("sha1", device="jax")
    t1 = eng.parse_target(hashlib.sha1(b"abc").hexdigest())
    w = eng.make_mask_worker(gen, [t1], batch=TILE, hit_capacity=8)
    assert isinstance(w, DeviceMaskWorker)
    assert "falling back" in capsys.readouterr().err


@pytest.mark.smoke
def test_sha512_rounds_unrolled_matches_loop_form():
    """The statically-unrolled pair-arithmetic rounds (the Mosaic
    form the kernel core uses) must be bit-identical to the fori_loop
    XLA form on random full blocks."""
    from dprf_tpu.ops import sha512 as s5

    rng = np.random.default_rng(3)
    words = jnp.asarray(rng.integers(0, 2 ** 32, (4, 32),
                                     dtype=np.uint32))
    ref = s5.sha512_compress(s5.INIT512, words)
    pairs = [(words[:, 2 * i], words[:, 2 * i + 1]) for i in range(16)]
    init = [(jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF))
            for v in s5.INIT512]
    vars8 = tuple((jnp.full((4,), h), jnp.full((4,), l))
                  for h, l in init)
    out = s5.sha512_rounds(vars8, pairs)
    got = []
    for v, iv in zip(out, init):
        h, l = s5._add64(v, iv)
        got.extend([h, l])
    assert np.array_equal(np.stack([np.asarray(g) for g in got], -1),
                          np.asarray(ref))


@pytest.mark.smoke
def test_position_tables_mixes_segments_and_luts():
    """Builtin charsets stay on the arithmetic mux; scrambled orders
    (Markov permutations) become lane-axis LUT inputs."""
    from dprf_tpu.ops.pallas_mask import position_tables

    scrambled = bytes(dict.fromkeys(
        b"qazwsxedcrfvtgbyhnujmikolp"))            # 26 letters, shuffled
    proc, luts = position_tables([BUILTIN_CHARSETS["l"], scrambled])
    assert isinstance(proc[0], list)               # arithmetic segments
    assert proc[1] == ("lut", 0)                   # LUT marker
    assert luts.shape == (2, 128)
    # LUT rows reconstruct the charset exactly
    assert bytes(int(luts.reshape(-1)[d]) for d in
                 range(len(scrambled))) == scrambled
    # all-arithmetic masks carry no LUT input
    proc2, luts2 = position_tables([BUILTIN_CHARSETS["l"]])
    assert luts2 is None and isinstance(proc2[0], list)


def test_kernel_finds_planted_markov_mask():
    """A Markov-permuted mask (arbitrary charset order at every
    position) rides the kernel via the LUT decode: planted password
    found at its exact index in interpret mode."""
    from dprf_tpu.ops.pallas_mask import position_tables

    counts = np.zeros((4, 256), np.uint64)
    rng = np.random.RandomState(11)
    counts[:, :] = rng.randint(1, 10**6, (4, 256))
    gen = MaskGenerator("?l?l?d?d", markov_counts=counts)
    proc, luts = position_tables(gen.charsets)
    assert luts is not None, \
        "the permutation should exceed the segment budget"
    plant = gen.candidate(12345)
    pidx = 12345
    step = make_pallas_mask_crack_step("md5", gen,
                                       _engine_target("md5", plant),
                                       batch=TILE, interpret=True)
    base = TILE * (pidx // TILE)
    bd = jnp.asarray(gen.digits(base), dtype=jnp.int32)
    count, lanes, _ = step(bd, jnp.int32(min(TILE, gen.keyspace - base)))
    assert int(count) == 1
    assert int(np.asarray(lanes)[0]) == pidx - base


def test_markov_worker_routes_to_kernel(monkeypatch):
    """DPRF_PALLAS=1: a Markov-ordered mask job gets the Pallas worker
    (pre-r5 it fell back to the XLA pipeline) and cracks end-to-end."""
    monkeypatch.setenv("DPRF_PALLAS", "1")
    counts = np.zeros((3, 256), np.uint64)
    rng = np.random.RandomState(7)
    counts[:, :] = rng.randint(1, 10**6, (3, 256))
    gen = MaskGenerator("?l?d?l", markov_counts=counts)
    secret = gen.candidate(404)
    eng = get_engine("md5", device="jax")
    t = eng.parse_target(hashlib.md5(secret).hexdigest())
    w = eng.make_mask_worker(gen, [t], batch=TILE, hit_capacity=8,
                             oracle=get_engine("md5", device="cpu"))
    assert isinstance(w, PallasMaskWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 404, secret)]


@pytest.mark.smoke
def test_unbounded_segment_decode_matches_oracle():
    """The heavy kernel families (krb5/pdf/7z/pbkdf2) decode Markov/
    scrambled charsets through the UNBOUNDED segment mux
    (segment_tables): eager decode_candidate_bytes must reproduce the
    generator's candidates byte-for-byte, and the families' eligibility
    predicates must now admit such masks."""
    from dprf_tpu.ops.pallas_7z import sevenzip_kernel_eligible
    from dprf_tpu.ops.pallas_krb5 import krb5_kernel_eligible
    from dprf_tpu.ops.pallas_mask import (decode_candidate_bytes,
                                          segment_tables)
    from dprf_tpu.ops.pallas_pdf import pdf_kernel_eligible

    counts = np.zeros((3, 256), np.uint64)
    rng = np.random.RandomState(3)
    counts[:, :] = rng.randint(1, 10**6, (3, 256))
    gen = MaskGenerator("?l?l?d", markov_counts=counts)
    tabs = segment_tables(gen.charsets)
    assert any(len(t) > 16 for t in tabs)     # really past the budget
    base = jnp.asarray(gen.digits(100), jnp.int32)
    carry = jnp.arange(16, dtype=jnp.int32).reshape(2, 8)
    byts = decode_candidate_bytes(gen.radices, tabs, gen.length,
                                  base, carry)
    got = np.stack([np.asarray(b) for b in byts], axis=-1).reshape(16, 3)
    want = np.stack([np.frombuffer(gen.candidate(100 + i), np.uint8)
                     for i in range(16)])
    assert (got == want).all()
    assert krb5_kernel_eligible(gen)
    assert pdf_kernel_eligible(gen, 3, 16)
    assert sevenzip_kernel_eligible(gen, 19, 2)
