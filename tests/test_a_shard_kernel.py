"""Kernel shard-compute coverage: the fused Pallas kernel as the
sharded superstep's per-shard compute (parallel/sharded.
make_sharded_kernel_mask_step), the single-chip loop superstep
(PallasMaskWorker SUPER_MODE="loop"), the eager kernel emulator vs the
pallas_call interpret path, probe tables on wordlist / combinator
workers, and the knob-sweep tune surface (sweep_values +
lookup_tuned_value / record_tuned_value).

Everything runs md5 in interpret mode on the conftest's 8 virtual CPU
devices (real-TPU numbers live in the TPU_PROBE_LOG records); parity
is always against the CpuWorker oracle, exact hit sets, so the kernel
path's sentinel/overflow disciplines are exercised end to end.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# kernel-pipeline compiles: full suite / tier-1, excluded from the
# <5-min smoke tier (tools/check_markers.py enforces a tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.base import Target
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.parallel import make_mesh
from dprf_tpu.parallel.worker import ShardedMaskWorker
from dprf_tpu.runtime.worker import CpuWorker, PallasMaskWorker
from dprf_tpu.runtime.workunit import WorkUnit

SUB = 32          # conftest pins DPRF_PALLAS_SUB=32; passed explicitly
TILE = SUB * 128  # so these shapes hold even without the env knob


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should fake 8 CPU devices"
    return make_mesh(8)


def _md5_targets(gen, idxs):
    return [Target(str(i), hashlib.md5(gen.candidate(i)).digest())
            for i in idxs]


def _cpu_hits(gen, targets, unit):
    return sorted((h.target_index, h.cand_index, h.plaintext)
                  for h in CpuWorker(get_engine("md5", device="cpu"),
                                     gen, targets).process(unit))


# ---------------------------------------------------------------------------
# sharded kernel compute: make_sharded_kernel_mask_step through
# ShardedMaskWorker(kernel={...})


def test_sharded_kernel_single_target_parity(mesh):
    """Single-target kernel shard compute: exact in-kernel compare, no
    probe, no oracle -- a plant at the LAST keyspace index must survive
    the window n_valid masking of the final partial stride."""
    gen = MaskGenerator("?d?d?d?d?d")       # 100000
    targets = _md5_targets(gen, [gen.keyspace - 1])
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=TILE, hit_capacity=16,
                          kernel={"interpret": True, "sub": SUB})
    assert "+kernel" in w.ATTACK
    unit = WorkUnit(0, 0, gen.keyspace)
    got = sorted((h.target_index, h.cand_index, h.plaintext)
                 for h in w.process(unit))
    assert got == _cpu_hits(gen, targets, unit)
    assert got[0][1] == gen.keyspace - 1


def test_sharded_kernel_multi_probe_boundaries(mesh):
    """Multi-target kernel shard compute: plants at shard edges, the
    superstep window edge, and the last index.  Kernel hits come back
    as SENTINEL-tagged blocked-probe survivors; the worker must
    resolve each with one oracle hash and match the CPU oracle
    exactly (no false positive may surface, no real hit may drop)."""
    gen = MaskGenerator("?d?d?d?d?d")       # 100000
    B = 8 * 128                 # sub=8 tile: 12 strides of 8192, so
    stride = 8 * B              # the superstep (SUPER_MIN=8) engages
    plant = [0, B - 1, B, stride - 1, stride,           # shard edges
             2 * stride - 1, 2 * stride,                # window edge
             gen.keyspace - 1]                          # last index
    targets = _md5_targets(gen, plant)
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=B, hit_capacity=16,
                          oracle=get_engine("md5", device="cpu"),
                          kernel={"interpret": True, "sub": 8})
    assert "+kernel" in w.ATTACK
    pend = w.submit(WorkUnit(0, 0, gen.keyspace))
    kinds = [k for k, _, _ in pend.queued]
    assert "sshard" in kinds       # fused windows actually dispatched
    got = sorted((h.target_index, h.cand_index, h.plaintext)
                 for h in pend.resolve())
    assert got == _cpu_hits(gen, targets,
                            WorkUnit(0, 0, gen.keyspace))
    assert [g[1] for g in got] == plant


def test_sharded_kernel_overflow_redrives_exactly(mesh):
    """More survivors in one shard's window than hit_capacity: the
    buffer truncates but the count survives, and the worker must
    redrive that window and report every hit exactly once."""
    gen = MaskGenerator("?d?d?d?d?d")       # 100000
    plant = [0, 1, 2, 3, 4, 5, gen.keyspace - 1]   # 6 > cap in shard 0
    targets = _md5_targets(gen, plant)
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=TILE, hit_capacity=2,
                          oracle=get_engine("md5", device="cpu"),
                          kernel={"interpret": True, "sub": SUB})
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted(h.cand_index for h in hits) == plant
    assert len(hits) == len(set(h.cand_index for h in hits))


def test_sharded_kernel_resume_resplit(mesh):
    """A sweep interrupted mid-keyspace resumes under a DIFFERENT
    shard count (mesh of 4): the kernel compute decodes from base +
    offset, so the union of the two partial sweeps must equal one
    full-oracle sweep."""
    gen = MaskGenerator("?d?d?d?d?d")       # 100000
    cut = 8 * TILE + 517            # mid-stride, mid-batch
    plant = [0, cut - 1, cut, cut + 1, gen.keyspace - 1]
    targets = _md5_targets(gen, plant)
    oracle = get_engine("md5", device="cpu")
    w8 = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                           mesh, batch_per_device=TILE, hit_capacity=16,
                           oracle=oracle,
                           kernel={"interpret": True, "sub": SUB})
    first = w8.process(WorkUnit(0, 0, cut))
    w4 = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                           make_mesh(4), batch_per_device=TILE,
                           hit_capacity=16, oracle=oracle,
                           kernel={"interpret": True, "sub": SUB})
    rest = w4.process(WorkUnit(0, cut, gen.keyspace - cut))
    got = sorted(h.cand_index for h in first + rest)
    assert got == plant


# ---------------------------------------------------------------------------
# eager kernel emulator vs the pallas_call interpret path


def test_emulate_matches_pallas_call_offset():
    """emulate_mask_kernel runs the kernel body eagerly; its output
    must match make_mask_pallas_fn(interpret=True) bit for bit,
    including the traced window-offset argument the sharded / loop
    supersteps rely on."""
    from dprf_tpu.ops import pallas_mask

    gen = MaskGenerator("?l?l?l")           # 17576
    batch, offset, n_valid = 2 * TILE, TILE, TILE + 321
    idx = offset + 100      # valid iff offset + lane < WINDOW n_valid
    tw = np.frombuffer(hashlib.md5(gen.candidate(idx)).digest(),
                       dtype="<u4").astype(np.uint32)
    ec, el = pallas_mask.emulate_mask_kernel(
        "md5", gen, tw, batch, gen.digits(0), n_valid, sub=SUB,
        offset=offset)
    fn = pallas_mask.make_mask_pallas_fn(
        "md5", gen, tw, batch, sub=SUB, interpret=True,
        with_offset=True)
    pc, pl = fn(jnp.asarray(gen.digits(0), jnp.int32),
                jnp.full((1,), n_valid, jnp.int32),
                jnp.full((1,), offset, jnp.int32))
    np.testing.assert_array_equal(ec, np.asarray(pc))
    np.testing.assert_array_equal(el, np.asarray(pl))
    assert int(ec.sum()) == 1               # exactly the planted hit


def test_emulate_matches_pallas_call_probe():
    """Multi-target blocked-probe compare: emulator and pallas_call
    agree on maybe-counts and lanes, and every planted target is a
    survivor (real hits can never be filtered)."""
    from dprf_tpu.ops import pallas_mask

    gen = MaskGenerator("?l?l?l")
    batch, n_valid = 2 * TILE, 2 * TILE
    plant = [0, 77, TILE - 1, TILE, batch - 1]
    tw = np.stack([np.frombuffer(hashlib.md5(gen.candidate(i)).digest(),
                                 dtype="<u4").astype(np.uint32)
                   for i in plant])
    ec, el = pallas_mask.emulate_mask_kernel(
        "md5", gen, tw, batch, gen.digits(0), n_valid, sub=SUB,
        probe_fp=1e-4)
    fn = pallas_mask.make_mask_pallas_fn(
        "md5", gen, tw, batch, sub=SUB, interpret=True,
        with_offset=True, probe_fp=1e-4)
    pc, pl = fn(jnp.asarray(gen.digits(0), jnp.int32),
                jnp.full((1,), n_valid, jnp.int32),
                jnp.full((1,), 0, jnp.int32))
    np.testing.assert_array_equal(ec, np.asarray(pc))
    np.testing.assert_array_equal(el, np.asarray(pl))
    assert int(ec.sum()) >= len(plant)      # probes may add FPs, never drop


# ---------------------------------------------------------------------------
# single-chip loop superstep (PallasMaskWorker SUPER_MODE="loop")


def test_loop_superstep_single_target_parity():
    """The loop superstep fuses `inner` kernel batches per dispatch;
    hits at batch boundaries inside the window, the window's last
    index, and the keyspace's last index (the per-batch remainder)
    must decode to the same global indices as the per-batch path."""
    gen = MaskGenerator("?d?d?d?d")     # 10000 over a sub=8 tile of
    b = 8 * 128                         # 1024: 9 strides, so the loop
    plant = [0, b, 8 * b - 1,           # (SUPER_MIN=8) engages
             gen.keyspace - 1]
    eng = get_engine("md5", device="jax")
    got = []
    for i in plant:
        targets = _md5_targets(gen, [i])
        w = PallasMaskWorker(eng, gen, targets, batch=b,
                             hit_capacity=16, interpret=True, sub=8)
        assert w.SUPER_MODE == "loop"
        # the fusion window really opens for this keyspace/stride
        assert w._super_inner(gen.keyspace // w.stride) >= 2
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
        got.append(sorted(h.cand_index for h in hits))
    assert got == [[i] for i in plant]


def test_loop_superstep_multi_matches_perbatch():
    """Multi-target loop supersteps (Bloom maybes + collided-tile
    rescan buffers accumulated across the window) against the CPU
    oracle, with two targets INSIDE one tile to force the collided
    path through the window accumulation."""
    gen = MaskGenerator("?d?d?d?d")         # 10000, sub=8 tile
    b = 8 * 128
    plant = [10, 11, b + 5, 2 * b - 1, gen.keyspace - 1]
    targets = _md5_targets(gen, plant)
    w = PallasMaskWorker(get_engine("md5", device="jax"), gen, targets,
                         batch=b, hit_capacity=16,
                         oracle=get_engine("md5", device="cpu"),
                         interpret=True, sub=8)
    assert w._super_inner(gen.keyspace // w.stride) >= 2
    unit = WorkUnit(0, 0, gen.keyspace)
    got = sorted((h.target_index, h.cand_index, h.plaintext)
                 for h in w.process(unit))
    assert got == _cpu_hits(gen, targets, unit)


# ---------------------------------------------------------------------------
# probe tables on the wordlist / combinator families


@pytest.fixture()
def low_probe_floor(monkeypatch):
    monkeypatch.setenv("DPRF_TARGETS_PROBE_MIN", "4")


def _full_sweep(worker, keyspace, unit=8192):
    hits = []
    for s in range(0, keyspace, unit):
        hits.extend(worker.process(WorkUnit(-1, s, min(unit,
                                                       keyspace - s))))
    return sorted((h.target_index, h.cand_index) for h in hits)


@pytest.fixture(scope="module")
def word_case():
    """(gen, targets, oracle, expected hits) -- the CPU oracle sweep
    runs once for both the device and the sharded parity test."""
    from dprf_tpu.bench import _synthetic_words
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import load_rules
    gen = WordlistRulesGenerator(_synthetic_words(256),
                                 load_rules("best64"), max_len=24)
    K = gen.keyspace
    idxs = sorted({0, 7, gen.n_rules + 3, K // 3, K // 2 + 1,
                   K - gen.n_rules, K - 1})
    oracle = get_engine("md5", device="cpu")
    raws = sorted(set(oracle.hash_batch([gen.candidate(i)
                                         for i in idxs])))
    targets = [oracle.parse_target(d.hex()) for d in raws]
    want = _full_sweep(CpuWorker(oracle, gen, targets), K)
    return gen, targets, oracle, want


@pytest.fixture(scope="module")
def combi_case():
    from dprf_tpu.bench import _synthetic_words
    from dprf_tpu.generators.combinator import CombinatorGenerator
    gen = CombinatorGenerator(_synthetic_words(128),
                              _synthetic_words(128), max_len=24)
    K = gen.keyspace
    idxs = sorted({0, 5, K // 4, K // 2, K - 1, 999})
    oracle = get_engine("md5", device="cpu")
    raws = sorted(set(oracle.hash_batch([gen.candidate(i)
                                         for i in idxs])))
    targets = [oracle.parse_target(d.hex()) for d in raws]
    want = _full_sweep(CpuWorker(oracle, gen, targets), K)
    return gen, targets, oracle, want


def test_wordlist_probe_parity(low_probe_floor, word_case):
    from dprf_tpu.runtime.worker import DeviceWordlistWorker
    gen, targets, oracle, want = word_case
    w = DeviceWordlistWorker(get_engine("md5", device="jax"), gen,
                             targets, batch=4096, oracle=oracle)
    assert "+probe" in w.ATTACK
    assert _full_sweep(w, gen.keyspace) == want


def test_combinator_probe_parity(low_probe_floor, combi_case):
    from dprf_tpu.runtime.worker import DeviceCombinatorWorker
    gen, targets, oracle, want = combi_case
    w = DeviceCombinatorWorker(get_engine("md5", device="jax"), gen,
                               targets, batch=4096, oracle=oracle)
    assert "+probe" in w.ATTACK
    assert _full_sweep(w, gen.keyspace) == want


def test_sharded_wordlist_probe_parity(mesh, low_probe_floor,
                                       word_case):
    from dprf_tpu.parallel.worker import ShardedWordlistWorker
    gen, targets, oracle, want = word_case
    w = ShardedWordlistWorker(get_engine("md5", device="jax"), gen,
                              targets, mesh, word_batch_per_device=32,
                              oracle=oracle)
    assert "+probe" in w.ATTACK
    assert _full_sweep(w, gen.keyspace) == want


def test_sharded_combinator_probe_parity(mesh, low_probe_floor,
                                         combi_case):
    from dprf_tpu.parallel.worker import ShardedCombinatorWorker
    gen, targets, oracle, want = combi_case
    w = ShardedCombinatorWorker(get_engine("md5", device="jax"), gen,
                                targets, mesh, batch_per_device=512,
                                oracle=oracle)
    assert "+probe" in w.ATTACK
    assert _full_sweep(w, gen.keyspace) == want


# ---------------------------------------------------------------------------
# knob-sweep tune surface


class _FakeWorker:
    """Deterministic worker for sweep_values: advances an injected
    clock by unit_len / speed per process() call."""

    stride = 64

    def __init__(self, speed, clock_cell, seen_units):
        self.speed = speed
        self._clock = clock_cell
        self._seen = seen_units

    def process(self, unit):
        self._seen.append(unit.length)
        self._clock[0] += unit.length / self.speed
        return []


def test_sweep_values_picks_fastest_and_skips_failures():
    from dprf_tpu.tune import sweep_values

    t = [0.0]
    seen = []
    speeds = {2: 100.0, 4: 500.0, 8: None}   # 8 fails to build

    def mk(v):
        if speeds[v] is None:
            raise RuntimeError("no such tile")
        return _FakeWorker(speeds[v], t, seen)

    res = sweep_values(mk, [2, 8, 4], keyspace=1 << 20,
                       probe_seconds=0.5, unit_strides=16,
                       clock=lambda: t[0], label="inner")
    assert res.batch == 4                    # the fastest value wins
    assert res.rate_hs == pytest.approx(500.0, rel=0.05)
    errs = [p for p in res.swept if p.error]
    assert [p.batch for p in errs] == [8]    # failure recorded, skipped
    # unit_strides actually sized the probe units (fusion engages)
    assert max(seen) == _FakeWorker.stride * 16


def test_sweep_values_all_fail_raises():
    from dprf_tpu.tune import sweep_values

    def mk(v):
        raise RuntimeError("nope")

    with pytest.raises(ValueError, match="every rung"):
        sweep_values(mk, [1, 2], keyspace=1024,
                     clock=lambda: 0.0)


def test_tuned_value_cache_roundtrip():
    from dprf_tpu.tune import (TuneResult, lookup_tuned_value,
                               record_tuned_value)

    res = TuneResult(32, 1.5e6, 0.25, [], source="swept")
    record_tuned_value("md5", "inner", "mask", "jax", res,
                       extras={"hit_cap": 64})
    assert lookup_tuned_value("md5", "inner", attack="mask",
                              device="jax",
                              extras={"hit_cap": 64}) == 32
    # the knob forks the key: neither another knob nor the plain
    # batch lookup may alias it
    assert lookup_tuned_value("md5", "sub", attack="mask",
                              device="jax",
                              extras={"hit_cap": 64}) is None
    assert lookup_tuned_value("md5", "inner", attack="mask",
                              device="jax",
                              extras={"hit_cap": 128}) is None
