"""Keccak/SHA3 Pallas kernel body vs hashlib oracles (eager emulation
on CPU -- the kernel itself is TPU-only; see
ops/pallas_keccak.keccak_kernel_eligible)."""

import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.keccak import keccak_f, keccak_f_unrolled
from dprf_tpu.ops.pallas_keccak import SUBK, emulate_keccak_kernel

pytestmark = pytest.mark.smoke

TILE = SUBK * 128


def test_keccak_f_unrolled_matches_fori():
    rng = np.random.default_rng(9)
    state = {(x, y): (jnp.asarray(rng.integers(0, 2 ** 32, (4,),
                                               dtype=np.uint32)),
                      jnp.asarray(rng.integers(0, 2 ** 32, (4,),
                                               dtype=np.uint32)))
             for x in range(5) for y in range(5)}
    a = keccak_f(dict(state))
    b = keccak_f_unrolled(dict(state))
    for k in state:
        assert np.array_equal(np.asarray(a[k][0]), np.asarray(b[k][0]))
        assert np.array_equal(np.asarray(a[k][1]), np.asarray(b[k][1]))


def _tw(plain: bytes, variant: str) -> np.ndarray:
    from dprf_tpu.engines import get_engine
    d = get_engine(variant, device="cpu").hash_batch([plain])[0]
    if variant.startswith("sha3"):   # cross-check vs the stdlib oracle
        assert d == getattr(hashlib,
                            variant.replace("-", "_"))(plain).digest()
    return np.frombuffer(d, ">u4").astype(np.uint32)


@pytest.mark.parametrize("variant,pad,rate,out", [
    ("sha3-256", 0x06, 136, 32),
    ("sha3-512", 0x06, 72, 64),
    ("sha3-224", 0x06, 144, 28),    # half-lane digest tail
    ("keccak-256", 0x01, 136, 32),
])
def test_keccak_kernel_body_emulated_finds_planted(variant, pad, rate,
                                                   out):
    gen = MaskGenerator("?l?l?l?l")
    plant = b"frog"
    pidx = gen.index_of(plant)
    tw = _tw(plant, variant)
    base = TILE * (pidx // TILE)
    bd = gen.digits(base)
    counts, lanes = emulate_keccak_kernel(
        gen, tw, batch=TILE, base_digits=bd,
        n_valid=min(TILE, gen.keyspace - base),
        pad_byte=pad, rate=rate, out_bytes=out)
    assert counts.sum() == 1
    assert base + int(lanes[0, 0]) == pidx
    # n_valid masking excludes the plant
    counts2, _ = emulate_keccak_kernel(
        gen, tw, batch=TILE, base_digits=bd, n_valid=pidx - base,
        pad_byte=pad, rate=rate, out_bytes=out)
    assert counts2.sum() == 0
