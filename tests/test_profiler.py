"""Kernel-level profiling plane (ISSUE 15): the single-flight
ProfileCapture, the dependency-free perfetto analyzer pinned against
the committed TPU-shaped fixture (exact op-class fractions +
generate/hash/compare phase mapping), capture-dir retention caps, the
op_profile / op_profile_push RPC flow through a real worker_loop, the
alert-triggered auto-capture chaos path (exactly one request, cooldown
enforced, journaled, rendered by `dprf report`), the exact
compile-cache classifier, and the disabled-path overhead guard.
"""

import gzip
import json
import logging
import os
import time

import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.engines import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.rpc import (CoordinatorClient, CoordinatorServer,
                                  CoordinatorState, worker_loop)
from dprf_tpu.runtime.session import SessionJournal
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.telemetry import profiler as profiler_mod
from dprf_tpu.telemetry.alerts import AlertEngine, AlertRule
from dprf_tpu.telemetry.profiler import (ProfileCapture, analyze_trace,
                                         classify_op, enforce_caps,
                                         render_summary,
                                         sanitize_summary)
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import TraceRecorder

pytestmark = [pytest.mark.smoke, pytest.mark.profiler]

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "tpu_profile_trace.json.gz")


# ---------------------------------------------------------------------------
# the analyzer against the committed TPU-shaped fixture (exact pins)

def test_fixture_exact_class_fractions_and_phase_mapping():
    """Acceptance pin: the committed fixture's op-class fractions and
    generate/hash/compare mapping are EXACT -- any analyzer change
    that moves them is a deliberate, reviewed change."""
    s = analyze_trace(FIXTURE)
    assert s["schema"] == 1 and not s.get("error")
    assert s["seconds"] == {"fusion": 0.008, "op": 0.0,
                            "custom_call": 0.0005, "collective": 0.001,
                            "copy": 0.0005, "compile": 0.003,
                            "host": 0.02, "infra": 0.0}
    assert s["device_s"] == 0.01
    assert s["fractions"] == {"compute": 0.85, "collective": 0.1,
                              "copy": 0.05}
    assert s["phases"] == {"generate": 0.001, "hash": 0.0065,
                           "compare": 0.001, "other": 0.0015}
    top = s["top_ops"][0]
    assert (top["name"], top["class"], top["self_s"], top["count"]) \
        == ("md5_fusion.1", "fusion", 0.006, 1)
    # the XLA Modules wrapper lane must NOT double-count device time
    names = {o["name"] for o in s["top_ops"]}
    assert "jit_crack_step_module" not in names


def test_fixture_candidates_turn_on_per_candidate_cost():
    reg = MetricsRegistry()
    s = analyze_trace(FIXTURE, candidates=1000, registry=reg)
    assert s["candidates"] == 1000
    assert s["device_s_per_cand"] == pytest.approx(0.01 / 1000)
    # no analyzed program for engine=None: divergence stays None
    assert s["divergence"] is None


def test_render_summary_shows_fractions_and_top_ops():
    text = render_summary(analyze_trace(FIXTURE))
    assert "compute 85.0%" in text
    assert "collective 10.0%" in text
    assert "md5_fusion.1" in text
    assert "compile 0.0030s" in text


def test_classify_op_table():
    assert classify_op("my_big_fusion.12", "device") == "fusion"
    assert classify_op("all-gather.1", "device") == "collective"
    assert classify_op("reduce-scatter.3", "device") == "collective"
    assert classify_op("copy.1", "device") == "copy"
    assert classify_op("convert.9", "device") == "copy"
    assert classify_op("custom-call.2", "device") == "custom_call"
    assert classify_op("reduce-window", "device") == "op"
    assert classify_op("ThunkExecutor::Execute", "device") == "infra"
    assert classify_op("$cli.py:1 main", "host") == "host"
    assert classify_op("anything", "compile") == "compile"


def test_self_time_subtracts_children(tmp_path):
    """A parent frame's self time loses every nested child's dur --
    the host lane would otherwise read as N x wall."""
    evs = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "$a.py:1 outer"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 10, "dur": 30,
         "name": "$b.py:2 inner"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 50, "dur": 20,
         "name": "$b.py:2 inner"},
    ]
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"traceEvents": evs}))
    s = analyze_trace(str(p))
    # outer self = 100 - 30 - 20 = 50us; total host = 100us
    assert s["seconds"]["host"] == pytest.approx(100e-6)


def test_analyze_bad_paths(tmp_path):
    assert "error" in analyze_trace(str(tmp_path))   # no trace under it
    bad = tmp_path / "perfetto_trace.json.gz"
    with gzip.open(bad, "wt") as fh:
        fh.write("{not json")
    assert "unparsable" in analyze_trace(str(bad))["error"]


def test_sanitize_summary_bounds_and_known_keys():
    dirty = {"schema": 1, "junk": object(), "path": "x" * 9999,
             "device_s": "0.5", "fractions": {"compute": "0.5"},
             "top_ops": ([{"name": "n" * 999, "class": "fusion",
                           "self_s": 0.25, "count": 2}] * 99
                         + [{"name": "bad", "self_s": "nope"}])}
    s = sanitize_summary(dirty)
    assert "junk" not in s
    assert len(s["path"]) <= profiler_mod.MAX_SUMMARY_STR
    assert s["fractions"] == {"compute": 0.5}
    assert len(s["top_ops"]) == profiler_mod.TOP_OPS
    assert s["top_ops"][0]["count"] == 2
    assert len(s["top_ops"][0]["name"]) <= profiler_mod.MAX_SUMMARY_STR
    # a row with an unparsable float is skipped entirely
    assert all(isinstance(r["self_s"], float) for r in s["top_ops"])
    assert sanitize_summary("nope") is None
    assert sanitize_summary({}) is None


def test_phase_patterns_merge_engine_declaration():
    """The md5 device engine's PROFILE_PHASES merge OVER the analyzer
    defaults -- the per-engine declaration site."""
    pats = profiler_mod.phase_patterns("md5")
    assert "md5" in pats["hash"]
    assert "fusion" in pats["hash"]          # defaults kept
    assert "decode_batch" in pats["generate"]
    # unknown engine: defaults only, never a crash
    assert profiler_mod.phase_patterns("no-such-engine") \
        == profiler_mod.phase_patterns(None)


def test_cli_profile_local_analyze(capsys):
    rc = cli_main(["profile", FIXTURE, "--json", "--quiet"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fractions"] == {"compute": 0.85, "collective": 0.1,
                                "copy": 0.05}
    rc = cli_main(["profile", "--quiet"])      # no target, no connect
    assert rc == 2


# ---------------------------------------------------------------------------
# retention caps

def test_enforce_caps_keep_last_n_and_xplane_drop(tmp_path):
    root = str(tmp_path)
    base = tmp_path / "plugins" / "profile"
    for i, name in enumerate(["r1", "r2", "r3"]):
        d = base / name
        d.mkdir(parents=True)
        (d / "perfetto_trace.json.gz").write_bytes(b"x" * 100)
        (d / "host.xplane.pb").write_bytes(b"y" * 1000)
        t = time.time() - 100 + i
        os.utime(d, (t, t))
    enforce_caps(root, keep=2, max_bytes=500)
    left = sorted(p.name for p in base.iterdir())
    assert left == ["r2", "r3"]               # oldest reaped
    for name in left:
        d = base / name
        assert (d / "perfetto_trace.json.gz").exists()
        assert not (d / "host.xplane.pb").exists()   # over the cap
    # keep=0 / max_bytes=0 disable both; a rootless dir is a no-op
    enforce_caps(root, keep=0, max_bytes=0)
    assert sorted(p.name for p in base.iterdir()) == ["r2", "r3"]
    enforce_caps(str(tmp_path / "nope"), keep=1, max_bytes=1)


# ---------------------------------------------------------------------------
# single-flight + the bounded window (live CPU-backend captures)

def test_single_flight_session_blocks_window_and_second_session(
        tmp_path, caplog):
    prof = ProfileCapture(registry=MetricsRegistry())
    with prof.session(str(tmp_path / "a"), owner="cli"):
        assert prof.busy() == "cli"
        # a second starter degrades to a refusal, never an exception
        assert not prof.begin_window(0.5,
                                     directory=str(tmp_path / "b"))
        with prof.session(str(tmp_path / "c"), owner="env"):
            pass                              # no-op, no crash
        assert prof.busy() == "cli"           # still the first owner
    assert prof.busy() is None
    # the slot frees: a window can start now, and abort releases it
    assert prof.begin_window(0.5, directory=str(tmp_path / "b"))
    assert prof.window_active()
    prof.abort_window()
    assert prof.busy() is None and not prof.window_active()


@pytest.mark.compileheavy
def test_live_cpu_capture_attributes_host_and_compile(tmp_path):
    """Live e2e on the CPU backend: a capture window around a COLD
    jit compile + dispatches attributes nonzero host-python and
    compile-pass time (per-HLO device lanes are TPU-only -- the
    committed fixture covers those), counts candidates through the
    window, and lands in the capture history."""
    import jax
    import jax.numpy as jnp
    prof = ProfileCapture(registry=MetricsRegistry())
    n = [0]
    # 7919 lanes: a prime no other test compiles, so the persistent
    # cache cannot have it and the compile runs INSIDE the window
    x = jnp.arange(7919, dtype=jnp.uint32)

    def busy():
        f = jax.jit(lambda v, s: ((v * jnp.uint32(2654435761)
                                   + s) ^ (v >> 7)).sum())
        f(x, jnp.uint32(n[0] % 3)).block_until_ready()
        n[0] += x.shape[0]

    s = prof.capture(seconds=1.0, directory=str(tmp_path / "cap"),
                     trigger="manual", engine="md5",
                     counter_fn=lambda: n[0], busy_fn=busy)
    assert s is not None and not s.get("error")
    assert s["seconds"]["host"] > 0
    assert s["seconds"]["compile"] > 0
    assert s["candidates"] and s["candidates"] >= 7919
    assert s["trigger"] == "manual" and s["window_s"] == 1.0
    assert os.path.isdir(s["path"])
    assert prof.last_summary() is s
    assert prof.last_capture_ts("manual") is not None
    # single-flight released: the next window starts cleanly
    assert prof.begin_window(0.5, directory=str(tmp_path / "cap"))
    prof.abort_window()


def _stub_traces(monkeypatch, stop=None):
    """Instant fake jax trace + analyzer: window state-machine tests
    must not pay real captures."""
    import jax
    monkeypatch.setitem(profiler_mod._deps, "state", "ready")
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: None)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        stop or (lambda: None))
    monkeypatch.setattr(
        profiler_mod, "analyze_trace",
        lambda path, **k: {"schema": 1, "path": path})


def _drive(prof, deadline_s=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        s = prof.poll()
        if s is not None:
            return s
        time.sleep(0.01)
    raise AssertionError("window never finished")


def test_new_window_never_clobbers_finishing_summary(
        tmp_path, monkeypatch):
    """A second capture armed while the first is still finishing on
    its background thread must not discard the first summary: both
    reach poll(), in order."""
    _stub_traces(monkeypatch)
    prof = ProfileCapture(registry=MetricsRegistry())
    assert prof.begin_window(0.5, directory=str(tmp_path / "a"),
                             request_id=1)
    assert prof.poll() is None               # trace started
    time.sleep(0.55)
    assert prof.poll() is None               # finishing (background)
    for _ in range(500):                     # slot frees post-stop
        if prof.busy() is None:
            break
        time.sleep(0.01)
    assert prof.begin_window(0.5, directory=str(tmp_path / "b"),
                             request_id=2)
    s1 = _drive(prof)                        # A's summary first
    assert s1["request_id"] == 1
    time.sleep(0.55)
    s2 = _drive(prof)
    assert s2["request_id"] == 2
    assert prof.busy() is None


def test_abort_leaves_finishing_window_to_its_thread(
        tmp_path, monkeypatch):
    """abort_window during the FINISHING state must not release the
    single-flight slot out from under the background thread (a
    successor owner's slot would be freed mid-capture); the thread
    still delivers the summary."""
    import threading
    gate = threading.Event()
    _stub_traces(monkeypatch, stop=lambda: gate.wait(5))
    prof = ProfileCapture(registry=MetricsRegistry())
    assert prof.begin_window(0.5, directory=str(tmp_path / "c"),
                             request_id=3)
    assert prof.poll() is None
    time.sleep(0.55)                         # window min is 0.5 s
    assert prof.poll() is None               # finishing; stop blocked
    prof.abort_window()
    assert prof.busy() is not None           # NOT released by abort
    gate.set()
    s = _drive(prof)
    assert s["request_id"] == 3
    assert prof.busy() is None


def test_profile_request_table_ttl_and_cap(monkeypatch):
    """Pending requests are client-fed: stale entries expire by TTL
    (a dead worker can't block its own future auto-captures) and the
    table is bounded like the other worker-keyed tables."""
    from dprf_tpu.runtime import rpc as rpc_mod
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        now = time.monotonic()
        with state.lock:
            state._profile_requests["dead"] = {
                "id": 1, "seconds": 1.0, "trigger": "straggler",
                "queued_at": now - rpc_mod.PROFILE_REQUEST_TTL_S - 1}
            state._profile_requests["fresh"] = {
                "id": 2, "seconds": 1.0, "trigger": "manual",
                "queued_at": now}
            state._prune_profile_requests(now)
            assert list(state._profile_requests) == ["fresh"]
        # the cap: a request flood with throwaway worker ids errors
        # out instead of growing the table without bound
        with state.lock:
            for i in range(state.MAX_WORKER_LABELS):
                state._profile_requests[f"w{i}"] = {
                    "id": i, "seconds": 1.0, "trigger": "manual",
                    "queued_at": now}
        c = CoordinatorClient(*server.address)
        from dprf_tpu.runtime.rpc import RpcError
        with pytest.raises(RpcError, match="too many pending"):
            c.call("profile", action="request", worker="one-more")
        # re-requesting an ALREADY-pending worker shares the queued
        # request's id (a second operator must not orphan the first
        # requester's poll), and neither the delivered request nor
        # the pending table on the wire carries the coordinator-clock
        # bookkeeping
        resp = c.call("profile", action="request", worker="w0")
        assert resp["worker"] == "w0" and resp["pending"] is True
        assert resp["request_id"] == 0          # the queued one's id
        st = c.call("profile")
        assert all("queued_at" not in r
                   for r in st["pending"].values())
        c.close()
        with state.lock:
            req = state._profile_request_for("w0")
            assert req is not None and "queued_at" not in req
            # delivery moved it to the inflight ledger
            assert 0 in state._profile_inflight
    finally:
        server.shutdown()


def test_disabled_path_overhead_negligible():
    """PR 4/9-style guard: with no capture active, the per-iteration
    work the worker loop gained (one poll probe + one lease-response
    dict read) must be microseconds -- <= 2% of even a 20 ms unit."""
    prof = ProfileCapture()
    resp = {"unit": None, "stop": False, "pull": 0}
    t0 = time.perf_counter()
    n = 10_000
    for _ in range(n):
        prof.poll()
        resp.get("profile")
    per_iter = (time.perf_counter() - t0) / n
    assert per_iter < 400e-6, \
        f"disabled-path probe {per_iter * 1e6:.1f}us/iter"


# ---------------------------------------------------------------------------
# RPC flow: op_profile request -> worker_loop capture -> push -> fetch

class SlowCpuWorker(CpuWorker):
    """CpuWorker with a per-unit floor so the loop outlasts a capture
    window (the md5 sweep alone finishes in milliseconds)."""

    def process(self, unit):
        time.sleep(0.05)
        return super().process(unit)


def _mask_job(keyspace_digits=4, unit=100):
    import hashlib
    eng = get_engine("md5")
    gen = MaskGenerator("?d" * keyspace_digits)
    plain = b"9" * keyspace_digits      # plant at the LAST index
    targets = [eng.parse_target(hashlib.md5(plain).hexdigest())]
    job = {"engine": "md5", "attack": "mask",
           "attack_arg": "?d" * keyspace_digits, "targets":
           [t.raw for t in targets], "keyspace": gen.keyspace,
           "unit_size": unit, "batch": 256, "hit_cap": 8,
           "fingerprint": "fp"}
    return eng, gen, targets, job


def _serve(job, gen, targets, lease_timeout=300.0):
    reg = MetricsRegistry()
    rec = TraceRecorder(registry=reg)
    eng = get_engine(job["engine"])
    disp = Dispatcher(gen.keyspace, job["unit_size"], registry=reg,
                      recorder=rec, job_id="j0",
                      lease_timeout=lease_timeout)
    state = CoordinatorState(
        job, disp, len(targets), registry=reg, recorder=rec,
        verifier=lambda ti, p: eng.verify(p, targets[ti]))
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    return state, server, reg


def test_op_profile_request_rides_lease_and_push_round_trips(
        tmp_path):
    """The fleet path end-to-end with a REAL capture: op_profile
    request -> the worker's next lease carries the window -> the
    worker sweeps through it, analyzes locally, pushes the summary ->
    op_profile serves it (raw trace stays on the worker host, path
    included) -> the journal hook fired."""
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    journaled = []
    state.on_profile = lambda w, s: journaled.append((w, s))
    try:
        c = CoordinatorClient(*server.address)
        # no live worker yet: auto-pick must refuse loudly
        from dprf_tpu.runtime.rpc import RpcError
        with pytest.raises(RpcError, match="no live worker"):
            c.call("profile", action="request")
        # target w1 explicitly; the request waits for its first lease
        resp = c.call("profile", action="request", worker="w1",
                      seconds=0.6)
        rid = resp["request_id"]
        assert resp["worker"] == "w1"
        with state.lock:
            assert state._profile_requests["w1"]["id"] == rid

        os.environ["DPRF_PROFILE_DIR"] = str(tmp_path / "wcap")
        try:
            w = CoordinatorClient(*server.address)
            done = worker_loop(
                w, SlowCpuWorker(eng, gen, targets), "w1",
                idle_sleep=0.01, depth=1,
                registry=MetricsRegistry(),
                recorder=TraceRecorder(registry=MetricsRegistry()))
            w.close()
        finally:
            os.environ.pop("DPRF_PROFILE_DIR", None)
        assert done == gen.keyspace // job["unit_size"]

        resp = c.call("profile")
        c.close()
        summaries = resp["summaries"]["w1"]
        assert summaries and summaries[0]["request_id"] == rid
        s = summaries[0]
        assert not s.get("error")
        assert s["trigger"] == "manual" and s["window_s"] == 0.6
        # the CpuWorker hashes on host: candidates still counted
        # through the window, and the raw path names the worker dir
        assert s["candidates"] and s["candidates"] > 0
        assert str(tmp_path / "wcap") in s["path"]
        assert journaled and journaled[0][0] == "w1"
        assert journaled[0][1]["request_id"] == rid
        # the request table drained; top sees the capture meta
        with state.lock:
            assert "w1" not in state._profile_requests
        c2 = CoordinatorClient(*server.address)
        status = c2.call("trace_tail", n=10)["status"]
        c2.close()
        assert status["profiles"]["w1"]["trigger"] == "manual"
        # the found crack is untouched by all the profiling traffic
        with state.lock:
            assert state.scheduler.get("j0").found
    finally:
        profiler_mod.DEFAULT.abort_window()
        server.shutdown()


def test_window_outlasting_job_still_pushes_cut_short(
        tmp_path, monkeypatch):
    """A capture window longer than the job's remaining work: the
    loop's clean-stop grace cuts the window short and still pushes
    the (real, shorter) summary instead of silently aborting it."""
    monkeypatch.setitem(profiler_mod._deps, "state", "ready")
    eng, gen, targets, job = _mask_job(keyspace_digits=3, unit=100)
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        # 30 s window vs ~1 s of job: can only land via the grace
        resp = c.call("profile", action="request", worker="w1",
                      seconds=30.0)
        rid = resp["request_id"]
        os.environ["DPRF_PROFILE_DIR"] = str(tmp_path / "wcap")
        try:
            w = CoordinatorClient(*server.address)
            worker_loop(
                w, SlowCpuWorker(eng, gen, targets), "w1",
                idle_sleep=0.01, depth=1,
                registry=MetricsRegistry(),
                recorder=TraceRecorder(registry=MetricsRegistry()))
            w.close()
        finally:
            os.environ.pop("DPRF_PROFILE_DIR", None)
        s = c.call("profile")["summaries"]["w1"][0]
        c.close()
        assert s["request_id"] == rid
        assert not s.get("error")
        assert s["window_s"] == 30.0      # asked; delivered early
        # the push cleared the inflight ledger: serve's drain loop
        # (which waits on profile_pending) is free to exit
        with state.lock:
            assert state._profile_inflight == {}
        assert not state.profile_pending()
    finally:
        profiler_mod.DEFAULT.abort_window()
        server.shutdown()


def test_summary_read_grace_and_worker_filtered_read():
    """A landed summary holds the serve drain (profile_pending) until
    somebody reads it -- the requester polls every ~0.5 s, and without
    the grace the drain could close the socket between the worker's
    push and the poller's next read.  A poll naming its worker ships
    that bucket alone and clears only that worker's grace."""
    from dprf_tpu.runtime import rpc as rpc_mod
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        for wid in ("wa", "wb"):
            c.call("profile_push", worker_id=wid,
                   summary={"schema": 1, "ts": 1.0,
                            "trigger": "manual"})
        assert state.profile_pending()        # unread: drain held
        st = c.call("profile", worker="wa")
        assert list(st["summaries"]) == ["wa"]    # filtered read
        assert state.profile_pending()        # wb still unread
        c.call("profile")                     # unfiltered read: all
        assert not state.profile_pending()
        # an unread grace a crashed requester never collects expires
        # on its own instead of pinning the drain table
        c.call("profile_push", worker_id="wa",
               summary={"schema": 1, "ts": 2.0, "trigger": "manual"})
        with state.lock:
            state._profile_unread["wa"] -= \
                rpc_mod.PROFILE_READ_GRACE_S + 1
        assert not state.profile_pending()
        with state.lock:
            assert state._profile_unread == {}
        c.close()
    finally:
        server.shutdown()


def test_connect_poll_tolerates_coordinator_exit(monkeypatch):
    """The serve session can legitimately end while `dprf profile
    --connect` is polling (short job, drained past the read-grace):
    the poll's ConnectionError means "no summary in time" (rc 1, the
    miss path), not the generic rc-2 error exit."""
    from dprf_tpu import cli as cli_mod
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        real = cli_mod._jobs_client

        def dying_client(args, log):
            # the request lands; every subsequent summary poll sees
            # the closed socket, as after a coordinator process exit
            client = real(args, log)
            orig = client.call

            def call(op, **kw):
                if op == "profile" and kw.get("action") != "request":
                    raise ConnectionError(
                        "coordinator closed the connection")
                return orig(op, **kw)

            client.call = call
            return client

        monkeypatch.setattr(cli_mod, "_jobs_client", dying_client)
        rc = cli_main(["profile", "--connect",
                       "%s:%d" % server.address, "--worker", "wz",
                       "--wait", "5", "--quiet"])
        assert rc == 1
    finally:
        server.shutdown()


def test_profile_push_sanitizes_and_bounds(tmp_path):
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    try:
        c = CoordinatorClient(*server.address)
        # junk summary: rejected without a crash
        assert c.call("profile_push", worker_id="w9",
                      summary="junk")["ok"] is False
        for i in range(6):
            c.call("profile_push", worker_id="w9",
                   summary={"schema": 1, "ts": float(i),
                            "trigger": "manual", "junk": "dropped"})
        resp = c.call("profile")
        c.close()
        bucket = resp["summaries"]["w9"]
        from dprf_tpu.runtime.rpc import PROFILE_SUMMARIES_PER_WORKER
        assert len(bucket) == PROFILE_SUMMARIES_PER_WORKER
        assert bucket[0]["ts"] == 5.0           # newest first
        assert all("junk" not in s for s in bucket)
    finally:
        server.shutdown()


def test_render_top_prof_column_age_and_trigger():
    """`dprf top` shows each worker's last-capture age + trigger rule
    from the status profiles table (pushed summaries, with the
    heartbeat payload as the env-local fallback)."""
    from dprf_tpu.telemetry.trace import render_top
    now = time.time()
    text = render_top({
        "status": {"done": 10, "total": 100, "found": 0,
                   "targets": 1, "parked": 0, "elapsed": 1.0,
                   "now": now,
                   "profiles": {"w0": {"ts": now - 90,
                                       "trigger": "straggler"}},
                   "health": {"w0": "healthy", "w1": "healthy"}},
        "spans": [], "leases": []})
    assert "PROF" in text
    assert "90s/straggle" in text
    w1 = [ln for ln in text.splitlines() if ln.startswith("w1")][0]
    assert "straggle" not in w1          # no capture yet: just a dash


# ---------------------------------------------------------------------------
# alert-triggered auto-capture (the chaos acceptance path)

def _straggler_state(tmp_path, session=None):
    """A serve state with 3 live workers (w3 far under the fleet
    median) and a fast straggler rule."""
    eng, gen, targets, job = _mask_job()
    state, server, reg = _serve(job, gen, targets)
    state.alerts = AlertEngine(
        rules=[AlertRule(name="straggler",
                         metric="dprf_worker_straggler",
                         op=">=", threshold=1, for_s=0.0,
                         severity="warning")],
        registry=reg)
    for wid, rate in (("w1", 100.0), ("w2", 100.0), ("w3", 10.0)):
        state.health.observe(wid, rate_hs=rate)
    return state, server, reg


def test_chaos_straggler_alert_yields_exactly_one_auto_capture(
        tmp_path, monkeypatch):
    """Acceptance: the planted straggler fires -> the health tick
    queues EXACTLY ONE capture request for the implicated worker;
    re-fires inside the cooldown are swallowed; the pushed summary is
    journaled as {"type": "profile"} and `dprf report` renders it."""
    monkeypatch.setenv("DPRF_PROFILE_COOLDOWN_S", "600")
    state, server, reg = _straggler_state(tmp_path)
    path = str(tmp_path / "auto.session")
    session = SessionJournal(path, snapshot_every=1)
    session.open(state.job, default_job="j0")
    state.on_profile = \
        lambda w, s: session.record_profile(w, s)
    try:
        state.health_tick()
        with state.lock:
            reqs = dict(state._profile_requests)
        assert list(reqs) == ["w3"]
        assert reqs["w3"]["trigger"] == "straggler"
        rid = reqs["w3"]["id"]

        # the SAME firing produces no second request, and a re-fire
        # within the cooldown is swallowed even after delivery
        state.health_tick()
        with state.lock:
            assert len(state._profile_requests) == 1
            state._profile_requests.clear()     # simulate delivery
        state.alerts = AlertEngine(
            rules=state.alerts.rules, registry=reg)  # fresh lifecycle
        state.health_tick()                          # fires again
        with state.lock:
            assert state._profile_requests == {}     # cooldown held

        # cooldown elapsed (0 = always): the next firing captures
        monkeypatch.setenv("DPRF_PROFILE_COOLDOWN_S", "0")
        state.alerts = AlertEngine(
            rules=state.alerts.rules, registry=reg)
        state.health_tick()
        with state.lock:
            assert list(state._profile_requests) == ["w3"]
            state._profile_requests.clear()

        # the worker's pushed summary is journaled and reportable
        c = CoordinatorClient(*server.address)
        c.call("profile_push", worker_id="w3",
               summary={"schema": 1, "ts": time.time(),
                        "trigger": "straggler", "request_id": rid,
                        "engine": "md5", "device_s": 0.01,
                        "fractions": {"compute": 0.85,
                                      "collective": 0.1,
                                      "copy": 0.05}})
        # retrievable via the same surface dprf profile --connect polls
        fetched = c.call("profile")["summaries"]["w3"][0]
        assert fetched["trigger"] == "straggler"
        c.close()
        session.close()

        loaded = SessionJournal.load(path)
        assert len(loaded.profiles) == 1
        assert loaded.profiles[0]["worker"] == "w3"
        assert loaded.profiles[0]["summary"]["trigger"] == "straggler"
        from dprf_tpu.perfreport.report import (build_report,
                                                render_report)
        doc = build_report(path)
        assert doc["profiles"][0]["worker"] == "w3"
        assert doc["profiles"][0]["trigger"] == "straggler"
        text = render_report(doc)
        assert "kernel profile" in text
        assert "straggler" in text
    finally:
        server.shutdown()


def test_autoprofile_disabled_and_job_stalled_picks_slowest(
        tmp_path, monkeypatch):
    state, server, reg = _straggler_state(tmp_path)
    try:
        # kill switch: no request queued no matter what fires
        monkeypatch.setenv("DPRF_AUTOPROFILE", "0")
        state.health_tick()
        with state.lock:
            assert state._profile_requests == {}
        monkeypatch.delenv("DPRF_AUTOPROFILE")
        monkeypatch.setenv("DPRF_PROFILE_COOLDOWN_S", "0")
        # a job_stalled firing names no worker: the slowest live
        # worker is implicated
        state._maybe_autoprofile([
            {"state": "firing", "rule": "job_stalled",
             "labels": {"job": "j0"}}])
        with state.lock:
            assert list(state._profile_requests) == ["w3"]
            assert state._profile_requests["w3"]["trigger"] \
                == "job_stalled"
        # unrelated rules never trigger captures
        with state.lock:
            state._profile_requests.clear()
        state._maybe_autoprofile([
            {"state": "firing", "rule": "trace_drops", "labels": {}}])
        with state.lock:
            assert state._profile_requests == {}
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# exact compile-cache classifier (ISSUE 15 satellite)

def test_compile_classifier_exact_from_cache_log_lines(tmp_path):
    """On this jax (explain-capable), the observer classifies from
    the compiler's own persistent-cache log lines: a cold compile is
    an exact miss, a same-key recompile served from disk an exact hit
    -- no wall-clock floor involved."""
    import jax
    import jax.numpy as jnp

    from dprf_tpu import compilecache
    assert compilecache.explain_capable()
    compilecache.enable(dir=str(tmp_path / "xla"))
    reg = MetricsRegistry()
    x = jnp.arange(4093, dtype=jnp.uint32)   # unique prime shape
    try:
        with compilecache.compile_observer("md5", registry=reg) as o1:
            jax.jit(lambda v: (v ^ jnp.uint32(41)).sum())(
                x).block_until_ready()
        assert o1.cache == "miss"
        # a FRESH jit of the same computation: jax's in-memory cache
        # cannot serve it, the persistent cache does -> exact hit
        with compilecache.compile_observer("md5", registry=reg) as o2:
            jax.jit(lambda v: (v ^ jnp.uint32(41)).sum())(
                x).block_until_ready()
        assert o2.cache == "hit"
        assert reg.get("dprf_compile_cache_hits_total").value(
            engine="md5") == 1
        assert reg.get("dprf_compile_cache_misses_total").value(
            engine="md5") == 1
    finally:
        compilecache.disable()
    # the watch restored the logger exactly (level + propagation)
    logger = logging.getLogger("jax._src.compiler")
    assert logger.propagate
    from dprf_tpu.compilecache import _watch_state
    assert _watch_state["count"] == 0


def test_compile_classifier_falls_back_when_watch_sees_nothing():
    """A window whose executable was already live in jax's in-memory
    cache logs nothing: classification falls back to the entry-delta
    + wall-floor heuristic (fast re-dispatch reads as a hit)."""
    import jax
    import jax.numpy as jnp

    from dprf_tpu import compilecache
    if not compilecache.enabled():
        compilecache.enable()
    f = jax.jit(lambda v: (v + jnp.uint32(5)).sum())
    x = jnp.arange(61, dtype=jnp.uint32)
    f(x).block_until_ready()                  # compile outside
    with compilecache.compile_observer("md5", publish=False) as obs:
        f(x).block_until_ready()              # pure dispatch
    assert obs.cache == "hit"
