"""Combinator / hybrid attack: generator bijection + holes, fused-step
device equivalence with the CPU oracle, worker end-to-end, sharded
variant, and the CLI surface."""

import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.generators.combinator import CombinatorGenerator
from dprf_tpu.ops.combine import make_combinator_crack_step
from dprf_tpu.ops.pipeline import target_words
from dprf_tpu.runtime.worker import CpuWorker, DeviceCombinatorWorker
from dprf_tpu.runtime.workunit import WorkUnit

LEFT = [b"sun", b"moon", b"x", b"aurora"]
RIGHT = [b"rise", b"set", b"", b"lightfall"]


def test_generator_decode_and_holes():
    gen = CombinatorGenerator(LEFT, RIGHT, max_len=10)
    assert gen.keyspace == 16
    assert gen.candidate(gen.index_of(b"sunrise")) == b"sunrise"
    assert gen.candidate(0) == b"sunrise"
    assert gen.candidate(1 * 4 + 1) == b"moonset"
    assert gen.candidate(2 * 4 + 2) == b"x"        # empty right side
    # aurora + lightfall = 15 bytes > max_len 10: a keyspace hole
    assert gen.candidate(3 * 4 + 3) is None
    # digits round-trip
    for i in range(gen.keyspace):
        li, ri = gen.digits(i)
        assert li * gen.n_right + ri == i


def test_fused_step_matches_oracle():
    gen = CombinatorGenerator(LEFT, RIGHT, max_len=12)
    eng = get_engine("md5", device="jax")
    secret = b"moonrise"
    planted = gen.index_of(secret)
    tgt = target_words(hashlib.md5(secret).digest(), little_endian=True)
    step = make_combinator_crack_step(eng, gen, tgt, batch=8)
    found = []
    for start in range(0, gen.keyspace, 8):
        base = jnp.asarray(gen.digits(start), jnp.int32)
        count, lanes, _ = step(base, jnp.int32(
            min(8, gen.keyspace - start)))
        if int(count):
            found.extend(start + int(l) for l in np.asarray(lanes)
                         if l >= 0)
    assert found == [planted]


@pytest.mark.parametrize("engine,secret", [
    ("sha256", b"sunset"),
    ("ntlm", b"xrise"),
])
def test_device_worker_end_to_end(engine, secret):
    gen = CombinatorGenerator(LEFT, RIGHT,
                              max_len=12 if engine != "ntlm" else 12)
    dev = get_engine(engine, device="jax")
    cpu = get_engine(engine, device="cpu")
    t = dev.parse_target(cpu.hash_batch([secret])[0].hex())
    w = dev.make_combinator_worker(gen, [t], batch=8, hit_capacity=4,
                                   oracle=cpu)
    assert isinstance(w, DeviceCombinatorWorker)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
    # CPU worker agrees
    cpu_hits = CpuWorker(cpu, gen, [t]).process(WorkUnit(0, 0,
                                                         gen.keyspace))
    assert [(h.cand_index, h.plaintext) for h in cpu_hits] == \
        [(h.cand_index, h.plaintext) for h in hits]


def test_sharded_combinator_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    left = [f"w{i}".encode() for i in range(20)]
    right = [f"{i:02d}".encode() for i in range(30)]
    gen = CombinatorGenerator(left, right, max_len=8)
    dev = get_engine("md5", device="jax")
    secret = b"w1711"
    t = dev.parse_target(hashlib.md5(secret).hexdigest())
    w = dev.make_sharded_combinator_worker(gen, [t], make_mesh(8),
                                           batch_per_device=16,
                                           hit_capacity=4)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
    assert gen.candidate(hits[0].cand_index) == secret


def test_cli_combinator_and_hybrid(tmp_path, capsys):
    from dprf_tpu.cli import main

    lp = tmp_path / "left.txt"
    lp.write_text("alpha\nbeta\n")
    rp = tmp_path / "right.txt"
    rp.write_text("99\n42\n")
    digest = hashlib.md5(b"beta42").hexdigest()
    hf = tmp_path / "h.txt"
    hf.write_text(digest + "\n")
    rc = main(["crack", f"{lp},{rp}", str(hf), "--engine", "md5",
               "-a", "combinator", "--device", "tpu", "--no-potfile",
               "--batch", "64", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{digest}:beta42" in out

    # hybrid-wm: words x ?d?d mask
    digest2 = hashlib.md5(b"alpha07").hexdigest()
    hf2 = tmp_path / "h2.txt"
    hf2.write_text(digest2 + "\n")
    rc = main(["crack", f"{lp},?d?d", str(hf2), "--engine", "md5",
               "-a", "hybrid-wm", "--device", "tpu", "--no-potfile",
               "--batch", "64", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{digest2}:alpha07" in out

    # hybrid-mw: ?d mask x words
    digest3 = hashlib.md5(b"7beta").hexdigest()
    hf3 = tmp_path / "h3.txt"
    hf3.write_text(digest3 + "\n")
    rc = main(["crack", f"?d,{lp}", str(hf3), "--engine", "md5",
               "-a", "hybrid-mw", "--device", "tpu", "--no-potfile",
               "--batch", "64", "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{digest3}:7beta" in out


def test_combinator_keccak_worker():
    """Round 4b: combinator attacks on the keccak family via the
    digest_candidates hook (previously no path)."""
    left = [f"w{i}".encode() for i in range(10)]
    right = [f"{i:02d}".encode() for i in range(12)]
    gen = CombinatorGenerator(left, right, max_len=8)
    dev = get_engine("sha3-256", device="jax")
    secret = b"w307"
    t = dev.parse_target(hashlib.sha3_256(secret).hexdigest())
    w = dev.make_combinator_worker(gen, [t], batch=64, hit_capacity=4,
                                   oracle=get_engine("sha3-256"))
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
    assert gen.candidate(hits[0].cand_index) == secret
