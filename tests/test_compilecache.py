"""Compile-cost elimination (ISSUE 3): persistent XLA compile cache
wiring (enable/idempotency/degradation), hit/miss classification +
metrics, the >=5x repeated-warmup acceptance case, `dprf prewarm`
populating entries a later worker warmup hits, overlapped (async)
warmup, and the tools/compile_report.py artifact summarizer."""

import json
import os
import threading
import time

import pytest

pytestmark = pytest.mark.smoke

from dprf_tpu import compilecache
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.telemetry import MetricsRegistry


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    """Point the persistent cache at a test-owned EMPTY dir (so the
    first compile is provably cold) and restore the session-wide dir
    afterwards -- compilecache state is process-global.  The env var
    is repointed too: library code calls enable() with no dir, which
    resolves through $DPRF_COMPILE_CACHE_DIR."""
    prev = compilecache.cache_dir()
    want = str(tmp_path / "xla")
    monkeypatch.setenv(compilecache.CACHE_DIR_ENV, want)
    d = compilecache.enable(dir=want)
    assert d is not None
    yield d
    if prev is not None:
        compilecache.enable(dir=prev)
    else:
        compilecache.disable()


# ---------------------------------------------------------------------------
# enable(): wiring, idempotency, degradation

def test_enable_idempotent_and_entry_count(fresh_cache):
    import jax
    assert compilecache.enabled()
    assert compilecache.cache_dir() == fresh_cache
    assert jax.config.jax_compilation_cache_dir == fresh_cache
    # persistence thresholds lowered so step compiles always persist
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == -1
    assert compilecache.enable(dir=fresh_cache) == fresh_cache  # no-op
    assert compilecache.entry_count() == 0                      # empty


def test_enable_kill_switch_and_unwritable_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(compilecache.DISABLE_ENV, "0")
    assert compilecache.enable(dir=str(tmp_path / "x")) is None
    monkeypatch.delenv(compilecache.DISABLE_ENV)
    # an unwritable "dir" (a plain file blocks makedirs) degrades to
    # None -- never an exception, never a half-enabled state
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    prev = compilecache.cache_dir()
    assert compilecache.enable(dir=str(blocker)) is None
    assert compilecache.cache_dir() == prev     # state untouched


def test_default_dir_honors_env(monkeypatch, tmp_path):
    monkeypatch.setenv(compilecache.CACHE_DIR_ENV, str(tmp_path / "e"))
    assert compilecache.default_cache_dir() == str(tmp_path / "e")


# ---------------------------------------------------------------------------
# hit/miss classification + metric surface

def test_classify_compile_rules(fresh_cache, monkeypatch):
    # new cache entries appeared -> miss, regardless of wall time
    assert compilecache.classify_compile(0.01, 3, 5) == "miss"
    # nothing new + under the cold floor -> hit
    assert compilecache.classify_compile(0.5, 5, 5) == "hit"
    # nothing new but OVER the floor -> still a miss (a backend whose
    # compiles cannot persist must not report eternal hits)
    assert compilecache.classify_compile(10.0, 5, 5) == "miss"
    monkeypatch.setenv(compilecache.COLD_FLOOR_ENV, "20")
    assert compilecache.classify_compile(10.0, 5, 5) == "hit"


def test_classify_off_when_disabled(monkeypatch):
    prev = compilecache.cache_dir()
    compilecache.disable()
    try:
        assert compilecache.classify_compile(9.0, 0, 5) == "off"
    finally:
        if prev is not None:
            compilecache.enable(dir=prev)


def test_observe_compile_metrics():
    m = MetricsRegistry()
    compilecache.observe_compile("md5", 3.0, "miss", registry=m)
    compilecache.observe_compile("md5", 0.2, "hit", registry=m)
    compilecache.observe_compile("md5", 0.2, "off", registry=m)
    assert m.counter("dprf_compile_cache_misses_total",
                     labelnames=("engine",)).value(engine="md5") == 1
    assert m.counter("dprf_compile_cache_hits_total",
                     labelnames=("engine",)).value(engine="md5") == 1
    h = compilecache.compile_histogram(m)
    assert h.count(engine="md5", cache="miss") == 1
    assert h.count(engine="md5", cache="hit") == 1
    assert h.count(engine="md5", cache="off") == 1   # off: observed,
    # not counted as cache behavior


# ---------------------------------------------------------------------------
# the acceptance case: repeated same-shape warmup >= 5x faster

def _make_worker(engine_name, mask, batch):
    from dprf_tpu import get_engine
    oracle = get_engine(engine_name, device="cpu")
    dev = get_engine(engine_name, device="jax")
    gen = MaskGenerator(mask)
    target = oracle.parse_target("ff" * oracle.digest_size)
    return dev.make_mask_worker(gen, [target], batch=batch,
                                hit_capacity=64, oracle=oracle)


@pytest.mark.compileheavy
def test_repeated_warmup_5x_faster_with_cache(fresh_cache):
    """Acceptance (ISSUE 3): with $DPRF_COMPILE_CACHE_DIR set, a
    repeated identically-shaped warmup's XLA compile is >= 5x faster
    than the cold compile -- the cache serves the executable instead
    of re-running XLA (measured ~10x for sha512 on this CPU backend;
    trace/lower time is host Python the cache can never touch, so the
    compile is compared to the compile).  Each build creates a NEW
    jit function, so nothing here can hit jax's in-memory trace
    cache; the end-to-end warmup must improve too."""
    w1 = _make_worker("sha512", "?l?l?l?d?d?d", 4096)
    w1.aot_compile()
    assert w1.compile_cache == "miss"
    assert compilecache.entry_count() > 0       # compile persisted
    warm = []
    for _ in range(2):
        w = _make_worker("sha512", "?l?l?l?d?d?d", 4096)
        w.aot_compile()
        assert w.compile_cache == "hit"
        warm.append(w.xla_compile_seconds)
    assert w1.xla_compile_seconds >= 5 * min(warm), (
        f"cold compile {w1.xla_compile_seconds:.2f}s vs cached "
        f"{min(warm):.2f}s")
    # the full dispatching warmup path hits and beats the cold total
    w3 = _make_worker("sha512", "?l?l?l?d?d?d", 4096)
    w3.warmup()
    assert w3.compile_cache == "hit"
    assert w3.compile_seconds < w1.compile_seconds
    # the metric surface saw one miss then the cache hits
    from dprf_tpu.telemetry import DEFAULT
    assert DEFAULT.get("dprf_compile_cache_hits_total").value(
        engine="sha512") >= 2


# ---------------------------------------------------------------------------
# dprf prewarm: AOT population a later worker warmup hits

def test_prewarm_populates_cache_for_subsequent_warmup(fresh_cache):
    from dprf_tpu.compilecache.prewarm import PrewarmSpec, run_prewarm

    spec = PrewarmSpec(engine="md5", attack="mask", batch=2048,
                       mask="?l?l?d?d")
    (res,) = run_prewarm([spec])
    assert res.error is None and res.cache == "miss"
    assert res.compile_s > 0 and compilecache.entry_count() > 0
    # a job-side worker of the SAME shape now warms from the cache
    w = _make_worker("md5", "?l?l?d?d", 2048)
    w.warmup()
    assert w.compile_cache == "hit"
    # prewarm is idempotent: a second pass is all hits
    (res2,) = run_prewarm([spec])
    assert res2.error is None and res2.cache == "hit"


def test_prewarm_wordlist_needs_the_real_wordlist(fresh_cache,
                                                  tmp_path):
    """The wordlist program embeds the packed word table (content is
    part of the cache key), so prewarm refuses to compile a wordlist
    shape without the job's file -- and with it, a job-side worker
    over the SAME file hits."""
    from dprf_tpu import get_engine
    from dprf_tpu.cli import _wordlist_max_len
    from dprf_tpu.compilecache.prewarm import PrewarmSpec, run_prewarm
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator

    (res,) = run_prewarm([PrewarmSpec(engine="md5", attack="wordlist",
                                      batch=1024)])
    assert res.error is not None and "--wordlist" in res.error

    wl = tmp_path / "words.txt"
    wl.write_text("".join(f"word{i:04d}\n" for i in range(512)))
    (res,) = run_prewarm([PrewarmSpec(engine="md5", attack="wordlist",
                                      batch=1024, wordlist=str(wl))])
    assert res.error is None and res.cache == "miss"
    oracle = get_engine("md5", device="cpu")
    gen = WordlistRulesGenerator.from_files(
        str(wl), None, max_len=_wordlist_max_len("md5", oracle, "jax"))
    w = get_engine("md5", device="jax").make_wordlist_worker(
        gen, [oracle.parse_target("ff" * 16)], batch=1024,
        hit_capacity=64, oracle=oracle)
    w.warmup()
    assert w.compile_cache == "hit"


def test_prewarm_cli_json_and_error_rows(fresh_cache, capsys):
    """The CLI prints a machine-checkable JSON line; a spec whose
    engine needs salted targets is reported as an error row, not a
    crashed prewarm (a fleet image bake must not die on one engine)."""
    from dprf_tpu.cli import main as cli_main

    rc = cli_main(["prewarm", "--engines", "md5,wpa2-pmkid",
                   "--attacks", "mask", "--mask", "?l?d?d",
                   "--batch", "2048", "-q"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["specs"] == 2 and doc["compiled"] == 1
    assert doc["errors"] == 1 and doc["cache_dir"] == fresh_cache
    rows = {r["engine"]: r for r in doc["results"]}
    assert "error" in rows["wpa2-pmkid"]      # unparseable fake target
    assert rows["md5"]["cache"] in ("hit", "miss")


def test_prewarm_seeds_from_tune_cache(fresh_cache, tmp_path,
                                       monkeypatch):
    """Without --engines, prewarm compiles exactly the shapes the
    tuning cache recorded for the jax device."""
    from dprf_tpu import tune
    from dprf_tpu.compilecache.prewarm import tune_seeded_specs

    monkeypatch.setenv("DPRF_TUNE_DIR", str(tmp_path / "tune"))
    env = tune.env_fingerprint("md5", "jax")
    tune.default_cache().put(
        tune.make_key("md5", attack="mask", device="jax", hit_cap=64),
        {"batch": 4096}, env)
    tune.default_cache().put(       # other device: filtered out
        tune.make_key("md5", attack="mask", device="cpu", hit_cap=64),
        {"batch": 512}, env)
    tune.default_cache().put(       # wordlist entry: needs --wordlist
        tune.make_key("sha256", attack="wordlist", device="jax",
                      hit_cap=64, rules_n=64),
        {"batch": 8192}, tune.env_fingerprint("sha256", "jax"))
    tune.default_cache().put(       # stale env: must NOT seed a spec
        tune.make_key("sha1", attack="mask", device="jax", hit_cap=64),
        {"batch": 2048}, dict(env, jax="0.0.0"))
    specs = tune_seeded_specs("jax")
    assert [(s.engine, s.attack, s.batch, s.hit_cap)
            for s in specs] == [("md5", "mask", 4096, 64)]
    # with the real wordlist supplied, the wordlist entry seeds too
    specs = tune_seeded_specs("jax", wordlist="words.txt",
                              rules="best64")
    assert ("sha256", "wordlist", 8192) in [
        (s.engine, s.attack, s.batch) for s in specs]
    assert [s for s in specs if s.attack == "wordlist"][0].wordlist \
        == "words.txt"


def test_prewarm_cli_refuses_without_cache(monkeypatch, capsys):
    from dprf_tpu.cli import main as cli_main
    monkeypatch.setenv(compilecache.DISABLE_ENV, "0")
    rc = cli_main(["prewarm", "--engines", "md5", "-q"])
    assert rc == 2


# ---------------------------------------------------------------------------
# overlapped warmup

class _RecordingWorker:
    """Minimal duck-typed worker borrowing MaskWorkerBase's async
    warmup machinery: records which thread ran warmup and whether a
    dispatch ever ran cold."""

    from dprf_tpu.runtime.worker import MaskWorkerBase as _B
    warmup_async = _B.warmup_async
    ensure_warm = _B.ensure_warm

    def __init__(self, fail=False, delay=0.05):
        self.fail = fail
        self.delay = delay
        self.warm_thread = None
        self.processed_cold = False
        self._warmed = False

    def warmup(self):
        self.warm_thread = threading.current_thread()
        time.sleep(self.delay)
        if self.fail:
            raise RuntimeError("mosaic exploded")
        self._warmed = True

    def process(self, unit):
        if not self._warmed:
            self.processed_cold = True
        return []


def test_warmup_async_runs_in_background_and_joins():
    w = _RecordingWorker()
    assert w.warmup_async() is w
    w.ensure_warm()
    assert w._warmed
    assert w.warm_thread is not threading.current_thread()
    w.ensure_warm()                    # idempotent after join
    # an already-warm worker never restarts a thread
    t = w.warm_thread
    w.warmup_async()
    w.ensure_warm()
    assert w.warm_thread is t


def test_warmup_async_error_surfaces_in_ensure_warm():
    w = _RecordingWorker(fail=True)
    w.warmup_async()
    with pytest.raises(RuntimeError, match="mosaic exploded"):
        w.ensure_warm()
    w.ensure_warm()                    # error consumed; no re-raise


def test_warmup_async_sync_fallback_env(monkeypatch):
    monkeypatch.setenv("DPRF_ASYNC_WARMUP", "0")
    w = _RecordingWorker()
    w.warmup_async()
    assert w._warmed                   # ran synchronously...
    assert w.warm_thread is threading.current_thread()


def test_coordinator_overlaps_warmup_before_first_dispatch():
    """Coordinator.run() kicks warmup_async at entry and joins it
    before the first submit: the step never dispatches cold, and the
    compile ran off the caller's thread."""
    from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
    from dprf_tpu.runtime.dispatcher import Dispatcher

    w = _RecordingWorker(delay=0.1)
    spec = JobSpec(engine="fake", device="jax", attack="mask",
                   attack_arg="?l", keyspace=256, fingerprint="f")
    coord = Coordinator(spec, [object()], Dispatcher(256, 64), w,
                        registry=MetricsRegistry())
    result = coord.run()
    assert result.exhausted
    assert w._warmed and not w.processed_cold
    assert w.warm_thread is not threading.current_thread()


def test_worker_loop_joins_async_warmup_before_processing():
    """The distributed path: worker_loop must ensure_warm before the
    first unit (cli.cmd_worker starts the compile before the loop)."""
    from dprf_tpu.runtime.dispatcher import Dispatcher
    from dprf_tpu.runtime.rpc import (CoordinatorClient,
                                      CoordinatorServer,
                                      CoordinatorState, worker_loop)

    m = MetricsRegistry()
    d = Dispatcher(keyspace=128, unit_size=64, registry=m)
    state = CoordinatorState({"engine": "md5"}, d, n_targets=1,
                             registry=m)
    server = CoordinatorServer(state, "127.0.0.1", 0)
    server.start_background()
    try:
        w = _RecordingWorker()
        w.warmup_async()
        client = CoordinatorClient(*server.address)
        done = worker_loop(client, w, "w0", idle_sleep=0.01,
                           registry=m)
        client.close()
        assert done == 2
        assert w._warmed and not w.processed_cold
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# bench JSON: compile_cache + cold/warm compile fields

@pytest.mark.compileheavy
def test_bench_reports_compile_cache_fields(fresh_cache):
    """Acceptance: bench JSON carries compile_cache plus cold/warm
    compile seconds.  First run on an empty cache dir is a miss that
    measures BOTH (warm via an AOT rebuild); a rerun is a hit."""
    from dprf_tpu.bench import run_bench
    from dprf_tpu.telemetry import DEFAULT

    misses = DEFAULT.counter("dprf_compile_cache_misses_total",
                             labelnames=("engine",))
    before = misses.value(engine="md5")
    res = run_bench(engine="md5", device="jax", mask="?l?l?l?l?l",
                    batch=2048, seconds=0.2, impl="xla")
    assert res["compile_cache"] == "miss"
    assert res["compile_cold_s"] > 0
    # ONE cold compile -> ONE miss observation (the compile site
    # publishes; _publish must not re-observe and double the counters
    # tools/compile_report.py sums)
    assert misses.value(engine="md5") == before + 1
    assert res["compile_warm_s"] is not None
    assert res["compile_warm_s"] < res["compile_cold_s"]
    res2 = run_bench(engine="md5", device="jax", mask="?l?l?l?l?l",
                     batch=2048, seconds=0.2, impl="xla")
    assert res2["compile_cache"] == "hit"
    assert res2["compile_cold_s"] is None
    assert res2["compile_warm_s"] is not None


@pytest.mark.compileheavy
def test_run_config_reports_compile_cache(fresh_cache):
    from dprf_tpu.bench import run_config

    res = run_config(1, device="jax", seconds=0.2, batch=2048)
    assert res["compile_cache"] == "miss"
    assert res["compile_cold_s"] > 0
    res2 = run_config(1, device="jax", seconds=0.2, batch=2048)
    assert res2["compile_cache"] == "hit"
    assert res2["compile_warm_s"] > 0


@pytest.mark.compileheavy
def test_tune_sweep_records_rung_cache(fresh_cache):
    """A cache-hit rung's fixed cost ~ 0: the sweep classifies each
    rung so the tune JSON shows which rungs paid a cold compile."""
    from dprf_tpu import get_engine
    from dprf_tpu.runtime.worker import CpuWorker
    from dprf_tpu.tune import sweep

    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator("?l?l?l?l")
    targets = [oracle.parse_target("ff" * 16)]

    def make_worker(batch):
        from dprf_tpu import get_engine as ge
        dev = ge("md5", device="jax")
        return dev.make_mask_worker(gen, targets, batch=batch,
                                    hit_capacity=64, oracle=oracle)

    res1 = sweep(make_worker, gen.keyspace, ladder=[2048],
                 probe_seconds=0.1)
    assert res1.swept[0].cache == "miss"
    res2 = sweep(make_worker, gen.keyspace, ladder=[2048],
                 probe_seconds=0.1)
    assert res2.swept[0].cache == "hit"
    assert "cache" in res2.swept[0].as_dict()
    # CpuWorker rungs compile nothing: still classified, never crash
    res3 = sweep(lambda b: CpuWorker(oracle, gen, targets, chunk=b),
                 gen.keyspace, ladder=[512], probe_seconds=0.05)
    assert res3.swept[0].cache in ("hit", "miss", "off")


# ---------------------------------------------------------------------------
# tools/compile_report.py: compile cost from snapshot artifacts

def test_compile_report_tool_summarizes_snapshots(tmp_path):
    import subprocess
    import sys

    from dprf_tpu.telemetry import TelemetrySnapshotter

    m = MetricsRegistry()
    for s, cache in ((4.0, "miss"), (6.0, "miss"), (0.3, "hit"),
                     (0.4, "hit"), (0.5, "hit")):
        compilecache.observe_compile("krb5aes", s, cache, registry=m)
    compilecache.observe_compile("md5", 1.2, "miss", registry=m)
    path = str(tmp_path / "job.session.telemetry.jsonl")
    TelemetrySnapshotter(path, m, interval=3600).write_once()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "compile_report.py")
    proc = subprocess.run([sys.executable, tool, path, "--json"],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["cache_hits"] == 3 and doc["cache_misses"] == 3
    rows = {(r["engine"], r["cache"]): r for r in doc["compiles"]}
    miss = rows[("krb5aes", "miss")]
    assert miss["count"] == 2 and miss["total_s"] == 10.0
    # bucket-interpolated percentiles land inside the observed band
    assert 2.5 < miss["p50_s"] <= 10.0
    assert miss["p95_s"] >= miss["p50_s"]
    hit = rows[("krb5aes", "hit")]
    assert hit["count"] == 3 and hit["p95_s"] <= 1.0
    # human rendering works too (smoke: table + hit ratio line)
    proc = subprocess.run([sys.executable, tool, path],
                          capture_output=True, text=True)
    assert proc.returncode == 0
    assert "hit ratio 50%" in proc.stdout
    # an empty/missing file is rc 1 ("no data"), not a crash
    proc = subprocess.run(
        [sys.executable, tool, str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True)
    assert proc.returncode == 1


# ---------------------------------------------------------------------------
# prewarm spec kinds: combinator/hybrid + sharded (ISSUE 5 satellite)

def test_prewarm_combinator_needs_real_files_and_skip_is_cheap(
        fresh_cache):
    """Combinator prewarm refuses stand-ins (both word tables are
    embedded in the program), and a sharded spec on a host with too
    few devices is SKIPPED -- reported, never an error, and never
    compiled."""
    from dprf_tpu.compilecache.prewarm import (PrewarmSpec,
                                               explicit_specs,
                                               run_prewarm)

    (res,) = run_prewarm([PrewarmSpec(engine="md5",
                                      attack="combinator",
                                      batch=512)])
    assert res.error is not None and "--combinator" in res.error
    (res,) = run_prewarm([PrewarmSpec(engine="md5",
                                      attack="hybrid-wm", batch=512)])
    assert res.error is not None and "--wordlist" in res.error
    # sharded shape on a host with fewer devices: graceful skip
    (res,) = run_prewarm([PrewarmSpec(engine="md5", attack="mask",
                                      batch=512, devices=999)])
    assert res.error is None and res.skipped
    assert res.cache == "skip" and res.devices == 999
    # explicit_specs threads the new fields through
    (spec,) = explicit_specs(["md5"], ["combinator"], batch=512,
                             combinator="l.txt,r.txt", devices=2)
    assert spec.combinator == "l.txt,r.txt" and spec.devices == 2
    (spec,) = explicit_specs(["md5"], ["hybrid-mw"], batch=512,
                             wordlist="w.txt")
    assert spec.wordlist == "w.txt" and spec.combinator is None


@pytest.mark.compileheavy
def test_prewarm_combinator_and_hybrid_shapes_warm_the_job(
        fresh_cache, tmp_path):
    """A combinator prewarm over the job's REAL files populates the
    cache the job-side DeviceCombinatorWorker warms from; the hybrid
    shape synthesizes its mask side exactly like a job."""
    from dprf_tpu import get_engine
    from dprf_tpu.compilecache.prewarm import (PrewarmSpec,
                                               run_prewarm)
    from dprf_tpu.generators.combinator import CombinatorGenerator
    from dprf_tpu.generators.wordlist import load_words

    lp, rp = tmp_path / "l.txt", tmp_path / "r.txt"
    lp.write_text("".join(f"left{i}\n" for i in range(64)))
    rp.write_text("".join(f"right{i}\n" for i in range(64)))
    (res,) = run_prewarm([PrewarmSpec(
        engine="md5", attack="combinator", batch=512,
        combinator=f"{lp},{rp}")])
    assert res.error is None and res.cache == "miss", res.as_dict()
    # the job path (same files, same batch) hits
    oracle = get_engine("md5", device="cpu")
    gen = CombinatorGenerator(load_words(str(lp), 55)[0],
                              load_words(str(rp), 55)[0], max_len=55)
    w = get_engine("md5", device="jax").make_combinator_worker(
        gen, [oracle.parse_target("ff" * 16)], batch=512,
        hit_capacity=64, oracle=oracle)
    w.warmup()
    assert w.compile_cache == "hit"
    # hybrid word+mask compiles too (its own program: different table)
    (res,) = run_prewarm([PrewarmSpec(
        engine="md5", attack="hybrid-wm", batch=512,
        wordlist=str(lp), mask="?d?d")])
    assert res.error is None and res.cache in ("miss", "hit")


@pytest.mark.compileheavy
def test_prewarm_sharded_shape_warms_the_sharded_job(fresh_cache,
                                                     capsys):
    """devices=N prewarms the SHARDED step through the same factory a
    `--devices N` job selects (the hermetic suite fakes 8 CPU chips);
    a later sharded worker of the same shape warms from the cache, and
    the CLI JSON reports skip counts separately from errors."""
    from dprf_tpu import get_engine
    from dprf_tpu.cli import main as cli_main
    from dprf_tpu.compilecache.prewarm import (PrewarmSpec,
                                               run_prewarm)
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.parallel.mesh import make_mesh

    (res,) = run_prewarm([PrewarmSpec(engine="md5", attack="mask",
                                      batch=512, mask="?l?d?d",
                                      devices=2)])
    assert res.error is None and res.cache == "miss", res.as_dict()
    assert res.devices == 2
    oracle = get_engine("md5", device="cpu")
    w = get_engine("md5", device="jax").make_sharded_mask_worker(
        MaskGenerator("?l?d?d"), [oracle.parse_target("ff" * 16)],
        make_mesh(2), 512, hit_capacity=64, oracle=oracle)
    w.warmup()
    assert w.compile_cache == "hit"
    # CLI: one compiled sharded spec + one skipped (too many devices)
    rc = cli_main(["prewarm", "--engines", "md5", "--attacks", "mask",
                   "--mask", "?l?d?d", "--batch", "512",
                   "--devices", "2", "-q"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["compiled"] == 1 and doc["skipped"] == 0
    rc = cli_main(["prewarm", "--engines", "md5", "--attacks", "mask",
                   "--batch", "512", "--devices", "64", "-q"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["skipped"] == 1 and doc["errors"] == 0
    assert doc["results"][0]["cache"] == "skip"
