"""The unified sharded runtime (parallel/sharded.py): superstep
semantics, shard-boundary hit parity, overflow redrive, and resume /
re-split of a sharded session under a DIFFERENT device count.

The per-batch compat contract is covered by tests/test_parallel.py;
this file exercises what the runtime added -- on-device candidate
generation across fused windows, the device-resident hit buffer, and
the one-collective-per-superstep discipline -- at hit-placement edges
(shard boundaries, window boundaries, the last keyspace index).
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.base import Target
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.parallel import make_mesh
from dprf_tpu.parallel.worker import ShardedMaskWorker, shard_super_cap
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.worker import CpuWorker, submit_or_process
from dprf_tpu.runtime.workunit import WorkUnit


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should fake 8 CPU devices"
    return make_mesh(8)


def _md5_targets(gen, idxs):
    return [Target(str(i), hashlib.md5(gen.candidate(i)).digest())
            for i in idxs]


def _cpu_hits(gen, targets, unit):
    return sorted((h.target_index, h.cand_index, h.plaintext)
                  for h in CpuWorker(get_engine("md5", device="cpu"),
                                     gen, targets).process(unit))


def test_superstep_hits_at_every_boundary(mesh):
    """One unit big enough to fuse superstep windows plus a per-batch
    remainder; plants sit at shard boundaries, window boundaries, and
    the LAST keyspace index -- the sharded sweep must equal the CPU
    oracle exactly."""
    gen = MaskGenerator("?l?l?l?l")        # 456976
    B = 1024
    stride = 8 * B
    plant = [0, B - 1, B, stride - 1, stride,           # shard edges
             8 * stride - 1, 8 * stride,                # window edge
             gen.keyspace - 1]                          # last index
    targets = _md5_targets(gen, plant)
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=B, hit_capacity=16)
    unit = WorkUnit(0, 0, gen.keyspace)
    pend = w.submit(unit)
    kinds = [k for k, _, _ in pend.queued]
    # the tentpole path really ran: fused windows AND a remainder
    assert "sshard" in kinds
    got = sorted((h.target_index, h.cand_index, h.plaintext)
                 for h in pend.resolve())
    assert got == _cpu_hits(gen, targets, unit)
    assert [g[1] for g in got] == plant


def test_superstep_single_collective_shape(mesh):
    """A superstep dispatch returns ONE replicated result tuple for
    the whole window (count/lanes/tpos per shard, window-relative
    lanes) -- not one per batch."""
    from dprf_tpu.parallel.sharded import make_sharded_mask_step
    from dprf_tpu.ops.pipeline import target_words
    gen = MaskGenerator("?l?l?l?l")
    step = make_sharded_mask_step(
        get_engine("md5", device="jax"), gen,
        target_words(hashlib.md5(gen.candidate(12345)).digest(),
                     little_endian=True),
        mesh, 512)
    ss = step.superstep(4)
    window = 4 * step.super_batch
    total, counts, lanes, tpos = ss(
        jnp.asarray(gen.digits(0), dtype=jnp.int32), jnp.int32(window))
    assert int(total) == 1
    assert counts.shape == (8,) and lanes.shape == (8, 64)
    lanes_np = np.asarray(lanes)
    assert list(lanes_np[lanes_np >= 0]) == [12345]   # window-relative
    # cached program identity: same inner -> same compiled callable
    assert step.superstep(4) is ss


def test_superstep_overflow_redrives_exactly(mesh):
    """A shard whose window collects more hits than hit_capacity
    truncates the buffer but keeps the true count; the worker must
    redrive the window per-batch and report every hit exactly once."""
    gen = MaskGenerator("?d?d?d?d?d")       # 100000
    B = 128
    stride = 8 * B
    # 6 plants inside shard 0's lane slices of the first window (> cap)
    plant = [0, 3, 7, stride + 1, 2 * stride + 2, 3 * stride + 5,
             gen.keyspace - 1]
    targets = _md5_targets(gen, plant)
    w = ShardedMaskWorker(get_engine("md5", device="jax"), gen, targets,
                          mesh, batch_per_device=B, hit_capacity=2,
                          oracle=get_engine("md5", device="cpu"))
    unit = WorkUnit(0, 0, gen.keyspace)
    hits = w.process(unit)
    assert sorted(h.cand_index for h in hits) == plant
    assert len(hits) == len(set(h.cand_index for h in hits))


def test_resume_resplit_under_different_device_count(mesh):
    """A sharded session interrupted mid-sweep resumes under a
    DIFFERENT device count (8 -> 2) and a different unit size with
    exact coverage and no overlap -- coverage is keyspace-indexed, so
    the mesh width is a per-run execution detail."""
    gen = MaskGenerator("?d?d?d?d")         # 10000
    plant = [0, 1234, 4999, 5000, 7777, gen.keyspace - 1]
    targets = _md5_targets(gen, plant)
    eng = get_engine("md5", device="jax")

    hits = []
    disp = Dispatcher(gen.keyspace, 2000)
    w8 = ShardedMaskWorker(eng, gen, targets, mesh,
                           batch_per_device=128, hit_capacity=16)
    for _ in range(3):                      # interrupt after 3 units
        unit = disp.lease("w8")
        hits.extend(submit_or_process(w8, unit).resolve())
        disp.complete(unit.unit_id, worker_id="w8")
    completed = disp.completed_intervals()
    assert sum(e - s for s, e in completed) == 6000

    # resume: different unit size AND a 2-device mesh
    disp2 = Dispatcher.from_completed(gen.keyspace, 1536, completed)
    w2 = ShardedMaskWorker(eng, gen, targets, make_mesh(2),
                           batch_per_device=128, hit_capacity=16)
    swept = []
    while True:
        unit = disp2.lease("w2")
        if unit is None:
            break
        swept.append((unit.start, unit.end))
        hits.extend(submit_or_process(w2, unit).resolve())
        disp2.complete(unit.unit_id, worker_id="w2")
    assert disp2.done()
    # resumed units never re-sweep covered ranges (no overlap)
    for s, e in swept:
        for cs, ce in completed:
            assert e <= cs or s >= ce, (swept, completed)
    # exact coverage: union of both phases is the whole keyspace
    assert sum(e - s for s, e in disp2.completed_intervals()) \
        == gen.keyspace
    assert sorted(h.cand_index for h in hits) == plant
    assert len(hits) == len(set(h.cand_index for h in hits))


def test_pertarget_sharded_workers_pipeline(mesh):
    """The per-target sharded workers are submit-based now: submit()
    enqueues every (target, batch) dispatch with ONE device-
    accumulated flag, so the remote worker loop pipelines them."""
    from dprf_tpu.engines.device.phpass import ShardedPhpassMaskWorker
    from dprf_tpu.engines.device.salted import ShardedSaltedMaskWorker
    for cls in (ShardedPhpassMaskWorker, ShardedSaltedMaskWorker,
                ShardedMaskWorker):
        assert getattr(cls.process, "_submit_based", False), cls
        assert "submit" in cls.__dict__ or any(
            "submit" in b.__dict__ for b in cls.__mro__[1:]), cls


def test_shard_super_cap_knob(monkeypatch):
    monkeypatch.setenv("DPRF_SHARD_SUPER_CAP", "100")
    assert shard_super_cap() == 64          # power-of-two clamp
    monkeypatch.setenv("DPRF_SHARD_SUPER_CAP", "junk")
    assert shard_super_cap() == 256         # registry default
    monkeypatch.setenv("DPRF_SHARD_SUPER_CAP", "1")
    assert shard_super_cap() == 2           # floor: fusing needs >= 2
