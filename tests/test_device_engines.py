"""SHA-1/SHA-256/NTLM device engines vs CPU oracles, plus fused-step
end-to-end per engine (the device-vs-oracle property strategy of
SURVEY.md section 4)."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops.pipeline import make_mask_crack_step, target_words

ENGINES = ["md5", "sha1", "sha256", "sha512", "sha384", "ntlm"]


@pytest.mark.parametrize("name", ENGINES)
def test_device_matches_oracle_random(name):
    dev = get_engine(name, "jax")
    oracle = get_engine(name, "cpu")
    rng = random.Random(hash(name) & 0xFFFF)
    maxlen = dev.max_candidate_len
    if name == "ntlm":
        # oracle widens via latin-1 text; keep candidates ascii-safe
        cands = [bytes(rng.randrange(0x20, 0x7F) for _ in range(rng.randrange(0, maxlen + 1)))
                 for _ in range(150)]
    else:
        cands = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, maxlen + 1)))
                 for _ in range(150)]
    assert dev.hash_batch(cands) == oracle.hash_batch(cands)


def test_sha1_vector():
    assert get_engine("sha1", "jax").hash_batch([b"abc"])[0].hex() == \
        "a9993e364706816aba3e25717850c26c9cd0d89d"


def test_sha256_vector():
    assert get_engine("sha256", "jax").hash_batch([b"abc"])[0].hex() == \
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"


def test_sha512_vector():
    # FIPS 180-4 "abc" vector
    assert get_engine("sha512", "jax").hash_batch([b"abc"])[0].hex() == (
        "ddaf35a193617abacc417349ae20413112e6fa4e89a97ea20a9eeee64b55d39a"
        "2192992a274fc1a836ba3c23a3feebbd454d4423643ce80e2a9ac94fa54ca49f")


def test_sha384_vector():
    assert get_engine("sha384", "jax").hash_batch([b"abc"])[0].hex() == (
        "cb00753f45a35e8bb5a03d699ac65007272c32ab0eded163"
        "1a8b605a43ff5bed8086072ba1e7cc2358baeca134c825a7")


def test_ntlm_vector():
    assert get_engine("ntlm", "jax").hash_batch([b"password"])[0].hex() == \
        "8846f7eaee8fb117ad06bdd830b7586c"


@pytest.mark.parametrize("name,mask,secret", [
    ("sha1", "?d?d?d?d", b"7319"),
    ("sha256", "?l?d?l", b"a7z"),
    ("sha512", "?l?d?l", b"k3y"),
    ("sha384", "?d?l?d", b"4q2"),
    ("ntlm", "?u?l?l", b"Pwd"),
])
def test_fused_step_each_engine(name, mask, secret):
    dev = get_engine(name, "jax")
    oracle = get_engine(name, "cpu")
    gen = MaskGenerator(mask)
    planted = gen.index_of(secret)
    tgt = target_words(oracle.hash_batch([secret])[0], dev.little_endian)
    batch = 512
    step = make_mask_crack_step(dev, gen, tgt, batch,
                                widen_utf16=getattr(dev, "widen_utf16", False))
    found = []
    for start in range(0, gen.keyspace, batch):
        n_valid = min(batch, gen.keyspace - start)
        base = jnp.asarray(gen.digits(start), dtype=jnp.int32)
        count, lanes, _ = step(base, jnp.int32(n_valid))
        if int(count):
            found.extend(start + int(l) for l in np.asarray(lanes) if l >= 0)
    assert found == [planted]


def test_cli_engines_lists_device_engines(capsys):
    from dprf_tpu.cli import main
    main(["engines", "--device", "jax"])
    out = capsys.readouterr().out
    for n in ENGINES:
        assert n in out


def test_sha224_vector_and_crack():
    import hashlib as _hl
    assert get_engine("sha224", "jax").hash_batch([b"abc"])[0].hex() == \
        _hl.sha224(b"abc").hexdigest()
    dev = get_engine("sha224", "jax")
    oracle = get_engine("sha224", "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = b"w7q"
    tgt = target_words(oracle.hash_batch([secret])[0], False)
    step = make_mask_crack_step(dev, gen, tgt, 512)
    found = []
    for start in range(0, gen.keyspace, 512):
        base = jnp.asarray(gen.digits(start), dtype=jnp.int32)
        count, lanes, _ = step(base,
                               jnp.int32(min(512, gen.keyspace - start)))
        if int(count):
            found.extend(start + int(l) for l in np.asarray(lanes)
                         if l >= 0)
    assert found == [gen.index_of(secret)]


# ---------------- SHA3/Keccak family (hashcat 17300-18000) ----------------

KECCAK_FAMILY = [(224, 144), (256, 136), (384, 104), (512, 72)]


@pytest.mark.parametrize("bits,rate", KECCAK_FAMILY)
def test_sha3_cpu_matches_hashlib(bits, rate):
    import hashlib
    import random

    cpu = get_engine(f"sha3-{bits}")
    rnd = random.Random(bits)
    cands = [bytes(rnd.randrange(256) for _ in range(rnd.randrange(0, 40)))
             for _ in range(8)]
    assert cpu.hash_batch(cands) == [
        hashlib.new(f"sha3_{bits}", c).digest() for c in cands]


@pytest.mark.parametrize("bits,rate", [(224, 144), (384, 104), (512, 72)])
@pytest.mark.parametrize("kind", ["sha3", "keccak"])
def test_keccak_family_device_crack(kind, bits, rate):
    """Each (variant, size) cracks a planted password on device; the
    224 sizes exercise the half-lane digest tail."""
    from dprf_tpu.runtime.workunit import WorkUnit

    cpu = get_engine(f"{kind}-{bits}")
    dev = get_engine(f"{kind}-{bits}", device="jax")
    assert dev.digest_size == bits // 8
    line = cpu.hash_batch([b"dog"])[0].hex()
    t = cpu.parse_target(line)
    gen = MaskGenerator("?l?l?l")
    w = dev.make_mask_worker(gen, [t], batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, b"dog")]


def test_sha3_224_multi_target_table():
    """28-byte digests (7 words, a half-lane tail) through the sorted
    multi-target table."""
    import hashlib

    from dprf_tpu.runtime.workunit import WorkUnit

    cpu = get_engine("sha3-224")
    dev = get_engine("sha3-224", device="jax")
    gen = MaskGenerator("?l?l?l")
    ts = [cpu.parse_target(hashlib.sha3_224(s).hexdigest())
          for s in (b"abc", b"zzz")]
    w = dev.make_mask_worker(gen, ts, batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert {(h.target_index, h.plaintext) for h in hits} == \
        {(0, b"abc"), (1, b"zzz")}


def test_keccak_block_limit_tracks_rate():
    """The single-block limit is rate-1 bytes: 71 for sha3-512, 143
    for sha3-224."""
    from dprf_tpu.ops.keccak import keccak_words

    import jax.numpy as jnp

    with pytest.raises(ValueError, match="<= 71"):
        keccak_words(jnp.zeros((8, 72), jnp.uint8),
                     jnp.zeros((8,), jnp.int32), rate=72)
