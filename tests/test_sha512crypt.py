"""sha512crypt ($6$): reference vs system crypt, device digests vs
reference (multi-block A-context, on-the-fly repeated-salt chaining,
runtime rounds), worker end-to-end, CLI.  Rounds kept at the format
minimum (1000) so test sweeps stay small."""

import numpy as np
import jax.numpy as jnp
import pytest

# device-pipeline compiles: full suite / tier-1, excluded from the <5-min
# smoke tier (tools/check_markers.py enforces an explicit tier decision)
pytestmark = pytest.mark.compileheavy

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.sha512crypt import (parse_sha512crypt,
                                              sha512crypt_hash,
                                              sha512crypt_raw)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def test_against_system_crypt_if_available():
    try:
        import crypt
    except ImportError:
        pytest.skip("no crypt module")
    for pw, salt, rounds in ((b"password", b"saltstring", 5000),
                             (b"", b"zz", 5000),
                             (b"hello", b"salt", 1000),
                             (b"pw15bytes_reach", b"0123456789abcdef",
                              7777)):
        spec = "$6$" + (f"rounds={rounds}$" if rounds != 5000 else "") \
            + salt.decode() + "$"
        want = crypt.crypt(pw.decode(), spec)
        if want is None:
            pytest.skip("system crypt lacks sha512crypt")
        assert sha512crypt_hash(pw, salt, rounds) == want


def test_parse_variants():
    line = sha512crypt_hash(b"abc", b"mysalt", 1000)
    rounds, salt, digest = parse_sha512crypt(line)
    assert rounds == 1000 and salt == b"mysalt"
    assert sha512crypt_raw(b"abc", salt, rounds) == digest
    line5k = sha512crypt_hash(b"abc", b"mysalt")
    assert "rounds=" not in line5k
    assert parse_sha512crypt(line5k)[0] == 5000
    with pytest.raises(ValueError):
        parse_sha512crypt("$5$notsix$x")


def test_device_digest_matches_reference():
    import random
    from dprf_tpu.engines.device.sha512crypt import \
        sha512crypt_digest_batch

    rng = random.Random(6)
    cands = [b"", b"abcdefghijklmno"] + [
        bytes(rng.randrange(1, 256) for _ in range(rng.randrange(0, 16)))
        for _ in range(6)]
    salt = b"Xy7"
    maxlen = max((len(c) for c in cands), default=1) or 1
    buf = np.zeros((len(cands), maxlen), np.uint8)
    lens = np.zeros((len(cands),), np.int32)
    for i, c in enumerate(cands):
        buf[i, :len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    sbuf = np.zeros((16,), np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    dw = sha512crypt_digest_batch(jnp.asarray(buf), jnp.asarray(lens),
                                  jnp.asarray(sbuf),
                                  jnp.int32(len(salt)), jnp.int32(1000))
    got = [np.asarray(dw)[i].astype(">u4").tobytes()
           for i in range(len(cands))]
    assert got == [sha512crypt_raw(c, salt, 1000) for c in cands]


def test_mask_worker_end_to_end():
    dev = get_engine("sha512crypt", "jax")
    cpu = get_engine("sha512crypt", "cpu")
    gen = MaskGenerator("?l?d")
    secret = b"k7"
    t = dev.parse_target(sha512crypt_hash(secret, b"NaCl", 1000))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_wordlist_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("sha512crypt", "jax")
    cpu = get_engine("sha512crypt", "cpu")
    words = [b"red", b"green", b"blue"]
    rules = [parse_rule(":"), parse_rule("u")]
    gen = WordlistRulesGenerator(words, rules, max_len=15)
    secret = b"GREEN"
    t = dev.parse_target(sha512crypt_hash(secret, b"pepper", 1000))
    w = dev.make_wordlist_worker(gen, [t], batch=8, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_cli_sha512crypt_crack(tmp_path, capsys):
    from dprf_tpu.cli import main

    line = sha512crypt_hash(b"q7", b"grain", 1000)
    hf = tmp_path / "h.txt"
    hf.write_text(line + "\n")
    rc = main(["crack", "?l?d", str(hf), "--engine", "sha512crypt",
               "--device", "tpu", "--no-potfile", "--batch", "512",
               "-q"])
    out = capsys.readouterr().out
    assert rc == 0 and f"{line}:q7" in out


def test_length_guard_rejects_over_budget_masks():
    dev = get_engine("sha512crypt", "jax")
    t = dev.parse_target(sha512crypt_hash(b"x" * 16, b"salt", 1000))
    gen = MaskGenerator("?l" * 16)
    with pytest.raises(ValueError, match="single-block budget"):
        dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8)


def test_sharded_sha512crypt_worker():
    import jax
    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("sha512crypt", "jax")
    cpu = get_engine("sha512crypt", "cpu")
    gen = MaskGenerator("?d?l")
    secret = b"7k"
    t = dev.parse_target(sha512crypt_hash(secret, b"mesa", 1000))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=16, hit_capacity=8,
                                     oracle=cpu)
    from dprf_tpu.runtime.workunit import WorkUnit
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]
