"""Kerberos AES etype-17/18 engines (hashcat 19600/19700/19800/19900/
32100): RFC vectors, forward construction, device-vs-oracle workers.
"""

import hashlib
import hmac as hmac_mod
import random

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.krb5aes import (USAGE_AS_REP,
                                          USAGE_PA_TIMESTAMP,
                                          USAGE_TGS_REP_TICKET,
                                          cts_decrypt, cts_encrypt,
                                          nfold, string_to_key,
                                          usage_keys)
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


@pytest.mark.smoke
def test_nfold_rfc3961_vectors():
    assert nfold(b"012345", 8).hex() == "be072631276b1955"
    assert nfold(b"password", 7).hex() == "78a07b6caf85fa"
    assert nfold(b"kerberos", 16).hex() == \
        "6b65726265726f737b9b5b2b93132b93"
    assert nfold(b"Rough Consensus, and Running Code", 8).hex() == \
        "bb6ed30870b7f0e0"
    assert nfold(b"password", 21).hex() == \
        "59e4a8ca7c0385c3c37b3f6d2000247cb6e6bd5b3e"


@pytest.mark.smoke
def test_string_to_key_rfc3962_vectors():
    """RFC 3962 appendix B (iteration counts that run fast)."""
    s = b"ATHENA.MIT.EDUraeburn"
    assert string_to_key(b"password", s, 16, iterations=1).hex() == \
        "42263c6e89f4fc28b8df68ee09799f15"
    assert string_to_key(b"password", s, 32, iterations=1).hex() == \
        "fe697b52bc0d3ce14432ba036a92e65bbb52280990a2fa27883998d72af30161"
    assert string_to_key(b"password", s, 16, iterations=2).hex() == \
        "c651bf29e2300ac27fa469d693bdda13"
    assert string_to_key(b"password", s, 32, iterations=1200).hex() == \
        "55a6ac740ad17b4846941051e1e8b0a7548d93b0ab30a8bc3ff16280382b8c2a"


@pytest.mark.smoke
def test_cts_rfc3962_vectors():
    """RFC 3962 appendix B AES-128-CBC-CS3 vectors (zero IV)."""
    key = bytes.fromhex("636869636b656e207465726979616b69")
    cases = [
        ("I would like the ",
         "c6353568f2bf8cb4d8a580362da7ff7f97"),
        ("I would like the General Gau's ",
         "fc00783e0efdb2c1d445d4c8eff7ed22"
         "97687268d6ecccc0c07b25e25ecfe5"),
        ("I would like the General Gau's C",
         "39312523a78662d5be7fcbcc98ebf5a8"
         "97687268d6ecccc0c07b25e25ecfe584"),
        ("I would like the General Gau's Chicken, please,",
         "97687268d6ecccc0c07b25e25ecfe584"
         "b3fffd940c16a18c1b5549d2f838029e"
         "39312523a78662d5be7fcbcc98ebf5"),
        ("I would like the General Gau's Chicken, please, ",
         "97687268d6ecccc0c07b25e25ecfe584"
         "9dad8bbb96c4cdc03bc103e1a194bbd8"
         "39312523a78662d5be7fcbcc98ebf5a8"),
    ]
    for pt, want in cases:
        assert cts_encrypt(key, pt.encode()).hex() == want, len(pt)
        assert cts_decrypt(key, bytes.fromhex(want)) == pt.encode()


def _der_blob(body_len: int, tag: int, fill: int) -> bytes:
    """A DER blob [tag] len <body> whose total length the filter can
    predict; body starts with a SEQUENCE so the window matches."""
    body = bytes([0x30, 0x82]) + (body_len - 2).to_bytes(2, "big") + \
        bytes((fill + i) % 256 for i in range(body_len - 4))
    total = len(body)
    assert total <= 0xFFFF
    return bytes([tag, 0x82]) + total.to_bytes(2, "big") + body


def _line(pw: bytes, tag_name: str, etype: int, usage: int,
          seed: int = 3, body_len: int = 400,
          user: str = "svc", realm: str = "EXAMPLE.COM",
          iterations: int = 4096) -> str:
    """Self-consistent hash line: run RFC 3962 forward with the true
    password and a deterministic DER plaintext, store checksum+edata.
    iterations: tests that lower it must ALSO lower the engines'
    `iterations` attribute (the line format does not carry it)."""
    rng = random.Random(seed)
    conf = bytes(rng.randrange(256) for _ in range(16))
    app_tag = {USAGE_TGS_REP_TICKET: 0x63, USAGE_AS_REP: 0x79,
               USAGE_PA_TIMESTAMP: 0x30}[usage]
    if usage == USAGE_PA_TIMESTAMP:
        inner = (b"\xa0\x11\x18\x0f20260731120000Z"
                 b"\xa1\x05\x02\x03\x01\xe2\x40")
        plain = conf + bytes([0x30, len(inner)]) + inner
    else:
        plain = conf + _der_blob(body_len, app_tag, seed)
    salt = (realm + user).encode()
    key = string_to_key(pw, salt, 16 if etype == 17 else 32,
                        iterations=iterations)
    ke, ki = usage_keys(key, usage)
    edata = cts_encrypt(ke, plain)
    chk = hmac_mod.new(ki, plain, hashlib.sha1).digest()[:12]
    return (f"${tag_name}${etype}${user}${realm}${chk.hex()}$"
            f"{edata.hex()}")


@pytest.mark.parametrize("etype", [17, 18])
def test_oracle_roundtrip_and_parse(etype):
    pw = b"Spr1ng"
    cpu = get_engine("krb5tgs-aes", device="cpu")
    t = cpu.parse_target(_line(pw, "krb5tgs", etype,
                               USAGE_TGS_REP_TICKET))
    assert t.params["etype"] == etype
    assert t.params["key_len"] == (16 if etype == 17 else 32)
    assert cpu.verify(pw, t) and not cpu.verify(b"nope", t)


def test_parse_errors():
    cpu = get_engine("krb5tgs-aes", device="cpu")
    with pytest.raises(ValueError):
        cpu.parse_target("$krb5tgs$23$a$B$" + "00" * 12 + "$" + "00" * 40)
    with pytest.raises(ValueError):
        cpu.parse_target("$krb5tgs$17$a$B$00$" + "00" * 40)   # short chk
    with pytest.raises(ValueError):
        cpu.parse_target("not-a-line")


@pytest.mark.smoke
@pytest.mark.parametrize("etype", [17, 18])
def test_mask_worker_end_to_end_tgs(etype):
    """End-to-end device mask sweep, shrunk for the smoke tier: a
    low KDF iteration count (the iteration loop is runtime-bound, not
    compile-bound -- the fori_loop body compiles once) and a tiny
    keyspace/batch.  The RFC-vector tests above pin the full-count
    math; this case proves the fused pipeline plumbing."""
    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    dev.iterations = cpu.iterations = 128
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(174)
    t = dev.parse_target(_line(secret, "krb5tgs", etype,
                               USAGE_TGS_REP_TICKET, iterations=128))
    w = dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8,
                             oracle=cpu)
    assert type(w).__name__ == "Krb5AesMaskWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 174, secret)]


def test_mask_worker_asrep_and_pa_fallback():
    # AS-REP big ticket: device path with the 0x79/0x7A tag mask
    dev = get_engine("krb5asrep-aes", device="jax")
    cpu = get_engine("krb5asrep-aes", device="cpu")
    gen = MaskGenerator("?d?d?d")
    s1 = gen.candidate(271)
    t1 = dev.parse_target(_line(s1, "krb5asrep", 18, USAGE_AS_REP,
                                seed=9))
    w = dev.make_mask_worker(gen, [t1], batch=256, hit_capacity=8,
                             oracle=cpu)
    assert type(w).__name__ == "Krb5AesMaskWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, s1)]

    # Pre-Auth timestamp: edata below the CTS-safe floor -> CPU worker
    # (tiny keyspace: the pure-python oracle runs the full PBKDF2+DK
    # chain per candidate)
    pa = get_engine("krb5pa", device="jax")
    pa_cpu = get_engine("krb5pa", device="cpu")
    gen2 = MaskGenerator("?d?d")
    secret = gen2.candidate(88)
    t2 = pa.parse_target(_line(secret, "krb5pa", 18,
                               USAGE_PA_TIMESTAMP, seed=4))
    w2 = pa.make_mask_worker(gen2, [t2], batch=256, hit_capacity=8,
                             oracle=pa_cpu)
    assert type(w2).__name__ == "CpuWorker"
    hits2 = w2.process(WorkUnit(0, 0, gen2.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits2] == \
        [(0, secret)]


def test_sharded_worker():
    import jax

    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(133)
    t = dev.parse_target(_line(secret, "krb5tgs", 18,
                               USAGE_TGS_REP_TICKET, seed=6))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=32,
                                     hit_capacity=8, oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_engine_listing_symmetry():
    from dprf_tpu.engines import engine_names
    for name in ("krb5tgs-aes", "krb5tgs17", "krb5tgs18", "krb5pa",
                 "krb5asrep-aes"):
        assert name in engine_names("cpu")
        assert name in engine_names("jax")


def test_wordlist_worker_device():
    """Wordlist+rules (the realistic Kerberoasting shape) on the
    device path: variable-length HMAC keys via pack_raw_varlen."""
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    words = [b"winter", b"summer2024", b"svc-backup"]
    rules = [parse_rule(":"), parse_rule("c $!")]
    gen = WordlistRulesGenerator(words, rules, max_len=16)
    secret = b"Summer2024!"               # rule 'c $!' on word 1
    t = dev.parse_target(_line(secret, "krb5tgs", 18,
                               USAGE_TGS_REP_TICKET, seed=13))
    w = dev.make_wordlist_worker(gen, [t], batch=16, hit_capacity=8,
                                 oracle=cpu)
    assert type(w).__name__ == "Krb5AesWordlistWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == \
        [(0, secret)]


def test_etype23_parse_hint():
    cpu23 = get_engine("krb5tgs", device="cpu")
    with pytest.raises(ValueError, match="krb5tgs-aes"):
        cpu23.parse_target("$krb5tgs$17$u$R$" + "00" * 12 + "$"
                           + "00" * 64)


def _short_line(pw: bytes, seed: int = 21) -> str:
    """A TGS line whose edata2 sits BELOW the CTS-safe device floor
    (minimal-DER short-form blob, 44-byte plaintext)."""
    rng = random.Random(seed)
    conf = bytes(rng.randrange(256) for _ in range(16))
    blob = bytes([0x63, 26, 0x30, 24]) + bytes(range(24))   # 28 B
    plain = conf + blob
    salt = b"EXAMPLE.COMsvc"
    key = string_to_key(pw, salt, 32)
    ke, ki = usage_keys(key, USAGE_TGS_REP_TICKET)
    edata = cts_encrypt(ke, plain)
    chk = hmac_mod.new(ki, plain, hashlib.sha1).digest()[:12]
    return f"$krb5tgs$18$svc$EXAMPLE.COM${chk.hex()}${edata.hex()}"


def test_mixed_floor_targets_stay_on_device():
    """One below-floor target must NOT demote the whole job: the
    device worker keeps CTS-safe targets on compiled steps and scans
    the short one with a host pseudo-step (VERDICT-style per-target
    routing)."""
    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    gen = MaskGenerator("?d?d")
    s_short, s_long = gen.candidate(31), gen.candidate(77)
    targets = [dev.parse_target(_short_line(s_short)),
               dev.parse_target(_line(s_long, "krb5tgs", 18,
                                      USAGE_TGS_REP_TICKET, seed=8))]
    w = dev.make_mask_worker(gen, targets, batch=128, hit_capacity=8,
                             oracle=cpu)
    assert type(w).__name__ == "Krb5AesMaskWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted((h.target_index, h.plaintext) for h in hits) == \
        [(0, s_short), (1, s_long)]


@pytest.mark.smoke
def test_pa_long_form_der_window():
    """Long-form DER length branches must expect the PA-ENC-TS-ENC [0]
    inner tag 0xA0 (not the SEQUENCE 0x30 of ticket payloads) -- the
    0x81 branch's byte 4 is the first content byte (ADVICE.md round-5
    low: a wrong expectation here is a silent missed-crack)."""
    from dprf_tpu.engines.device.krb5aes import (CONF,
                                                 der_filter_words_aes)

    # 0x81 long form: L - 2 >= 0x80, L - 3 <= 0xFF -> window byte 4 is
    # the inner tag
    L = 200
    exp, msk = der_filter_words_aes(CONF + L, USAGE_PA_TIMESTAMP)
    b = [(exp >> (8 * i)) & 0xFF for i in range(4)]
    assert b == [0x30, 0x81, L - 3, 0xA0]
    assert msk == 0xFFFFFFFF
    # ticket usages keep the inner SEQUENCE expectation
    exp_t, _ = der_filter_words_aes(CONF + L, USAGE_TGS_REP_TICKET)
    assert [(exp_t >> (8 * i)) & 0xFF for i in range(4)] == \
        [0x63, 0x81, L - 3, 0x30]
    # short form: 24-bit window (byte 4 masked out), PA inner tag 0xA0
    exp_s, msk_s = der_filter_words_aes(CONF + 40, USAGE_PA_TIMESTAMP)
    assert [(exp_s >> (8 * i)) & 0xFF for i in range(4)] == \
        [0x30, 38, 0xA0, 0x00]
    assert msk_s == 0x00FFFFFF
    # 0x82 windows carry tag + 3 length bytes only -- no content byte
    exp_w, msk_w = der_filter_words_aes(CONF + 0x1000, USAGE_PA_TIMESTAMP)
    C = 0x1000 - 4
    assert [(exp_w >> (8 * i)) & 0xFF for i in range(4)] == \
        [0x30, 0x82, (C >> 8) & 0xFF, C & 0xFF]


_LONG_REALM = "VERY-LONG-SUBDOMAIN.CORP.EXAMPLE-ENTERPRISES.COM"


def test_long_salt_targets_demote_to_oracle():
    """A salt (realm+user) above the one-block PBKDF2 budget must
    route to the CPU oracle instead of crashing the job with 'salt too
    long for one block' at the first step() (ADVICE.md round-5
    medium)."""
    from dprf_tpu.engines.device.krb5aes import (MAX_DEVICE_SALT,
                                                 _target_device_ok)

    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    gen = MaskGenerator("?d?d")
    secret = gen.candidate(42)
    line = _line(secret, "krb5tgs", 18, USAGE_TGS_REP_TICKET, seed=5,
                 user="svc-backup", realm=_LONG_REALM)
    t = dev.parse_target(line)
    assert len(t.params["salt"]) > MAX_DEVICE_SALT
    assert not _target_device_ok(t)

    # single long-salt target: the whole job demotes (mask worker)
    w = dev.make_mask_worker(gen, [t], batch=128, hit_capacity=8,
                             oracle=cpu)
    assert type(w).__name__ == "CpuWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]

    # wordlist scaffold demotes too (it has no per-target host steps)
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    wgen = WordlistRulesGenerator([secret, b"nope"], max_len=16)
    ww = dev.make_wordlist_worker(wgen, [t], batch=16, hit_capacity=8,
                                  oracle=cpu)
    assert type(ww).__name__ == "CpuWorker"


def test_mixed_long_salt_target_gets_host_step():
    """Mixed hashlist: the long-salt target rides a host pseudo-step
    while eligible targets keep compiled device steps (same per-target
    routing as the below-floor edata case).  The device step is only
    CONSTRUCTED here (jit is lazy) -- the host step is driven directly
    so the test stays off the multi-minute XLA PBKDF2 compile."""
    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    gen = MaskGenerator("?d?d")
    s_long = gen.candidate(31)
    t_long = dev.parse_target(_line(s_long, "krb5tgs", 18,
                                    USAGE_TGS_REP_TICKET, seed=5,
                                    user="svc-backup",
                                    realm=_LONG_REALM))
    t_ok = dev.parse_target(_line(gen.candidate(77), "krb5tgs", 18,
                                  USAGE_TGS_REP_TICKET, seed=8))
    w = dev.make_mask_worker(gen, [t_long, t_ok], batch=128,
                             hit_capacity=8, oracle=cpu)
    assert type(w).__name__ == "Krb5AesMaskWorker"
    # index 0 (long salt) is a plain-python host pseudo-step; index 1
    # is a jitted device step
    assert not hasattr(w._steps[0], "lower")
    assert hasattr(w._steps[1], "lower")
    import numpy as np
    count, lanes, _ = w._steps[0](
        np.zeros(gen.length, np.int32), np.int32(gen.keyspace), None)
    assert int(count) == 1 and int(lanes[0]) == 31


def test_machine_account_principal_parses():
    """AD machine accounts end in '$'; the parser must split
    checksum/edata from the right, not count fields."""
    pw = b"W1"
    line = _line(pw, "krb5tgs", 18, USAGE_TGS_REP_TICKET,
                 user="WS01$", realm="CORP.LOCAL")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    t = cpu.parse_target(line)
    assert t.params["salt"] == b"CORP.LOCALWS01$"
    assert cpu.verify(pw, t)


def test_pbkdf2_lanes_matches_hashlib():
    """The generic PBKDF2 kernel body (ops/pallas_pbkdf2.pbkdf2_lanes)
    reproduces hashlib's PBKDF2-HMAC-SHA1 bit-for-bit on an eager tiny
    batch, at both deployed key widths (T1-only and T1||T2[:3]).  The
    pallas wrapper follows the PMKID kernel's convention: interpret
    mode is NOT executed hermetically (known multi-minute jit-of-
    interpret cost); the wrapper is proven on hardware like the other
    KDF kernels.  The worker's kernel route shares the XLA verdict
    tail (make_krb5aes_check) with the XLA filter, which the e2e
    worker tests above already cover."""
    import jax.numpy as jnp
    import numpy as np

    from dprf_tpu.ops.pallas_pbkdf2 import pbkdf2_lanes

    salt, iters = b"EXAMPLE.COMsvc", 3
    shape = (1, 128)
    cands = [b"pw%02d" % i for i in range(100)] + \
        [b"x%03d" % i for i in range(28)]
    byts = [jnp.asarray(np.array([c[p] for c in cands], np.uint32)
                        .reshape(1, 128)) for p in range(4)]
    for n_words in (4, 8):
        out = pbkdf2_lanes(byts, list(salt), len(salt),
                           jnp.int32(iters), n_words, shape)
        got = np.stack([np.asarray(w).reshape(128) for w in out],
                       axis=1)
        for i, c in enumerate(cands):
            want = hashlib.pbkdf2_hmac("sha1", c, salt, iters,
                                       4 * n_words)
            want_w = np.frombuffer(want, ">u4")
            assert (got[i] == want_w).all(), (n_words, i)


def test_kernel_route_builds_and_marks(monkeypatch):
    """DPRF_PALLAS=1: the mask worker routes eligible targets onto the
    PBKDF2 kernel step (kernel_targets marker).  The kernel itself is
    stubbed to the XLA filter so the test checks ROUTING without the
    multi-minute interpret compile (see test_pbkdf2_lanes_matches_
    hashlib for the math proof)."""
    from dprf_tpu.engines.device import krb5aes as dev_mod

    monkeypatch.setenv("DPRF_PALLAS", "1")
    calls = {}

    def fake_kdf_step(gen, batch, params, hit_capacity, interpret,
                      iterations=4096, kdf=None):
        calls["built"] = (batch, params["key_len"], iterations)
        fb = dev_mod.make_krb5aes_filter(params, iterations)
        return dev_mod._make_step(gen, batch, fb, hit_capacity), None

    monkeypatch.setattr(dev_mod, "_make_kdf_kernel_step", fake_kdf_step)
    dev = get_engine("krb5tgs-aes", device="jax")
    cpu = get_engine("krb5tgs-aes", device="cpu")
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(117)
    t = dev.parse_target(_line(secret, "krb5tgs", 18,
                               USAGE_TGS_REP_TICKET, seed=2))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert w.kernel_targets == {0}
    assert calls["built"][1] == 32
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == \
        [(0, secret)]


def test_extra_metadata_field_rejected():
    """A starred metadata field between realm and checksum must error
    at load time, not silently corrupt the salt."""
    cpu = get_engine("krb5tgs-aes", device="cpu")
    good = _line(b"W1", "krb5tgs", 18, USAGE_TGS_REP_TICKET)
    parts = good.split("$")
    bad = "$".join(parts[:5] + ["*spn*"] + parts[5:])
    with pytest.raises(ValueError, match="malformed"):
        cpu.parse_target(bad)
