"""Kerberos etype-23 (krb5tgs 13100 / krb5asrep 18200): RC4 vectors,
oracle round-trip, DER-header filter math, device RC4 vs reference,
workers (mask/wordlist/sharded), parsing."""

import hmac as hmac_mod
import random

import pytest

from dprf_tpu.engines import get_engine
from dprf_tpu.engines.cpu.krb5 import (ASREP_MSG_TYPE, TGS_MSG_TYPE,
                                       krb5_rc4_checksum, parse_krb5asrep,
                                       parse_krb5tgs, rc4)
from dprf_tpu.engines.cpu.md4 import md4
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.workunit import WorkUnit


def test_rc4_published_vectors():
    # Classic test vector (appears in the original cypherpunks posting)
    assert rc4(b"Key", b"Plaintext").hex() == "bbf316e8d940af0ad3"
    # RFC 6229, 128-bit key: first 16 keystream bytes
    key = bytes.fromhex("0102030405060708090a0b0c0d0e0f10")
    ks = rc4(key, bytes(16))
    assert ks.hex() == "9ac7cc9a609d1ef7b2932899cde41b97"


def _der_wrap(tag: int, content: bytes) -> bytes:
    C = len(content)
    if C < 0x80:
        return bytes([tag, C]) + content
    if C <= 0xFF:
        return bytes([tag, 0x81, C]) + content
    if C <= 0xFFFF:
        return bytes([tag, 0x82, C >> 8, C & 0xFF]) + content
    return bytes([tag, 0x83, C >> 16, (C >> 8) & 0xFF, C & 0xFF]) + content


def _ticket(password: bytes, msg_type: int, body_len: int,
            tag: int) -> tuple[bytes, bytes, bytes]:
    """Build a VALID (checksum, edata2, plaintext) triple by running
    RFC 4757 forward: plaintext = 8-byte random confounder || DER
    ticket, exactly as a real KDC emits."""
    rng = random.Random(body_len * 1000 + msg_type)
    body = bytes(rng.randrange(256) for _ in range(body_len))
    confounder = bytes(rng.randrange(256) for _ in range(8))
    plain = confounder + _der_wrap(tag, _der_wrap(0x30, body))
    nt = md4(password.decode("latin-1").encode("utf-16-le"))
    k1 = hmac_mod.new(nt, msg_type.to_bytes(4, "little"), "md5").digest()
    checksum = hmac_mod.new(k1, plain, "md5").digest()
    k3 = hmac_mod.new(k1, checksum, "md5").digest()
    return checksum, rc4(k3, plain), plain


def _tgs_line(password: bytes, body_len: int = 300) -> str:
    chk, edata, _ = _ticket(password, TGS_MSG_TYPE, body_len, 0x63)
    return f"$krb5tgs$23$*svc$EXAMPLE.COM$http/web*${chk.hex()}${edata.hex()}"


def _asrep_line(password: bytes, body_len: int = 200) -> str:
    chk, edata, _ = _ticket(password, ASREP_MSG_TYPE, body_len, 0x79)
    return f"$krb5asrep$23$user@EXAMPLE.COM:{chk.hex()}${edata.hex()}"


@pytest.mark.smoke
def test_oracle_roundtrip_and_parse():
    pw = b"Winter2024"
    line = _tgs_line(pw)
    chk, edata = parse_krb5tgs(line)
    assert krb5_rc4_checksum(pw, TGS_MSG_TYPE, chk, edata) == chk
    assert krb5_rc4_checksum(b"wrong", TGS_MSG_TYPE, chk, edata) != chk

    cpu = get_engine("krb5tgs", "cpu")
    t = cpu.parse_target(line)
    assert cpu.verify(pw, t) and not cpu.verify(b"nope", t)

    cpu_as = get_engine("krb5asrep", "cpu")
    t2 = cpu_as.parse_target(_asrep_line(pw))
    assert cpu_as.verify(pw, t2) and not cpu_as.verify(b"nope", t2)


def test_parse_variants_and_errors():
    pw = b"x"
    chk, edata, _ = _ticket(pw, TGS_MSG_TYPE, 300, 0x63)
    # no account-metadata block
    bare = f"$krb5tgs$23${chk.hex()}${edata.hex()}"
    assert parse_krb5tgs(bare) == (chk, edata)
    with pytest.raises(ValueError):
        parse_krb5tgs("$krb5tgs$18$aes-etype-not-supported$00")
    with pytest.raises(ValueError):
        parse_krb5tgs(f"$krb5tgs$23$*unterminated${chk.hex()}${edata.hex()}")
    with pytest.raises(ValueError):
        parse_krb5asrep("not-a-krb5-line")
    # asrep without the account field
    chk2, edata2, _ = _ticket(pw, ASREP_MSG_TYPE, 80, 0x79)
    assert parse_krb5asrep(
        f"$krb5asrep$23${chk2.hex()}${edata2.hex()}") == (chk2, edata2)
    # AES etypes must be rejected loudly, not cracked-to-exhaustion
    with pytest.raises(ValueError):
        parse_krb5asrep(
            f"$krb5asrep$17$user@REALM:{chk2.hex()}${edata2.hex()}")
    # ...but an all-decimal 32-char checksum is NOT an etype field
    digit_chk = bytes.fromhex("12" * 16)
    assert parse_krb5asrep(
        f"$krb5asrep${digit_chk.hex()}${edata2.hex()}") == \
        (digit_chk, edata2)
    # packed-output tile limit is enforced, not silently corrupted
    from dprf_tpu.ops import pallas_krb5
    with pytest.raises(ValueError):
        pallas_krb5.make_krb5_pallas_fn(MaskGenerator("?l?l?l"),
                                        1 << 16, sub=32, chunks=2048)


@pytest.mark.parametrize("body_len,form", [(60, "short"), (180, "0x81"),
                                           (400, "0x82"),
                                           (70_000, "0x83")])
def test_der_filter_matches_real_plaintext(body_len, form):
    """The masked 4-byte expectation must MATCH the true plaintext for
    every DER length form (a filter miss is a false negative)."""
    from dprf_tpu.engines.device.krb5 import der_filter_words

    for msg_type, tag in ((TGS_MSG_TYPE, 0x63), (ASREP_MSG_TYPE, 0x79),
                          (ASREP_MSG_TYPE, 0x7A)):
        _, edata, plain = _ticket(b"pw", msg_type, body_len, tag)
        expected, mask = der_filter_words(len(edata), msg_type)
        # the DER header sits AFTER the 8-byte confounder
        hdr4 = int.from_bytes(plain[8:12], "little")
        assert (hdr4 & mask) == expected, (form, hex(tag))


def test_device_rc4_prefix_matches_reference():
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.ops.rc4 import (rc4_keystream_words,
                                  rc4_keystream_words_reference)

    rng = random.Random(7)
    keys = [bytes(rng.randrange(256) for _ in range(16))
            for _ in range(32)]
    key4 = np.frombuffer(b"".join(keys), "<u4").reshape(32, 4)
    got = np.asarray(rc4_keystream_words(jnp.asarray(key4), 3))
    want = [rc4_keystream_words_reference(k, 3) for k in keys]
    assert got.tolist() == want


@pytest.mark.smoke
@pytest.mark.parametrize("name,line_fn", [("krb5tgs", _tgs_line),
                                          ("krb5asrep", _asrep_line)])
def test_mask_worker_end_to_end(name, line_fn):
    dev = get_engine(name, "jax")
    cpu = get_engine(name, "cpu")
    gen = MaskGenerator("?l?d?l")
    secret = gen.candidate(3333)
    t = dev.parse_target(line_fn(secret))
    w = dev.make_mask_worker(gen, [t], batch=2048, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 3333, secret)]


@pytest.mark.parametrize("body_len", [60, 180, 400])
def test_mask_worker_every_der_form(body_len):
    dev = get_engine("krb5tgs", "jax")
    cpu = get_engine("krb5tgs", "cpu")
    gen = MaskGenerator("?d?d?d")
    secret = gen.candidate(512)
    t = dev.parse_target(_tgs_line(secret, body_len=body_len))
    w = dev.make_mask_worker(gen, [t], batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index) for h in hits] == [(0, 512)]


def test_wordlist_worker():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    from dprf_tpu.rules.parser import parse_rule

    dev = get_engine("krb5tgs", "jax")
    cpu = get_engine("krb5tgs", "cpu")
    words = [b"autumn", b"spring"]
    rules = [parse_rule(":"), parse_rule("c $9")]
    gen = WordlistRulesGenerator(words, rules, max_len=20)
    secret = b"Spring9"
    t = dev.parse_target(_tgs_line(secret))
    w = dev.make_wordlist_worker(gen, [t], batch=16, hit_capacity=8,
                                 oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_sharded_worker():
    import jax

    from dprf_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    dev = get_engine("krb5asrep", "jax")
    cpu = get_engine("krb5asrep", "cpu")
    gen = MaskGenerator("?d?l")
    secret = gen.candidate(117)
    t = dev.parse_target(_asrep_line(secret))
    w = dev.make_sharded_mask_worker(gen, [t], make_mesh(8),
                                     batch_per_device=32, hit_capacity=8,
                                     oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.plaintext) for h in hits] == [(0, secret)]


def test_rc4_unrolled_matches_loop_form():
    """The two KSA forms of the kernel's RC4 op are bit-identical
    (eager, no pallas_call: the unrolled graph is compiler-hostile --
    it SIGABRTs Mosaic -- but its math must stay correct for future
    toolchains)."""
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.ops.pallas_krb5 import _rc4_word2
    from dprf_tpu.ops.rc4 import rc4_keystream_words_reference

    rng = random.Random(11)
    keys = [bytes(rng.randrange(256) for _ in range(16))
            for _ in range(8)]
    key_np = np.frombuffer(b"".join(keys), "<u4").reshape(8, 4)
    key4 = tuple(jnp.broadcast_to(
        jnp.asarray(key_np[:, w])[:, None], (8, 128)).astype(jnp.uint32)
        for w in range(4))
    want = [rc4_keystream_words_reference(k, 3)[2] for k in keys]
    for unroll in (False, True):
        got = np.asarray(_rc4_word2(key4, (8, 128), unroll))[:, 0]
        assert got.tolist() == want, f"unroll={unroll}"


def test_pallas_kernel_matches_xla_filter():
    """Interpret-mode kernel vs the XLA filter step over one batch:
    identical found sets, planted hit at its exact index."""
    import numpy as np
    import jax.numpy as jnp

    from dprf_tpu.engines.device.krb5 import _targs, krb5_filter_batch
    from dprf_tpu.ops import pallas_krb5

    gen = MaskGenerator("?l?l?l")
    plant = 21
    cpu = get_engine("krb5tgs", "cpu")
    t = cpu.parse_target(_tgs_line(gen.candidate(plant)))
    sub, chunks = 8, 2
    tile = sub * chunks
    batch = tile * 2                     # 2 grid cells, plant in cell 1
    fn = pallas_krb5.make_krb5_pallas_fn(gen, batch, sub=sub,
                                         chunks=chunks,
                                         interpret=True)
    base = jnp.asarray(gen.digits(0), jnp.int32)
    counts, lanes = fn(base, jnp.asarray([batch], jnp.int32),
                       *pallas_krb5.target_scalars(t))
    counts = np.asarray(counts)[:, 0]
    lanes = np.asarray(lanes)[:, 0]
    hits = [ti * tile + lanes[ti] for ti in np.nonzero(counts)[0]]
    assert hits == [plant] and counts.sum() == 1

    # cross-check the whole batch against the XLA filter step
    (tb, tn, cb, cn, c4, mk, ex) = _targs([t])[0]
    cand = jnp.asarray(np.stack(
        [np.frombuffer(gen.candidate(i).ljust(gen.length, b"\0"),
                       np.uint8) for i in range(batch)]))
    word = krb5_filter_batch(cand,
                             jnp.full((batch,), gen.length, jnp.int32),
                             tb, tn, cb, cn, c4, mk)
    xla_found = np.asarray(word[:, 0] == ex[0])
    assert xla_found.sum() == 1 and xla_found[plant]


def test_pallas_worker_planted(monkeypatch):
    """DPRF_PALLAS=1 routes make_mask_worker to the kernel worker
    (interpret mode off-TPU); planted crack through the production
    sweep, including the small-tile rescan contract."""
    from dprf_tpu.engines.device import krb5 as dkrb5
    from dprf_tpu.ops import pallas_krb5

    monkeypatch.setenv("DPRF_PALLAS", "1")
    monkeypatch.setattr(pallas_krb5, "SUBC", 8)
    monkeypatch.setattr(pallas_krb5, "CHUNKS", 2)
    dev = get_engine("krb5asrep", "jax")
    cpu = get_engine("krb5asrep", "cpu")
    gen = MaskGenerator("?d?d?l")
    secret = gen.candidate(1517)
    t = dev.parse_target(_asrep_line(secret))
    w = dev.make_mask_worker(gen, [t], batch=64, hit_capacity=8,
                             oracle=cpu)
    assert type(w).__name__ == "PallasKrb5MaskWorker"
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert [(h.target_index, h.cand_index, h.plaintext)
            for h in hits] == [(0, 1517, secret)]


def test_multi_target_sweep_and_engine_listing():
    cpu = get_engine("krb5tgs", "cpu")
    dev = get_engine("krb5tgs", "jax")
    gen = MaskGenerator("?d?d?d")
    secrets = [gen.candidate(12), gen.candidate(900)]
    targets = [dev.parse_target(_tgs_line(s, body_len=100 + 50 * i))
               for i, s in enumerate(secrets)]
    w = dev.make_mask_worker(gen, targets, batch=512, hit_capacity=8,
                             oracle=cpu)
    hits = w.process(WorkUnit(0, 0, gen.keyspace))
    assert sorted((h.target_index, h.plaintext) for h in hits) == \
        [(0, secrets[0]), (1, secrets[1])]

    from dprf_tpu.engines import engine_names
    for name in ("krb5tgs", "krb5asrep"):
        assert name in engine_names("cpu") and name in engine_names("jax")
