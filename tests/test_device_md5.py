"""Device MD5 engine vs CPU oracle + fused crack step end-to-end."""

import os
import random

import jax.numpy as jnp
import numpy as np
import pytest

from dprf_tpu import get_engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.pipeline import make_mask_crack_step, target_words


@pytest.fixture(scope="module")
def oracle():
    return get_engine("md5", "cpu")


@pytest.fixture(scope="module")
def dev():
    return get_engine("md5", "jax")


@pytest.mark.smoke
def test_md5_vectors(dev):
    got = dev.hash_batch([b"", b"abc", b"message digest"])
    assert got[0].hex() == "d41d8cd98f00b204e9800998ecf8427e"
    assert got[1].hex() == "900150983cd24fb0d6963f7d28e17f72"
    assert got[2].hex() == "f96b697d7cb7938d525a2f31aaf161d0"


def test_md5_random_batch_matches_oracle(dev, oracle):
    rng = random.Random(7)
    cands = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 56)))
             for _ in range(200)]
    assert dev.hash_batch(cands) == oracle.hash_batch(cands)


@pytest.mark.smoke
def test_fused_step_finds_planted_password(dev, oracle):
    gen = MaskGenerator("?l?l?l")
    secret = b"wxy"
    planted = gen.index_of(secret)
    tgt = target_words(oracle.hash_batch([secret])[0])
    batch = 512
    step = make_mask_crack_step(dev, gen, tgt, batch)

    found_at = []
    for start in range(0, gen.keyspace, batch):
        n_valid = min(batch, gen.keyspace - start)
        base = jnp.asarray(gen.digits(start), dtype=jnp.int32)
        count, lanes, _ = step(base, jnp.int32(n_valid))
        if int(count):
            lanes = np.asarray(lanes)
            found_at.extend(start + int(l) for l in lanes if l >= 0)
    assert found_at == [planted]


def test_fused_step_tail_unit_masks_invalid_lanes(dev, oracle):
    gen = MaskGenerator("?d?d?d")
    # plant the very first candidate; run the *last* partial unit where
    # wrapped lanes would re-decode index 0 and must be masked out.
    secret = gen.candidate(0)
    tgt = target_words(oracle.hash_batch([secret])[0])
    batch = 256
    step = make_mask_crack_step(dev, gen, tgt, batch)
    start = 896   # last unit: 104 valid lanes, 152 wrapped
    base = jnp.asarray(gen.digits(start), dtype=jnp.int32)
    count, lanes, _ = step(base, jnp.int32(gen.keyspace - start))
    assert int(count) == 0


def test_hit_compaction_many_hits():
    found = jnp.zeros(100, dtype=bool).at[jnp.arange(0, 100, 7)].set(True)
    payload = jnp.arange(100, dtype=jnp.int32) * 10
    count, lanes, pay = cmp_ops.compact_hits(found, payload, capacity=8)
    assert int(count) == 15          # true count survives overflow
    lanes = [int(x) for x in np.asarray(lanes)]
    assert lanes == [0, 7, 14, 21, 28, 35, 42, 49]
    assert [int(x) for x in np.asarray(pay)] == [x * 10 for x in lanes]
