"""The fault-injection chaos harness and its auditor-backed zero-loss
gate (ISSUE 19): one scripted run through all declared fault types
must land every planted hit exactly once with coverage fraction 1.0,
and ``dprf audit`` over the artifacts it leaves behind must say CLEAN
from the files alone.  Plus the worker-side half of the audit trail:
a sharded overflow redrive's coverage notes must tile the unit.
"""

import hashlib
import json

import jax
import pytest

from dprf_tpu.cli import main as cli_main
from dprf_tpu.telemetry import coverage
from dprf_tpu.telemetry.coverage import IntervalSet, coverage_digest
from dprf_tpu.testing import FAULTS, run_chaos

pytestmark = [pytest.mark.smoke, pytest.mark.audit]


@pytest.fixture(scope="module")
def chaos_session(tmp_path_factory):
    """One chaos run shared by the harness + CLI assertions below --
    the artifacts are the point, re-running buys nothing."""
    path = str(tmp_path_factory.mktemp("chaos") / "c.session")
    return path, run_chaos(path)


def test_chaos_zero_loss_gate(chaos_session):
    _, res = chaos_session
    assert res["clean"] is True
    assert res["violations"] == []
    assert sorted(res["faults"]) == sorted(FAULTS)
    assert len(FAULTS) >= 5                  # acceptance floor
    assert "coordinator_restart" in res["faults"]
    assert res["fraction"] == 1.0
    assert res["overlap"] == 0 and res["gap_total"] == 0
    assert res["hits_found"] == res["hits_planted"]
    assert res["audit_verdict"] == "clean"
    assert res["audit_problems"] == []


def test_cli_audit_clean_from_artifacts_alone(chaos_session, capsys):
    path, res = chaos_session
    assert cli_main(["audit", path]) == 0
    assert cli_main(["audit", path, "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out[out.index("{"):])
    assert doc["verdict"] == "clean"
    # the offline digest is rebuilt from the journal, not trusted
    assert doc["jobs"][0]["digest_rebuilt"] == res["digest"]
    assert doc["jobs"][0]["digest_match"] is True


def test_cli_audit_missing_artifacts(tmp_path):
    assert cli_main(["audit", str(tmp_path / "nope.session")]) == 2


def test_cli_audit_gates_on_dirty(tmp_path):
    from dprf_tpu.runtime.session import SessionJournal
    j = SessionJournal(str(tmp_path / "d.session"))
    j.open({"engine": "md5", "attack": "mask", "keyspace": 1000})
    j.snapshot([(0, 1000)], digest=coverage_digest(1000, [(0, 500)]))
    j.close()
    assert cli_main(["audit", j.path]) == 3


def test_chaos_cli_entrypoint(tmp_path, capsys):
    """``python -m dprf_tpu.testing.chaos`` is the CI audit-tier gate:
    exit 0 iff the auditor verdict is clean, JSON report on stdout."""
    from dprf_tpu.testing import chaos
    rc = chaos.main(["--session", str(tmp_path / "ci" / "c.session"),
                     "--keyspace", "8000", "--unit-size", "256"])
    res = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert res["clean"] is True and res["audit_verdict"] == "clean"


@pytest.mark.compileheavy
def test_sharded_overflow_redrive_notes_tile_unit():
    """The sharded superstep path under overflow pressure: the
    'window' notes it emits must tile the unit EXACTLY (no gap, no
    double-tile), and the overflow must surface as deliberate
    redrive/rescan notes inside the unit -- the worker-side evidence
    the auditor pairs with the coordinator's ledger."""
    from dprf_tpu.engines import get_engine
    from dprf_tpu.engines.base import Target
    from dprf_tpu.generators.mask import MaskGenerator
    from dprf_tpu.parallel import make_mesh
    from dprf_tpu.parallel.worker import ShardedMaskWorker
    from dprf_tpu.runtime.workunit import WorkUnit

    assert len(jax.devices()) >= 8, "conftest should fake 8 CPU devices"
    mesh = make_mesh(8)
    gen = MaskGenerator("?d?d?d?d?d")        # 100000
    B = 128
    stride = 8 * B
    plant = [0, 3, 7, stride + 1, 2 * stride + 2, 3 * stride + 5,
             gen.keyspace - 1]
    targets = [Target(str(i), hashlib.md5(gen.candidate(i)).digest())
               for i in plant]
    got = []
    coverage.reset_notes()
    coverage.install_collector(
        lambda name, s, e, attrs: got.append((name, s, e)))
    try:
        w = ShardedMaskWorker(get_engine("md5", device="jax"), gen,
                              targets, mesh, batch_per_device=B,
                              hit_capacity=2,
                              oracle=get_engine("md5", device="cpu"))
        hits = w.process(WorkUnit(0, 0, gen.keyspace))
    finally:
        coverage.install_collector(None)
    assert sorted(h.cand_index for h in hits) == plant

    windows = [(s, e) for name, s, e in got if name == "window"]
    tiled = IntervalSet()
    newly = sum(tiled.add(s, e) for s, e in windows)
    assert tiled.intervals() == [(0, gen.keyspace)]      # no gap
    assert newly == sum(e - s for s, e in windows)       # no double-tile
    # the overflow really redrove, and stayed inside the unit
    redrives = [(s, e) for name, s, e in got
                if name in ("redrive", "rescan")]
    assert redrives, "overflow produced no redrive/rescan notes"
    assert all(0 <= s < e <= gen.keyspace for s, e in redrives)
    assert coverage.notes()["redrive"] >= 1
