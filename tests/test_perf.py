"""Performance attribution (ISSUE 9): sampled per-phase sweep
accounting, the live busy-fraction gauge, the roofline model, the
bench regression sentinel, and `dprf report`.

Device-engine cases run the XLA md5 pipeline on the CPU backend
(conftest pins jax to cpu); everything is loopback/local.
"""

import hashlib
import json
import time

import pytest

from dprf_tpu import get_engine
from dprf_tpu.cli import main as cli_main
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.runtime.coordinator import Coordinator, JobSpec
from dprf_tpu.runtime.dispatcher import Dispatcher
from dprf_tpu.runtime.worker import CpuWorker
from dprf_tpu.runtime.workunit import WorkUnit
from dprf_tpu.telemetry import perf
from dprf_tpu.telemetry.registry import MetricsRegistry
from dprf_tpu.telemetry.trace import (TraceRecorder, load_trace,
                                      overlap_report, trace_path)

pytestmark = pytest.mark.smoke

UNMATCHABLE = "ff" * 16


def _recorder(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("registry", MetricsRegistry())
    return TraceRecorder(**kw)


def _device_worker(mask="?l?l?d", batch=2048):
    eng = get_engine("md5", device="jax")
    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator(mask)
    targets = [oracle.parse_target(UNMATCHABLE)]
    return eng.make_mask_worker(gen, targets, batch=batch,
                                hit_capacity=16, oracle=oracle), gen


def _local_sweep(mask, unit_size, worker=None, gen=None,
                 registry=None, recorder=None):
    reg = registry or MetricsRegistry()
    rec = recorder or _recorder(registry=reg)
    oracle = get_engine("md5", device="cpu")
    if worker is None:
        gen = MaskGenerator(mask)
        targets = [oracle.parse_target(UNMATCHABLE)]
        worker = CpuWorker(oracle, gen, targets, chunk=8192)
    disp = Dispatcher(gen.keyspace, unit_size, registry=reg,
                      recorder=rec)
    spec = JobSpec(engine="md5", device="cpu", attack="mask",
                   attack_arg=mask, keyspace=gen.keyspace,
                   fingerprint="perftest")
    coord = Coordinator(spec, worker.targets, disp, worker,
                        registry=reg, recorder=rec,
                        oracle=None)
    t0 = time.perf_counter()
    result = coord.run()
    return result, time.perf_counter() - t0, rec, reg


# ---------------------------------------------------------------------------
# probed sweep: phases + hits through the real device worker contract

def test_probe_pending_digit_worker_phases_and_hits(monkeypatch):
    eng = get_engine("md5", device="jax")
    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator("?l?l?d")
    # planted crack so the probed sweep must decode a REAL hit
    targets = [oracle.parse_target(hashlib.md5(b"zz9").hexdigest())]
    worker = eng.make_mask_worker(gen, targets, batch=2048,
                                  hit_capacity=16, oracle=oracle)
    reg = MetricsRegistry()
    rec = _recorder(registry=reg)
    sampler = perf.PerfSampler(registry=reg, recorder=rec, every=1)
    worker.warmup()
    unit = WorkUnit(7, 0, gen.keyspace)
    p = perf.probe_pending(worker, unit, sampler, trace="t1")
    assert p.resolve() == worker.process(unit)   # identical hits
    assert [h.plaintext for h in p.resolve()] == [b"zz9"]
    for ph in ("generate", "h2d", "device", "d2h"):
        assert p.phases[ph] >= 0.0
    assert p.phases["device"] > 0.0
    # spans: one per phase, parented on the pre-allocated sweep id
    assert {s["attrs"]["phase"] for s in p.phase_spans} == {
        "generate", "h2d", "device", "d2h"}
    assert all(s["parent"] == p.sweep_span for s in p.phase_spans)
    assert all(s["trace"] == "t1" for s in p.phase_spans)
    # histogram observed once per phase
    h = reg.get("dprf_phase_seconds")
    assert h.count(phase="device", engine="md5", job="j0") == 1


def test_probe_pending_coarse_for_custom_process_worker():
    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator("?l?l")
    targets = [oracle.parse_target(
        hashlib.md5(b"zz").hexdigest())]      # planted at last index
    worker = CpuWorker(oracle, gen, targets)
    reg = MetricsRegistry()
    sampler = perf.PerfSampler(registry=reg, recorder=_recorder(),
                               every=1)
    unit = WorkUnit(0, 0, gen.keyspace)
    p = perf.probe_pending(worker, unit, sampler)
    assert [h.plaintext for h in p.resolve()] == [b"zz"]
    assert set(p.phases) == {"device"}       # coarse: one honest total


# ---------------------------------------------------------------------------
# phase spans sum to ~the sweep span (acceptance criterion)

def test_phase_spans_sum_to_sweep_within_tolerance(monkeypatch):
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "1")
    monkeypatch.setenv("DPRF_PIPELINE_DEPTH", "1")
    worker, gen = _device_worker()
    worker.warmup()
    _, _, rec, _ = _local_sweep("?l?l?d", 2000, worker=worker,
                                gen=gen)
    spans = rec.tail(100000)
    sweeps = {s["span"]: s for s in spans
              if s["name"] == "sweep" and s["attrs"].get("probed")}
    assert len(sweeps) >= 3                  # every unit probed
    by_parent: dict = {}
    for s in spans:
        if s["name"] == "phase":
            by_parent.setdefault(s["parent"], 0.0)
            by_parent[s["parent"]] += s["dur"]
    for sid, sw in sweeps.items():
        total = by_parent.get(sid)
        assert total is not None, "probed sweep lost its phase spans"
        # phases cover the probe work inside the sweep span; the
        # sweep adds only queue/pop overhead at depth 1
        assert total <= sw["dur"] * 1.05 + 0.02
        assert total >= sw["dur"] * 0.5 - 0.02


# ---------------------------------------------------------------------------
# sampling cadence: exactly every Nth unit

def test_sampler_cadence_exact():
    s = perf.PerfSampler(registry=MetricsRegistry(),
                         recorder=_recorder(), every=4)
    takes = [s.take() for _ in range(12)]
    assert takes == [i % 4 == 0 for i in range(12)]
    off = perf.PerfSampler(registry=MetricsRegistry(),
                           recorder=_recorder(), every=0)
    assert not any(off.take() for _ in range(8))


def test_sampled_mode_records_on_configured_cadence(monkeypatch):
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "4")
    _, _, rec, _ = _local_sweep("?l?l?d", 600)   # 6760 -> 12 units
    spans = rec.tail(100000)
    probed = [s for s in spans
              if s["name"] == "sweep" and s["attrs"].get("probed")]
    n_units = len([s for s in spans if s["name"] == "sweep"])
    assert n_units == 12
    assert len(probed) == 3                  # units 1, 5, 9
    # coarse CPU probe: exactly one phase span per probed unit
    assert len([s for s in spans if s["name"] == "phase"]) == 3


def test_sample_zero_disables_probing(monkeypatch):
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "0")
    _, _, rec, reg = _local_sweep("?l?l?d", 600)
    spans = rec.tail(100000)
    assert not [s for s in spans if s["name"] == "phase"]
    assert reg.get("dprf_phase_seconds").count(
        phase="device", engine="md5", job="j0") == 0


# ---------------------------------------------------------------------------
# steady-state overhead <= 2% (acceptance criterion; the PR 4
# noise-free pattern: cost the probes at a measured per-probe price)

def test_sampling_overhead_within_2_percent(monkeypatch):
    mask, unit_size = "?l?l?l?l", 1 << 14     # 456,976 -> 28 units
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "0")
    offs = [_local_sweep(mask, unit_size)[1] for _ in range(2)]
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "16")
    ons = [_local_sweep(mask, unit_size)[1] for _ in range(2)]
    t_off, t_on = min(offs), min(ons)
    # primary, noise-free bound: the per-probe EXTRA cost vs the
    # plain path, measured directly, times the probes a sampled
    # sweep runs, must be <= 2% of the sweep
    oracle = get_engine("md5", device="cpu")
    gen = MaskGenerator(mask)
    targets = [oracle.parse_target(UNMATCHABLE)]
    worker = CpuWorker(oracle, gen, targets, chunk=8192)
    sampler = perf.PerfSampler(registry=MetricsRegistry(),
                               recorder=_recorder(), every=1)
    unit = WorkUnit(0, 0, unit_size)
    t_plain = min(_timed(lambda: worker.process(unit))
                  for _ in range(3))
    t_probe = min(_timed(lambda: perf.probe_pending(worker, unit,
                                                    sampler))
                  for _ in range(3))
    per_probe_extra = max(0.0, t_probe - t_plain)
    n_probes = -(-28 // 16)                   # ceil(units / cadence)
    assert per_probe_extra * n_probes <= 0.02 * t_on, (
        f"{n_probes} probes x {per_probe_extra * 1e3:.2f}ms extra "
        f"> 2% of the {t_on:.3f}s sweep")
    # generous wall guard against gross regressions (loaded 2-core
    # box: not a tight bound)
    assert t_on <= t_off * 1.25 + 0.1, (t_on, t_off)


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# live busy fraction == tools/trace_overlap.py union math

def test_busy_fraction_gauge_matches_trace_overlap(tmp_path):
    clk = [1000.0]
    reg = MetricsRegistry()
    rec = TraceRecorder(enabled=True, registry=reg,
                        clock=lambda: clk[0])
    stream = str(tmp_path / "s.session.trace.jsonl")
    rec.attach_file(stream, max_bytes=0)
    # worker A: two sweeps with a 2 s hole; worker B: overlapping
    # pipelined sweeps, no hole
    plan = {"wA": [(1000.0, 3.0), (1005.0, 3.0)],
            "wB": [(1000.0, 4.0), (1003.0, 4.0)]}
    for proc, sweeps in plan.items():
        for ts, dur in sweeps:
            clk[0] = ts + dur
            rec.record("sweep", dur=dur, ts=ts, proc=proc,
                       unit=1, length=100)
    clk[0] = 1008.0          # == global last end
    live = rec.busy_fractions()
    rec.detach_file()
    rep = overlap_report(load_trace(stream))
    for proc in plan:
        sweeps = plan[proc]
        t0 = min(ts for ts, _ in sweeps)
        t1 = max(ts + dur for ts, dur in sweeps)
        covered = (t1 - t0) - rep["workers"][proc]["idle_s"]
        expected = covered / (1008.0 - t0)
        assert live[proc] == pytest.approx(expected, abs=1e-3), proc
    assert live["wA"] == pytest.approx(6.0 / 8.0, abs=1e-3)
    assert live["wB"] == pytest.approx(7.0 / 8.0, abs=1e-3)
    # the gauge carries the same values
    g = reg.get("dprf_device_busy_fraction")
    assert g.value(worker="wA") == pytest.approx(6.0 / 8.0, abs=1e-3)


def test_busy_fraction_prunes_outside_window():
    clk = [0.0]
    rec = TraceRecorder(enabled=True, registry=MetricsRegistry(),
                        clock=lambda: clk[0])
    clk[0] = 10.0
    rec.record("sweep", dur=10.0, ts=0.0, proc="w")
    assert rec.busy_fractions()["w"] == pytest.approx(1.0)
    # 100% idle for a window's length: the old interval falls out
    from dprf_tpu.telemetry.trace import BUSY_WINDOW_S
    clk[0] = 10.0 + BUSY_WINDOW_S + 1
    assert rec.busy_fractions()["w"] == 0.0


# ---------------------------------------------------------------------------
# roofline model + gauges

def _no_analyzed_model(monkeypatch):
    """Pin the HAND-model fallback: earlier tests in the session may
    have warmed real workers, landing XLA-derived records in the
    process-global program registry (ISSUE 13) -- these tests assert
    the hand table's band, so the analyzed model must read absent."""
    from dprf_tpu.telemetry import programs
    monkeypatch.setattr(programs, "analyzed_ops_per_candidate",
                        lambda engine, programs=None: None)


def test_roofline_band_and_fraction(monkeypatch):
    _no_analyzed_model(monkeypatch)
    lo, hi = perf.roofline_band_hs("md5")
    assert (lo, hi) == (4.0e9, 8.0e9)        # documented band
    assert perf.roofline_fraction("md5", 4.0e9) == pytest.approx(0.5)
    assert perf.roofline_band_hs("sha1") == pytest.approx(
        (3.0e12 / 1000, 6.0e12 / 1000))
    assert perf.roofline_band_hs("bcrypt") is None   # no model: None
    assert perf.roofline_fraction("bcrypt", 1e9) is None


def test_roofline_prefers_analyzed_model(monkeypatch):
    """ISSUE 13: an analyzed program's flops/candidate beats the hand
    table, and covers engines the table never listed."""
    from dprf_tpu.telemetry import programs
    monkeypatch.setattr(programs, "analyzed_ops_per_candidate",
                        lambda engine, programs=None: 1500.0)
    assert perf.ops_per_candidate("sha512") == 1500.0
    assert perf.roofline_band_hs("sha512") == pytest.approx(
        (3.0e12 / 1500, 6.0e12 / 1500))
    # md5's documented hand band yields to the derived one too
    assert perf.roofline_band_hs("md5") == pytest.approx(
        (3.0e12 / 1500, 6.0e12 / 1500))
    assert perf.analyzed_roofline_fraction(
        "md5", 2.0e9) == pytest.approx(2.0e9 / (6.0e12 / 1500))


def test_publish_roofline_smooths_and_snapshots(monkeypatch):
    _no_analyzed_model(monkeypatch)
    reg = MetricsRegistry()
    f1 = perf.publish_roofline("md5", 4.0e9, registry=reg)
    assert f1 == pytest.approx(0.5)          # first sample unsmoothed
    f2 = perf.publish_roofline("md5", 8.0e9, registry=reg)
    assert 0.5 < f2 < 1.0                    # EWMA toward 1.0
    snap = perf.roofline_snapshot(reg)
    assert snap["md5"] == pytest.approx(f2)
    assert perf.publish_roofline("bcrypt", 1e9, registry=reg) is None


def test_scaling_gauges_published():
    reg = MetricsRegistry()
    perf.publish_scaling("md5", 2.0e9, 0.85, 8, registry=reg)
    assert reg.get("dprf_per_chip_rate_hs").value(
        engine="md5") == 2.0e9
    assert reg.get("dprf_scaling_efficiency").value(
        engine="md5") == pytest.approx(0.85)


# ---------------------------------------------------------------------------
# bench JSON carries phases

def test_run_bench_cpu_reports_phases():
    from dprf_tpu.bench import run_bench
    res = run_bench(engine="md5", device="cpu", mask="?l?l?l?l",
                    batch=2048, seconds=0.2)
    assert set(res["phases"]) == {"generate", "device"}
    assert all(v >= 0 for v in res["phases"].values())


def test_run_config_reports_phases():
    from dprf_tpu.bench import run_config
    res = run_config(1, device="jax", seconds=0.2, batch=4096)
    ph = res["phases"]
    assert ph["device"] > 0
    assert {"generate", "h2d", "device", "d2h"} <= set(ph)


# ---------------------------------------------------------------------------
# bench regression sentinel

def _plant_bench(tmp_path, values, device="tpu", start_round=1):
    for i, v in enumerate(values):
        line = json.dumps({"metric": "md5 candidates/sec/chip",
                           "value": v, "unit": "H/s",
                           "device": device, "engine": "md5"})
        (tmp_path / f"BENCH_r{start_round + i:02d}.json").write_text(
            json.dumps({"n": start_round + i, "rc": 0,
                        "tail": "noise line\n" + line + "\n"}))


def test_bench_compare_passes_and_fails_planted_trajectories(tmp_path):
    from dprf_tpu.perfreport import compare
    _plant_bench(tmp_path, [5.0e9, 5.1e9, 4.9e9, 5.05e9])
    base = compare.load_bench_records(str(tmp_path))
    assert [r["round"] for r in base] == [1, 2, 3, 4]
    cur = {"value": 4.9e9, "device": "tpu", "engine": "md5"}
    assert compare.gate(cur, base)["verdict"] == "pass"
    bad = {"value": 3.0e9, "device": "tpu", "engine": "md5"}
    v = compare.gate(bad, base)
    assert v["verdict"] == "regression" and v["ratio"] < 0.7
    # a CPU-fallback run must not regress against a TPU baseline
    cpu = {"value": 3.0e6, "device": "cpu", "engine": "md5"}
    assert compare.gate(cpu, base)["verdict"] == "no-baseline"
    # noisy trajectories widen their own tolerance
    noisy = [{"value": x, "device": "tpu", "engine": "md5"}
             for x in (4.0e9, 6.0e9, 5.0e9)]
    dip = {"value": 4.2e9, "device": "tpu", "engine": "md5"}
    v = compare.gate(dip, noisy)
    assert v["verdict"] == "pass" and v["tolerance"] >= 0.4


def test_bench_compare_dry_mode_and_tool_exit_codes(tmp_path):
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_compare_tool", os.path.join(repo, "tools",
                                           "bench_compare.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    _plant_bench(tmp_path, [5.0e9, 5.1e9, 4.9e9, 2.0e9])
    assert tool.main(["--dry", "--dir", str(tmp_path), "-q"]) == 1
    _plant_bench(tmp_path, [5.0e9], start_round=5)
    assert tool.main(["--dry", "--dir", str(tmp_path), "-q"]) == 0
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps({"value": 1.0e9, "device": "tpu",
                               "engine": "md5"}))
    assert tool.main(["--current", str(cur), "--dir", str(tmp_path),
                      "-q"]) == 1


def test_bench_gate_dry_cli(tmp_path, capsys):
    _plant_bench(tmp_path, [5.0e9, 5.1e9, 4.9e9, 5.0e9])
    rc = cli_main(["bench", "--gate-dry", "--baseline-dir",
                   str(tmp_path), "--quiet"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["gate"]["verdict"] == "pass"
    _plant_bench(tmp_path, [1.0e9], start_round=5)
    rc = cli_main(["bench", "--gate-dry", "--baseline-dir",
                   str(tmp_path), "--quiet"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["gate"]["verdict"] == "regression"


# ---------------------------------------------------------------------------
# dprf report: the whole post-mortem from session artifacts alone

def test_report_from_session_artifacts(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("DPRF_PERF_SAMPLE", "2")
    monkeypatch.setenv("DPRF_TELEMETRY_INTERVAL", "0.25")
    hashes = tmp_path / "h.txt"
    hashes.write_text(hashlib.md5(b"zz9").hexdigest() + "\n")
    session = str(tmp_path / "job.session")
    rc = cli_main(["crack", "--engine", "md5", "--device", "cpu",
                   "-a", "mask", "?l?l?d", str(hashes),
                   "--session", session, "--unit-size", "600",
                   "--no-potfile", "--quiet"])
    assert rc == 0
    capsys.readouterr()
    from dprf_tpu.perfreport import build_report, render_report
    doc = build_report(session)
    assert doc["engine"] == "md5"
    assert doc["units"] >= 1 and doc["probed_units"] >= 1
    assert doc["phases"]["device"]["count"] >= 1
    assert doc["throughput"]["hs"] and doc["throughput"]["hs"] > 0
    assert doc["busy"] and all(0 <= v <= 1
                               for v in doc["busy"].values())
    assert doc["fair_share"] and doc["fair_share"][0]["job"] == "j0"
    text = render_report(doc)
    assert "phase breakdown" in text and "device busy fraction" in text
    # the CLI renders the same report; --json round-trips
    assert cli_main(["report", session, "--quiet"]) == 0
    assert "throughput" in capsys.readouterr().out
    assert cli_main(["report", session, "--json", "--quiet"]) == 0
    doc2 = json.loads(capsys.readouterr().out)
    assert doc2["units"] == doc["units"]
    # no artifacts at all -> rc 2
    assert cli_main(["report", str(tmp_path / "nope.session"),
                     "--quiet"]) == 2


# ---------------------------------------------------------------------------
# top header carries busy/roofline; status ships them over the RPC

def test_render_top_header_busy_and_roofline():
    from dprf_tpu.telemetry.trace import render_top
    resp = {"status": {"done": 5, "total": 10, "found": 0,
                       "targets": 1, "parked": 0, "stop": False,
                       "elapsed": 3.0, "now": time.time(),
                       "busy": {"w1": 0.9, "w2": 0.7},
                       "roofline": {"md5": 0.62}},
            "spans": [], "leases": [
                {"worker": "w1", "unit": 3, "start": 0,
                 "length": 100, "job": "j1", "deadline_s": 10.0},
                {"worker": "w2", "unit": 4, "start": 100,
                 "length": 100, "job": "j0", "deadline_s": 10.0}]}
    text = render_top(resp)
    assert "busy 80%" in text
    assert "roofline md5:0.62" in text
    # per-job grouping: the j0 worker row sorts before the j1 row
    lines = text.splitlines()
    w1 = next(i for i, ln in enumerate(lines) if ln.startswith("w1"))
    w2 = next(i for i, ln in enumerate(lines) if ln.startswith("w2"))
    assert w2 < w1                            # grouped by job id


def test_probe_pending_wordlist_worker_phases_and_hits():
    from dprf_tpu.generators.wordlist import WordlistRulesGenerator
    oracle = get_engine("md5", device="cpu")
    words = [b"alpha", b"bravo", b"zulu9", b"kilo", b"tango", b"echo"]
    gen = WordlistRulesGenerator(words, None, max_len=16)
    # planted at the LAST word so the probe sweeps the whole range
    targets = [oracle.parse_target(hashlib.md5(b"echo").hexdigest())]
    worker = get_engine("md5", device="jax").make_wordlist_worker(
        gen, targets, batch=4, hit_capacity=8, oracle=oracle)
    worker.warmup()
    reg = MetricsRegistry()
    sampler = perf.PerfSampler(registry=reg, recorder=_recorder(),
                               every=1)
    unit = WorkUnit(0, 0, gen.keyspace)
    p = perf.probe_pending(worker, unit, sampler)
    assert p.resolve() == worker.process(unit)
    assert [h.plaintext for h in p.resolve()] == [b"echo"]
    # wordlist contract: generation happens ON device, so the split
    # is h2d (scalars) / device / d2h
    assert p.phases["device"] > 0.0
    assert {"h2d", "device", "d2h"} <= set(p.phases)


def test_phase_share_scales_sampled_against_unsampled_verify():
    """1 probed unit in 16 contributes sampled phase durations that
    stand for ~16 units of fleet time; verify spans are per-hit-batch
    and unsampled -- the share must not let verify inflate by the
    sampling factor."""
    from dprf_tpu.perfreport.report import _phase_stats
    spans = ([{"name": "phase", "dur": 1.0, "ts": 0.0,
               "attrs": {"phase": "device"}}]
             + [{"name": "hit_verify", "dur": 1.0, "ts": 0.0}] * 4)
    st = _phase_stats(spans, sample_scale=16.0)
    assert st["device"]["share"] == pytest.approx(16 / 20)
    assert st["verify"]["share"] == pytest.approx(4 / 20)
    assert st["device"]["total_s"] == 1.0      # observed, not scaled
    # unscaled: verify would wrongly dominate
    raw = _phase_stats(spans, sample_scale=1.0)
    assert raw["verify"]["share"] == pytest.approx(0.8)


def test_probe_drains_device_backlog_before_measuring():
    """A sampled probe submitted behind queued pipelined units must
    wait for THEIR device work first, so its synced phase boundaries
    attribute only the probed unit (code-review finding)."""
    calls = []

    class _Flag:
        def block_until_ready(self):
            calls.append("blocked")

    class _Pending:
        flag = _Flag()

        def resolve(self):
            return []

    queue = [(None, _Pending(), 0.0, None),
             (None, object(), 0.0, None)]   # flag-less: skipped
    perf.drain_backlog(queue)
    assert calls == ["blocked"]
